"""Distributed-tracing span runtime (mxnet_tpu/tracing.py): contextvar
parentage across threads and the serving batcher queue, W3C traceparent
propagation over the HTTP front end and the parameter-server frame
wire, tail-based retention under low head sampling, the bounded ring
buffer, the watchdog's active-span-tree dump, and the hard-off mode.

Beyond-reference observability behavior specified by ISSUE 16 (the
reference's profiler only covered single-process op windows).
"""
import http.client
import json
import os
import threading
import time
from concurrent.futures import Future

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import health, metrics, serving, tracing
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving import (BucketPolicy, DynamicBatcher, ModelServer,
                               Request)

from tests.test_distributed import _free_port


@pytest.fixture(autouse=True)
def _fresh_tracing():
    tracing.configure(sample=1.0)
    yield
    tracing.configure()          # back to env-derived config, empty ring


def _names(recs):
    return {r["name"] for r in recs}


# ---------------------------------------------------------------------------
# propagation: threads + the batcher queue
# ---------------------------------------------------------------------------

def test_parentage_across_threads_and_batcher_queue():
    """capture()/attach() carries the trace onto a worker thread, and a
    Request submitted to the DynamicBatcher under a trace gets its
    queue.wait span parented under the submitting span."""
    done = threading.Event()
    with tracing.span("root", kind="unit") as root:
        ctx = tracing.capture()

        def work():
            with tracing.attach(ctx), tracing.child_span("worker.task"):
                pass
            done.set()

        threading.Thread(target=work, daemon=True).start()
        assert done.wait(10)

        p = BucketPolicy(batch_buckets=(1,))
        b = DynamicBatcher(p, timeout_ms=1, queue_limit=4)
        sample = (onp.ones(3, "float32"),)
        b.submit(Request(sample, p.bucket_key(sample), Future(), None))
        take = b.next_batch()
        assert take is not None and len(take) == 1
        b.close()

    recs = tracing.spans(root.trace_id)
    by = {r["name"]: r for r in recs}
    assert {"root", "worker.task", "queue.wait"} <= set(by)
    # both hops parent under the span that was active at hand-off time
    assert by["worker.task"]["parent_id"] == root.span_id
    assert by["worker.task"]["thread"] != by["root"]["thread"]
    assert by["queue.wait"]["parent_id"] == root.span_id
    # nothing leaked into a second trace
    assert len({r["trace_id"] for r in recs}) == 1


# ---------------------------------------------------------------------------
# propagation: the HTTP wire
# ---------------------------------------------------------------------------

def test_traceparent_http_round_trip_on_the_wire():
    """A client-sent traceparent header continues the client's trace:
    the server's spans carry the client's trace id (http.request is a
    remote child of the client's span id), the response echoes the
    header, and GET /v1/traces exports them on the raw wire."""
    tid, sid = "a" * 32, "b" * 16
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    net(mx.np.zeros((2, 12), dtype="float32"))
    model = serving.load_served(net)
    srv = ModelServer(model, model.default_policy(max_batch=2),
                      timeout_ms=3, warmup=True).start()
    httpd = serving.make_http_server(srv, port=0)
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        host, port = httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("POST", "/v1/inference",
                     json.dumps({"data": [0.5] * 12}),
                     {"Content-Type": "application/json",
                      "traceparent": f"00-{tid}-{sid}-01"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and "predictions" in body
        echo = resp.getheader("traceparent")
        assert echo is not None and echo.split("-")[1] == tid

        conn.request("GET", "/v1/traces", headers={})
        payload = json.loads(conn.getresponse().read())
        conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.stop()

    mine = [e for e in payload["traceEvents"]
            if e.get("ph") == "X" and e["args"].get("trace_id") == tid]
    by = {e["name"]: e for e in mine}
    assert {"http.request", "queue.wait"} <= set(by), sorted(by)
    assert by["http.request"]["args"]["parent_id"] == sid


# ---------------------------------------------------------------------------
# propagation: the PS frame wire
# ---------------------------------------------------------------------------

def test_ps_frame_carries_trace_across_push(monkeypatch):
    """A worker push under a trace stamps its traceparent into the PS
    frame header; the server's handling shows up as a ps.handle remote
    child span with the worker's trace id."""
    from mxnet_tpu.kvstore_async import KVStoreDistAsync, run_server

    port = _free_port()
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    ev = threading.Event()
    th = threading.Thread(target=run_server, args=(port, 1, ev),
                          daemon=True)
    th.start()
    assert ev.wait(20), "parameter server did not come up"
    kv = KVStoreDistAsync()
    try:
        kv.init("w", mx.np.zeros(4))        # untraced: no header field
        with tracing.span("push.root") as root:
            kv.push("w", mx.np.array(onp.ones(4, "float32")))
            got = kv.pull("w", out=mx.np.zeros(4)).asnumpy()
        assert onp.allclose(got, 1.0)
    finally:
        kv.stop_servers()
        th.join(10)

    recs = tracing.spans(root.trace_id)
    ps = [r for r in recs if r["name"] == "ps.handle"]
    assert ps, f"no ps.handle span in the push trace: {_names(recs)}"
    # a REMOTE child: same trace id, parented on the worker-side span
    # that was on the wire, handled on the server thread
    assert all(r["parent_id"] == root.span_id for r in ps)
    assert any(r["attrs"].get("cmd") == "P" for r in ps)
    assert all(r["thread"] != root._thread for r in ps)


# ---------------------------------------------------------------------------
# tail-based retention
# ---------------------------------------------------------------------------

def test_tail_upgrade_keeps_slow_and_error_traces_at_low_sampling():
    """At 1% head sampling, a trace that lost the coin flip is still
    retained whole when one of its spans runs past MXNET_TRACE_SLOW_MS
    or exits with an exception."""
    tracing.configure(sample=0.01, slow_ms=20.0)

    def unsampled_root(body):
        # P(sampled) = 0.01 per attempt: 200 attempts make a sampled-
        # only streak vanishingly unlikely (1e-400)
        for _ in range(200):
            with tracing.span("tail.root") as root:
                sampled = tracing.current_context().sampled
                if not sampled:
                    body()
            if not sampled:
                return root
        pytest.fail("never drew an unsampled trace at sample=0.01")

    slow = unsampled_root(lambda: tracing.record_span(
        "tail.slow", time.perf_counter() - 0.05, time.perf_counter()))
    recs = tracing.spans(slow.trace_id)
    assert {"tail.root", "tail.slow"} <= _names(recs)

    def raise_in_child():
        with pytest.raises(ValueError):
            with tracing.child_span("tail.err"):
                raise ValueError("boom")

    err = unsampled_root(raise_in_child)
    recs = tracing.spans(err.trace_id)
    by = {r["name"]: r for r in recs}
    assert {"tail.root", "tail.err"} <= set(by)
    assert by["tail.err"]["status"] == "error"
    assert "boom" in by["tail.err"]["error"]

    # a fast, clean, unsampled trace is NOT retained
    fast = unsampled_root(lambda: None)
    assert tracing.spans(fast.trace_id) == []


# ---------------------------------------------------------------------------
# ring buffer bound
# ---------------------------------------------------------------------------

def test_ring_buffer_keeps_only_the_newest_spans():
    tracing.configure(sample=1.0, buffer_spans=8)
    for i in range(50):
        with tracing.span("ring", i=i):
            pass
    recs = tracing.spans()
    assert len(recs) == 8
    assert [r["attrs"]["i"] for r in recs] == list(range(42, 50))


# ---------------------------------------------------------------------------
# watchdog integration
# ---------------------------------------------------------------------------

def test_watchdog_dump_names_the_open_span_tree(tmp_path, monkeypatch):
    """A hang-watchdog diagnostic dump includes the currently-open
    spans as an indented tree, so a stall names the span it wedged in."""
    metrics.reset()
    monkeypatch.setenv("MXNET_HEALTH_DIAG_DIR", str(tmp_path))
    with tracing.span("stall.root", step=7):
        with tracing.child_span("stall.child"):
            with health.watch_section("unit.trace", deadline_s=0.05):
                time.sleep(0.3)
    deadline = time.monotonic() + 10
    while (metrics.value("mxnet_health_events_total", kind="hang") < 1
           and time.monotonic() < deadline):
        time.sleep(0.02)
    path = health.last_dump_path()
    assert path and os.path.dirname(path) == str(tmp_path)
    text = open(path).read()
    assert "== active spans ==" in text
    assert "stall.root trace=" in text and "step=7" in text
    # the child is nested (indented) under the root
    assert "\n  stall.child trace=" in text


# ---------------------------------------------------------------------------
# hard off
# ---------------------------------------------------------------------------

def test_sample_zero_records_nothing_ever():
    """MXNET_TRACE_SAMPLE=0 is fully off: slow spans, error spans and
    explicit record_span calls all record nothing, and no trace context
    exists to propagate."""
    tracing.configure(sample=0.0, slow_ms=0.0)
    with tracing.span("off.slow"):
        assert tracing.current_context() is None
        assert tracing.traceparent() is None
        time.sleep(0.01)
    with pytest.raises(ValueError):
        with tracing.span("off.err"):
            raise ValueError("boom")
    tracing.record_span("off.rec", 0.0, 1.0)
    assert tracing.parse_traceparent(f"00-{'a'*32}-{'b'*16}-01") is None
    assert tracing.spans() == []
