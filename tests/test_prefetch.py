"""Async device-prefetch input pipeline suite (ISSUE 9).

Proves the DevicePrefetcher contract: prefetched training is
bit-identical to unprefetched (same batches, same order, same loss) —
including across a HealthGuard rewind and a checkpoint kill-and-resume;
depth is a scheduling knob, not a numeric one; a ``dataloader.worker``
fault inside the prefetch thread surfaces as a structured error, never
a hang; and a *wedged* producer is a named watchdog stall
(``prefetch.get``), not a silent one.
"""
import os
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, health, metrics
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.health import HealthGuard
from mxnet_tpu.io import DevicePrefetcher

# SPMD trainers + watchdog/prefetch threads: virtual-CPU-mesh territory
pytestmark = pytest.mark.host_mesh


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(autouse=True)
def _diag_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_HEALTH_DIAG_DIR", str(tmp_path / "diag"))
    yield


def _spmd_trainer(seed=0):
    import jax
    from mxnet_tpu.parallel import SPMDTrainer, make_mesh
    mx.random.seed(seed)
    net = mx.gluon.nn.Dense(4)
    net.initialize()
    net(mx.np.zeros((2, 8)))
    return SPMDTrainer(net, mx.gluon.loss.L2Loss(), "sgd",
                       {"learning_rate": 0.05},
                       mesh=make_mesh({"dp": 1},
                                      devices=jax.devices()[:1]))


def _batch_fn(step, salt=0):
    rng = onp.random.RandomState(100 + step + 1000 * salt)
    return (mx.np.array(rng.uniform(-1, 1, (8, 8)).astype("f4")),
            mx.np.array(rng.uniform(-1, 1, (8, 4)).astype("f4")))


# ---------------------------------------------------------------------------
# determinism: prefetch is a scheduling change, not a numeric one
# ---------------------------------------------------------------------------

def test_smoke_prefetched_fit_loss_identical_and_in_order():
    plain = float(_spmd_trainer().fit(_batch_fn, 6).asnumpy())

    fetched = []

    def recording(step, salt=0):
        fetched.append((step, salt))
        return _batch_fn(step, salt)

    pf = DevicePrefetcher(recording, depth=2)
    piped = float(_spmd_trainer().fit(pf, 6).asnumpy())
    pf.close()
    assert piped == plain
    # the producer runs ahead (up to depth) but never out of order, and
    # the 6 consumed steps were fetched exactly once each, in order
    assert fetched[:6] == [(s, 0) for s in range(6)]


def test_smoke_depth1_matches_depth2():
    losses = []
    for depth in (1, 2):
        pf = DevicePrefetcher(_batch_fn, depth=depth)
        losses.append(float(_spmd_trainer().fit(pf, 5).asnumpy()))
        pf.close()
    assert losses[0] == losses[1]


def test_smoke_iterable_mode_order_and_epoch_restart():
    rng = onp.random.RandomState(0)
    batches = [(rng.randn(4, 3).astype("f4"),
                rng.randn(4, 1).astype("f4")) for _ in range(5)]
    pf = DevicePrefetcher(batches, depth=2)
    for _ in range(2):                    # each iter() is a fresh epoch
        got = list(iter(pf))
        assert len(got) == len(batches)
        for (x, y), (gx, gy) in zip(batches, got):
            onp.testing.assert_array_equal(x, gx.asnumpy())
            onp.testing.assert_array_equal(y, gy.asnumpy())


def test_smoke_seek_and_salt_invalidate():
    pf = DevicePrefetcher(_batch_fn, depth=2)
    seeks0 = metrics.value("mxnet_prefetch_invalidated_total",
                           reason="seek")
    salts0 = metrics.value("mxnet_prefetch_invalidated_total",
                           reason="salt")
    x0, _ = pf.get(0)
    pf.get(1)
    # non-consecutive step (checkpoint restore / resume): reseek
    x5, _ = pf.get(5)
    onp.testing.assert_array_equal(x5.asnumpy(),
                                   _batch_fn(5)[0].asnumpy())
    assert metrics.value("mxnet_prefetch_invalidated_total",
                         reason="seek") == seeks0 + 1
    # perturbed salt (HealthGuard rewind replay): different data
    xs, _ = pf.get(5, salt=1)
    onp.testing.assert_array_equal(xs.asnumpy(),
                                   _batch_fn(5, salt=1)[0].asnumpy())
    assert metrics.value("mxnet_prefetch_invalidated_total",
                         reason="salt") == salts0 + 1
    # and the stream keeps flowing consecutively after the seeks
    onp.testing.assert_array_equal(pf.get(6, salt=1)[0].asnumpy(),
                                   _batch_fn(6, salt=1)[0].asnumpy())
    pf.close()
    assert onp.isfinite(x0.asnumpy()).all()


def test_smoke_api_misuse_raises():
    pf = DevicePrefetcher(_batch_fn)
    with pytest.raises(MXNetError, match="iter"):
        iter(pf)
    pf.close()
    pf2 = DevicePrefetcher([_batch_fn(0)])
    with pytest.raises(MXNetError, match="callable"):
        pf2.get(0)
    pf2.close()
    with pytest.raises(MXNetError, match="depth"):
        DevicePrefetcher(_batch_fn, depth=0)


# ---------------------------------------------------------------------------
# rewind / resume composition
# ---------------------------------------------------------------------------

def test_prefetch_healthguard_rewind_loss_identical(tmp_path):
    """A mid-run rewind (restore + salted replay) must invalidate the
    prefetched batches and land on the exact loss of the unprefetched
    run under the identical fault schedule."""
    def run(source, ckdir, wrap=None):
        guard = HealthGuard(policy="rewind", max_rewinds=2)
        mgr = CheckpointManager(str(ckdir), max_to_keep=3)
        tr = _spmd_trainer()
        with faults.fault_plan("trainer.step:kind=nan:times=1:after=3"):
            loss = tr.fit(source, 6, checkpoint_manager=mgr,
                          checkpoint_every=2, health_guard=guard)
        return float(loss.asnumpy()), guard

    plain, g0 = run(_batch_fn, tmp_path / "a")
    pf = DevicePrefetcher(_batch_fn, depth=2)
    piped, g1 = run(pf, tmp_path / "b")
    pf.close()
    assert g0.rewinds == 1 and g1.rewinds == 1
    assert g0.replay_salt == g1.replay_salt == 1
    assert piped == plain
    # the rewind's seek + salt change invalidated the queued batches
    assert metrics.value("mxnet_prefetch_invalidated_total",
                         reason="salt") >= 1


def test_prefetch_checkpoint_resume_parity(tmp_path):
    """Kill-and-resume analog: a prefetched run split across two fit()
    incarnations (fresh trainer + fresh prefetcher, restore from the
    manager) lands on the loss of the uninterrupted prefetched run."""
    pf = DevicePrefetcher(_batch_fn, depth=2)
    straight = float(_spmd_trainer().fit(pf, 6).asnumpy())
    pf.close()

    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=3)
    pf1 = DevicePrefetcher(_batch_fn, depth=2)
    _spmd_trainer().fit(pf1, 3, checkpoint_manager=mgr,
                        checkpoint_every=1)
    pf1.close()
    # "new process": everything rebuilt, state comes from the manager
    pf2 = DevicePrefetcher(_batch_fn, depth=2)
    resumed = float(_spmd_trainer().fit(
        pf2, 6, checkpoint_manager=mgr).asnumpy())
    pf2.close()
    assert resumed == straight


# ---------------------------------------------------------------------------
# failure semantics: structured error, never a hang
# ---------------------------------------------------------------------------

def test_smoke_fault_in_prefetch_thread_is_structured():
    faults.arm("dataloader.worker", kind="error", times=1)
    pf = DevicePrefetcher(_batch_fn, depth=2)
    t0 = time.monotonic()
    with pytest.raises(faults.FaultInjected, match="dataloader.worker"):
        pf.get(0)
    assert time.monotonic() - t0 < 30          # structured, not a hang
    pf.close()


def test_smoke_producer_crash_mid_epoch_is_structured():
    def gen():
        yield _batch_fn(0)
        raise RuntimeError("decoder exploded")

    pf = DevicePrefetcher(gen(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(MXNetError, match="prefetch worker failed"):
        next(it)


def test_smoke_watchdog_names_stalled_prefetcher(monkeypatch):
    """A wedged loader is a NAMED stall: the blocking get() is armed on
    the hang watchdog as site 'prefetch.get' and dumps all-thread
    stacks instead of hanging silently."""
    monkeypatch.setenv("MXNET_HEALTH_STEP_DEADLINE_S", "0.15")
    fired0 = metrics.value("mxnet_health_watchdog_fires_total",
                           site="prefetch.get")

    def wedged(step):
        if step == 0:
            time.sleep(0.8)                # well past the deadline
        return _batch_fn(step)

    pf = DevicePrefetcher(wedged, depth=1)
    x, _ = pf.get(0)                       # survives the stall ...
    pf.close()
    assert onp.isfinite(x.asnumpy()).all()
    # ... but the watchdog named it and dumped diagnostics
    assert metrics.value("mxnet_health_watchdog_fires_total",
                         site="prefetch.get") == fired0 + 1
    dump = health.last_dump_path()
    assert dump is not None and "prefetch_get" in os.path.basename(dump)
    assert os.path.exists(dump)


# ---------------------------------------------------------------------------
# donation + instrumentation
# ---------------------------------------------------------------------------

def test_smoke_donation_scoped_to_prefetched_fit():
    """fit() with a prefetcher donates batch buffers into the step;
    manual step() calls afterwards must be able to REUSE a batch (no
    donation — a donated buffer would be deleted under the caller)."""
    tr = _spmd_trainer()
    pf = DevicePrefetcher(_batch_fn, depth=2)
    tr.fit(pf, 3)
    pf.close()
    assert tr._donate_inputs is False
    X, Y = _batch_fn(0)
    l1 = float(tr.step(X, Y).asnumpy())
    l2 = float(tr.step(X, Y).asnumpy())    # same buffers, second use
    assert onp.isfinite(l1) and onp.isfinite(l2)


def test_smoke_prefetch_metrics_flow():
    b0 = metrics.value("mxnet_prefetch_batches_total")
    pf = DevicePrefetcher(_batch_fn, depth=2)
    tr = _spmd_trainer()
    tr.fit(pf, 4)
    pf.close()
    assert metrics.value("mxnet_prefetch_batches_total") >= b0 + 4
    # the step loop's input wait was observed (possibly ~0, but counted)
    total, count = metrics.hist_stats("mxnet_prefetch_stall_seconds")
    assert count >= 4
    h2d_total, h2d_count = metrics.hist_stats("mxnet_prefetch_h2d_seconds")
    assert h2d_count >= 4


def test_smoke_closed_prefetcher_errors_not_hangs():
    pf = DevicePrefetcher(_batch_fn, depth=2)
    pf.get(0)
    pf.close()
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match="closed"):
        pf.get(1)
    assert time.monotonic() - t0 < 30
    # iterable mode: a finished (self-closed) epoch keeps raising
    # StopIteration instead of spinning on the empty queue
    it = iter(DevicePrefetcher([_batch_fn(0)], depth=1))
    assert len(list(it)) == 1
    with pytest.raises(StopIteration):
        next(it)
