"""Serving resilience (ISSUE 7): replicated workers, exactly-once
stream recovery, circuit breaker, graceful drain.

The invariants under test:

* a dead worker is a ROUTINE event: its work requeues/recovers onto
  healthy replicas and the supervisor restarts it with backoff;
* recovered generation streams are TOKEN-IDENTICAL to a fault-free
  greedy run (deterministic re-prefill of prompt+emitted + TokenStream
  index dedupe = exactly-once on the wire);
* a crash-loop trips the circuit breaker into explicit degraded mode
  (structured DegradedError; readiness 503, liveness 200) and a manual
  reset re-admits traffic;
* SIGTERM drains: admissions shed 429 (never a connection reset),
  resident sequences finish inside MXNET_SERVING_DRAIN_DEADLINE_S,
  exit code 0.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, metrics, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (BucketPolicy, DecodeModel, DegradedError,
                               GenerationEngine, GenerationServer,
                               ModelServer, OverloadError)

VOCAB = 97
PROMPT_A = onp.array([5, 9, 3, 17], dtype="int32")
PROMPT_B = onp.array([1, 2], dtype="int32")
PROMPT_C = onp.array([7, 4, 11], dtype="int32")


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt():
    """Tiny decoder LM, strong init (same rationale as
    tests/test_generation.py: varied deterministic-greedy output)."""
    from mxnet_tpu.gluon.model_zoo.gpt import GPTModel
    mx.random.seed(0)
    net = GPTModel(vocab_size=VOCAB, num_layers=2, units=32,
                   hidden_size=48, num_heads=4, max_length=64,
                   dropout=0.0)
    net.initialize(mx.init.Normal(1.0))
    net(mx.np.zeros((1, 4), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def decode_model(gpt):
    return DecodeModel.from_block(gpt)


def _reference_greedy(gpt, prompt, n):
    """Uncompiled full-forward-per-token reference (the ground truth a
    recovered stream must match)."""
    PAD = 64
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        padded = toks + [0] * (PAD - len(toks))
        logits = gpt(mx.np.array(
            onp.asarray([padded], "int32"))).asnumpy()
        nxt = int(logits[0, len(toks) - 1].argmax())
        out.append(nxt)
        toks.append(nxt)
    return out


def _engine(decode_model, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("kv_buckets", (16, 32, 64))
    kw.setdefault("max_tokens", 48)
    eng = GenerationEngine(decode_model, **kw)
    eng.warmup()
    return eng


def _model_server(**kw):
    net = mx.gluon.nn.Dense(3)
    net.initialize()
    net.hybridize()
    net(mx.np.zeros((1, 6), dtype="float32"))
    model = serving.load_served(net)
    kw.setdefault("policy", BucketPolicy(batch_buckets=(1, 2)))
    kw.setdefault("timeout_ms", 1.0)
    kw.setdefault("restart_backoff_ms", 10.0)
    return ModelServer(model, **kw)


def _wait(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# ModelServer: transient worker death -> requeue + restart, no caller error
# ---------------------------------------------------------------------------

def test_worker_death_requeues_batch_and_restarts():
    restarts0 = metrics.value("mxnet_serving_worker_restarts_total",
                              server="oneshot")
    srv = _model_server().start()
    try:
        x = onp.ones(6, "f4")
        with faults.fault_plan("serving.worker:times=1"):
            # the worker dies holding this request's batch; it must
            # requeue and complete on the restarted worker — the CALLER
            # sees a result, not an error
            out = srv.infer(x, timeout=20.0)
        assert out.shape == (3,)
        assert metrics.value("mxnet_serving_worker_restarts_total",
                             server="oneshot") == restarts0 + 1
        _wait(srv.healthy, what="server healthy after restart")
        assert not srv.degraded
        # and it keeps serving
        assert srv.infer(x, timeout=20.0).shape == (3,)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# crash loop -> breaker -> readiness 503 / liveness 200 -> manual reset
# ---------------------------------------------------------------------------

def test_crash_loop_trips_breaker_reset_readmits():
    from mxnet_tpu.serving.http import make_http_server
    srv = _model_server(max_restarts=2)
    srv.start()
    httpd = make_http_server(srv, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = httpd.server_address
    x = onp.ones(6, "f4")
    try:
        with faults.fault_plan("serving.worker:p=1"):
            fut = srv.infer_async(x)
            # every restart re-crashes at the site: after
            # max_restarts=2 the breaker must trip
            _wait(lambda: srv.degraded, what="breaker trip")
            with pytest.raises(MXNetError,
                               match="worker thread died.*degraded"):
                fut.result(timeout=10)
            # structured refusal, not a queue-forever
            with pytest.raises(DegradedError, match="degraded"):
                srv.infer_async(x)
            assert metrics.value("mxnet_serving_breaker_open",
                                 server="oneshot") == 1
            # readiness 503, liveness 200 — the orchestrator must NOT
            # kill the pod, the balancer must route away
            try:
                urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=10)
                raise AssertionError("readiness should be 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["status"] == "degraded"
            with urllib.request.urlopen(
                    f"http://{host}:{port}/livez", timeout=10) as r:
                live = json.loads(r.read())
            assert live["status"] == "alive" and live["degraded"]
        # cause gone (plan disarmed): the operator resets the breaker
        # and traffic re-admits through the same server object
        srv.reset_breaker()
        assert srv.infer(x, timeout=20.0).shape == (3,)
        assert srv.healthy()
        assert metrics.value("mxnet_serving_breaker_open",
                             server="oneshot") == 0
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
    finally:
        httpd.shutdown()
        srv.stop()


# ---------------------------------------------------------------------------
# exactly-once recovery: decode fault mid-stream, token-identical resume
# ---------------------------------------------------------------------------

@pytest.mark.slow    # tier-1 time budget (r8): resilience-smoke gates token-identical recovery in tier 1
def test_decode_fault_recovers_token_identical(gpt, decode_model):
    want = _reference_greedy(gpt, PROMPT_A, 16)
    rec0 = metrics.value("mxnet_serving_recoveries_total", site="decode")
    eng = _engine(decode_model, max_slots=1)
    with GenerationServer(eng) as gs:
        # site hits: #1 prefill, #2.. decode iterations; after=3:times=1
        # detonates one decode step mid-stream (a few tokens emitted)
        with faults.fault_plan("serving.execute:after=3:times=1"):
            s = gs.generate(PROMPT_A, max_new_tokens=16)
            got = s.result(timeout=30)
        assert got == want, "recovered stream diverged from the " \
            "fault-free greedy run"
        assert s.finish_reason == "length"
    assert metrics.value("mxnet_serving_recoveries_total",
                         site="decode") == rec0 + 1
    assert metrics.value("mxnet_serving_recovered_tokens_total") > 0
    # the engine survived a decode fault WITHOUT a worker restart
    assert faults.injected_count("serving.execute") == 0  # plan left scope


@pytest.mark.slow    # tier-1 time budget (r8): resilience-smoke gates worker-death recovery in tier 1
def test_worker_death_recovers_on_surviving_replica(gpt, decode_model):
    prompts = [PROMPT_A, PROMPT_B, PROMPT_C, PROMPT_A]
    budgets = [14, 10, 12, 8]
    wants = [_reference_greedy(gpt, p, n)
             for p, n in zip(prompts, budgets)]
    factory = lambda: _engine(decode_model, max_slots=2)  # noqa: E731
    rec0 = (metrics.value("mxnet_serving_recoveries_total", site="worker")
            + metrics.value("mxnet_serving_recoveries_total",
                            site="queue"))
    gs = GenerationServer(engine_factory=factory, replicas=2,
                          restart_backoff_ms=10)
    gs.start()
    try:
        # the third busy worker pass dies (whichever replica gets
        # there), with sequences resident and/or queued — all of them
        # must complete token-identical on the survivors
        with faults.fault_plan("serving.worker:after=2:times=1"):
            streams = [gs.generate(p, max_new_tokens=n)
                       for p, n in zip(prompts, budgets)]
            results = [s.result(timeout=60) for s in streams]
        for got, want, s in zip(results, wants, streams):
            assert got == want, "stream diverged after worker death"
            assert s.finish_reason == "length"
        assert faults.injected_count("serving.worker") == 0  # left scope
        recs = (metrics.value("mxnet_serving_recoveries_total",
                              site="worker")
                + metrics.value("mxnet_serving_recoveries_total",
                                site="queue"))
        assert recs > rec0, "the kill recovered nothing (did it fire?)"
    finally:
        gs.stop()


def test_recovery_budget_exhausted_fails_structurally(decode_model):
    """A sequence that keeps crashing its decode step must eventually
    FAIL with the underlying error (bounded resurrection), not bounce
    through recovery forever."""
    from mxnet_tpu.serving.generation import GenRequest
    eng = _engine(decode_model, max_slots=1)
    gs = GenerationServer(eng).start()
    try:
        req = GenRequest(PROMPT_A, 8, None, None)
        req.stream.put(5, index=0)               # one emitted token
        req.recoveries = gs.supervisor.max_restarts
        gs._recover([req], MXNetError("boom"), "decode")
        with pytest.raises(MXNetError, match="recovery budget"):
            req.stream.result(timeout=5)
    finally:
        gs.stop()


# ---------------------------------------------------------------------------
# queued-request cancellation frees budget immediately
# ---------------------------------------------------------------------------

def test_queued_cancel_frees_queue_budget_immediately(decode_model):
    eng = _engine(decode_model, max_slots=1, queue_limit=1)
    s1 = eng.submit(PROMPT_A, max_new_tokens=40)
    eng.run_iteration()                      # s1 occupies the only slot
    s2 = eng.submit(PROMPT_B, max_new_tokens=4)
    with pytest.raises(OverloadError):       # queue full
        eng.submit(PROMPT_C, max_new_tokens=4)
    s2.cancel()
    # eviction happens AT cancel, not at the next admission pass: the
    # budget is free with no iteration in between
    assert len(eng.scheduler) == 0
    s4 = eng.submit(PROMPT_C, max_new_tokens=4)
    assert not s4.finished                   # accepted, not shed
    assert not s1.finished                   # resident seq untouched
    eng.close()


# ---------------------------------------------------------------------------
# graceful drain (in-process semantics; SIGTERM e2e below + CI gate)
# ---------------------------------------------------------------------------

def test_generation_drain_finishes_resident_sheds_new(decode_model):
    eng = _engine(decode_model, max_slots=2)
    gs = GenerationServer(eng).start()
    s = gs.generate(PROMPT_A, max_new_tokens=20)
    assert s.next_token(timeout=10) is not None   # resident + streaming
    gs.start_drain()
    assert not gs.ready()                    # out of rotation...
    with pytest.raises(OverloadError) as ei:
        gs.generate(PROMPT_B, max_new_tokens=4)
    assert ei.value.reason == "draining"     # ...and sheds structurally
    rest = [t for t in s]                    # the resident one finishes
    assert len(rest) == 19 and s.finish_reason == "length"
    assert gs.await_drained(timeout=10)
    gs.stop()


def test_model_server_drain_sheds_structurally():
    srv = _model_server().start()
    x = onp.ones(6, "f4")
    try:
        assert srv.infer(x, timeout=20.0).shape == (3,)
        srv.start_drain()
        assert not srv.ready()
        with pytest.raises(OverloadError) as ei:
            srv.infer(x)
        assert ei.value.reason == "draining"
        assert srv.await_drained(timeout=10)
    finally:
        srv.stop()


@pytest.mark.slow
def test_sigterm_drains_and_exits_zero(tmp_path):
    """E2E rolling-restart contract: SIGTERM under streaming load ->
    resident streams finish, new admissions shed 429 (no connection
    reset), readiness 503 / liveness 200 during the window, exit 0.

    Slow-marked (subprocess boot + drain ~15s): the tier-1 wall budget
    is tight, and ``ci/run.sh resilience-smoke`` gates the same
    contract (with 8 clients) on every tier-1 CI run; the in-process
    drain tests above stay in the fast selection."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_SERVING_DRAIN_DEADLINE_S="60")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tools", "serve.py"),
         "--generate", "--zoo-gpt", "tiny", "--platform", "cpu",
         "--host", "127.0.0.1", "--port", "0", "--max-slots", "2",
         "--kv-buckets", "160", "--no-warmup"],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    port = None
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if "serving on http://" in line:
                port = int(line.split("http://")[1].split()[0]
                           .rsplit(":", 1)[1])
                break
        assert port, "server never reported its address"
        base = f"http://127.0.0.1:{port}"

        results = {}

        def client(ci):
            body = json.dumps({"tokens": [3 + ci, 7, 11],
                               "max_new_tokens": 120}).encode()
            req = urllib.request.Request(f"{base}/v1/generate",
                                         data=body)
            with urllib.request.urlopen(req, timeout=120) as r:
                toks, done = 0, None
                for ln in r:
                    obj = json.loads(ln)
                    if "token" in obj:
                        toks += 1
                    if obj.get("done"):
                        done = obj
                results[ci] = (toks, done)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        # wait until generation is demonstrably resident (tokens flow)
        _wait(lambda: _gen_active(base), timeout=90,
              what="resident generation load")
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.1)
        # during the drain window: admission sheds 429 + structured
        # payload, readiness 503 ("draining"), liveness 200
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/generate",
                data=json.dumps({"tokens": [1, 2],
                                 "max_new_tokens": 4}).encode()),
                timeout=10)
            raise AssertionError("draining admission should be 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert json.loads(e.read())["reason"] == "draining"
        try:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
            raise AssertionError("draining readiness should be 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "draining"
        with urllib.request.urlopen(f"{base}/livez", timeout=10) as r:
            assert json.loads(r.read())["status"] == "alive"
        for t in threads:
            t.join(timeout=120)
        assert all(not t.is_alive() for t in threads)
        # every accepted stream finished completely: full budget + a
        # clean done trailer (never a reset mid-stream)
        assert sorted(results) == [0, 1, 2, 3]
        for toks, done in results.values():
            assert done is not None and done.get("done")
            assert toks == 120
        assert proc.wait(timeout=90) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def _gen_active(base):
    with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
        h = json.loads(r.read())
    return h.get("generation", {}).get("slots", {}).get("active", 0) > 0
