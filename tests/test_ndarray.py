"""NDArray basics (reference analog: tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, rand_ndarray


def test_creation():
    x = mx.np.array([[1, 2], [3, 4]], dtype="float32")
    assert x.shape == (2, 2)
    assert x.dtype == onp.float32
    assert x.size == 4
    assert x.ndim == 2
    assert_almost_equal(x, onp.array([[1, 2], [3, 4]], dtype="float32"))

    z = mx.np.zeros((3, 4))
    assert z.asnumpy().sum() == 0
    o = mx.np.ones((2, 5), dtype="int32")
    assert o.asnumpy().sum() == 10
    f = mx.np.full((2, 2), 7.0)
    assert f.asnumpy().mean() == 7.0
    a = mx.np.arange(5)
    assert a.shape == (5,)
    e = mx.np.eye(3)
    assert e.asnumpy().trace() == 3.0


def test_elementwise_arith():
    a = mx.np.array([1.0, 2.0, 3.0])
    b = mx.np.array([4.0, 5.0, 6.0])
    assert_almost_equal(a + b, onp.array([5, 7, 9.0]))
    assert_almost_equal(a - b, onp.array([-3, -3, -3.0]))
    assert_almost_equal(a * b, onp.array([4, 10, 18.0]))
    assert_almost_equal(b / a, onp.array([4, 2.5, 2.0]))
    assert_almost_equal(a ** 2, onp.array([1, 4, 9.0]))
    assert_almost_equal(2 + a, onp.array([3, 4, 5.0]))
    assert_almost_equal(2 * a, onp.array([2, 4, 6.0]))
    assert_almost_equal(1 / a, onp.array([1, 0.5, 1 / 3]))
    assert_almost_equal(-a, onp.array([-1, -2, -3.0]))
    assert_almost_equal(abs(mx.np.array([-1.0, 2.0])), onp.array([1, 2.0]))


def test_inplace_ops():
    a = mx.np.ones((3,))
    a += 2
    assert_almost_equal(a, onp.full(3, 3.0))
    a *= 2
    assert_almost_equal(a, onp.full(3, 6.0))
    a -= 1
    a /= 5
    assert_almost_equal(a, onp.full(3, 1.0))


def test_comparison_ops():
    a = mx.np.array([1.0, 2.0, 3.0])
    b = mx.np.array([3.0, 2.0, 1.0])
    assert (a == b).asnumpy().tolist() == [False, True, False]
    assert (a != b).asnumpy().tolist() == [True, False, True]
    assert (a < b).asnumpy().tolist() == [True, False, False]
    assert (a >= b).asnumpy().tolist() == [False, True, True]


def test_indexing():
    x = mx.np.arange(12).reshape(3, 4)
    assert x[1, 2].item() == 6.0
    assert x[1].shape == (4,)
    assert x[:, 1].shape == (3,)
    assert x[1:3].shape == (2, 4)
    assert x[-1, -1].item() == 11.0
    idx = mx.np.array([0, 2], dtype="int32")
    assert x[idx].shape == (2, 4)


def test_setitem():
    x = mx.np.zeros((3, 3))
    x[1, 1] = 5.0
    assert x[1, 1].item() == 5.0
    x[0] = 2.0
    assert_almost_equal(x[0], onp.full(3, 2.0))
    x[:] = 1.0
    assert x.asnumpy().sum() == 9.0


def test_shape_methods():
    x = rand_ndarray((2, 3, 4))
    assert x.reshape(6, 4).shape == (6, 4)
    assert x.reshape((-1,)).shape == (24,)
    assert x.transpose().shape == (4, 3, 2)
    assert x.transpose(1, 0, 2).shape == (3, 2, 4)
    assert x.T.shape == (4, 3, 2)
    assert x.swapaxes(0, 2).shape == (4, 3, 2)
    assert x.expand_dims(0).shape == (1, 2, 3, 4)
    assert x.expand_dims(0).squeeze(0).shape == (2, 3, 4)
    assert x.flatten().shape == (2, 12)
    assert x.ravel().shape == (24,)
    assert x.tile((2, 1, 1)).shape == (4, 3, 4)
    assert x.repeat(2, axis=1).shape == (2, 6, 4)


def test_reduce_methods():
    x = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    assert x.sum().item() == 10.0
    assert_almost_equal(x.sum(axis=0), onp.array([4.0, 6.0]))
    assert x.mean().item() == 2.5
    assert x.max().item() == 4.0
    assert x.min().item() == 1.0
    assert x.prod().item() == 24.0
    assert x.argmax().item() == 3
    assert x.argmin(axis=1).asnumpy().tolist() == [0, 0]
    assert_almost_equal(x.norm(), onp.sqrt(30.0).astype("float32"))
    assert x.sum(axis=0, keepdims=True).shape == (1, 2)


def test_dtype_cast():
    x = mx.np.array([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == onp.int32
    assert y.asnumpy().tolist() == [1, 2]
    z = x.astype("float16")
    assert z.dtype == onp.float16
    b = x.astype("bfloat16")
    assert "bfloat16" in str(b.dtype)


def test_context_placement():
    x = mx.np.ones((2, 2), ctx=mx.cpu())
    assert x.context.device_type == "cpu"
    y = x.as_in_context(mx.cpu(0))
    assert y is x  # same ctx: no copy
    c = x.copy()
    c[0, 0] = 9.0
    assert x[0, 0].item() == 1.0  # copy is deep


def test_sync_and_wait():
    x = mx.np.ones((8, 8))
    y = mx.np.dot(x, x)
    y.wait_to_read()
    mx.waitall()
    assert y.asnumpy().sum() == 8 * 8 * 8


def test_scalar_conversions():
    x = mx.np.array([3.5])
    assert float(x) == 3.5
    assert int(mx.np.array([2])) == 2
    assert bool(mx.np.array([1.0]))
    with pytest.raises(ValueError):
        bool(mx.np.ones((2,)))
    assert len(mx.np.ones((5, 2))) == 5
    assert mx.np.array([1.0, 2.0]).tolist() == [1.0, 2.0]


def test_zeros_ones_like():
    x = rand_ndarray((2, 3))
    assert x.zeros_like().asnumpy().sum() == 0
    assert x.ones_like().asnumpy().sum() == 6


def test_concat_stack_split():
    a = mx.np.ones((2, 3))
    b = mx.np.zeros((2, 3))
    c = mx.np.concatenate([a, b], axis=0)
    assert c.shape == (4, 3)
    c2 = mx.nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    s = mx.np.stack([a, b], axis=0)
    assert s.shape == (2, 2, 3)
    parts = mx.np.split(mx.np.arange(10), 2)
    assert len(parts) == 2 and parts[0].shape == (5,)


def test_take_gather():
    x = mx.np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    t = mx.np.take(x, mx.np.array([0, 2], dtype="int32"), axis=0)
    assert_almost_equal(t, onp.array([[1, 2], [5, 6.0]]))
    oh = mx.npx.one_hot(mx.np.array([0, 2], dtype="int32"), 3)
    assert_almost_equal(oh, onp.array([[1, 0, 0], [0, 0, 1.0]]))


def test_ordering():
    x = mx.np.array([3.0, 1.0, 2.0])
    assert mx.nd.sort(x).asnumpy().tolist() == [1, 2, 3]
    assert mx.nd.sort(x, is_ascend=False).asnumpy().tolist() == [3, 2, 1]
    assert mx.nd.argsort(x).asnumpy().tolist() == [1, 2, 0]
    tk = mx.nd.topk(x, k=2, ret_typ="value")
    assert tk.asnumpy().tolist() == [3, 2]


def test_where_clip():
    x = mx.np.array([-1.0, 0.5, 2.0])
    assert_almost_equal(x.clip(0.0, 1.0), onp.array([0, 0.5, 1.0]))
    w = mx.np.where(x > 0, x, x.zeros_like())
    assert_almost_equal(w, onp.array([0, 0.5, 2.0]))


def test_numpy_interop():
    x = mx.np.ones((2, 2))
    n = onp.asarray(x)
    assert n.sum() == 4.0
    y = mx.np.array(onp.eye(3))
    assert y.shape == (3, 3)


def test_waitall_tracks_arrays():
    from mxnet_tpu import engine
    x = mx.np.ones((4, 4))
    y = x * 2
    assert len(engine._LIVE) > 0
    mx.waitall()


def test_multinomial_get_prob():
    p = mx.np.array([0.1, 0.2, 0.7])
    s, logp = mx.nd.random.multinomial(p, shape=4, get_prob=True)
    assert s.shape == (4,) and logp.shape == (4,)
    probs = onp.array([0.1, 0.2, 0.7])
    expect = onp.log(probs / probs.sum())
    # accelerator libm log deviates at the ~1e-4 level (cross-backend
    # tolerance class, see test_utils.check_consistency)
    from mxnet_tpu.test_utils import default_context
    tol = 1e-3 if default_context().device_type != "cpu" else 1e-5
    for si, lp in zip(s.asnumpy(), logp.asnumpy()):
        assert abs(lp - expect[int(si)]) < tol


def test_norm_ord_high_rank():
    x = mx.np.ones((2, 3, 4))
    assert abs(x.norm(ord=1).item() - 24.0) < 1e-5
    assert abs(x.norm().item() - onp.sqrt(24.0)) < 1e-5


def test_legacy_broadcast_elemwise_aliases():
    """1.x op-name surface: broadcast_*/elemwise_* spellings (reference
    src/operator/tensor/elemwise_binary_broadcast_op*)."""
    a = mx.np.array(onp.arange(6.0).reshape(2, 3).astype("float32"))
    b = mx.np.array(onp.ones((1, 3), dtype="float32"))
    assert onp.allclose(mx.nd.broadcast_add(a, b).asnumpy(),
                        a.asnumpy() + 1)
    assert onp.allclose(mx.nd.broadcast_mul(a, a).asnumpy(),
                        a.asnumpy() ** 2)
    assert onp.allclose(mx.nd.elemwise_sub(a, a).asnumpy(), 0)
    assert mx.nd.broadcast_axis(mx.np.ones((1, 3)), axis=0,
                                size=4).shape == (4, 3)
    assert mx.nd.broadcast_like(mx.np.ones((1, 3)),
                                mx.np.ones((5, 3))).shape == (5, 3)
    assert mx.nd.reshape_like(a, mx.np.ones((3, 2))).shape == (3, 2)
    assert onp.allclose(mx.nd.reverse(a, axis=1).asnumpy(),
                        a.asnumpy()[:, ::-1])
    assert onp.allclose(mx.nd.slice(a, (0, 1), (2, 3)).asnumpy(),
                        a.asnumpy()[0:2, 1:3])
    sm = mx.nd.softmin(a, axis=1).asnumpy()
    assert onp.allclose(sm.sum(axis=1), 1, atol=1e-5)
    m, v = mx.nd.moments(a, axes=(0,))
    assert onp.allclose(m.asnumpy(), a.asnumpy().mean(0))
    assert onp.allclose(v.asnumpy(), a.asnumpy().var(0))
    assert mx.nd.shape_array(a).asnumpy().tolist() == [2, 3]
    assert mx.nd.size_array(a).asnumpy().tolist() == [6]
    assert mx.nd.batch_take(a, mx.np.array(onp.array([2, 0]))) \
        .asnumpy().tolist() == [2.0, 3.0]


def test_spatial_transformer_sampling():
    """grid_generator + bilinear_sampler (reference
    src/operator/{grid_generator,bilinear_sampler}.cc): identity affine
    and zero warp reproduce the input; gradients flow to the data."""
    img = mx.np.array(onp.random.rand(2, 3, 5, 7).astype("float32"))
    theta = mx.np.array(onp.tile(
        onp.array([1, 0, 0, 0, 1, 0], dtype="float32"), (2, 1)))
    grid = mx.nd.grid_generator(theta, "affine", target_shape=(5, 7))
    out = mx.nd.bilinear_sampler(img, grid)
    assert onp.allclose(out.asnumpy(), img.asnumpy(), atol=1e-4)
    flow = mx.np.array(onp.zeros((2, 2, 5, 7), dtype="float32"))
    out2 = mx.nd.bilinear_sampler(img, mx.nd.grid_generator(flow, "warp"))
    assert onp.allclose(out2.asnumpy(), img.asnumpy(), atol=1e-4)
    # translation by a full grid-width pushes samples out of range -> 0
    theta_t = mx.np.array(onp.tile(
        onp.array([1, 0, 2.5, 0, 1, 0], dtype="float32"), (2, 1)))
    out3 = mx.nd.bilinear_sampler(
        img, mx.nd.grid_generator(theta_t, "affine", target_shape=(5, 7)))
    assert (onp.asarray(out3.asnumpy())[:, :, :, -1] == 0).all()
    img.attach_grad()
    with mx.autograd.record():
        s = mx.nd.bilinear_sampler(img, grid).sum()
    s.backward()
    g = img.grad.asnumpy()
    assert onp.isfinite(g).all() and abs(g).sum() > 0
