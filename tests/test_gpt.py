"""GPT decoder-only LM (beyond-reference model family; causal attention
through the same transformer op stack as BERT)."""
import jax
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo.gpt import GPTModel, get_gpt
from mxnet_tpu.parallel import (SPMDTrainer, make_mesh,
                                DEFAULT_TRANSFORMER_RULES)
from mxnet_tpu.test_utils import assert_almost_equal


def _tiny(dropout=0.0):
    mx.random.seed(0)
    net = GPTModel(vocab_size=101, num_layers=2, units=32, hidden_size=64,
                   num_heads=4, max_length=16, dropout=dropout)
    net.initialize()
    return net


def test_gpt_causality():
    net = _tiny()
    x = mx.np.array(onp.random.RandomState(0)
                    .randint(0, 101, (2, 10)).astype("int32"))
    out = net(x)
    assert out.shape == (2, 10, 101)
    x2 = onp.asarray(x.asnumpy()).copy()
    x2[:, -1] = (x2[:, -1] + 1) % 101
    out2 = net(mx.np.array(x2.astype("int32")))
    # past logits unchanged, final position changed
    assert_almost_equal(out.asnumpy()[:, :-1], out2.asnumpy()[:, :-1],
                        rtol=1e-5, atol=1e-6)
    assert not onp.allclose(out.asnumpy()[:, -1], out2.asnumpy()[:, -1])


def test_gpt_hybridize_equivalence():
    net = _tiny()
    x = mx.np.array(onp.random.RandomState(1)
                    .randint(0, 101, (2, 8)).astype("int32"))
    eager = net(x)
    net.hybridize()
    compiled = net(x)
    assert_almost_equal(eager, compiled, rtol=1e-5, atol=1e-6)


@pytest.mark.host_mesh   # needs a 4-device mesh — skipped under the chip ctx-flip
def test_gpt_spmd_tp_training_converges():
    net = _tiny()
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    tr = SPMDTrainer(net, loss_fn, optimizer="adamw",
                     optimizer_params={"learning_rate": 1e-3},
                     mesh=mesh, rules=DEFAULT_TRANSFORMER_RULES)
    rng = onp.random.RandomState(2)
    x = mx.np.array(rng.randint(0, 101, (4, 10)).astype("int32"))
    y = mx.np.array(rng.randint(0, 101, (4, 10)).astype("int32"))
    l0 = float(tr.step(x, y).asnumpy())
    for _ in range(5):
        l = float(tr.step(x, y).asnumpy())
    assert l < l0


def test_gpt_specs_and_max_length_guard():
    import pytest
    with pytest.raises(ValueError):
        get_gpt("gpt_unknown")
    net = _tiny()
    with pytest.raises(mx.MXNetError):
        net(mx.np.zeros((1, 32), dtype="int32"))  # > max_length 16


# ---------------------------------------------------------------------------
# KV-cache generation (model_zoo.generation)
# ---------------------------------------------------------------------------

def _tiny_gpt(vocab=97, layers=2, units=32, heads=4, max_len=64):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTModel
    mx.random.seed(0)
    net = GPTModel(vocab_size=vocab, num_layers=layers, units=units,
                   hidden_size=units * 4, num_heads=heads,
                   max_length=max_len, dropout=0.0)
    net.initialize()
    net(mx.np.zeros((1, 4), dtype="int32"))      # finish deferred init
    return net


@pytest.mark.slow    # tier-1 time budget (r8): greedy decode parity is gated end-to-end by generation-smoke
def test_generate_greedy_matches_full_forward():
    """The cached incremental decoder must produce exactly the tokens a
    naive full-recompute greedy decode produces (cache math == forward
    math)."""
    import numpy as onp
    import mxnet_tpu as mx
    net = _tiny_gpt()
    rng = onp.random.RandomState(0)
    prompt = rng.randint(0, 97, (2, 5)).astype("int32")

    got = net.generate(prompt, max_new_tokens=8).asnumpy()

    # reference: recompute the full forward per step, take argmax
    toks = prompt.copy()
    want = []
    for _ in range(8):
        logits = net(mx.np.array(toks)).asnumpy()
        nxt = logits[:, -1, :].argmax(-1).astype("int32")
        want.append(nxt)
        toks = onp.concatenate([toks, nxt[:, None]], axis=1)
    onp.testing.assert_array_equal(got, onp.stack(want, axis=1))


@pytest.mark.slow    # tier-1 time budget (r8): decode-path numerics ride the generation-smoke zoo decode gate
def test_generate_respects_layer_norm_eps():
    """A non-default layer_norm_eps must flow into the decode path (the
    pure-jax mirror reads the model's epsilon, not a constant)."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTModel
    mx.random.seed(0)
    net = GPTModel(vocab_size=61, num_layers=1, units=16,
                   hidden_size=32, num_heads=2, max_length=32,
                   dropout=0.0, layer_norm_eps=1e-2)
    net.initialize()
    net(mx.np.zeros((1, 3), dtype="int32"))
    prompt = onp.random.RandomState(1).randint(0, 61, (1, 4)).astype(
        "int32")
    got = net.generate(prompt, 5).asnumpy()
    toks = prompt.copy()
    for _ in range(5):
        nxt = net(mx.np.array(toks)).asnumpy()[:, -1].argmax(-1)
        toks = onp.concatenate([toks, nxt[:, None].astype("int32")], 1)
    onp.testing.assert_array_equal(got, toks[:, 4:])


@pytest.mark.slow    # tier-1 time budget (r8)
def test_generate_sampling_and_eos():
    import numpy as onp
    net = _tiny_gpt()
    prompt = onp.array([[1, 2, 3]], dtype="int32")
    a = net.generate(prompt, 6, method="sample", temperature=0.8,
                     seed=7).asnumpy()
    b = net.generate(prompt, 6, method="sample", temperature=0.8,
                     seed=7).asnumpy()
    c = net.generate(prompt, 6, method="sample", temperature=0.8,
                     seed=8).asnumpy()
    onp.testing.assert_array_equal(a, b)       # same seed -> same draw
    assert a.shape == (1, 6) and c.shape == (1, 6)

    # top_k=1 is greedy
    tk = net.generate(prompt, 6, method="top_k", top_k=1,
                      seed=3).asnumpy()
    gd = net.generate(prompt, 6).asnumpy()
    onp.testing.assert_array_equal(tk, gd)

    # eos: once emitted, the tail is all eos
    eos = int(gd[0, 1])                        # force a hit at step 2
    e = net.generate(prompt, 6, eos_token=eos).asnumpy()
    hit = onp.argmax(e[0] == eos)
    assert (e[0, hit:] == eos).all()


def test_generate_validates_args():
    import numpy as onp
    import pytest
    import mxnet_tpu as mx
    net = _tiny_gpt(max_len=16)
    with pytest.raises(mx.MXNetError, match="max_length"):
        net.generate(onp.zeros((1, 10), "int32"), 10)
    with pytest.raises(mx.MXNetError, match=">= 1"):
        net.generate(onp.zeros((1, 4), "int32"), 0)
    with pytest.raises(mx.MXNetError, match="top_k"):
        net.generate(onp.zeros((1, 4), "int32"), 2, method="top_k",
                     top_k=0)
    # top_k beyond the vocab clamps instead of silently degrading
    out = net.generate(onp.zeros((1, 4), "int32"), 2, method="top_k",
                       top_k=10_000, seed=1)
    assert out.asnumpy().shape == (1, 2)


@pytest.mark.slow    # tier-1 time budget (r8)
def test_beam_search_beats_greedy_and_matches_at_k1():
    """beam_size=1 must equal greedy; larger beams never score worse
    than the greedy sequence under the same (alpha=1) normalization."""
    import numpy as onp
    import jax.numpy as jnp
    import jax
    net = _tiny_gpt()
    rng = onp.random.RandomState(3)
    prompt = rng.randint(0, 97, (2, 4)).astype("int32")

    seqs1, scores1 = net.beam_search(prompt, 6, beam_size=1)
    greedy = net.generate(prompt, 6).asnumpy()
    onp.testing.assert_array_equal(seqs1.asnumpy()[:, 0, :], greedy)

    seqs4, scores4 = net.beam_search(prompt, 6, beam_size=4)
    assert seqs4.asnumpy().shape == (2, 4, 6)
    s1, s4 = scores1.asnumpy(), scores4.asnumpy()
    assert (s4[:, 0] >= s1[:, 0] - 1e-4).all()   # beam >= greedy score
    # beams come back best-first
    assert (onp.diff(s4, axis=1) <= 1e-5).all()


@pytest.mark.slow    # tier-1 time budget (r8)
def test_beam_search_eos_normalization():
    import numpy as onp
    net = _tiny_gpt()
    prompt = onp.array([[5, 6]], dtype="int32")
    g = net.generate(prompt, 5).asnumpy()
    eos = int(g[0, 0])                         # eos on the first step
    seqs, scores = net.beam_search(prompt, 5, beam_size=3,
                                   eos_token=eos)
    s = seqs.asnumpy()
    # any beam that emitted eos is eos-padded afterwards
    for b in range(3):
        row = s[0, b]
        if (row == eos).any():
            hit = onp.argmax(row == eos)
            assert (row[hit:] == eos).all()


@pytest.mark.slow    # tier-1 time budget (r8)
def test_generate_top_p_nucleus():
    """Nucleus sampling (r4): a tiny top_p is greedy (only the argmax
    survives the nucleus), top_p=1.0 equals plain sampling at the same
    seed, draws are seed-deterministic, and bounds are validated."""
    import numpy as onp
    import pytest
    net = _tiny_gpt()
    prompt = onp.array([[1, 2, 3]], dtype="int32")

    # nucleus collapsing to one token == greedy
    tp = net.generate(prompt, 6, method="top_p", top_p=1e-6,
                      seed=5).asnumpy()
    gd = net.generate(prompt, 6).asnumpy()
    onp.testing.assert_array_equal(tp, gd)

    # top_p=1.0 keeps the whole vocab == unrestricted sampling
    a = net.generate(prompt, 6, method="top_p", top_p=1.0,
                     temperature=0.8, seed=7).asnumpy()
    b = net.generate(prompt, 6, method="sample", temperature=0.8,
                     seed=7).asnumpy()
    onp.testing.assert_array_equal(a, b)

    # deterministic per seed, varies across seeds
    c = net.generate(prompt, 6, method="top_p", top_p=0.9,
                     temperature=1.2, seed=11).asnumpy()
    d = net.generate(prompt, 6, method="top_p", top_p=0.9,
                     temperature=1.2, seed=11).asnumpy()
    onp.testing.assert_array_equal(c, d)

    with pytest.raises(mx.MXNetError, match="top_p"):
        net.generate(prompt, 2, method="top_p", top_p=0.0)
    with pytest.raises(mx.MXNetError, match="top_p"):
        net.generate(prompt, 2, method="top_p", top_p=1.5)
