"""GPT decoder-only LM (beyond-reference model family; causal attention
through the same transformer op stack as BERT)."""
import jax
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo.gpt import GPTModel, get_gpt
from mxnet_tpu.parallel import (SPMDTrainer, make_mesh,
                                DEFAULT_TRANSFORMER_RULES)
from mxnet_tpu.test_utils import assert_almost_equal


def _tiny(dropout=0.0):
    mx.random.seed(0)
    net = GPTModel(vocab_size=101, num_layers=2, units=32, hidden_size=64,
                   num_heads=4, max_length=16, dropout=dropout)
    net.initialize()
    return net


def test_gpt_causality():
    net = _tiny()
    x = mx.np.array(onp.random.RandomState(0)
                    .randint(0, 101, (2, 10)).astype("int32"))
    out = net(x)
    assert out.shape == (2, 10, 101)
    x2 = onp.asarray(x.asnumpy()).copy()
    x2[:, -1] = (x2[:, -1] + 1) % 101
    out2 = net(mx.np.array(x2.astype("int32")))
    # past logits unchanged, final position changed
    assert_almost_equal(out.asnumpy()[:, :-1], out2.asnumpy()[:, :-1],
                        rtol=1e-5, atol=1e-6)
    assert not onp.allclose(out.asnumpy()[:, -1], out2.asnumpy()[:, -1])


def test_gpt_hybridize_equivalence():
    net = _tiny()
    x = mx.np.array(onp.random.RandomState(1)
                    .randint(0, 101, (2, 8)).astype("int32"))
    eager = net(x)
    net.hybridize()
    compiled = net(x)
    assert_almost_equal(eager, compiled, rtol=1e-5, atol=1e-6)


def test_gpt_spmd_tp_training_converges():
    net = _tiny()
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    tr = SPMDTrainer(net, loss_fn, optimizer="adamw",
                     optimizer_params={"learning_rate": 1e-3},
                     mesh=mesh, rules=DEFAULT_TRANSFORMER_RULES)
    rng = onp.random.RandomState(2)
    x = mx.np.array(rng.randint(0, 101, (4, 10)).astype("int32"))
    y = mx.np.array(rng.randint(0, 101, (4, 10)).astype("int32"))
    l0 = float(tr.step(x, y).asnumpy())
    for _ in range(5):
        l = float(tr.step(x, y).asnumpy())
    assert l < l0


def test_gpt_specs_and_max_length_guard():
    import pytest
    with pytest.raises(ValueError):
        get_gpt("gpt_unknown")
    net = _tiny()
    with pytest.raises(mx.MXNetError):
        net(mx.np.zeros((1, 32), dtype="int32"))  # > max_length 16
