"""Data pipeline (reference analogs: test_io.py, test_recordio.py,
test_gluon_data.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.gluon.data.vision import SyntheticImageDataset, transforms
from mxnet_tpu.test_utils import assert_almost_equal


def test_array_dataset_and_transform():
    X = onp.arange(20, dtype="float32").reshape(10, 2)
    Y = onp.arange(10, dtype="int32")
    ds = gdata.ArrayDataset(X, Y)
    assert len(ds) == 10
    x0, y0 = ds[3]
    assert (x0 == X[3]).all() and y0 == 3
    ds2 = ds.transform(lambda x, y: (x * 2, y))
    assert (ds2[1][0] == X[1] * 2).all()
    ds3 = ds.transform_first(lambda x: x + 1)
    assert (ds3[0][0] == X[0] + 1).all()
    assert len(ds.take(4)) == 4
    assert len(ds.shard(3, 0)) == 4


def test_samplers():
    s = gdata.SequentialSampler(5)
    assert list(s) == [0, 1, 2, 3, 4]
    r = list(gdata.RandomSampler(100))
    assert sorted(r) == list(range(100))
    b = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "keep")
    batches = list(b)
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    assert len(b) == 3
    b2 = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "discard")
    assert len(list(b2)) == 2
    b3 = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "rollover")
    assert len(list(b3)) == 2
    assert len(list(b3)) == 2  # rollover carries remainder


def test_dataloader_basic():
    X = onp.random.rand(17, 3).astype("float32")
    Y = onp.arange(17, dtype="int32")
    loader = gdata.DataLoader(gdata.ArrayDataset(X, Y), batch_size=5)
    batches = list(loader)
    assert len(batches) == 4
    xb, yb = batches[0]
    assert xb.shape == (5, 3) and yb.shape == (5,)
    assert_almost_equal(xb, X[:5])
    assert batches[-1][0].shape == (2, 3)
    assert len(loader) == 4


def test_dataloader_shuffle_covers_all():
    X = onp.arange(12, dtype="float32")
    loader = gdata.DataLoader(gdata.ArrayDataset(X), batch_size=4,
                              shuffle=True)
    seen = onp.concatenate([b.asnumpy() for b in loader])
    assert sorted(seen.tolist()) == list(range(12))


def test_dataloader_multiworker():
    X = onp.arange(40, dtype="float32").reshape(20, 2)
    loader = gdata.DataLoader(gdata.ArrayDataset(X), batch_size=4,
                              num_workers=2)
    batches = list(loader)
    assert len(batches) == 5
    got = onp.concatenate([b.asnumpy() for b in batches])
    assert_almost_equal(got, X)
    # second epoch works with the persistent pool
    assert len(list(loader)) == 5


def test_synthetic_dataset_and_transforms():
    ds = SyntheticImageDataset(length=8, shape=(32, 32, 3), num_classes=10)
    img, label = ds[0]
    assert img.shape == (32, 32, 3) and img.dtype == onp.uint8
    assert 0 <= label < 10
    img2, label2 = ds[0]
    assert (img.asnumpy() == img2.asnumpy()).all()  # deterministic

    t = transforms.Compose([
        transforms.Resize(16), transforms.ToTensor(),
        transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))])
    out = t(img)
    assert out.shape == (3, 16, 16)
    assert out.asnumpy().min() >= -1.0 and out.asnumpy().max() <= 1.0


def test_transform_crops_flips():
    x = mx.np.array(onp.random.randint(0, 255, (40, 60, 3), dtype=onp.uint8))
    assert transforms.CenterCrop((20, 10))(x).shape == (10, 20, 3)
    assert transforms.RandomResizedCrop(24)(x).shape == (24, 24, 3)
    assert transforms.RandomCrop(16)(x).shape == (16, 16, 3)
    f = transforms.RandomFlipLeftRight(p=1.0)(x)
    assert (f.asnumpy() == x.asnumpy()[:, ::-1]).all()
    j = transforms.RandomColorJitter(0.3, 0.3, 0.3)(x)
    assert j.shape == x.shape


def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(f"record-{i}".encode())
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert r.read() == f"record-{i}".encode()
    assert r.read() is None
    r.close()


def test_indexed_recordio_and_pack_img(tmp_path):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "img.rec")
    idx = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    imgs = []
    for i in range(3):
        img = onp.random.randint(0, 255, (8, 8, 3), dtype=onp.uint8)
        imgs.append(img)
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack_img(header, img, img_fmt=".png"))
    w.close()

    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.keys == [0, 1, 2]
    h, img = recordio.unpack_img(r.read_idx(1))
    assert h.label == 1.0
    assert (img == imgs[1]).all()  # png is lossless
    r.close()

    # ImageRecordDataset reads it
    ds = mx.gluon.data.vision.ImageRecordDataset(rec)
    data, label = ds[2]
    assert data.shape == (8, 8, 3) and label == 2.0


def test_recordio_pack_multilabel():
    from mxnet_tpu import recordio
    header = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    buf = recordio.pack(header, b"payload")
    h, s = recordio.unpack(buf)
    assert h.flag == 3 and list(h.label) == [1, 2, 3] and h.id == 7
    assert s == b"payload"


def test_ndarray_iter():
    X = onp.random.rand(10, 4).astype("float32")
    Y = onp.arange(10, dtype="float32")
    it = mx.io.NDArrayIter(X, Y, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4
    it2 = mx.io.NDArrayIter(X, Y, batch_size=3, last_batch_handle="discard")
    assert len(list(it2)) == 3
    desc = it.provide_data[0]
    assert desc.shape == (3, 4)


def test_model_zoo_constructs():
    from mxnet_tpu.gluon.model_zoo import vision as zoo
    x = mx.np.ones((1, 3, 32, 32))
    net = zoo.get_model("resnet18_v1", classes=10)
    net.initialize()
    assert net(x).shape == (1, 10)
    net2 = zoo.resnet18_v2(classes=10)
    net2.initialize()
    assert net2(x).shape == (1, 10)
    with pytest.raises(mx.MXNetError):
        zoo.get_model("resnet13_v9")


@pytest.mark.slow    # tier-1 time budget (r8)
def test_mobilenet_squeezenet_densenet_construct():
    from mxnet_tpu.gluon.model_zoo import vision as zoo
    x = mx.np.ones((1, 3, 64, 64))
    for name in ("mobilenet0.25", "mobilenetv2_0.25", "squeezenet1.1"):
        net = zoo.get_model(name, classes=10)
        net.initialize()
        assert net(x).shape == (1, 10), name


def _pad_batchify(batch):
    """Module-level: custom batchify fns ship to spawned workers by
    pickle (a closure would only survive the opt-in fork mode)."""
    L = max(len(b) for b in batch)
    out = onp.zeros((len(batch), L), dtype="float32")
    for i, b in enumerate(batch):
        out[i, :len(b)] = onp.asarray(b)
    return mx.np.array(out)


@pytest.mark.host_mesh   # spawns DataLoader worker processes — skipped under the chip ctx-flip
def test_dataloader_custom_batchify_multiworker():
    """Custom batchify_fn must run in workers too (pads ragged samples)."""
    from mxnet_tpu.gluon.data import SimpleDataset
    samples = [onp.ones(n, dtype="float32") * n for n in (1, 2, 3, 4)]

    for workers in (0, 2):
        loader = gdata.DataLoader(SimpleDataset(samples), batch_size=2,
                                  batchify_fn=_pad_batchify,
                                  num_workers=workers)
        batches = list(loader)
        assert batches[0].shape == (2, 2), workers
        assert batches[1].shape == (2, 4), workers


class _JaxTouchingDataset(gdata.Dataset):
    """Returns jax-backed NDArrays from __getitem__ — the shape of every
    real image dataset (ImageRecordDataset), and exactly the case whose
    fork-after-jax deadlock VERDICT r5 weak 1 reproduced.  Module-level
    so it pickles into spawned workers."""

    def __init__(self, n: int) -> None:
        self._n = n

    def __getitem__(self, idx: int):
        img = onp.full((4, 4), float(idx), dtype="float32")
        return mx.np.array(img), idx   # device-backed NDArray

    def __len__(self) -> int:
        return self._n


def _jax_center2(img, label):
    """Transform that TOUCHES jax in the worker (asnumpy syncs)."""
    a = img.asnumpy()
    return onp.ascontiguousarray(a[1:3, 1:3]), label


@pytest.mark.host_mesh   # spawns DataLoader worker processes — skipped under the chip ctx-flip
def test_dataloader_workers_jax_touching_dataset():
    """Regression (VERDICT r5 weak 1): multi-worker loading over a
    dataset whose __getitem__/transform touch jax must COMPLETE — the
    old fork-context pool deadlocked here (benchmark/decode_scaling.py
    at workers>=1) because jax's dispatch threads don't survive fork.
    Workers spawn by default now; this pins both completion and
    numerical equality with the in-process path."""
    # the parent's jax runtime must be live before the pool exists —
    # that's the deadlock precondition the spawn context removes
    mx.np.ones((2, 2)).asnumpy()
    ds = _JaxTouchingDataset(12).transform(_jax_center2)
    ref = [(xb.asnumpy(), yb.asnumpy()) for xb, yb in
           gdata.DataLoader(ds, batch_size=4, num_workers=0)]
    loader = gdata.DataLoader(ds, batch_size=4, num_workers=2)
    for epoch in range(2):     # persistent pool serves a second epoch
        got = [(xb.asnumpy(), yb.asnumpy()) for xb, yb in loader]
        assert len(got) == len(ref) == 3
        for (gx, gy), (rx, ry) in zip(got, ref):
            assert_almost_equal(gx, rx)
            assert_almost_equal(gy, ry)


def test_ndarray_iter_roll_over():
    X = onp.arange(10, dtype="float32")
    it = mx.io.NDArrayIter(X, None, batch_size=3,
                           last_batch_handle="roll_over")
    e1 = [b.data[0].asnumpy() for b in it]
    assert len(e1) == 3  # only full batches; 1 sample carried
    it.reset()
    e2 = [b.data[0].asnumpy() for b in it]
    assert len(e2) == 3
    # epoch 2 starts where epoch 1 left off (sample 9 first)
    assert e2[0][0] == 9.0
    # across both epochs every sample is seen exactly... (9+9=18 of 20)
    seen = onp.concatenate(e1 + e2)
    assert len(seen) == 18


def test_prefetching_iter_reset():
    X = onp.arange(8, dtype="float32")
    inner = mx.io.NDArrayIter(X, None, batch_size=4)
    it = mx.io.PrefetchingIter(inner)
    assert len(list(it)) == 2
    it.reset()
    assert len(list(it)) == 2  # second epoch does not hang


def test_transform_first_bare_sample():
    from mxnet_tpu.gluon.data import SimpleDataset
    ds = SimpleDataset([onp.ones(3), onp.zeros(3)])
    out = ds.transform_first(lambda x: x + 1)[0]
    assert not isinstance(out, tuple)
    assert (out == 2).all()


def test_random_crop_small_image_upscales():
    x = mx.np.array(onp.random.randint(0, 255, (28, 28, 3), dtype=onp.uint8))
    out = transforms.RandomCrop(32)(x)
    assert out.shape == (32, 32, 3)


def test_random_hue():
    x = mx.np.array(onp.random.randint(0, 255, (8, 8, 3), dtype=onp.uint8))
    out = transforms.RandomHue(0.4)(x)
    assert out.shape == x.shape
    jit = transforms.RandomColorJitter(hue=0.4)
    assert len(jit._ts) == 1


@pytest.mark.slow    # tier-1 time budget (r8): zoo construction stays tier-1 via test_model_zoo_constructs
def test_mobilenet_v3_constructs():
    from mxnet_tpu.gluon.model_zoo import vision as zoo
    x = mx.np.ones((1, 3, 64, 64))
    for name in ("mobilenetv3_small", "mobilenetv3_large"):
        net = zoo.get_model(name, classes=10)
        net.initialize()
        assert net(x).shape == (1, 10), name


@pytest.mark.slow    # tier-1 time budget (r8)
def test_inception_v3_constructs():
    from mxnet_tpu.gluon.model_zoo import vision as zoo
    net = zoo.get_model("inceptionv3", classes=10)
    net.initialize()
    x = mx.np.ones((1, 3, 299, 299))
    assert net(x).shape == (1, 10)


def test_inception_v3_hybridize_equivalence():
    from mxnet_tpu.gluon.model_zoo import vision as zoo
    import numpy as _onp
    net = zoo.get_model("inceptionv3", classes=4)
    net.initialize()
    x = mx.np.array(_onp.random.RandomState(0).uniform(
        -1, 1, (1, 3, 299, 299)).astype("float32"))
    ref = net(x).asnumpy()
    net.hybridize()
    out = net(x).asnumpy()
    _onp.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_recordio_raw_format_roundtrip(tmp_path):
    """r4 '.raw' packing: frombuffer decode (the high-throughput option
    when JPEG decode, not the wire, is the bottleneck), byte-exact
    roundtrip, and grayscale conversion matching the PIL path's ITU-R
    601 luma so pack format never changes pixel values."""
    import numpy as onp
    from PIL import Image
    from mxnet_tpu import recordio

    rs = onp.random.RandomState(0)
    img = rs.randint(0, 256, (24, 20, 3)).astype("uint8")
    header = recordio.IRHeader(0, 7.0, 3, 0)
    packed = recordio.pack_img(header, img, img_fmt=".raw")
    h2, back = recordio.unpack_img(packed)
    assert float(h2.label) == 7.0
    onp.testing.assert_array_equal(back, img)

    _, gray = recordio.unpack_img(packed, flag=0)
    ref = onp.asarray(Image.fromarray(img).convert("L"))
    assert int(onp.abs(gray[:, :, 0].astype(int)
                       - ref.astype(int)).max()) <= 1

    # grayscale source replicates to RGB on color decode
    g1 = rs.randint(0, 256, (8, 8, 1)).astype("uint8")
    p1 = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), g1,
                           img_fmt=".raw")
    _, rgb = recordio.unpack_img(p1, flag=1)
    assert rgb.shape == (8, 8, 3)
    onp.testing.assert_array_equal(rgb[:, :, 0], g1[:, :, 0])

    # file roundtrip through the indexed record container
    rec = recordio.MXIndexedRecordIO(str(tmp_path / "a.idx"),
                                     str(tmp_path / "a.rec"), "w")
    rec.write_idx(0, packed)
    rec.close()
    rd = recordio.MXIndexedRecordIO(str(tmp_path / "a.idx"),
                                    str(tmp_path / "a.rec"), "r")
    _, again = recordio.unpack_img(rd.read_idx(0))
    onp.testing.assert_array_equal(again, img)
