"""Golden checkpoint-format fixtures (VERDICT r4 missing 4 / directive 9).

The reference pins its serialization formats with nightly model-compat
tests that load checkpoints saved by PREVIOUS releases (SURVEY.md
section 4).  These fixtures were generated at r5 (2026-08-01) and are
committed; every later round must keep loading them byte-identically —
a format drift fails here, not silently in a user's saved model.
Regenerate ONLY with a deliberate, documented format break:
`python tests/gen_golden_fixtures.py` (see that script's header).
"""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx

FIX = os.path.join(os.path.dirname(__file__), "fixtures")


def _fresh_net():
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(8, in_units=4, activation="relu"),
            mx.gluon.nn.Dense(3, in_units=8))
    return net


def test_golden_params_load_exact():
    """.params from r5 loads and reproduces the recorded forward output
    to last-ulp tolerance.  The fixture was recorded with per-op
    dispatch, so the forward pins MXNET_BULK_MAX_OPS=1 (fused bulked
    segments may FMA-contract — docs/performance.md numerics caveat).

    Tolerance rationale (r6): bit-equality additionally pinned the XLA
    CPU backend's instruction selection, which drifts across rig/XLA
    updates (observed: 1.2e-10 abs / 1.6e-5 rel on near-zero logits —
    last-ulp FMA/reassociation differences in the dot kernels, failing
    identically on the seed).  The FORMAT drift this test exists to
    catch (key loss, dtype/shape change, de/serialization corruption)
    shows up orders of magnitude larger or as a load error, so a tight
    rtol keeps the guard without pinning codegen: params themselves
    must still load EXACTLY (asserted bit-for-bit below)."""
    from mxnet_tpu import engine
    from mxnet_tpu.ndarray_io import load_params
    net = _fresh_net()
    params_file = os.path.join(FIX, "golden_r5.params")
    # format guard proper: the deserialized tensors are bit-exact and
    # complete (this is what a serialization break would corrupt)
    raw = load_params(params_file)
    assert sorted(raw) == ["0.bias", "0.weight", "1.bias", "1.weight"]
    assert all(a._data.dtype == onp.float32 for a in raw.values())
    net.load_parameters(params_file)
    for name, arr in raw.items():
        got_p = dict(net.collect_params())[name].data().asnumpy()
        onp.testing.assert_array_equal(got_p, arr.asnumpy())
    x = mx.np.array(onp.arange(8, dtype="float32").reshape(2, 4) / 10.0)
    with engine.bulk(1):
        got = net(x).asnumpy()
    want = onp.load(os.path.join(FIX, "golden_r5_output.npy"))
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-8)


def test_golden_export_symbol_json_loads():
    """export()'s -symbol.json + -NNNN.params pair from r5 round-trips
    through SymbolBlock.imports at output parity with load_parameters."""
    sym_json = os.path.join(FIX, "golden_r5_export-symbol.json")
    params = os.path.join(FIX, "golden_r5_export-0007.params")
    with open(sym_json) as f:
        sym = json.load(f)
    # this framework's deploy-graph schema — the keys ARE the pinned
    # format (format_version bumps on deliberate breaks)
    assert sym["format_version"] == 1 and "deploy_graph" in sym
    from mxnet_tpu.gluon import SymbolBlock
    net = SymbolBlock.imports(sym_json, ["data"], params)
    x = mx.np.array(onp.arange(8, dtype="float32").reshape(2, 4) / 10.0)
    got = net(x).asnumpy()
    want = onp.load(os.path.join(FIX, "golden_r5_output.npy"))
    onp.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_golden_trainer_states_load():
    """Trainer momentum states from r5 load; the restored updater holds
    per-param state of the right shapes and nonzero momentum (the
    fixture was saved after 3 sgd-momentum steps)."""
    net = _fresh_net()
    net.load_parameters(os.path.join(FIX, "golden_r5.params"))
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9})
    tr.load_states(os.path.join(FIX, "golden_r5.states"))
    # run one step to prove the restored state drives an update
    lf = mx.gluon.loss.L2Loss()
    x = mx.np.array(onp.arange(8, dtype="float32").reshape(2, 4) / 10.0)
    t = mx.np.array(onp.ones((2, 3), dtype="float32"))
    with mx.autograd.record():
        l = lf(net(x), t).mean()
    l.backward()
    tr.step(1)
    assert onp.isfinite(float(l.asnumpy()))
