"""mx.image + nd.image op tests (reference: tests/python/unittest/test_image.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import image as ndimg


def _png_bytes(h=32, w=40, seed=0):
    from PIL import Image
    import io
    rng = onp.random.RandomState(seed)
    arr = rng.randint(0, 255, (h, w, 3), dtype=onp.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return arr, buf.getvalue()


def test_imdecode_roundtrip():
    arr, data = _png_bytes()
    img = mx.image.imdecode(data)
    assert img.shape == arr.shape
    assert onp.array_equal(img.asnumpy(), arr)


def test_imdecode_gray_and_bgr():
    arr, data = _png_bytes()
    gray = mx.image.imdecode(data, flag=0)
    assert gray.shape == (32, 40, 1)
    bgr = mx.image.imdecode(data, to_rgb=False)
    assert onp.array_equal(bgr.asnumpy()[:, :, ::-1], arr)


def test_to_tensor_normalize():
    arr = onp.random.randint(0, 255, (8, 10, 3)).astype(onp.uint8)
    t = ndimg.to_tensor(mx.nd.array(arr, dtype="uint8"))
    assert t.shape == (3, 8, 10)
    assert t.dtype == onp.float32
    onp.testing.assert_allclose(t.asnumpy(),
                                arr.transpose(2, 0, 1) / 255.0, rtol=1e-6)
    n = ndimg.normalize(t, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    onp.testing.assert_allclose(n.asnumpy(), (t.asnumpy() - 0.5) / 0.2,
                                rtol=1e-5)


def test_resize_crop():
    arr = onp.random.randint(0, 255, (32, 48, 3)).astype(onp.uint8)
    img = mx.nd.array(arr, dtype="uint8")
    r = ndimg.resize(img, (24, 16))
    assert r.shape == (16, 24, 3)
    rs = mx.image.resize_short(img, 16)
    assert min(rs.shape[:2]) == 16
    c = ndimg.crop(img, 4, 2, 10, 20)
    assert c.shape == (20, 10, 3)
    assert onp.array_equal(c.asnumpy(), arr[2:22, 4:14])
    cc, rect = mx.image.center_crop(img, (16, 16))
    assert cc.shape == (16, 16, 3)


def test_flips():
    arr = onp.arange(2 * 3 * 3).reshape(2, 3, 3).astype(onp.uint8)
    img = mx.nd.array(arr, dtype="uint8")
    lr = ndimg.flip_left_right(img)
    assert onp.array_equal(lr.asnumpy(), arr[:, ::-1])
    tb = ndimg.flip_top_bottom(img)
    assert onp.array_equal(tb.asnumpy(), arr[::-1])


def test_color_jitter_ops_bounded():
    arr = onp.random.randint(0, 255, (8, 8, 3)).astype(onp.uint8)
    img = mx.nd.array(arr, dtype="uint8")
    for fn in [lambda: ndimg.random_brightness(img, 0.7, 1.3),
               lambda: ndimg.random_contrast(img, 0.7, 1.3),
               lambda: ndimg.random_saturation(img, 0.7, 1.3),
               lambda: ndimg.random_hue(img, -0.1, 0.1),
               lambda: ndimg.random_lighting(img, 0.05),
               lambda: ndimg.random_color_jitter(img, 0.3, 0.3, 0.3, 0.1)]:
        out = fn()
        assert out.shape == img.shape
        a = out.asnumpy()
        assert a.min() >= 0 and a.max() <= 255


def test_augmenter_pipeline():
    arr = onp.random.randint(0, 255, (50, 60, 3)).astype(onp.uint8)
    img = mx.nd.array(arr, dtype="uint8")
    augs = mx.image.CreateAugmenter((3, 24, 24), resize=30, rand_crop=True,
                                    rand_mirror=True, mean=True, std=True,
                                    brightness=0.1)
    for a in augs:
        img = a(img)
    assert img.shape == (24, 24, 3)
    assert img.dtype == onp.float32


def test_image_iter_imglist(tmp_path):
    arrs = [onp.random.randint(0, 255, (40, 40, 3)).astype(onp.uint8)
            for _ in range(7)]
    imglist = [(float(i), mx.nd.array(a, dtype="uint8"))
               for i, a in enumerate(arrs)]
    it = mx.image.ImageIter(batch_size=3, data_shape=(3, 24, 24),
                            imglist=imglist, aug_list=[
                                mx.image.CenterCropAug((24, 24)),
                                mx.image.CastAug()])
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (3, 3, 24, 24)
    assert batches[-1].pad == 2
    it.reset()
    assert next(it).data[0].shape == (3, 3, 24, 24)


def test_image_iter_recordio(tmp_path):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(5):
        arr, data = _png_bytes(40, 40, seed=i)
        hdr = recordio.IRHeader(0, float(i), i, 0)
        rec.write_idx(i, recordio.pack(hdr, data))
    rec.close()

    it = mx.image.ImageIter(batch_size=2, data_shape=(3, 32, 32),
                            path_imgrec=rec_path, path_imgidx=idx_path,
                            shuffle=False, last_batch_handle="discard",
                            aug_list=[mx.image.CenterCropAug((32, 32)),
                                      mx.image.CastAug()])
    batches = list(it)
    assert len(batches) == 2
    labels = onp.concatenate([b.label[0].asnumpy() for b in batches])
    assert onp.array_equal(labels, onp.array([0.0, 1.0, 2.0, 3.0]))


def test_imrotate():
    arr = onp.random.randint(0, 255, (20, 20, 3)).astype(onp.uint8)
    out = mx.image.imrotate(mx.nd.array(arr, dtype="uint8"), 90)
    assert out.shape == arr.shape
