"""Worker script for the multi-process distributed test (launched by
tools/launch.py — the analog of tests/nightly/dist_sync_kvstore.py's
worker). Each process joins the jax.distributed job, trains a tiny net
data-parallel over the global 2-process mesh, and writes its result."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")


def kvstore_main(out_dir: str, expect_nw: int = 2) -> None:
    """Reference dist_sync contract (tests/nightly/dist_sync_kvstore.py):
    pulled == sum over workers of pushed, multi-key pushes fuse into
    bucket collectives, and gluon.Trainer(kvstore='ici') keeps parameters
    bit-identical across processes WITHOUT SPMDTrainer."""
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kvs
    kvs._maybe_init_distributed()
    import numpy as onp

    rank = jax.process_index()
    kv = kvs.create("dist_sync")
    nw = kv.num_workers
    assert nw == expect_nw, (nw, expect_nw)

    # raw push/pull invariant with rank-dependent values
    base = onp.arange(12, dtype="float32").reshape(3, 4)
    kv.init(0, mx.np.array(onp.zeros((3, 4), "float32")))
    kv.push(0, mx.np.array(base * (rank + 1)))
    pulled = kv.pull(0).asnumpy()
    expect = base * sum(r + 1 for r in range(nw))
    assert onp.allclose(pulled, expect), (pulled, expect)

    # bucketed multi-key push: 10 small keys must cross the process
    # boundary as ONE fused collective (kvstore_dist.h BIGARRAY_BOUND
    # aggregation analog), each key still summing over workers
    keys = list(range(10, 20))
    kv.init(keys, [mx.np.array(onp.zeros((3, 4), "float32"))
                   for _ in keys])
    before = kv.reduce_collectives
    kv.push(keys, [mx.np.array(base + k * (rank + 1)) for k in keys])
    fused = kv.reduce_collectives - before
    assert fused == 1, f"expected 1 fused collective, used {fused}"
    for k in keys:
        got = kv.pull(k).asnumpy()
        want = base * nw + k * sum(r + 1 for r in range(nw))
        assert onp.allclose(got, want), (k, got, want)

    # BIGARRAY_BOUND honored: arrays at/over the bound reduce alone
    os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "8"
    try:
        kv.init([30, 31], [mx.np.array(onp.zeros((3, 4), "float32"))
                           for _ in range(2)])
        before = kv.reduce_collectives
        kv.push([30, 31], [mx.np.array(base * (rank + 1))
                           for _ in range(2)])
        solo = kv.reduce_collectives - before
        assert solo == 2, f"12-elem arrays over bound=8 must go solo: {solo}"
    finally:
        del os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"]

    # plain gluon.Trainer over the kvstore: per-rank batches differ, the
    # summed-grad update must keep params bit-identical across ranks
    mx.random.seed(0)
    net = mx.gluon.nn.Dense(2, in_units=3)
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1}, kvstore="ici")
    loss_fn = mx.gluon.loss.L2Loss()
    rng = onp.random.RandomState(100 + rank)
    for _ in range(3):
        x = mx.np.array(rng.uniform(-1, 1, (2, 3)).astype("float32"))
        y = mx.np.array(rng.uniform(-1, 1, (2, 2)).astype("float32"))
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(2 * nw)
    w = net.weight.data().asnumpy().ravel()
    b = net.bias.data().asnumpy().ravel()
    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write(" ".join(f"{v:.8f}" for v in pulled.ravel()) + "\n")
        f.write(" ".join(f"{v:.8f}" for v in list(w) + list(b)) + "\n")


def async_main(out_dir: str) -> None:
    """kvstore='dist_async' under the launcher (-n 2 -s 1): workers push
    gradients at their own pace, the server applies sgd immediately per
    push (Hogwild), weights converge on a shared quadratic despite
    staleness. Reference: kvstore_dist_server.h async DataHandleDefault.
    No jax.distributed here — async workers are independent processes."""
    import time
    import numpy as onp
    import mxnet_tpu as mx

    rank = int(os.environ["DMLC_WORKER_ID"])
    kv = mx.kvstore.create("dist_async")
    assert kv.num_workers == 2
    target = onp.arange(6, dtype="float32").reshape(2, 3)

    if rank == 0:
        kv.init("w", mx.np.zeros((2, 3)))
        kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
    kv.barrier()                 # everyone waits for init + optimizer

    rng = onp.random.RandomState(rank)
    for step in range(60):
        w = kv.pull("w").asnumpy()
        grad = (w - target) + rng.normal(0, 0.01, w.shape).astype("f4")
        kv.push("w", mx.np.array(grad))
        if rank == 1:
            time.sleep(0.002)    # a deliberately slower worker: async
            #                      must tolerate it (no sync barrier)
    kv.barrier()
    final = kv.pull("w").asnumpy()
    err = float(onp.abs(final - target).max())
    stats = kv.server_stats()[0]
    assert stats["pushes"] >= 120, stats   # both workers' pushes landed

    # gluon.Trainer over the async service: update_on_kvstore engages
    # automatically (weights + optimizer live server-side), each rank
    # trains at its own pace on its own data
    mx.random.seed(0)
    net = mx.gluon.nn.Dense(2, in_units=3)
    net.initialize()
    net(mx.np.zeros((1, 3)))
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.05}, kvstore="dist_async")
    loss_fn = mx.gluon.loss.L2Loss()
    rng2 = onp.random.RandomState(200 + rank)
    W = onp.ones((3, 2), "float32")
    first = last = None
    for _ in range(30):
        x = rng2.uniform(-1, 1, (4, 3)).astype("float32")
        y = x @ W
        with mx.autograd.record():
            loss = loss_fn(net(mx.np.array(x)), mx.np.array(y))
        loss.backward()
        tr.step(4)
        v = float(loss.asnumpy().mean())
        first = v if first is None else first
        last = v
    assert tr._update_on_kvstore, "async store must update on kvstore"
    assert last < first, (first, last)      # Hogwild still converges
    kv.barrier()
    # the server holds ONE weight copy: both ranks see identical params
    tr_w = tr._kvstore.pull(0).asnumpy()

    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write(f"{err:.6f}\n")
        f.write(f"{stats['pushes']}\n")
        f.write(" ".join(f"{v:.8f}" for v in tr_w.ravel()) + "\n")
    kv.barrier()
    if rank == 0:
        kv.stop_servers()


def async_sliced_main(out_dir: str) -> None:
    """PSKV big-array slicing over the async service (-n 2 -s 2 with
    MXNET_KVSTORE_BIGARRAY_BOUND=100): a 200-element key slices across
    BOTH servers, raw sum-mode push/pull reassembles correctly, and
    server-side sgd training over the slices converges with one shared
    model. Reference: kvstore_dist.h EncodeDefaultKey."""
    import numpy as onp
    import mxnet_tpu as mx

    rank = int(os.environ["DMLC_WORKER_ID"])
    kv = mx.kvstore.create("dist_async")
    assert kv.num_servers == 2
    big = onp.arange(200, dtype="float32").reshape(20, 10)

    if rank == 0:
        kv.init("big", mx.np.zeros((20, 10)))      # 200 >= bound: sliced
        kv.init("small", mx.np.zeros(4))           # whole-key assignment
    kv.barrier()
    # sum mode (no server optimizer): pulled == sum of pushes per slice
    kv.push("big", mx.np.array(big * (rank + 1)))
    kv.push("small", mx.np.array(onp.ones(4, "float32") * (rank + 1)))
    kv.barrier()
    got = kv.pull("big", out=mx.np.zeros((20, 10))).asnumpy()
    assert onp.allclose(got, big * 3), "sliced reassembly wrong"
    small = kv.pull("small", out=mx.np.zeros(4)).asnumpy()
    assert onp.allclose(small, 3.0), small
    # placement: the big key's slices live on BOTH servers, and neither
    # holds the whole array
    stats = kv.server_stats()
    for s in stats:
        from mxnet_tpu.kvstore_async import _SLICE_SEP
        assert any(k.startswith("big" + _SLICE_SEP)
                   for k in s["keys"]), stats
        assert "big" not in s["keys"], stats
    line0 = "sliced-ok"

    # server-side optimizer over sliced weights: Dense(20, in_units=10)
    # puts its 200-element weight above the bound
    mx.random.seed(0)
    net = mx.gluon.nn.Dense(20, in_units=10)
    net.initialize()
    net(mx.np.zeros((1, 10)))
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.2}, kvstore="dist_async")
    loss_fn = mx.gluon.loss.L2Loss()
    rng = onp.random.RandomState(300 + rank)
    W = onp.eye(10, 20, dtype="float32") * 0.5
    last = None
    for _ in range(40):
        x = rng.uniform(-1, 1, (8, 10)).astype("float32")
        y = x @ W
        with mx.autograd.record():
            loss = loss_fn(net(mx.np.array(x)), mx.np.array(y))
        loss.backward()
        tr.step(8)
        last = float(loss.asnumpy().mean())
    kv.barrier()
    w_final = tr._kvstore.pull(
        0, out=mx.np.zeros((20, 10))).asnumpy()

    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write(line0 + "\n")
        f.write(f"{last:.6f}\n")
        f.write(" ".join(f"{v:.8f}" for v in w_final.ravel()[:20]) + "\n")
    kv.barrier()
    if rank == 0:
        kv.stop_servers()


def async_compress_main(out_dir: str) -> None:
    """Wire compression on the async push path (-n 2 -s 1): 2-bit packs
    16x and is exact on code points with per-worker error feedback;
    blockwise int8 stays inside its quantization bound; the server
    decodes before applying. Sum mode isolates codec correctness."""
    import numpy as onp
    import mxnet_tpu as mx

    rank = int(os.environ["DMLC_WORKER_ID"])
    kv = mx.kvstore.create("dist_async")
    n = 1000
    base = onp.random.RandomState(7).normal(0, 1, n).astype("float32")
    tern = onp.sign(base).astype("float32")
    lines = []

    if rank == 0:
        for key in ("t", "i"):
            kv.init(key, mx.np.zeros(n))
        kv.init("r", mx.np.zeros(4))
    kv.barrier()

    # 2bit: 16x less wire, exact on {-thr, 0, +thr} inputs
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    before = kv.push_wire_bytes
    kv.push("t", mx.np.array(tern))
    assert kv.push_wire_bytes - before == (n + 3) // 4
    kv.barrier()
    got = kv.pull("t", out=mx.np.zeros(n)).asnumpy()
    assert onp.allclose(got, tern * 2, atol=1e-6), "2bit not exact"
    lines.append(" ".join(f"{v:.6f}" for v in got[:8]))

    # int8 blockwise: scales + padded codes on the wire, bounded error
    kv.set_gradient_compression({"type": "int8"})
    before = kv.push_wire_bytes
    kv.push("i", mx.np.array(base * (rank + 1)))
    nb = (n + 255) // 256
    assert kv.push_wire_bytes - before == 4 * nb + nb * 256
    kv.barrier()
    got = kv.pull("i", out=mx.np.zeros(n)).asnumpy()
    expect = base * 3
    bound = 3 * (onp.abs(base).max() / 127) + 1e-6
    assert onp.abs(got - expect).max() <= bound, "int8 out of bound"
    lines.append(" ".join(f"{v:.6f}" for v in got[:8]))

    # per-worker error feedback: 0.6 quantizes to 0, the residual makes
    # the second 0.6 cross the 1.0 threshold on each worker
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kv.push("r", mx.np.array(onp.full(4, 0.6, "float32")))
    kv.barrier()
    assert onp.allclose(
        kv.pull("r", out=mx.np.zeros(4)).asnumpy(), 0.0, atol=1e-6)
    kv.barrier()       # nobody's second push may overlap the pull above
    kv.push("r", mx.np.array(onp.full(4, 0.6, "float32")))
    kv.barrier()
    assert onp.allclose(
        kv.pull("r", out=mx.np.zeros(4)).asnumpy(), 2.0, atol=1e-6), \
        "per-worker error feedback lost"
    lines.append("residual-ok")

    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")
    kv.barrier()
    if rank == 0:
        kv.stop_servers()


def compress_main(out_dir: str) -> None:
    """Compressed ICI collectives (EQuARX-style, SURVEY 5.8): each codec
    reduces correctly across 2 processes, every rank gets the identical
    result, and the packed payloads genuinely shrink the wire bytes."""
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kvs
    kvs._maybe_init_distributed()
    import numpy as onp

    rank = jax.process_index()
    kv = kvs.create("ici")
    nw = kv.num_workers
    n = 1000
    base = onp.random.RandomState(7).normal(0, 1, n).astype("float32")
    lines = []

    def reduce_with(ctype, value, key):
        kv.set_gradient_compression({"type": ctype, "threshold": 1.0}
                                    if ctype == "2bit" else {"type": ctype})
        kv.init(key, mx.np.array(onp.zeros(n, "float32")))
        before = kv.reduce_wire_bytes
        kv.push(key, mx.np.array(value))
        wire = kv.reduce_wire_bytes - before
        return kv.pull(key).asnumpy(), wire

    # uncompressed: the wire reference point (4 bytes/elem)
    got, wire_full = reduce_with("none", base * (rank + 1), 0)
    expect = base * sum(r + 1 for r in range(nw))
    assert onp.allclose(got, expect, atol=1e-5), "none codec wrong"
    assert wire_full == 4 * n, wire_full
    lines.append(" ".join(f"{v:.6f}" for v in got[:8]))

    # bf16: half the wire, ~1e-2 relative accuracy
    got, wire = reduce_with("bf16", base * (rank + 1), 1)
    assert onp.allclose(got, expect, rtol=2e-2, atol=2e-2), "bf16 wrong"
    assert wire == 2 * n, wire
    lines.append(" ".join(f"{v:.6f}" for v in got[:8]))

    # int8: ~1/4 the wire (+ 1 f32 scale per 256-block), blockwise bound
    got, wire = reduce_with("int8", base * (rank + 1), 2)
    nb = (n + 255) // 256
    assert wire == nb * 256 + 4 * nb, wire
    bound = sum(r + 1 for r in range(nw)) * (
        onp.abs(base).max() / 127) + 1e-6
    assert onp.abs(got - expect).max() <= bound, "int8 out of bound"
    lines.append(" ".join(f"{v:.6f}" for v in got[:8]))

    # 2bit: 16x less wire; exact on code points; residual carries over
    tern = onp.sign(base).astype("float32")     # values in {-1, 0, +1}
    got, wire = reduce_with("2bit", tern, 3)
    assert wire == ((n + 3) // 4), wire
    assert onp.allclose(got, tern * nw, atol=1e-6), "2bit not exact"
    lines.append(" ".join(f"{v:.6f}" for v in got[:8]))
    # residual: 0.6 -> quantizes to 0, second push 0.6+0.6 crosses 1.0
    kv.init(4, mx.np.array(onp.zeros(4, "float32")))
    kv.push(4, mx.np.array(onp.full(4, 0.6, "float32")))
    assert onp.allclose(kv.pull(4).asnumpy(), 0.0, atol=1e-6)
    kv.push(4, mx.np.array(onp.full(4, 0.6, "float32")))
    assert onp.allclose(kv.pull(4).asnumpy(), 1.0 * nw, atol=1e-6), \
        "error feedback lost"
    lines.append("residual-ok")

    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")


def resilient_sum_main(out_dir: str) -> None:
    """Exactly-once proof for the durable PS (-n 2 -s 1 --supervise,
    MXNET_PS_SNAPSHOT_DIR + MXNET_PS_SNAPSHOT_EVERY=1, a seeded
    ps.server:kind=crash plan): each rank pushes 40 integer-valued
    vectors in sum mode while the server is crash-killed mid-stream and
    supervisor-restarted; integer-valued float adds are exact and
    commutative, so the final pulled value equals the exact sum IFF no
    push was lost (RPC replay across the restart) AND none was
    double-applied (snapshot-persisted per-worker seq dedupe)."""
    import time
    import numpy as onp
    # pure PS job: no collectives, so do NOT join jax.distributed (the
    # launcher exports the coordinator env to every worker).  A killed
    # rank must be a PS-layer event only — with a live coordination
    # service the surviving rank's process ABORTS at exit when its
    # peer vanished, cascading supervisor restarts through the job.
    os.environ["MXNET_NO_AUTO_DISTRIBUTED"] = "1"
    import mxnet_tpu as mx

    rank = int(os.environ["DMLC_WORKER_ID"])
    kv = mx.kvstore.create("dist_async")
    nw = kv.num_workers
    if rank == 0:
        kv.init("acc", mx.np.zeros(8))
    kv.barrier()
    for _ in range(40):
        kv.push("acc", mx.np.array(
            onp.full(8, float(rank + 1), "float32")))
        time.sleep(0.005)        # spread pushes: the crash lands mid-run
    kv.barrier()
    got = kv.pull("acc", out=mx.np.zeros(8)).asnumpy()
    expect = 40.0 * sum(r + 1 for r in range(nw))
    assert (got == expect).all(), (got, expect)   # EXACT, not allclose
    stats = kv.server_stats()[0]
    # applied-push accounting survives the restart (snapshot-restored
    # counter + exactly-once): 40 per worker, no more, no less
    assert stats["pushes"] == 40 * nw, stats
    assert stats["generation"] >= 2, stats        # it really restarted
    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write("sum-exact\n")
        f.write(f"{stats['generation']}\n")
    kv.barrier()
    if rank == 0:
        kv.stop_servers()


class _StepCounter:
    """Tiny worker-side resume state for the worker-kill leg: the PR-3
    CheckpointManager target (save_checkpoint/load_checkpoint)."""

    def __init__(self) -> None:
        self.step = 0

    def save_checkpoint(self, prefix: str) -> None:
        with open(prefix + ".step", "w") as f:
            f.write(str(self.step))

    def load_checkpoint(self, prefix: str) -> None:
        with open(prefix + ".step") as f:
            self.step = int(f.read())


def resilient_worker_kill_main(out_dir: str) -> None:
    """Worker-rank death under supervision (-n 2 -s 1 --supervise):
    rank 1 os._exits once at the top of step 12 (after checkpointing
    step 11), the supervisor restarts it, and the PR-3 auto-resume path
    (CheckpointManager restore of the step counter; weights live on
    the durable server) continues EXACTLY at step 12 — so each rank
    lands exactly 30 pushes and the Hogwild quadratic converges."""
    import numpy as onp
    # PS-only job: stay out of jax.distributed (see resilient_sum_main)
    os.environ["MXNET_NO_AUTO_DISTRIBUTED"] = "1"
    import mxnet_tpu as mx
    from mxnet_tpu.checkpoint import CheckpointManager

    rank = int(os.environ["DMLC_WORKER_ID"])
    kv = mx.kvstore.create("dist_async")
    target = onp.arange(8, dtype="float32") / 4.0
    mgr = CheckpointManager(os.path.join(out_dir, f"resume-r{rank}"),
                            max_to_keep=2)
    counter = _StepCounter()
    resumed = mgr.restore(counter)
    if resumed is None:
        if rank == 0:
            kv.init("w", mx.np.zeros(8))
            kv.set_optimizer(mx.optimizer.create("sgd",
                                                 learning_rate=0.2))
        kv.barrier()             # first incarnation only: init rendezvous
    kill_marker = os.path.join(out_dir, "killed-once")
    for step in range(counter.step, 30):
        if rank == 1 and step == 12 and not os.path.exists(kill_marker):
            with open(kill_marker, "w") as f:
                f.write("x")
            os._exit(17)         # SIGKILL analog: no cleanup, no ack
        w = kv.pull("w", out=mx.np.zeros(8)).asnumpy()
        kv.push("w", mx.np.array(w - target))       # grad of 1/2|w-t|^2
        counter.step = step + 1
        mgr.save(counter, step=counter.step)
    kv.barrier()
    final = kv.pull("w", out=mx.np.zeros(8)).asnumpy()
    err = float(onp.abs(final - target).max())
    stats = kv.server_stats()[0]
    assert stats["pushes"] == 60, stats   # exactly 30 per rank: the
    #                                       kill point is checkpointed,
    #                                       so no step reruns
    assert err < 0.1, (final, target)
    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write(f"{err:.6f}\n")
        f.write(f"{stats['pushes']}\n")
    kv.barrier()
    if rank == 0:
        kv.stop_servers()


def dptp_main(out_dir: str) -> None:
    """dp x tp over 2 processes x 2 local devices: one SPMD program
    shards the batch over dp AND the layer weights over tp across the
    process boundary (VERDICT r2 weak 9: no multi-host dp x tp test)."""
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kvs
    kvs._maybe_init_distributed()
    import numpy as onp
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import SPMDTrainer, make_mesh, PartitionRules

    rank = jax.process_index()
    assert jax.process_count() == 2 and jax.device_count() == 4

    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(8, in_units=3, activation="relu"),
            mx.gluon.nn.Dense(2, in_units=8))
    net.initialize()
    rules = PartitionRules([
        (r"0\.weight$", P("tp", None)),     # Megatron column split
        (r"0\.bias$", P("tp")),
        (r"1\.weight$", P(None, "tp")),     # row split back
    ])
    mesh = make_mesh({"dp": 2, "tp": 2})
    tr = SPMDTrainer(net, mx.gluon.loss.L2Loss(), optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     mesh=mesh, rules=rules, data_spec=P("dp"),
                     label_spec=P("dp"))
    rng = onp.random.RandomState(100 + rank)
    x = rng.uniform(-1, 1, (2, 3)).astype("float32")
    y = rng.uniform(-1, 1, (2, 2)).astype("float32")
    losses = [float(tr.step(x, y).asnumpy()) for _ in range(4)]
    from jax.experimental import multihost_utils
    w = multihost_utils.process_allgather(
        net[0].weight.data()._data, tiled=True)  # gathered full tp weight
    w = onp.asarray(w).ravel()
    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write(" ".join(f"{v:.8f}" for v in losses) + "\n")
        f.write(" ".join(f"{v:.8f}" for v in w[:16]) + "\n")


def main() -> None:
    out_dir = sys.argv[1]
    if len(sys.argv) > 2 and sys.argv[2] == "kvstore":
        kvstore_main(out_dir,
                     expect_nw=int(sys.argv[3]) if len(sys.argv) > 3 else 2)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "dptp":
        dptp_main(out_dir)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "compress":
        compress_main(out_dir)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "async":
        async_main(out_dir)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "async_sliced":
        async_sliced_main(out_dir)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "async_compress":
        async_compress_main(out_dir)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "resilient_sum":
        resilient_sum_main(out_dir)
        return
    if len(sys.argv) > 2 and sys.argv[2] == "resilient_worker_kill":
        resilient_worker_kill_main(out_dir)
        return
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore as kvs
    kvs._maybe_init_distributed()   # reads the launcher's env contract

    import numpy as onp
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import (SPMDTrainer, make_mesh,
                                    DATA_PARALLEL_RULES)

    rank = jax.process_index()
    nproc = jax.process_count()
    assert nproc == 2, nproc
    ndev = jax.device_count()               # nproc * devices-per-process

    mx.random.seed(0)
    net = mx.gluon.nn.Dense(2, in_units=3)
    net.initialize()
    mesh = make_mesh({"dp": ndev})
    tr = SPMDTrainer(net, mx.gluon.loss.L2Loss(), optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     mesh=mesh, rules=DATA_PARALLEL_RULES)

    # each process feeds its OWN local batch (the reference dist_sync
    # pattern) — _place globalizes it as this process's shard of the
    # global batch; same data per rank on every run so both processes
    # must agree bit-for-bit
    rng = onp.random.RandomState(100 + rank)
    x = rng.uniform(-1, 1, (2, 3)).astype("float32")
    y = rng.uniform(-1, 1, (2, 2)).astype("float32")

    losses = [float(tr.step(x, y).asnumpy()) for _ in range(3)]
    w = onp.asarray(
        net.weight.data()._data.addressable_data(0)).ravel()

    with open(os.path.join(out_dir, f"worker{rank}.txt"), "w") as f:
        f.write(" ".join(f"{v:.8f}" for v in losses) + "\n")
        f.write(" ".join(f"{v:.8f}" for v in w) + "\n")


if __name__ == "__main__":
    main()
