"""INT8 quantization tests (reference: tests/python/quantization/
test_quantization.py — op-level roundtrips + quantize_net accuracy)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import quantization as qop
from mxnet_tpu.contrib.quantization import (quantize_net,
                                            optimal_threshold_entropy,
                                            QuantizedDense, QuantizedConv)


def test_quantize_dequantize_roundtrip_int8():
    x = mx.np.array(onp.random.RandomState(0)
                    .uniform(-3, 3, (4, 16)).astype("float32"))
    q, mn, mx_ = qop.quantize(x, x.min(), x.max(), out_type="int8")
    assert q.dtype == onp.int8
    back = qop.dequantize(q, mn, mx_)
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy(),
                                atol=3.0 / 127 + 1e-6)


def test_quantize_dequantize_roundtrip_uint8():
    x = mx.np.array(onp.random.RandomState(1)
                    .uniform(0, 5, (8, 8)).astype("float32"))
    q, mn, mx_ = qop.quantize(x, x.min(), x.max(), out_type="uint8")
    assert q.dtype == onp.uint8
    back = qop.dequantize(q, mn, mx_)
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy(),
                                atol=5.0 / 255 + 1e-6)


def test_quantize_v2_calibrated_clips():
    x = mx.np.array(onp.array([[-10.0, 0.5, 10.0]], dtype="float32"))
    q, mn, mx_ = qop.quantize_v2(x, min_calib_range=-1.0,
                                 max_calib_range=1.0)
    back = qop.dequantize(q, mn, mx_).asnumpy()
    onp.testing.assert_allclose(back, [[-1.0, 0.5, 1.0]], atol=1e-2)


def test_quantized_fully_connected_matches_float():
    rng = onp.random.RandomState(2)
    x = rng.uniform(-1, 1, (8, 32)).astype("float32")
    w = rng.uniform(-0.5, 0.5, (16, 32)).astype("float32")
    b = rng.uniform(-0.2, 0.2, (16,)).astype("float32")

    xq, xmn, xmx = qop.quantize_v2(mx.np.array(x))
    wq, wmn, wmx = qop.quantize_v2(mx.np.array(w))
    bq, bmn, bmx = qop.quantize_v2(mx.np.array(b))
    y32, mn_o, mx_o = qop.quantized_fully_connected(
        xq, wq, bq, xmn, xmx, wmn, wmx, bmn, bmx, num_hidden=16)
    assert y32.dtype == onp.int32
    y = qop.dequantize(y32, mn_o, mx_o).asnumpy()
    ref = x @ w.T + b
    # int8 with per-tensor scales: ~1% of the output range
    assert onp.abs(y - ref).max() < 0.05 * onp.abs(ref).max() + 0.05


def test_quantized_conv_matches_float():
    rng = onp.random.RandomState(3)
    x = rng.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
    w = rng.uniform(-0.5, 0.5, (4, 3, 3, 3)).astype("float32")

    xq, xmn, xmx = qop.quantize_v2(mx.np.array(x))
    wq, wmn, wmx = qop.quantize_v2(mx.np.array(w))
    y32, mn_o, mx_o = qop.quantized_conv(
        xq, wq, None, xmn, xmx, wmn, wmx, kernel=(3, 3), pad=(1, 1),
        num_filter=4, no_bias=True)
    y = qop.dequantize(y32, mn_o, mx_o).asnumpy()

    ref = mx.npx.convolution(mx.np.array(x), mx.np.array(w),
                             kernel=(3, 3), pad=(1, 1),
                             num_filter=4, no_bias=True).asnumpy()
    assert onp.abs(y - ref).max() < 0.05 * onp.abs(ref).max() + 0.05


def test_quantized_pooling_and_act():
    rng = onp.random.RandomState(4)
    x = rng.uniform(-2, 2, (1, 2, 4, 4)).astype("float32")
    q, mn, mx_ = qop.quantize_v2(mx.np.array(x))
    p, pmn, pmx = qop.quantized_pooling(q, mn, mx_, kernel=(2, 2),
                                        stride=(2, 2), pool_type="max")
    ref = mx.npx.pooling(mx.np.array(x), kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    back = qop.dequantize(p, pmn, pmx).asnumpy()
    onp.testing.assert_allclose(back, ref.asnumpy(), atol=4.0 / 127 + 1e-6)

    r, rmn, rmx = qop.quantized_act(q, mn, mx_)
    assert (r.asnumpy() >= 0).all()
    assert float(rmn.asnumpy()) >= 0.0


def test_entropy_threshold_ignores_outlier():
    """KL calibration should clip a lone outlier that min/max keeps."""
    vals = onp.concatenate([onp.random.RandomState(5).normal(0, 1, 100000),
                            [50.0]])
    hist, edges = onp.histogram(onp.abs(vals), bins=2048, range=(0, 50.0))
    t = optimal_threshold_entropy(hist, edges)
    assert t < 25.0, t


def _mlp():
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"),
            mx.gluon.nn.Dense(10))
    net.initialize()
    return net


@pytest.mark.parametrize("mode", ["naive", "entropy", "none"])
def test_quantize_net_dense_close_to_float(mode):
    mx.random.seed(0)
    net = _mlp()
    rng = onp.random.RandomState(6)
    X = mx.np.array(rng.uniform(-1, 1, (16, 20)).astype("float32"))
    ref = net(X).asnumpy()
    calib = None if mode == "none" else [X]
    qnet = quantize_net(net, calib_data=calib, calib_mode=mode)
    out = qnet(X).asnumpy()
    assert isinstance(qnet._children["0"], QuantizedDense)
    denom = onp.abs(ref).max()
    assert onp.abs(out - ref).max() < 0.1 * denom + 0.05, mode


def test_quantize_net_conv_and_exclude():
    mx.random.seed(1)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            mx.gluon.nn.Conv2D(8, 3, padding=1))
    net.initialize()
    rng = onp.random.RandomState(7)
    X = mx.np.array(rng.uniform(-1, 1, (2, 3, 8, 8)).astype("float32"))
    ref = net(X).asnumpy()
    qnet = quantize_net(net, calib_data=[X], calib_mode="naive",
                        exclude_layers=["1"])
    assert isinstance(qnet._children["0"], QuantizedConv)
    assert not isinstance(qnet._children["1"], QuantizedConv)   # excluded
    out = qnet(X).asnumpy()
    assert onp.abs(out - ref).max() < 0.1 * onp.abs(ref).max() + 0.05


def test_quantize_net_preserves_classification():
    """End-to-end: train a tiny MLP, quantize, assert argmax agreement."""
    mx.random.seed(2)
    rng = onp.random.RandomState(8)
    X = rng.uniform(-1, 1, (64, 16)).astype("float32")
    Y = (X[:, :8].sum(1) > X[:, 8:].sum(1)).astype("int32")
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"), mx.gluon.nn.Dense(2))
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "adam",
                          {"learning_rate": 1e-2})
    lf = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    Xn, Yn = mx.np.array(X), mx.np.array(Y)
    for _ in range(40):
        with mx.autograd.record():
            loss = lf(net(Xn), Yn).mean()
        loss.backward()
        tr.step(64)
    ref_pred = net(Xn).asnumpy().argmax(1)
    qnet = quantize_net(net, calib_data=[Xn], calib_mode="entropy")
    q_pred = qnet(Xn).asnumpy().argmax(1)
    assert (ref_pred == q_pred).mean() >= 0.95


def test_quantize_net_hybridizes():
    net = _mlp()
    X = mx.np.array(onp.random.RandomState(9)
                    .uniform(-1, 1, (4, 12)).astype("float32"))
    ref = net(X).asnumpy()
    qnet = quantize_net(net, calib_data=[X], calib_mode="naive")
    eager = qnet(X).asnumpy()
    qnet.hybridize()
    hybrid = qnet(X).asnumpy()
    onp.testing.assert_allclose(hybrid, eager, rtol=1e-5, atol=1e-5)
    assert onp.abs(hybrid - ref).max() < 0.1 * onp.abs(ref).max() + 0.05


def test_quantize_net_on_hybridized_net():
    """Calibration must run eagerly: on a hybridized net the cached
    compiled graph would bypass the observer hooks, producing garbage
    ranges (regression — predictions collapsed to ~random)."""
    mx.random.seed(4)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
            mx.gluon.nn.GlobalAvgPool2D(), mx.gluon.nn.Dense(3))
    net.initialize()
    X = mx.np.array(onp.random.RandomState(11)
                    .uniform(-1, 1, (8, 2, 8, 8)).astype("float32"))
    net(X)
    net.hybridize()
    ref = net(X).asnumpy()
    qnet = quantize_net(net, calib_data=[X], calib_mode="naive")
    out = qnet(X).asnumpy()
    assert onp.abs(out - ref).max() < 0.1 * onp.abs(ref).max() + 0.05
    # calibrated ranges are real, not +-inf garbage
    qconv = qnet._children["0"]
    assert onp.isfinite([qconv._in_min, qconv._in_max]).all()


def test_quantize_errors():
    net = _mlp()
    with pytest.raises(mx.MXNetError):
        quantize_net(net, quantized_dtype="uint4")
    with pytest.raises(mx.MXNetError):
        quantize_net(net, calib_mode="bogus")
    with pytest.raises(mx.MXNetError):
        quantize_net(net, calib_mode="naive")   # no calib_data
    with pytest.raises(mx.MXNetError):
        qop.quantize(mx.np.zeros((2,)), mx.np.array(0.0),
                     mx.np.array(1.0), out_type="int4")
