"""Bounding-box contrib ops (reference:
src/operator/contrib/bounding_box.cc, multibox_prior.cc)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.ops  # noqa: F401  (registers box ops)


def test_box_iou_corner_and_center():
    a = mx.np.array(onp.array([[0, 0, 2, 2], [1, 1, 3, 3]], dtype="float32"))
    b = mx.np.array(onp.array([[0, 0, 2, 2], [10, 10, 12, 12]],
                              dtype="float32"))
    iou = mx.nd.contrib.box_iou(a, b).asnumpy()
    assert abs(iou[0, 0] - 1.0) < 1e-6
    assert iou[0, 1] == 0
    assert abs(iou[1, 0] - 1 / 7) < 1e-5
    # center format: (cx=1, cy=1, w=2, h=2) == corner (0, 0, 2, 2)
    ac = mx.np.array(onp.array([[1, 1, 2, 2]], dtype="float32"))
    bc = mx.np.array(onp.array([[0, 0, 2, 2]], dtype="float32"))  # corner
    iou_c = mx.nd.contrib.box_iou(ac, ac, format="center").asnumpy()
    assert abs(iou_c[0, 0] - 1.0) < 1e-6
    cross = mx.nd.contrib.box_iou(
        a[:1], bc[:1], format="corner").asnumpy()
    assert abs(cross[0, 0] - 1.0) < 1e-6


def test_box_nms_class_aware_and_force():
    data = onp.array([[
        [0, 0.9, 0, 0, 2, 2],
        [0, 0.8, 0.1, 0.1, 2.1, 2.1],
        [1, 0.7, 5, 5, 7, 7],
    ]], dtype="float32")
    out = mx.nd.contrib.box_nms(
        mx.np.array(data), overlap_thresh=0.5, coord_start=2,
        score_index=1, id_index=0).asnumpy()
    assert out[0, 0, 1] == onp.float32(0.9)
    assert (out[0, 1] == -1).all()   # overlapping same-class suppressed
    assert out[0, 2, 1] == onp.float32(0.7)
    same_box = onp.array([[[0, 0.9, 0, 0, 2, 2],
                           [1, 0.8, 0, 0, 2, 2]]], dtype="float32")
    keep = mx.nd.contrib.box_nms(
        mx.np.array(same_box), overlap_thresh=0.5, coord_start=2,
        score_index=1, id_index=0).asnumpy()
    assert (keep[0] != -1).all()     # different class -> both kept
    forced = mx.nd.contrib.box_nms(
        mx.np.array(same_box), overlap_thresh=0.5, coord_start=2,
        score_index=1, id_index=0, force_suppress=True).asnumpy()
    assert (forced[0, 1] == -1).all()


def test_box_nms_valid_thresh_topk_2d_center():
    d = onp.array([[0.9, 0.5, 0.5, 1.0, 1.0],
                   [0.05, 0.5, 0.5, 1.0, 1.0]], dtype="float32")
    o = mx.nd.contrib.box_nms(
        mx.np.array(d), overlap_thresh=0.5, valid_thresh=0.1,
        coord_start=1, score_index=0, in_format="center").asnumpy()
    assert o.shape == (2, 5)
    assert o[0, 0] == onp.float32(0.9)
    assert (o[1] == -1).all()        # below valid_thresh
    many = onp.stack([
        onp.array([0.9 - 0.1 * i, 10.0 * i, 10.0 * i,
                   10.0 * i + 2, 10.0 * i + 2], dtype="float32")
        for i in range(5)])
    topped = mx.nd.contrib.box_nms(
        mx.np.array(many), overlap_thresh=0.5, coord_start=1,
        score_index=0, topk=3).asnumpy()
    assert (topped[3:] == -1).all()  # beyond topk invalid
    assert (topped[:3, 0] > 0).all()


def test_box_nms_out_format_conversion():
    d = onp.array([[[0.9, 0.0, 0.0, 2.0, 2.0]]], dtype="float32")
    o = mx.nd.contrib.box_nms(
        mx.np.array(d), coord_start=1, score_index=0,
        in_format="corner", out_format="center").asnumpy()
    assert onp.allclose(o[0, 0], [0.9, 1.0, 1.0, 2.0, 2.0])


def test_bipartite_matching_greedy():
    scores = onp.array([[[0.9, 0.2], [0.8, 0.7]]], dtype="float32")
    rm, cm = mx.nd.contrib.bipartite_matching(
        mx.np.array(scores), threshold=0.1)
    assert rm.asnumpy().tolist() == [[0.0, 1.0]]
    assert cm.asnumpy().tolist() == [[0.0, 1.0]]
    # threshold excludes weak pairs
    rm2, cm2 = mx.nd.contrib.bipartite_matching(
        mx.np.array(scores), threshold=0.75)
    assert rm2.asnumpy().tolist() == [[0.0, -1.0]]
    assert cm2.asnumpy().tolist() == [[0.0, -1.0]]
    # ascending mode: smaller is better (distance matrices)
    dist = onp.array([[[0.1, 0.9], [0.9, 0.2]]], dtype="float32")
    rma, _ = mx.nd.contrib.bipartite_matching(
        mx.np.array(dist), threshold=0.5, is_ascend=True)
    assert rma.asnumpy().tolist() == [[0.0, 1.0]]


def test_multibox_prior_anchors():
    x = mx.np.zeros((1, 3, 2, 2))
    anc = mx.nd.contrib.multibox_prior(
        x, sizes=(0.5, 0.25), ratios=(1, 2)).asnumpy()
    assert anc.shape == (1, 12, 4)   # H*W*(S+R-1) = 2*2*3
    assert onp.allclose(anc[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    # ratio-2 anchor: w = s0*sqrt(2), h = s0/sqrt(2)
    w = anc[0, 2, 2] - anc[0, 2, 0]
    h = anc[0, 2, 3] - anc[0, 2, 1]
    assert abs(w / h - 2.0) < 1e-5
    clipped = mx.nd.contrib.multibox_prior(
        x, sizes=(1.5,), clip=True).asnumpy()
    assert clipped.min() >= 0.0 and clipped.max() <= 1.0


def test_box_nms_gradient_passthrough():
    d = mx.np.array(onp.array([[[0.9, 0.0, 0.0, 2.0, 2.0]]],
                              dtype="float32"))
    d.attach_grad()
    with mx.autograd.record():
        out = mx.nd.contrib.box_nms(d, coord_start=1, score_index=0).sum()
    out.backward()
    assert d.grad is not None


def test_multibox_target_matching_and_encoding():
    anchor = mx.np.array(onp.array(
        [[[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]]], dtype="float32"))
    label = mx.np.array(onp.array(
        [[[1, 0.1, 0.1, 0.4, 0.4], [-1, 0, 0, 0, 0]]], dtype="float32"))
    cls_pred = mx.np.array(onp.zeros((1, 3, 2), dtype="float32"))
    lt, lm, ct = mx.nd.contrib.multibox_target(anchor, label, cls_pred)
    assert ct.asnumpy().tolist() == [[2.0, 0.0]]  # gt class 1 -> target 2
    assert onp.allclose(lt.asnumpy()[0, :4], 0, atol=1e-5)  # exact match
    assert lm.asnumpy()[0].tolist() == [1, 1, 1, 1, 0, 0, 0, 0]
    # offset gt: dx = (gcx-acx)/aw/v0
    label2 = mx.np.array(onp.array(
        [[[0, 0.15, 0.1, 0.45, 0.4], [-1, 0, 0, 0, 0]]], dtype="float32"))
    lt2, _, ct2 = mx.nd.contrib.multibox_target(anchor, label2, cls_pred)
    assert ct2.asnumpy()[0, 0] == 1.0
    assert abs(lt2.asnumpy()[0, 0] - (0.05 / 0.3 / 0.1)) < 1e-4


def test_multibox_detection_decode_roundtrip():
    anchor = mx.np.array(onp.array(
        [[[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]]], dtype="float32"))
    prob = onp.zeros((1, 3, 2), dtype="float32")
    prob[0, 1, 0] = 0.9
    prob[0, 2, 1] = 0.8
    loc = onp.zeros((1, 8), dtype="float32")
    det = mx.nd.contrib.multibox_detection(
        mx.np.array(prob), mx.np.array(loc), anchor).asnumpy()
    assert det.shape == (1, 2, 6)
    assert det[0, 0, 0] == 0.0 and abs(det[0, 0, 1] - 0.9) < 1e-6
    assert onp.allclose(det[0, 0, 2:], [0.1, 0.1, 0.4, 0.4], atol=1e-5)
    assert det[0, 1, 0] == 1.0
    # below-threshold anchors come back as -1 rows
    weak = onp.zeros((1, 3, 2), dtype="float32")
    weak[0, 0] = 1.0  # all background
    det2 = mx.nd.contrib.multibox_detection(
        mx.np.array(weak), mx.np.array(loc), anchor,
        threshold=0.5).asnumpy()
    assert (det2 == -1).all()


def test_multibox_target_padding_does_not_clobber_forced_match():
    """Regression: a padded label row (cls=-1) argmaxes to anchor 0 and
    must not overwrite a valid gt's force-match there (scatter-max, not
    scatter-set)."""
    anchor = mx.np.array(onp.array(
        [[[0.0, 0.0, 0.3, 0.3], [0.6, 0.6, 0.9, 0.9]]], dtype="float32"))
    # gt overlaps anchor0 weakly (IoU < 0.5) -> only the forced match
    # can claim it; the padding row must not erase that
    label = mx.np.array(onp.array(
        [[[1, 0.0, 0.0, 0.15, 0.3], [-1, 0, 0, 0, 0]]], dtype="float32"))
    cls_pred = mx.np.array(onp.zeros((1, 3, 2), dtype="float32"))
    _, _, ct = mx.nd.contrib.multibox_target(anchor, label, cls_pred)
    assert ct.asnumpy().tolist() == [[2.0, 0.0]], ct.asnumpy()
