"""RecordIO — splittable binary record format + image packing.

Reference parity (leezu/mxnet): ``python/mxnet/recordio.py`` +
``3rdparty/dmlc-core/include/dmlc/recordio.h``. The on-disk format is kept
COMPATIBLE with the reference (same magic, same record framing, same
IRHeader struct), so ``.rec`` files packed by the reference's
``tools/im2rec.py`` read directly and vice versa:

  record  := magic:u32 (0xced7230a) | lrecord:u32 | data | pad to 4B
  lrecord := cflag:u3 << 29 | length:u29    (cflag 0 = whole record;
             1/2/3 = begin/middle/end of a multi-part record)
  IRHeader:= flag:u32 | label:f32 | id:u64 | id2:u64   ('<IfQQ');
             flag>0 means flag float labels follow the header.
"""
from __future__ import annotations

import ctypes
import io
import numbers
import os
import struct
from collections import namedtuple
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as _np

from .base import MXNetError, register_env

register_env("MXNET_NATIVE_RECORDIO", 1,
             "Set to 0 to bypass the libmxtpu.so C RecordIO "
             "reader/writer and use the pure-Python implementation "
             "even when the native library is loaded (debugging / "
             "byte-level parity checks).")

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_KMAGIC = 0xCED7230A
_LEN_MASK = (1 << 29) - 1

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def _use_native() -> bool:
    from . import _native
    return (_native.LIB is not None
            and os.environ.get("MXNET_NATIVE_RECORDIO", "1") != "0")


class MXRecordIO:
    """Sequential reader/writer of RecordIO files.

    Backed by the native C++ reader/writer (``src/recordio.cc``, the
    dmlc::RecordIOReader analog) when ``libmxtpu.so`` is available;
    pure-Python fallback otherwise.  Both produce identical bytes.
    """

    def __init__(self, uri: str, flag: str) -> None:
        self.uri = uri
        self.flag = flag
        self.fid: Optional[io.BufferedIOBase] = None
        self._nat = None
        self.open()

    def open(self) -> None:
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag!r} (use 'r'/'w')")
        if _use_native():
            from . import _native
            self._nat = (_native.NativeRecordWriter(self.uri)
                         if self.writable
                         else _native.NativeRecordReader(self.uri))
        else:
            self.fid = open(self.uri, "wb" if self.writable else "rb")

    def close(self) -> None:
        if self._nat is not None:
            self._nat.close()
            self._nat = None
        if self.fid is not None:
            self.fid.close()
            self.fid = None

    def reset(self) -> None:
        self.close()
        self.open()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001 - interpreter shutdown
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["fid"] = None
        d["_nat"] = None
        d["_pos"] = self.tell() if (self.fid or self._nat) else 0
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        if not self.writable:
            self.seek(pos)

    def write(self, buf: bytes) -> None:
        if not self.writable:
            raise MXNetError("file opened for reading")
        length = len(buf)
        if length > _LEN_MASK:
            raise MXNetError(f"record too large ({length} bytes)")
        if self._nat is not None:
            self._nat.write(bytes(buf))
            return
        self.fid.write(struct.pack("<II", _KMAGIC, length))
        self.fid.write(buf)
        pad = (-(8 + length)) % 4
        if pad:
            self.fid.write(b"\0" * pad)

    def read(self) -> Optional[bytes]:
        if self.writable:
            raise MXNetError("file opened for writing")
        if self._nat is not None:
            return self._nat.read()
        head = self.fid.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _KMAGIC:
            raise MXNetError(f"{self.uri}: bad record magic {magic:#x}")
        length = lrec & _LEN_MASK
        data = self.fid.read(length)
        pad = (-(8 + length)) % 4
        if pad:
            self.fid.read(pad)
        return data

    def tell(self) -> int:
        if self._nat is not None:
            return self._nat.tell()
        return self.fid.tell()

    def seek(self, pos: int) -> None:
        if self.writable:
            raise MXNetError("cannot seek a writable recordio")
        if self._nat is not None:
            self._nat.seek(pos)
        else:
            self.fid.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a sidecar ``.idx`` (key\\tposition) for random access."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type: type = int) -> None:
        self.idx_path = idx_path
        self.idx: Dict[Any, int] = {}
        self.keys: List[Any] = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self) -> None:
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)

    def close(self) -> None:
        if (self.fid is not None or self._nat is not None) and self.writable:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def read_idx(self, idx: Any) -> bytes:
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx: Any, buf: bytes) -> None:
        pos = self.tell()
        self.write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Serialize IRHeader + payload (reference ``recordio.pack``)."""
    label = header.label
    if isinstance(label, numbers.Number):
        header = header._replace(flag=0, label=float(label))
        payload = b""
    else:
        label_arr = _np.asarray(label, dtype=_np.float32).reshape(-1)
        header = header._replace(flag=label_arr.size, label=0.0)
        payload = label_arr.tobytes()
    return struct.pack(_IR_FORMAT, int(header.flag), float(header.label),
                       int(header.id), int(header.id2)) + payload + s


def unpack(s: bytes) -> Tuple[IRHeader, bytes]:
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        return IRHeader(flag, arr, id_, id2), s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


_RAW_MAGIC = b"RAW0"


def pack_img(header: IRHeader, img: Any, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode an HWC uint8 image and pack it. ``img_fmt``: '.jpg' /
    '.png' (PIL-encoded, the reference formats) or '.raw' — an
    uncompressed ``RAW0 + u16 h,w,c + bytes`` payload whose decode is a
    frombuffer (the high-throughput packing for hosts where JPEG decode,
    not the wire, is the bottleneck)."""
    from PIL import Image
    arr = img.asnumpy() if hasattr(img, "asnumpy") else _np.asarray(img)
    if img_fmt.lower() == ".raw":
        a = _np.ascontiguousarray(arr, dtype=_np.uint8)
        if a.ndim == 2:
            a = a[:, :, None]
        h, w, c = a.shape
        payload = _RAW_MAGIC + struct.pack("<HHH", h, w, c) + a.tobytes()
        return pack(header, payload)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]
    pil = Image.fromarray(arr)
    buf = io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor: int = -1, flag: int = 1
               ) -> Tuple[IRHeader, _np.ndarray]:
    from PIL import Image
    header, img_bytes = unpack(s)
    if img_bytes[:4] == _RAW_MAGIC:
        h, w, c = struct.unpack("<HHH", img_bytes[4:10])
        arr = _np.frombuffer(img_bytes, dtype=_np.uint8,
                             offset=10).reshape(h, w, c)
        if flag and c == 1:
            arr = _np.repeat(arr, 3, axis=2)
        elif not flag and c == 3:
            # ITU-R 601 luma, same as the PIL path's convert('L') — the
            # pack format must not change grayscale pixel values
            luma = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
                    + arr[..., 2] * 0.114)
            arr = _np.rint(luma).astype(_np.uint8)[..., None]
        return header, arr
    pil = Image.open(io.BytesIO(img_bytes))
    pil = pil.convert("RGB" if flag else "L")
    arr = _np.asarray(pil, dtype=_np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return header, arr
