"""Deterministic, seeded fault injection — chaos testing as a first-class
runtime capability.

NEW capability beyond the reference (no leezu/mxnet analog): the
reference's fault story is "checkpoint-restart exists" (SURVEY.md 5.3);
nothing in either codebase can *prove* a run survives a kill, a wedged
parameter server, or a crashing dataloader worker.  This module makes
failure a routine, testable event: named fault **sites** are compiled
into the runtime's choke points, and a **plan** arms them with a
deterministic, seeded probability sequence, so a chaos test replays the
exact same fault schedule on every run.

Sites (each named site is one ``maybe_fault(site)`` call in the code;
``known_sites()`` returns this table and CI lints that every site is
documented in docs/fault_tolerance.md):

* ``checkpoint.write``  — CheckpointManager.save, after staging starts
* ``kvstore.send``      — dist_async client, before a frame is sent
* ``kvstore.recv``      — dist_async client, before a reply is read
* ``dataloader.worker`` — inside a DataLoader worker, per batch job
  (also fires inside the ``DevicePrefetcher`` background thread)
* ``serving.execute``   — ModelServer worker, per assembled batch
* ``serving.worker``    — the serving worker loop itself (worker-death
  chaos: an error here kills the worker thread, exercising the replica
  supervisor's requeue/recover/restart/breaker path)
* ``ps.server``         — the dist_async parameter-server serve loop
  (``kind=crash`` kills the server process, the chaos lever behind the
  durable-PS / supervised-restart proof)
* ``worker.heartbeat``  — the dist_async worker heartbeat thread (an
  error here suppresses the beat: the wedged-not-dead rank simulation)
* ``dispatch.op``       — the imperative op dispatch path, per op
* ``compile_cache.read``  — persistent compile-cache lookup (an error
  degrades to a miss + recompile, never a step failure)
* ``compile_cache.write`` — persistent compile-cache write-back (an
  error abandons the write; memory still serves)
* ``trainer.step``      — the optimizer-step boundary, per step (the
  tensor-corrupting site: ``kind=nan`` plants a NaN via
  :func:`maybe_corrupt`)

Arming: the ``MXNET_FAULT_PLAN`` environment variable (parsed at import,
so subprocess chaos tests arm via env alone), or the API::

    from mxnet_tpu import faults
    faults.arm("kvstore.recv", p=0.05, kind="timeout")
    with faults.fault_plan("checkpoint.write:p=1:kind=error:times=1"):
        ...

Plan grammar — ``;``-separated clauses, each ``site:k=v:k=v...``::

    kvstore.recv:p=0.05:kind=timeout;checkpoint.write:p=1:times=2

Clause fields: ``p`` (injection probability per hit, default 1),
``kind`` (``error`` | ``timeout`` | ``crash`` | ``delay`` | ``nan``,
default error), ``after`` (skip the first N hits), ``times`` (stop
after M injections; default unlimited), ``delay_ms`` (for kind=delay),
``seed`` (per-clause RNG seed override).

Determinism: every clause draws from its own ``random.Random`` seeded by
``MXNET_FAULT_SEED`` (default 0) xor a stable hash of the site name —
the same plan + seed produces the same fault schedule in every process,
independent of thread timing or global RNG use elsewhere.

Kinds:

* ``error``   — raise :class:`FaultInjected` (an MXNetError)
* ``timeout`` — raise ``socket.timeout`` (``TimeoutError``), exercising
  the same handling as a real dead-peer timeout
* ``crash``   — ``os._exit(17)``: the process dies NOW, no cleanup —
  the SIGKILL analog for in-process chaos
* ``delay``   — sleep ``delay_ms`` then continue (slow-peer simulation)
* ``nan``     — corrupt the first tensor at a :func:`maybe_corrupt`
  site with NaN (the silent-numerics-failure simulation the health
  sentry trains against; tensor-less sites reject it loudly)

Every injection counts into the PR-1 metrics registry
(``mxnet_faults_injected_total{site,kind}``), so a chaos run's metric
dump states exactly which faults fired.

The disarmed cost is one module-attribute bool check at each site
(``_ARMED``); the per-op dispatch site stays out of the hot path until
a plan arms.
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence

from .base import MXNetError, register_env
from . import metrics as _metrics

__all__ = [
    "FaultInjected", "FaultSpec", "arm", "disarm", "fault_plan",
    "parse_plan", "arm_from_env", "armed_sites", "known_sites",
    "maybe_fault", "maybe_corrupt", "injected_count",
]

register_env(
    "MXNET_FAULT_PLAN", "",
    "Deterministic fault-injection plan, ';'-separated clauses of "
    "'site:p=0.05:kind=timeout' form (kinds: error, timeout, crash, "
    "delay, nan; fields: p, kind, after, times, delay_ms, seed). "
    "Sites: see docs/fault_tolerance.md. Parsed once at import; empty "
    "(default) disarms everything.")
register_env(
    "MXNET_FAULT_SEED", 0,
    "Base seed for the per-site fault-injection RNGs: the same "
    "MXNET_FAULT_PLAN + seed replays the identical fault schedule in "
    "every process (per-clause 'seed=' overrides).")

FAULTS_INJECTED = _metrics.counter(
    "mxnet_faults_injected_total",
    "Faults injected by the chaos layer (mxnet_tpu.faults), by site and "
    "kind. Nonzero outside a chaos run means MXNET_FAULT_PLAN is set in "
    "production.", labels=("site", "kind"))

# The authoritative site table (name -> where it lives). ci/run.sh lints
# that every name appears in docs/fault_tolerance.md.
_SITES: Dict[str, str] = {
    "checkpoint.write":
        "CheckpointManager.save — after the staging dir exists, before "
        "files rename into place (crash here leaves an orphan staging "
        "dir for the __init__ sweep)",
    "kvstore.send":
        "dist_async worker client, before a request frame is sent to a "
        "parameter server",
    "kvstore.recv":
        "dist_async worker client, before a reply frame is read (a "
        "timeout here is the silent-dead-server case)",
    "dataloader.worker":
        "inside a DataLoader worker process/thread, per batch job "
        "(kind=crash is the killed-worker case)",
    "serving.execute":
        "ModelServer worker thread, per assembled batch, before the "
        "model executes",
    "serving.worker":
        "the serving worker loop itself (ModelServer per dequeued "
        "batch, GenerationServer per decode-loop pass), OUTSIDE the "
        "per-request error handling — an injected error here kills the "
        "worker thread, the in-process worker-death analog the replica "
        "supervisor trains against (requeue/recover + restart + "
        "circuit breaker)",
    "ps.server":
        "the dist_async parameter-server serve loop (per received "
        "frame, OUTSIDE the per-request error handling that would "
        "convert an exception into an error reply): kind=crash "
        "os._exits the server process — the SIGKILL analog the launch "
        "supervisor + durable snapshot restore train against — and "
        "kind=error kills the serve loop itself; seedable like "
        "serving.worker",
    "worker.heartbeat":
        "the dist_async worker heartbeat thread, per (tick, server): "
        "an injected error SUPPRESSES that beat, simulating a "
        "wedged-not-dead rank whose lease expires so barriers and "
        "coordinated checkpoints name it DEAD within "
        "MXNET_PS_HEARTBEAT_DEADLINE_S",
    "dispatch.op":
        "the imperative op dispatch path (ndarray.register.invoke), "
        "per op call",
    "compile_cache.read":
        "persistent compile-cache lookup (CompileCache.load), before "
        "the entry manifest is opened — an injected error degrades "
        "the lookup to a miss (the program recompiles); never a step "
        "or request failure",
    "compile_cache.write":
        "persistent compile-cache write-back (CompileCache.store), "
        "before serialization/staging — an injected error abandons "
        "the write; the freshly compiled executable still serves this "
        "process from memory",
    "trainer.step":
        "the optimizer-step boundary (gluon Trainer.step before the "
        "gradient reduction, SPMDTrainer.step before the compiled "
        "program), per step — a tensor-corrupting site: kind=nan "
        "poisons the first gradient (gluon) / the batch (SPMD) with "
        "NaN so the health sentry's detect/skip/rewind schedule "
        "replays deterministically",
}

_KINDS = ("error", "timeout", "crash", "delay", "nan")

_ARMED = False                       # hot-path gate, rebuilt on arm/disarm
_PLAN: Dict[str, List["FaultSpec"]] = {}
_LOCK = threading.Lock()


class FaultInjected(MXNetError):
    """An injected fault (kind=error) — never raised outside a plan."""

    def __init__(self, site: str, ctx: Dict[str, Any]) -> None:
        self.site = site
        self.ctx = dict(ctx)
        extra = f" ({ctx})" if ctx else ""
        super().__init__(f"injected fault at site {site!r}{extra} "
                         "[mxnet_tpu.faults]")

    def __reduce__(self):
        # cross-process propagation (a DataLoader pool re-raises worker
        # exceptions by pickle) needs the real constructor args
        return (FaultInjected, (self.site, self.ctx))


class FaultSpec:
    """One armed clause: site + probability + kind + hit accounting."""

    __slots__ = ("site", "p", "kind", "after", "times", "delay_ms",
                 "hits", "injected", "_rng", "_lock")

    def __init__(self, site: str, p: float = 1.0, kind: str = "error",
                 after: int = 0, times: Optional[int] = None,
                 delay_ms: float = 10.0,
                 seed: Optional[int] = None) -> None:
        if site not in _SITES:
            raise MXNetError(
                f"unknown fault site {site!r}; known sites: "
                f"{sorted(_SITES)}")
        if kind not in _KINDS:
            raise MXNetError(
                f"unknown fault kind {kind!r}; known kinds: {_KINDS}")
        if not 0.0 <= p <= 1.0:
            raise MXNetError(f"fault probability must be in [0,1], "
                             f"got {p}")
        self.site = site
        self.p = float(p)
        self.kind = kind
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.delay_ms = float(delay_ms)
        self.hits = 0
        self.injected = 0
        if seed is None:
            seed = int(os.environ.get("MXNET_FAULT_SEED", "0") or 0)
        import random
        # a stable per-site stream: thread scheduling and unrelated RNG
        # use cannot perturb the fault schedule
        self._rng = random.Random(seed ^ zlib.crc32(site.encode()))
        self._lock = threading.Lock()

    def __repr__(self) -> str:
        return (f"FaultSpec({self.site}:p={self.p}:kind={self.kind}"
                f":after={self.after}:times={self.times}"
                f" hits={self.hits} injected={self.injected})")

    def _check(self, ctx: Dict[str, Any],
               corrupt: Optional[Any] = None) -> None:
        with self._lock:
            self.hits += 1
            if self.hits <= self.after:
                return
            if self.times is not None and self.injected >= self.times:
                return
            if self.p < 1.0 and self._rng.random() >= self.p:
                return
            self.injected += 1
        FAULTS_INJECTED.labels(site=self.site, kind=self.kind).inc()
        if self.kind == "delay":
            time.sleep(self.delay_ms / 1e3)
            return
        if self.kind == "timeout":
            import socket
            raise socket.timeout(
                f"injected timeout at site {self.site!r} "
                "[mxnet_tpu.faults]")
        if self.kind == "crash":
            os._exit(17)
        if self.kind == "nan":
            # tensor corruption: only sites that pass arrays through
            # maybe_corrupt can apply it — a kind=nan clause armed at a
            # tensor-less site is a plan bug and fails loudly
            if corrupt is None:
                raise MXNetError(
                    f"fault kind 'nan' armed at site {self.site!r}, "
                    "which passes no tensor to corrupt — use a "
                    "tensor-carrying site (trainer.step)")
            corrupt()
            return
        raise FaultInjected(self.site, ctx)


def _rebuild_armed() -> None:
    global _ARMED
    _ARMED = any(_PLAN.values())


def parse_plan(plan: str) -> List[FaultSpec]:
    """Parse a ``MXNET_FAULT_PLAN`` string into specs (no arming)."""
    specs: List[FaultSpec] = []
    for clause in plan.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        site = parts[0].strip()
        kw: Dict[str, Any] = {}
        for field in parts[1:]:
            if "=" not in field:
                raise MXNetError(
                    f"bad fault-plan field {field!r} in clause "
                    f"{clause!r} (want k=v)")
            k, v = field.split("=", 1)
            k = k.strip()
            if k == "kind":
                kw["kind"] = v.strip()
            elif k == "p":
                kw["p"] = float(v)
            elif k == "delay_ms":
                kw["delay_ms"] = float(v)
            elif k in ("after", "times", "seed"):
                kw[k] = int(v)
            else:
                raise MXNetError(
                    f"unknown fault-plan field {k!r} in clause "
                    f"{clause!r} (known: p, kind, after, times, "
                    "delay_ms, seed)")
        specs.append(FaultSpec(site, **kw))
    return specs


def arm(site: str, p: float = 1.0, kind: str = "error", after: int = 0,
        times: Optional[int] = None, delay_ms: float = 10.0,
        seed: Optional[int] = None) -> FaultSpec:
    """Arm one site programmatically; returns the live spec (its
    ``hits``/``injected`` counters are readable for assertions)."""
    spec = FaultSpec(site, p=p, kind=kind, after=after, times=times,
                     delay_ms=delay_ms, seed=seed)
    with _LOCK:
        _PLAN.setdefault(site, []).append(spec)
        _rebuild_armed()
    return spec


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site, or everything (``site=None``)."""
    with _LOCK:
        if site is None:
            _PLAN.clear()
        else:
            _PLAN.pop(site, None)
        _rebuild_armed()


class fault_plan:
    """Context manager: arm a plan string for the block, then restore
    the previous arming exactly."""

    def __init__(self, plan: str) -> None:
        self._plan_str = plan
        self._saved: Optional[Dict[str, List[FaultSpec]]] = None
        self.specs: List[FaultSpec] = []

    def __enter__(self) -> "fault_plan":
        self.specs = parse_plan(self._plan_str)
        with _LOCK:
            self._saved = {k: list(v) for k, v in _PLAN.items()}
            for spec in self.specs:
                _PLAN.setdefault(spec.site, []).append(spec)
            _rebuild_armed()
        return self

    def __exit__(self, *exc: Any) -> None:
        with _LOCK:
            _PLAN.clear()
            if self._saved:
                _PLAN.update(self._saved)
            _rebuild_armed()


def arm_from_env() -> int:
    """(Re-)arm from ``MXNET_FAULT_PLAN``; returns the number of clauses
    armed.  Called once at import; callable again after an env change."""
    plan = os.environ.get("MXNET_FAULT_PLAN", "")
    if not plan.strip():
        return 0
    specs = parse_plan(plan)
    with _LOCK:
        for spec in specs:
            _PLAN.setdefault(spec.site, []).append(spec)
        _rebuild_armed()
    return len(specs)


def armed_sites() -> List[str]:
    with _LOCK:
        return sorted(k for k, v in _PLAN.items() if v)


def known_sites() -> Dict[str, str]:
    """The full site table (name -> location doc) — the CI doc lint and
    docs/fault_tolerance.md are generated against this."""
    return dict(_SITES)


def injected_count(site: str) -> int:
    """Total injections at ``site`` across all armed specs."""
    with _LOCK:
        return sum(s.injected for s in _PLAN.get(site, ()))


def maybe_fault(site: str, **ctx: Any) -> None:
    """The site call: no-op unless a plan armed this site.  Callers on
    hot paths should gate on the module's ``_ARMED`` bool first."""
    if not _ARMED:
        return
    specs = _PLAN.get(site)
    if not specs:
        return
    for spec in list(specs):
        spec._check(ctx)


def _float_idx(arrays: Sequence[Any]) -> Optional[int]:
    """Index of the first float-dtype array (only floats can carry a
    NaN; token-id int batches pass through).  jnp.issubdtype, not
    numpy's: bfloat16 (the standard TPU training dtype) is an
    ml_dtypes float that numpy refuses to classify as floating."""
    import jax.numpy as jnp
    for i, a in enumerate(arrays):
        dt = getattr(a, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.floating):
            return i
    return None


def _poison_nan(a: Any) -> Any:
    """Return ``a`` with its first element overwritten by NaN."""
    import numpy as onp
    if isinstance(a, onp.ndarray):
        a = a.copy()
        a.reshape(-1)[0] = onp.nan
        return a
    import jax.numpy as jnp
    idx = (0,) * a.ndim
    return a.at[idx].set(jnp.nan)


def maybe_corrupt(site: str, arrays: Sequence[Any], **ctx: Any) -> List[Any]:
    """Tensor-carrying site call: like :func:`maybe_fault`, but a firing
    ``kind=nan`` clause corrupts the first FLOAT array with NaN instead
    of raising (other kinds behave exactly as at any site).  Returns
    the (possibly corrupted) arrays; callers gate on ``_ARMED``
    first."""
    out = list(arrays)
    if not _ARMED:
        return out
    specs = _PLAN.get(site)
    if not specs:
        return out
    fire = []
    fi = _float_idx(out)

    def _do() -> None:
        if fi is None:
            # the clause fired but there is nothing that can carry a
            # NaN (int-only tensors): a silent no-injection would make
            # the plan's metrics lie — fail loudly instead
            raise MXNetError(
                f"fault kind 'nan' fired at site {site!r} but none of "
                f"the {len(out)} tensors present has a float dtype — "
                "nothing can carry a NaN (int token batches?); target "
                "a float-input model or a different site")
        fire.append(True)

    for spec in list(specs):
        spec._check(ctx, corrupt=_do)
    if fire:
        out[fi] = _poison_nan(out[fi])
    return out


# Arm from the environment at import: chaos subprocesses configure the
# whole schedule with MXNET_FAULT_PLAN alone.
arm_from_env()
