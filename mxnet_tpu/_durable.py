"""Shared durable-write recipe — ONE hardened implementation of the
atomic stage/fsync/rename/verify discipline, used by every subsystem
that persists restart-critical state.

Extracted from :mod:`mxnet_tpu.checkpoint` (the PR-3 hardening) so the
checkpoint manager and the persistent compile cache cannot drift apart:

* :func:`fsync_dir` — make renames/creates inside a directory durable;
* :func:`sha256_file` / :func:`sha256_bytes` — the manifest digests;
* :func:`write_bytes_durable` — stage into a same-directory temp file,
  flush + fsync, then atomically rename into place (and fsync the
  directory), so a crash at ANY point leaves either the old file or the
  complete new one — never a torn write.  Returns the staged content's
  SHA-256 so callers record exactly the bytes that hit the disk;
* :func:`sweep_orphans` — remove staging leftovers a crashed writer
  abandoned, with an age guard so a LIVE writer's staging entry (a
  preempted process still finishing its final write) always survives.

The invariants every caller gets for free:

1. after the write returns, the bytes the recorded digest covers are
   the bytes on disk, crash or no crash (fsync BEFORE rename);
2. a reader either sees the complete previous value or the complete new
   value (atomic ``os.replace`` within one filesystem);
3. concurrent writers of the same path are safe: both stage privately,
   the last rename wins wholesale — no interleaving;
4. crash debris is bounded: any later process sweeps aged-out staging
   entries carrying the caller's prefix (the prefix scoping means the
   sweep can never touch user data).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
import time
from typing import Iterable, Optional

__all__ = ["fsync_dir", "sha256_file", "sha256_bytes",
           "write_bytes_durable", "sweep_orphans", "ORPHAN_MIN_AGE_S"]

# A staging entry younger than this is presumed to belong to a live
# writer (e.g. a preempted trainer finishing its final checkpoint while
# the replacement process starts up) and is never swept.
ORPHAN_MIN_AGE_S = 300.0


def fsync_dir(path: str) -> None:
    """fsync a directory so renames/creates inside it are durable; best
    effort on filesystems without directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def write_bytes_durable(path: str, data: bytes,
                        staging_prefix: str = "stage-") -> str:
    """Atomically, durably write ``data`` to ``path``; returns the
    content SHA-256.

    Stages into a ``staging_prefix``-named temp file in the SAME
    directory (os.replace must not cross filesystems), fsyncs the file,
    renames it into place, then fsyncs the directory.  On any failure
    the staged file is removed and ``path`` is untouched."""
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=staging_prefix,
                               dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    fsync_dir(directory)
    return sha256_bytes(data)


def sweep_orphans(directory: str, prefixes: Iterable[str],
                  min_age_s: float = ORPHAN_MIN_AGE_S,
                  match: Optional[callable] = None) -> int:
    """Remove staging files/dirs under ``directory`` whose names start
    with one of ``prefixes`` (or satisfy ``match``) and whose mtime is
    older than ``min_age_s``.  Returns how many entries were removed.

    Nothing a completed write references ever carries a staging prefix,
    so the sweep can only ever reclaim crash debris."""
    prefixes = tuple(prefixes)
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0
    now = time.time()
    removed = 0
    for entry in entries:
        if not (entry.startswith(prefixes)
                or (match is not None and match(entry))):
            continue
        path = os.path.join(directory, entry)
        try:
            if now - os.path.getmtime(path) < min_age_s:
                continue
        except OSError:
            continue                # vanished mid-scan: done
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            try:
                os.remove(path)
            except OSError:
                continue
        removed += 1
    return removed
