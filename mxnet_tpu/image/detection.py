"""Object-detection image pipeline.

Reference parity (leezu/mxnet): ``python/mxnet/image/detection.py`` —
``ImageDetIter`` (detection label format over the ImageIter transport)
and the ``Det*Aug`` augmenters that keep boxes consistent with the image
transform (flip mirrors boxes, crop clips/filters them).

Label format per image (reference convention): ``[header_width A,
object_width B, extra..., obj0(B), obj1(B), ...]`` where each object is
``[class_id, xmin, ymin, xmax, ymax, ...]`` with coordinates normalized
to [0, 1].
"""
from __future__ import annotations

import random as pyrandom
from typing import Any, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .image import (Augmenter, CastAug, ImageIter, ResizeAug,
                    fixed_crop, imresize)

__all__ = ["ImageDetIter", "DetHorizontalFlipAug", "DetRandomCropAug",
           "DetBorderAug", "CreateDetAugmenter"]


class DetAugmenter:
    """Base: ``__call__(src, label) -> (src, label)``; label is the
    (N_obj, width) float array of [cls, xmin, ymin, xmax, ymax, ...]."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p."""

    def __init__(self, p: float = 0.5) -> None:
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            xmin = 1.0 - label[:, 3]
            xmax = 1.0 - label[:, 1]
            label[:, 1], label[:, 3] = xmin, xmax
        return src, label


class DetBorderAug(DetAugmenter):
    """Pad to a square canvas, rescaling boxes (reference uses border
    fill for aspect-preserving resize)."""

    def __init__(self, fill: float = 127.0) -> None:
        self.fill = fill

    def __call__(self, src, label):
        arr = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
        h, w = arr.shape[:2]
        s = max(h, w)
        if h == w:
            return src, label
        canvas = onp.full((s, s, arr.shape[2]), self.fill, arr.dtype)
        y0, x0 = (s - h) // 2, (s - w) // 2
        canvas[y0:y0 + h, x0:x0 + w] = arr
        label = label.copy()
        label[:, 1] = (label[:, 1] * w + x0) / s
        label[:, 3] = (label[:, 3] * w + x0) / s
        label[:, 2] = (label[:, 2] * h + y0) / s
        label[:, 4] = (label[:, 4] * h + y0) / s
        return NDArray(canvas), label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping boxes whose centers survive; clips the rest
    (simplified reference DetRandomCropAug: min_object_covered via
    center-inclusion)."""

    def __init__(self, min_scale: float = 0.5, max_trials: int = 10,
                 p: float = 0.5) -> None:
        self.min_scale = min_scale
        self.max_trials = max_trials
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() >= self.p or label.shape[0] == 0:
            return src, label
        arr = src.asnumpy() if isinstance(src, NDArray) else onp.asarray(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_trials):
            scale = pyrandom.uniform(self.min_scale, 1.0)
            cw, ch = int(w * scale), int(h * scale)
            x0 = pyrandom.randint(0, w - cw)
            y0 = pyrandom.randint(0, h - ch)
            cx = (label[:, 1] + label[:, 3]) / 2 * w
            cy = (label[:, 2] + label[:, 4]) / 2 * h
            keep = ((cx >= x0) & (cx < x0 + cw)
                    & (cy >= y0) & (cy < y0 + ch))
            if not keep.any():
                continue
            new = label[keep].copy()
            new[:, 1] = onp.clip((new[:, 1] * w - x0) / cw, 0, 1)
            new[:, 3] = onp.clip((new[:, 3] * w - x0) / cw, 0, 1)
            new[:, 2] = onp.clip((new[:, 2] * h - y0) / ch, 0, 1)
            new[:, 4] = onp.clip((new[:, 4] * h - y0) / ch, 0, 1)
            return NDArray(arr[y0:y0 + ch, x0:x0 + cw].copy()), new
        return src, label


class _ImgOnlyAug(DetAugmenter):
    """Adapt a plain image augmenter whose transform leaves normalized
    boxes invariant (uniform resize, color normalize)."""

    def __init__(self, aug) -> None:
        self.aug = aug

    def __call__(self, src, label):
        return self.aug(src), label


class DetColorNormalizeAug(DetAugmenter):
    def __init__(self, mean, std) -> None:
        self.mean = None if mean is None else onp.asarray(
            mean, dtype=onp.float32)
        self.std = None if std is None else onp.asarray(
            std, dtype=onp.float32)

    def __call__(self, src, label):
        arr = src.asnumpy() if isinstance(src, NDArray) \
            else onp.asarray(src)
        arr = arr.astype(onp.float32)
        if self.mean is not None:
            arr = arr - self.mean
        if self.std is not None:
            arr = arr / self.std
        return NDArray(arr), label


def CreateDetAugmenter(data_shape, resize: int = 0, rand_crop: float = 0,
                       rand_pad: float = 0, rand_mirror: bool = False,
                       mean=None, std=None, fill: float = 127.0,
                       **kwargs: Any) -> List[DetAugmenter]:
    """Build the standard detection augmenter chain (reference
    ``CreateDetAugmenter``): resize, random crop/pad, mirror, color
    normalization. mean/std may be True for ImageNet defaults."""
    augs: List[DetAugmenter] = []
    if resize > 0:
        augs.append(_ImgOnlyAug(ResizeAug(resize)))
    if rand_crop > 0:
        augs.append(DetRandomCropAug(p=rand_crop))
    if rand_pad > 0:
        augs.append(DetBorderAug(fill=fill))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if mean is True:
        mean = [123.68, 116.28, 103.53]
    if std is True:
        std = [58.395, 57.12, 57.375]
    if mean is not None or std is not None:
        augs.append(DetColorNormalizeAug(mean, std))
    return augs


class ImageDetIter(ImageIter):
    """Detection iterator: ImageIter transport + multi-object labels
    (reference ``mx.image.ImageDetIter``).

    Labels per batch come out as (batch, max_objects, object_width),
    padded with -1 rows (the reference's invalid-object marker).
    """

    def __init__(self, batch_size: int, data_shape, path_imgrec=None,
                 path_imglist=None, path_root: str = "", imglist=None,
                 aug_list: Optional[List[DetAugmenter]] = None,
                 max_objects: int = 16, object_width: int = 5,
                 **kwargs: Any) -> None:
        self._det_augs = aug_list or []
        self.max_objects = max_objects
        self.object_width = object_width
        kwargs.pop("label_width", None)
        from .image import ForceResizeAug
        c, hh, ww = data_shape
        # the transport resizes to the declared shape (normalized boxes
        # are resize-invariant); det augs then run per image in next()
        super().__init__(batch_size, data_shape,
                         label_width=max_objects * object_width,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         imglist=imglist,
                         aug_list=[ForceResizeAug((ww, hh)), CastAug()],
                         **kwargs)

    def _parse_det_label(self, raw) -> onp.ndarray:
        """Flat label -> (N_obj, object_width), reference header layout."""
        raw = onp.asarray(raw, dtype=onp.float32).ravel()
        if raw.size >= 2 and raw[0] >= 2 and raw[1] >= 5:
            a, b = int(raw[0]), int(raw[1])
            objs = raw[a:]
        else:                        # headerless: plain flat objects
            b = self.object_width
            objs = raw
        n = objs.size // b
        out = objs[: n * b].reshape(n, b)[:, :self.object_width]
        # the flat-label transport zero-pads: drop degenerate boxes
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
        return out[valid]

    def next(self):
        from ..io.io import DataBatch
        batch = super().next()
        data = batch.data[0]
        raw_labels = batch.label[0].asnumpy()
        B = data.shape[0]
        out_label = onp.full(
            (B, self.max_objects, self.object_width), -1.0,
            dtype=onp.float32)
        imgs = []
        for i in range(B):
            img = data[i].transpose((1, 2, 0))      # CHW -> HWC for augs
            label = self._parse_det_label(raw_labels[i])
            for aug in self._det_augs:
                img, label = aug(img, label)
            # back to the declared spatial size (crops change it)
            c, hh, ww = self.data_shape
            arr = img.asnumpy() if isinstance(img, NDArray) \
                else onp.asarray(img)
            if arr.shape[0] != hh or arr.shape[1] != ww:
                arr = imresize(NDArray(arr), ww, hh).asnumpy()
            imgs.append(arr.transpose((2, 0, 1)))
            n = min(label.shape[0], self.max_objects)
            out_label[i, :n] = label[:n]
        return DataBatch([NDArray(onp.stack(imgs))],
                         [NDArray(out_label)], pad=batch.pad)
