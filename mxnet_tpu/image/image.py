"""Image I/O, augmenters, and ImageIter.

Reference parity (leezu/mxnet): ``python/mxnet/image/image.py`` — decode
(``imdecode`` over OpenCV there, PIL here), geometry helpers
(``resize_short``, ``center_crop``, ``random_size_crop``), the ``Augmenter``
class hierarchy with ``CreateAugmenter``, and ``ImageIter`` reading
``.rec``/``.lst``/folder inputs.

Design (tpu-first): decode + augmentation are host-side (they feed the
device, as in the reference where OpenCV runs on CPU worker threads); the
pixel arithmetic goes through the ``nd.image`` XLA ops so the same code is
traceable when composed on-device. Batches come out NCHW float ready for a
``Mesh``-sharded training step.
"""
from __future__ import annotations

import io as _io
import logging
import os
import random as _pyrandom
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as _np

from ..base import MXNetError
from ..ndarray import image as ndimg
from ..ndarray.ndarray import NDArray, from_jax
from ..ndarray import ops as ndops
from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["imdecode", "imread", "imresize", "imrotate", "fixed_crop",
           "center_crop", "random_crop", "random_size_crop", "resize_short",
           "color_normalize", "scale_down", "Augmenter", "SequentialAug",
           "RandomOrderAug", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "HorizontalFlipAug",
           "CastAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "ColorNormalizeAug", "RandomGrayAug",
           "CreateAugmenter", "ImageIter"]


def _to_nd(arr: _np.ndarray) -> NDArray:
    import jax.numpy as jnp
    return from_jax(jnp.asarray(arr))


# ---------------------------------------------------------------------------
# Decode / basic geometry (reference: mx.image.imdecode & friends)
# ---------------------------------------------------------------------------

def imdecode(buf: Union[bytes, bytearray, _np.ndarray], flag: int = 1,
             to_rgb: bool = True, out=None) -> NDArray:
    """Decode an encoded image buffer to an HWC uint8 NDArray
    (reference: cv::imdecode-backed ``mx.image.imdecode``)."""
    from PIL import Image
    if isinstance(buf, _np.ndarray):
        buf = buf.tobytes()
    img = Image.open(_io.BytesIO(bytes(buf)))
    if flag == 0:
        img = img.convert("L")
        arr = _np.asarray(img, dtype=_np.uint8)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = _np.asarray(img, dtype=_np.uint8)
        if not to_rgb:
            arr = arr[:, :, ::-1].copy()  # BGR, matching cv2 default
    return _to_nd(arr)


def imread(filename: str, flag: int = 1, to_rgb: bool = True) -> NDArray:
    """Read and decode an image file (reference: ``mx.image.imread``)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    """Resize HWC image to (w, h) (reference: ``mx.image.imresize``)."""
    return ndimg.resize(src, (w, h), interp=interp)


def imrotate(src, rotation_degrees: float, zoom_in: bool = False,
             zoom_out: bool = False) -> NDArray:
    """Rotate an HWC image around its center
    (reference: ``mx.image.imrotate``)."""
    from PIL import Image
    arr = src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    img = Image.fromarray(arr.squeeze(-1) if squeeze else arr)
    out = _np.asarray(img.rotate(rotation_degrees, resample=Image.BILINEAR,
                                 expand=False), dtype=arr.dtype)
    if squeeze:
        out = out[:, :, None]
    return _to_nd(out)


def scale_down(src_size: Tuple[int, int], size: Tuple[int, int]
               ) -> Tuple[int, int]:
    """Shrink crop size to fit in src (reference: ``mx.image.scale_down``)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = w * sh // h, sh
    if sw < w:
        w, h = sw, h * sw // w
    return w, h


def resize_short(src, size: int, interp: int = 2) -> NDArray:
    """Resize so the shorter edge == size, preserving aspect
    (reference: ``mx.image.resize_short``)."""
    return ndimg.resize(src, size, keep_ratio=True, interp=interp)


def fixed_crop(src, x0: int, y0: int, w: int, h: int,
               size: Optional[Tuple[int, int]] = None,
               interp: int = 2) -> NDArray:
    """Crop then optionally resize (reference: ``mx.image.fixed_crop``)."""
    out = ndimg.crop(src, x0, y0, w, h)
    if size is not None and (w, h) != size:
        out = ndimg.resize(out, size, interp=interp)
    return out


def random_crop(src, size: Tuple[int, int], interp: int = 2):
    """Random crop (scaled down if needed); returns (img, (x, y, w, h))."""
    sh = src.shape
    w, h = scale_down((sh[1], sh[0]), size)
    x0 = _pyrandom.randint(0, sh[1] - w)
    y0 = _pyrandom.randint(0, sh[0] - h)
    return fixed_crop(src, x0, y0, w, h, size, interp), (x0, y0, w, h)


def center_crop(src, size: Tuple[int, int], interp: int = 2):
    """Center crop; returns (img, (x, y, w, h))
    (reference: ``mx.image.center_crop``)."""
    sh = src.shape
    w, h = scale_down((sh[1], sh[0]), size)
    x0 = (sh[1] - w) // 2
    y0 = (sh[0] - h) // 2
    return fixed_crop(src, x0, y0, w, h, size, interp), (x0, y0, w, h)


def random_size_crop(src, size: Tuple[int, int], area: Union[float, Tuple[float, float]],
                     ratio: Tuple[float, float], interp: int = 2, max_attempts: int = 10):
    """Random crop with area and aspect-ratio constraints
    (reference: ``mx.image.random_size_crop`` — the inception/ResNet aug)."""
    sh = src.shape
    src_area = sh[0] * sh[1]
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(max_attempts):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        w = int(round(_np.sqrt(target_area * aspect)))
        h = int(round(_np.sqrt(target_area / aspect)))
        if w <= sh[1] and h <= sh[0]:
            x0 = _pyrandom.randint(0, sh[1] - w)
            y0 = _pyrandom.randint(0, sh[0] - h)
            return fixed_crop(src, x0, y0, w, h, size, interp), (x0, y0, w, h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """Subtract mean / divide std on HWC float input
    (reference: ``mx.image.color_normalize``)."""
    src = src - (mean if isinstance(mean, NDArray) else ndops.array(_np.asarray(mean, dtype=_np.float32)))
    if std is not None:
        src = src / (std if isinstance(std, NDArray) else ndops.array(_np.asarray(std, dtype=_np.float32)))
    return src


# ---------------------------------------------------------------------------
# Augmenters (reference: mx.image.Augmenter hierarchy)
# ---------------------------------------------------------------------------

class Augmenter:
    """Image augmenter base (reference: ``mx.image.Augmenter``)."""

    def __init__(self, **kwargs) -> None:
        self._kwargs = kwargs

    def dumps(self) -> str:
        import json

        def clean(v):
            if isinstance(v, (_np.ndarray, NDArray)):
                return _np.asarray(v.asnumpy() if isinstance(v, NDArray)
                                   else v).tolist()
            return v
        kwargs = {k: clean(v) for k, v in self._kwargs.items()}
        return json.dumps([self.__class__.__name__.lower(), kwargs])

    def __call__(self, src: NDArray) -> NDArray:
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts: Sequence[Augmenter]) -> None:
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src: NDArray) -> NDArray:
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts: Sequence[Augmenter]) -> None:
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src: NDArray) -> NDArray:
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size: int, interp: int = 2) -> None:
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2) -> None:
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return ndimg.resize(src, self.size, interp=self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp: int = 2) -> None:
        super().__init__(size=size, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp: int = 2) -> None:
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.area, self.ratio, self.interp = area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp: int = 2) -> None:
        super().__init__(size=size, interp=interp)
        self.size = (size, size) if isinstance(size, int) else size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p: float = 0.5) -> None:
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        return ndimg.random_flip_left_right(src, self.p)


class CastAug(Augmenter):
    def __init__(self, typ: str = "float32") -> None:
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness: float) -> None:
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        return ndimg.random_brightness(src, 1 - self.brightness,
                                       1 + self.brightness)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast: float) -> None:
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        return ndimg.random_contrast(src, 1 - self.contrast,
                                     1 + self.contrast)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation: float) -> None:
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        return ndimg.random_saturation(src, 1 - self.saturation,
                                       1 + self.saturation)


class HueJitterAug(Augmenter):
    def __init__(self, hue: float) -> None:
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        return ndimg.random_hue(src, -self.hue, self.hue)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness: float, contrast: float,
                 saturation: float) -> None:
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd: float, eigval=None, eigvec=None) -> None:
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval, self.eigvec = eigval, eigvec

    def __call__(self, src):
        return ndimg.random_lighting(src, self.alphastd,
                                     eigval=self.eigval, eigvec=self.eigvec)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std) -> None:
        super().__init__(mean=mean, std=std)
        self.mean = _np.asarray(mean, dtype=_np.float32)
        self.std = _np.asarray(std, dtype=_np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, ndops.array(self.mean),
                               None if self.std is None else ndops.array(self.std))


class RandomGrayAug(Augmenter):
    def __init__(self, p: float = 0.5) -> None:
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            coef = ndops.array(_np.array([0.299, 0.587, 0.114],
                                         dtype=_np.float32))
            gray = (src.astype("float32") * coef).sum(axis=-1, keepdims=True)
            src = ndops.broadcast_to(gray, src.shape).astype(src.dtype)
        return src


def CreateAugmenter(data_shape: Tuple[int, int, int], resize: int = 0,
                    rand_crop: bool = False, rand_resize: bool = False,
                    rand_mirror: bool = False, mean=None, std=None,
                    brightness: float = 0, contrast: float = 0,
                    saturation: float = 0, hue: float = 0,
                    pca_noise: float = 0, rand_gray: float = 0,
                    inter_method: int = 2) -> List[Augmenter]:
    """Build the standard augmenter list (reference:
    ``mx.image.CreateAugmenter``); data_shape is CHW."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ---------------------------------------------------------------------------
# ImageIter (reference: mx.image.ImageIter)
# ---------------------------------------------------------------------------

class ImageIter(DataIter):
    """Image iterator over ``.rec`` files, ``.lst`` files, or an in-memory
    imglist, with pluggable augmenters (reference: ``mx.image.ImageIter`` —
    there a python loop over C-backed decode; here PIL decode + XLA aug ops).

    Emits NCHW float batches. ``path_imgrec`` expects records packed by
    ``tools/im2rec.py`` / ``mx.recordio.pack_img``.
    """

    def __init__(self, batch_size: int, data_shape: Tuple[int, int, int],
                 label_width: int = 1, path_imgrec: Optional[str] = None,
                 path_imglist: Optional[str] = None, path_root: str = "",
                 path_imgidx: Optional[str] = None, shuffle: bool = False,
                 part_index: int = 0, num_parts: int = 1,
                 aug_list: Optional[List[Augmenter]] = None,
                 imglist: Optional[List] = None,
                 data_name: str = "data", label_name: str = "softmax_label",
                 dtype: str = "float32", last_batch_handle: str = "pad",
                 **kwargs) -> None:
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be CHW")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.path_root = path_root
        self.shuffle = shuffle
        self.dtype = dtype
        self.last_batch_handle = last_batch_handle

        self._rec = None
        self.imglist = None
        if path_imgrec is not None:
            from ..recordio import MXIndexedRecordIO, MXRecordIO
            idx_path = path_imgidx or os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self._keys = list(self._rec.keys)
            else:
                # no index: slurp sequentially once
                rec = MXRecordIO(path_imgrec, "r")
                self._all_records = []
                while True:
                    s = rec.read()
                    if s is None:
                        break
                    self._all_records.append(s)
                rec.close()
                self._keys = list(range(len(self._all_records)))
        elif path_imglist is not None:
            self.imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    label = _np.array([float(v) for v in parts[1:-1]],
                                      dtype=_np.float32)
                    self.imglist.append((label, parts[-1]))
            self._keys = list(range(len(self.imglist)))
        elif imglist is not None:
            self.imglist = []
            for entry in imglist:
                label = _np.asarray(entry[0], dtype=_np.float32).reshape(-1)
                self.imglist.append((label, entry[1]))
            self._keys = list(range(len(self.imglist)))
        else:
            raise MXNetError(
                "one of path_imgrec, path_imglist, imglist is required")

        # sharding for distributed data loading (reference: part_index/num_parts)
        n = len(self._keys)
        per = n // num_parts
        start = part_index * per
        end = n if part_index == num_parts - 1 else start + per
        self._keys = self._keys[start:end]

        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in {"resize", "rand_crop",
                                                    "rand_resize", "rand_mirror",
                                                    "mean", "std", "brightness",
                                                    "contrast", "saturation",
                                                    "hue", "pca_noise",
                                                    "rand_gray", "inter_method"}})
        self.data_name, self.label_name = data_name, label_name
        self._order = list(range(len(self._keys)))
        self.reset()

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape, self.dtype)]

    @property
    def provide_label(self) -> List[DataDesc]:
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, "float32")]

    def reset(self) -> None:
        if self.shuffle:
            _pyrandom.shuffle(self._order)
        self._cursor = 0

    def _read_sample(self, key) -> Tuple[_np.ndarray, NDArray]:
        from ..recordio import unpack
        if self._rec is not None:
            s = self._rec.read_idx(key)
            header, buf = unpack(s)
            label = _np.asarray(header.label, dtype=_np.float32).reshape(-1)
            img = imdecode(buf)
        elif hasattr(self, "_all_records"):
            header, buf = unpack(self._all_records[key])
            label = _np.asarray(header.label, dtype=_np.float32).reshape(-1)
            img = imdecode(buf)
        else:
            label, src = self.imglist[key]
            if isinstance(src, str):
                img = imread(os.path.join(self.path_root, src))
            else:
                img = src if isinstance(src, NDArray) else _to_nd(_np.asarray(src))
        return label, img

    def next(self) -> DataBatch:
        if self._cursor >= len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), dtype=self.dtype)
        label = _np.zeros((self.batch_size, self.label_width),
                          dtype=_np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            if self._cursor >= len(self._order):
                if self.last_batch_handle == "discard":
                    raise StopIteration
                pad = self.batch_size - i
                break
            key = self._keys[self._order[self._cursor]]
            self._cursor += 1
            lab, img = self._read_sample(key)
            for aug in self.auglist:
                img = aug(img)
            arr = img.asnumpy()
            if arr.ndim == 2:
                arr = arr[:, :, None]
            if arr.shape[2] != c and c == 3 and arr.shape[2] == 1:
                arr = _np.repeat(arr, 3, axis=2)
            data[i] = arr.transpose(2, 0, 1).astype(self.dtype)
            label[i, :lab.shape[0]] = lab[:self.label_width]
            i += 1
        lab_out = label[:, 0] if self.label_width == 1 else label
        return DataBatch(data=[ndops.array(data)],
                         label=[ndops.array(lab_out)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
