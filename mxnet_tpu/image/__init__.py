"""``mx.image`` — image loading, augmentation, and iterators.

Reference parity: ``python/mxnet/image/image.py`` (ImageIter + augmenter
pipeline over C-backed OpenCV decode) and ``detection.py`` (ImageDetIter).
"""
from .image import (imdecode, imread, imresize, imrotate, fixed_crop,
                    center_crop, random_crop, random_size_crop, resize_short,
                    color_normalize, scale_down,
                    Augmenter, SequentialAug, RandomOrderAug, ResizeAug,
                    ForceResizeAug, RandomCropAug, RandomSizedCropAug,
                    CenterCropAug, HorizontalFlipAug, CastAug,
                    BrightnessJitterAug, ContrastJitterAug,
                    SaturationJitterAug, HueJitterAug, ColorJitterAug,
                    LightingAug, ColorNormalizeAug, RandomGrayAug,
                    CreateAugmenter, ImageIter)

from .detection import (ImageDetIter, DetHorizontalFlipAug,  # noqa: F401,E402
                        DetRandomCropAug, DetBorderAug,
                        CreateDetAugmenter)
