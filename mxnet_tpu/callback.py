"""Training callbacks (reference: ``python/mxnet/callback.py``).

``Speedometer`` prints samples/sec every N batches — the number the
BASELINE configs report (SURVEY.md section 5.5).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "ProgressBar", "module_checkpoint"]


class Speedometer:
    """Log throughput + metrics every ``frequent`` batches."""

    def __init__(self, batch_size: int, frequent: int = 50,
                 auto_reset: bool = True) -> None:
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param: Any) -> None:
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
                    metrics = "\t".join(f"{n}={v:.6f}" for n, v in name_value)
                    logging.info(msg, param.epoch, count, speed, metrics)
                else:
                    logging.info(
                        "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


def do_checkpoint(prefix: str, period: int = 1) -> Callable:
    """Epoch-end callback saving module checkpoints every ``period``."""
    period = int(max(1, period))

    def _callback(iter_no: int, sym: Any = None, arg: Any = None,
                  aux: Any = None) -> None:
        if (iter_no + 1) % period == 0:
            from .model import save_checkpoint
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux or {})

    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period: int, auto_reset: bool = False) -> Callable:
    def _callback(param: Any) -> None:
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            metrics = "\t".join(f"{n}={v:.6f}" for n, v in name_value)
            logging.info("Iter[%d] Batch[%d] Train-%s",
                         param.epoch, param.nbatch, metrics)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class ProgressBar:
    """Text progress bar batch callback."""

    def __init__(self, total: int, length: int = 80) -> None:
        self.total = total
        self.length = length

    def __call__(self, param: Any) -> None:
        count = param.nbatch
        filled = int(round(self.length * count / float(self.total)))
        pct = round(100.0 * count / float(self.total), 1)
        bar = "=" * filled + "-" * (self.length - filled)
        print(f"[{bar}] {pct}%", end="\r")
