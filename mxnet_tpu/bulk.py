"""Lazy eager-op bulking: fuse imperative dispatch into segment-compiled
XLA executables.

Reference parity (leezu/mxnet): the dependency engine's op bulking
(``Imperative`` bulk scope + ``CachedOp``, ``MXNET_EXEC_BULK_EXEC_*``) —
the reference batches runs of imperative engine pushes into one engine op
so Python returns immediately and the engine dispatches once per segment.

Design (tpu-first): eager dispatch no longer executes each op as its own
XLA program.  ``register.invoke`` appends a node (op name, impl, input
bindings, attr token) to a per-thread *pending segment* and returns
NDArrays backed by :class:`PendingBuffer` promises (shape/dtype known via
``jax.eval_shape``; no device work dispatched yet).  A segment flushes
when

* a host read forces materialization (``asnumpy``/``item``/any direct
  ``._data`` access — shape/dtype peeks do NOT force),
* it reaches ``MXNET_BULK_MAX_OPS`` ops (1 = bulking off, the previous
  per-op dispatch),
* an un-jittable op or an in-place write to a pending buffer arrives,
* ``engine.waitall()`` or an autograd ``backward()`` boundary requires
  it.

On flush the segment's nodes (appended in program order, which IS a
topological order of the segment DAG) are traced once as a single
function, jitted, and the compiled callable is cached by *segment
signature* (op sequence + attr tokens + input binding structure + output
liveness; ``jax.jit`` keys input avals internally).  With
``MXNET_COMPILE_CACHE_DIR`` set, un-recorded segment executables are
additionally persisted AOT through :mod:`mxnet_tpu.compile_cache`, so a
restarted process replays them with zero XLA compiles (recorded
segments keep the in-memory path — their vjp closures do not
serialize).  Steady-state
training replays one fused executable per segment instead of N per-op
dispatches, and XLA fuses elementwise chains (optimizer updates, loss
arithmetic, LSTM cell math) that previously crossed executable
boundaries.

Autograd: with ``MXNET_BULK_AUTOGRAD=fused`` (default) recorded ops stay
bulked — the flush runs ``jax.vjp`` over the whole segment function and
installs ONE TapeNode whose pullback maps segment-output cotangents to
segment-input cotangents (the fused analog of per-op TapeNodes; backward
dispatches it as one compiled program).  A recorded op consuming a
*pending un-recorded* value flushes first, so gradients never flow
through ops the per-op tape would not have recorded.  ``off`` forces
per-op dispatch inside ``record()`` scopes.

Mutation hazards: external inputs are captured *by value* at append time
(the raw buffer object), so a later in-place rebind of an input wrapper
cannot corrupt a pending node — eager call-time semantics are preserved
without ordering constraints.  Writing INTO a wrapper whose own buffer
is still pending (``x[k] = v``) flushes first (reason ``mutation``).

Numerics: a fused segment lets XLA contract patterns like ``a*b + c``
into a single FMA, so results can differ from per-op dispatch in the
last ulp — the same property hybridize has today.  Replays of the same
segment signature are bit-identical; see docs/performance.md.
"""
from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as _onp

from . import engine
from . import metrics as _metrics
from . import tracing as _tracing
from ._tape import TapeNode, is_recording
from .base import MXNetError, getenv, register_env

__all__ = ["PendingBuffer", "NOT_BULKED", "active", "max_ops",
           "set_max_ops", "flush_all", "flush_current", "flush_holding",
           "flush_recorded", "backward_segments_mode", "bulk_stats",
           "reset_caches"]

register_env("MXNET_BULK_MAX_OPS", 16,
             "Eager-op bulking segment size: imperative dispatch defers "
             "up to this many ops into one pending segment, then compiles "
             "and dispatches them as a single fused XLA executable. 1 "
             "disables bulking (per-op dispatch, the pre-bulking "
             "behavior). engine.set_bulk_size()/engine.bulk scope the "
             "same knob at runtime.")
register_env("MXNET_BULK_AUTOGRAD", "fused",
             "Bulking behavior inside autograd.record() scopes: 'fused' "
             "(default) keeps recorded ops bulked and differentiates the "
             "whole segment with one jax.vjp (one fused TapeNode); 'off' "
             "forces per-op dispatch while recording.")
register_env("MXNET_BULK_BACKWARD_SEGMENTS", "param",
             "Backward granularity of fused-autograd bulking: 'param' "
             "(default) closes the recorded segment whenever the op "
             "stream crosses a parameter boundary (a recorded op "
             "consuming a fresh attach_grad leaf) once the segment has "
             "captured at least MXNET_KV_BUCKET_BYTES of parameter "
             "bytes (the coalescing floor: layers smaller than one "
             "reduction bucket share a segment, so tiny models keep one "
             "fused backward and deep models cannot blow the segment "
             "cache).  The resulting chain of per-layer TapeNodes "
             "replays backward layer-by-layer in reverse, so parameter "
             "gradients materialize incrementally and the overlapped "
             "kvstore scheduler can stream reduction buckets DURING "
             "backward instead of only under optimizer compute.  'off' "
             "keeps the whole recorded run as one fused segment "
             "(pre-segmentation behavior).  Re-cut segments move XLA "
             "fusion (FMA) boundaries: losses match the monolithic "
             "backward to float ulp, replays of the same mode are "
             "bit-identical (see docs/performance.md).")

# runtime-settable copies of the env knobs (env read once, lazily)
_state: Dict[str, Any] = {"max_ops": None, "autograd": None}

# distinct-signature churn guard: an op whose attr token varies call to
# call would force a fresh segment compile per flush — after this many
# cache-missing flushes containing the same (op, code) the op is
# dispatched per-op instead (a cache hit clears its count).
_CHURN_LIMIT = 16

_SEG_CACHE_CAP = 256        # compiled segment executables (LRU)
_AVAL_CACHE_CAP = 4096      # eval_shape results
_POISON_CAP = 1024          # trace-failed signatures

NOT_BULKED = object()       # try_append result: caller takes per-op path


def max_ops() -> int:
    n = _state["max_ops"]
    if n is None:
        n = _state["max_ops"] = int(getenv("MXNET_BULK_MAX_OPS", 16))
    return n


def set_max_ops(n: int) -> int:
    """Set the bulking segment cap; returns the previous value.
    ``n <= 1`` disables bulking for subsequent ops (it does not flush
    an already-pending segment by itself)."""
    prev = max_ops()
    _state["max_ops"] = int(n)
    return prev


def _autograd_mode() -> str:
    m = _state["autograd"]
    if m is None:
        m = _state["autograd"] = getenv("MXNET_BULK_AUTOGRAD", "fused")
    return m


def backward_segments_mode() -> str:
    """'param' cuts recorded segments at parameter boundaries (subject
    to the coalescing floor), 'off' keeps one fused backward segment.
    Read live (not cached like max_ops): the dist-comm smoke and tests
    A/B the modes within one process."""
    m = getenv("MXNET_BULK_BACKWARD_SEGMENTS", "param")
    return m if m in ("param", "off") else "param"


def _segment_floor_bytes() -> int:
    """The coalescing floor for param-boundary cuts: segments keep
    absorbing layers until they hold one reduction bucket's worth of
    parameter bytes (MXNET_KV_BUCKET_BYTES), so per-layer cutting on a
    deep model of small layers neither blows the segment LRU nor
    recompiles per step — the segment grid stays O(model_bytes /
    bucket_bytes)."""
    try:
        return max(1, int(getenv("MXNET_KV_BUCKET_BYTES", 4 << 20)))
    except (TypeError, ValueError):
        return 4 << 20


def active() -> bool:
    """Bulking engages only when the segment cap exceeds one op and the
    engine is not in fully-synchronous NaiveEngine mode."""
    return max_ops() > 1 and not engine.is_naive()


# ---------------------------------------------------------------------------
# Pending buffers and segment nodes
# ---------------------------------------------------------------------------

_FAILED = object()   # PendingBuffer.value after a failed flush


class PendingBuffer:
    """A promised device buffer: the not-yet-materialized output of a
    pending segment node.  Carries the abstract value (shape/dtype/
    weak_type from ``jax.eval_shape``) so shape queries and dispatch
    never force materialization; any concrete read flushes the owning
    segment, after which :attr:`value` holds the real array."""

    __slots__ = ("shape", "dtype", "weak_type", "segment", "ni", "oi",
                 "value", "wref", "__weakref__")

    def __init__(self, sds: Any, segment: "Segment", ni: int,
                 oi: int) -> None:
        self.shape = tuple(sds.shape)
        self.dtype = sds.dtype
        self.weak_type = bool(getattr(sds, "weak_type", False))
        self.segment = segment
        self.ni = ni            # producing node index within the segment
        self.oi = oi            # output index within that node
        self.value = None       # concrete array once flushed
        self.wref = None        # weakref to the owning NDArray wrapper

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def force(self, reason: str = "host_read") -> Any:
        """Materialize: flush the owning segment (idempotent) and return
        the concrete array."""
        v = self.value
        if v is None:
            self.segment.flush(reason)
            v = self.value
        if v is _FAILED or v is None:
            raise MXNetError(
                "pending bulked segment failed to execute; the promised "
                f"buffer (shape {self.shape}, {self.dtype}) is lost: "
                f"{self.segment.error or 'unknown error'}")
        return v


class _Node:
    __slots__ = ("name", "impl", "token", "ins", "single", "out_sds",
                 "out_phs", "tainted", "ctx")

    def __init__(self, name, impl, token, ins, single, out_sds, tainted,
                 ctx=None):
        self.name = name
        self.impl = impl
        self.token = token
        self.ins = ins            # tuple of ('e', ext_idx) | ('n', ni, oi)
        self.single = single      # impl returned one array, not a tuple
        self.out_sds = out_sds    # tuple of ShapeDtypeStruct
        self.out_phs: List[Any] = []   # weakrefs to PendingBuffers
        self.tainted = tainted    # recorded: on the autograd tape
        self.ctx = ctx            # Context the outputs report


# live (unflushed) segments, all threads — waitall/backward flush them
_REG_LOCK = threading.Lock()
_LIVE_SEGMENTS: Dict[int, "Segment"] = {}

_TLS = threading.local()


class Segment:
    """One pending run of bulked ops owned by a single dispatching
    thread.  Appends happen only on the owner thread; a flush may come
    from any thread (cross-thread read, waitall) — both serialize on
    ``lock``."""

    __slots__ = ("nodes", "ext", "ext_wrappers", "ext_ids", "flushed",
                 "lock", "error", "leaf_ids", "param_bytes", "n_tainted",
                 "bwd_mode", "bwd_floor", "__weakref__")

    def __init__(self) -> None:
        self.nodes: List[_Node] = []
        self.ext: List[Any] = []            # captured raw input arrays
        self.ext_wrappers: List[Any] = []   # NDArray wrappers (tape ids)
        self.ext_ids: Dict[Tuple[int, int], int] = {}  # (wrapper,raw) ids
        self.flushed = False
        self.lock = threading.RLock()
        self.error: Optional[str] = None
        # backward segmentation bookkeeping: which attach_grad leaves
        # (parameters) this segment captured, and their raw byte total —
        # the param-boundary cut in try_append fires only once
        # param_bytes clears the coalescing floor
        self.leaf_ids: set = set()
        self.param_bytes = 0
        self.n_tainted = 0                  # recorded nodes appended
        # segmentation knobs resolved lazily, ONCE per segment (a
        # per-op env read would tax the whole dispatch hot path; a
        # segment's mode must not flip mid-build anyway, and tests
        # that monkeypatch the env get fresh segments constantly)
        self.bwd_mode: Optional[str] = None
        self.bwd_floor = 0
        with _REG_LOCK:
            _LIVE_SEGMENTS[id(self)] = self

    def ext_index(self, wrapper: Any, raw: Any) -> int:
        # key on BOTH identities: the same wrapper can be re-captured
        # with a different buffer if it was rebound between appends
        # (e.g. checkpoint restore set_data while a segment from the
        # settle forward was still pending) — each (wrapper, value)
        # pair is its own external input, value captured at append time
        key = (id(wrapper), id(raw))
        idx = self.ext_ids.get(key)
        if idx is None:
            idx = len(self.ext)
            self.ext_ids[key] = idx
            self.ext.append(raw)
            self.ext_wrappers.append(wrapper)
            if getattr(wrapper, "_grad_req", "null") != "null" and \
                    id(wrapper) not in self.leaf_ids:
                self.leaf_ids.add(id(wrapper))
                try:
                    self.param_bytes += int(raw.size) * int(
                        getattr(raw.dtype, "itemsize", 4))
                except Exception:   # noqa: BLE001 - sizeless capture
                    pass
        return idx

    # -- flush ---------------------------------------------------------
    def flush(self, reason: str) -> None:
        with self.lock:
            if self.flushed:
                return
            self.flushed = True
            nodes = self.nodes
            if not nodes:
                self._release()
                return
            _metrics.inc_bulk_segment(reason)
            _metrics.BULK_OPS_PER_SEGMENT.observe(len(nodes))
            # liveness: a node output is returned only while its promise
            # is still reachable (someone can still read it); dead
            # promises become XLA dead code inside the fused program
            returns: List[Tuple[int, int]] = []
            phs: List[PendingBuffer] = []
            for ni, node in enumerate(nodes):
                for oi, ref in enumerate(node.out_phs):
                    ph = ref()
                    if ph is not None and ph.value is None:
                        returns.append((ni, oi))
                        phs.append(ph)
            try:
                if returns:
                    # child of whatever step/backward span is active;
                    # reason="param_boundary" marks the per-layer
                    # backward segments
                    with _tracing.child_span("bulk.segment",
                                             reason=reason,
                                             ops=len(nodes)):
                        self._execute(nodes, returns, phs)
            except BaseException as exc:
                self.error = f"{type(exc).__name__}: {exc}"
                for ph in phs:
                    if ph.value is None:
                        ph.value = _FAILED
                raise
            finally:
                self._release()

    def _release(self) -> None:
        self.nodes = []
        self.ext = []
        self.ext_wrappers = []
        self.ext_ids = {}
        with _REG_LOCK:
            _LIVE_SEGMENTS.pop(id(self), None)

    def _execute(self, nodes, returns, phs) -> None:
        any_tainted = any(n.tainted for n in nodes)
        sig = (tuple((n.name, n.token, n.ins) for n in nodes),
               tuple(returns), any_tainted)
        if sig in _SEG_POISON:
            self._run_sequential(nodes, returns, phs)
            return
        fn = _SEG_CACHE.get(sig)
        if fn is not None:
            _SEG_CACHE.move_to_end(sig)
            _metrics.BULK_CACHE_HITS.inc()
            # attrs repeat: these ops are not the per-call-varying
            # pattern the churn guard targets
            for n in nodes:
                _CHURN_COUNT.pop((n.name, _token_head(n.token)), None)
        else:
            _metrics.BULK_CACHE_MISSES.inc()
            seg_fn = _make_seg_fn(
                [(n.impl, n.ins, n.single) for n in nodes], returns)
            if any_tainted:
                # recorded segments stay on the in-memory jit path:
                # their vjp closure (a tree_util.Partial over local
                # functions) cannot be serialized to disk
                fn = jax.jit(lambda *xs: jax.vjp(seg_fn, *xs))
            else:
                from . import compile_cache as _cc
                fn = _cc.persistently_cached(jax.jit(seg_fn),
                                             surface="bulk")
            _SEG_CACHE[sig] = fn
            if len(_SEG_CACHE) > _SEG_CACHE_CAP:
                _SEG_CACHE.popitem(last=False)
            _metrics.BULK_CACHE_SIZE.set(len(_SEG_CACHE))
            # churn guard: count only NOVEL attr tokens per (op, code)
            # with no intervening cache hit — that is the signature of a
            # per-call-varying attr (annealed scalar) compiling a fresh
            # segment every flush.  Segment-shape diversity with
            # repeated tokens does not count.
            for n in nodes:
                key = (n.name, _token_head(n.token))
                seen = _CHURN_SEEN.get(key)
                if seen is None:
                    seen = _CHURN_SEEN[key] = set()
                if n.token not in seen:
                    if len(seen) > 4 * _CHURN_LIMIT:
                        seen.clear()
                    seen.add(n.token)
                    c = _CHURN_COUNT[key] = _CHURN_COUNT.get(key, 0) + 1
                    if c > _CHURN_LIMIT:
                        _BULK_EAGER.add(key)
        try:
            if any_tainted:
                outs, vjp_fn = fn(*self.ext)
            else:
                outs, vjp_fn = fn(*self.ext), None
        except jax.errors.JAXTypeError:
            # the segment needs concrete values somewhere eval_shape did
            # not catch — poison this signature and run per-op eagerly
            _SEG_POISON.add(sig)
            _SEG_CACHE.pop(sig, None)
            self._run_sequential(nodes, returns, phs)
            return
        engine.mark_clean(list(outs))
        for ph, arr in zip(phs, outs):
            ph.value = arr
            engine.track(arr)
        if any_tainted:
            self._install_tape(nodes, phs, vjp_fn)

    def _install_tape(self, nodes, phs, vjp_fn) -> None:
        """One fused TapeNode for the whole segment: cotangents of the
        live outputs map to cotangents of the external inputs.  Only
        recorded (tainted) outputs join the tape; un-recorded slots keep
        a None out_arrays entry so a cotangent later accumulated on such
        a wrapper (it has no _ag_node) can never leak into this node's
        pullback — matching per-op semantics where un-recorded ops have
        no TapeNode at all."""
        avals = [(ph.shape, ph.dtype) for ph in phs]
        node = TapeNode("_bulk_segment", vjp_fn, list(self.ext_wrappers),
                        avals, out_is_tuple=True)
        node.jit_pull = True
        outs: List[Any] = []
        for idx, ph in enumerate(phs):
            w = ph.wref() if ph.wref is not None else None
            if nodes[ph.ni].tainted and w is not None and w._buf is ph:
                outs.append(weakref.ref(w))
                w._ag_node = node
                w._ag_out_idx = idx
            else:
                outs.append(None)
        node.out_arrays = outs

    def _run_sequential(self, nodes, returns, phs) -> None:
        """Per-op fallback for trace-poisoned segments: execute node by
        node (per-op TapeNodes for recorded ops), preserving exact
        pre-bulking semantics."""
        vals: List[Tuple[Any, ...]] = []
        tape_nodes: Dict[int, TapeNode] = {}
        # (ni, oi) -> stub wrapper standing in for an intermediate whose
        # NDArray died (or was rebound) before the flush.  Stubs are
        # SHARED across consumers and linked to their producer's
        # TapeNode, so the backward chain through a dead temporary stays
        # connected exactly as per-op dispatch kept it (the consumer's
        # TapeNode.inputs strong ref keeps the stub alive).
        stubs: Dict[Tuple[int, int], Any] = {}

        def _node_wrapper(ni, oi):
            ref = nodes[ni].out_phs[oi]()
            w = ref.wref() if ref is not None and ref.wref is not None \
                else None
            if w is not None and w._buf is ref:
                return w
            stub = stubs.get((ni, oi))
            if stub is None:
                stub = _ndarray_cls()(vals[ni][oi], _wrap=True)
                ptn = tape_nodes.get(ni)
                if ptn is not None:
                    stub._ag_node = ptn
                    stub._ag_out_idx = oi
                    ptn.out_arrays[oi] = weakref.ref(stub)
                stubs[(ni, oi)] = stub
            return stub

        for ni, node in enumerate(nodes):
            ins = [self.ext[d[1]] if d[0] == "e" else vals[d[1]][d[2]]
                   for d in node.ins]
            if node.tainted:
                outs, vjp_fn = jax.vjp(node.impl, *ins)
            else:
                outs, vjp_fn = node.impl(*ins), None
            outs_t = (outs,) if node.single else tuple(outs)
            vals.append(outs_t)
            if node.tainted:
                in_wrappers = [
                    self.ext_wrappers[d[1]] if d[0] == "e"
                    else _node_wrapper(d[1], d[2]) for d in node.ins]
                tn = TapeNode(node.name, vjp_fn, in_wrappers,
                              [(tuple(o.shape), o.dtype) for o in outs_t],
                              out_is_tuple=not node.single)
                tn.out_arrays = [None] * len(outs_t)
                tape_nodes[ni] = tn
        for (ni, oi), ph in zip(returns, phs):
            ph.value = vals[ni][oi]
            engine.track(ph.value)
            tn = tape_nodes.get(ni)
            if tn is not None:
                w = ph.wref() if ph.wref is not None else None
                if w is not None and w._buf is ph:
                    tn.out_arrays[oi] = weakref.ref(w)
                    w._ag_node = tn
                    w._ag_out_idx = oi


def _make_seg_fn(plan, returns):
    """Build the single traced function for a segment.  ``plan`` holds
    (impl, input bindings, single-output flag) per node in program
    (= topological) order; the function is pure over the external
    arrays, so one jax.jit covers the whole run of ops."""
    def seg_fn(*ext):
        vals = []
        for impl, ins, single in plan:
            args = [ext[d[1]] if d[0] == "e" else vals[d[1]][d[2]]
                    for d in ins]
            out = impl(*args)
            vals.append((out,) if single else tuple(out))
        return tuple(vals[ni][oi] for ni, oi in returns)
    return seg_fn


def _token_head(token):
    return token[0] if isinstance(token, tuple) and token else token


class _LruSet:
    """Bounded membership set with incremental (oldest-first) eviction
    — a wholesale clear at cap would make every known entry re-pay its
    discovery cost at once (the clear-at-cap cliff this PR removes from
    the SPMD scalar cache)."""

    __slots__ = ("_cap", "_d")

    def __init__(self, cap: int) -> None:
        self._cap = cap
        self._d: "OrderedDict[Any, None]" = OrderedDict()

    def __contains__(self, key: Any) -> bool:
        if key in self._d:
            self._d.move_to_end(key)
            return True
        return False

    def __len__(self) -> int:
        return len(self._d)

    def add(self, key: Any) -> None:
        self._d[key] = None
        if len(self._d) > self._cap:
            self._d.popitem(last=False)

    def clear(self) -> None:
        self._d.clear()


# segment signature -> compiled callable (LRU)
_SEG_CACHE: "OrderedDict[Any, Callable]" = OrderedDict()
_SEG_POISON = _LruSet(_POISON_CAP)
_CHURN_COUNT: Dict[Any, int] = {}
_CHURN_SEEN: Dict[Any, set] = {}
_BULK_EAGER: set = set()

# (name, token, in-aval key) -> (tuple_of_sds, single) | _AVAL_BAD (LRU)
_AVAL_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_AVAL_BAD = object()

_ND_CLS = [None]


def _ndarray_cls():
    cls = _ND_CLS[0]
    if cls is None:
        from .ndarray.ndarray import NDArray
        cls = _ND_CLS[0] = NDArray
    return cls


def _sds_of(raw: Any) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(
        _onp.shape(raw), getattr(raw, "dtype", None),
        weak_type=bool(getattr(raw, "weak_type", False)))


def _out_avals(name, impl, token, in_sds):
    """eval_shape with memoization — the per-append cost collapses to a
    dict lookup in steady state."""
    key = (name, token, tuple((s.shape, str(s.dtype), s.weak_type)
                              for s in in_sds))
    got = _AVAL_CACHE.get(key)
    if got is not None:
        _AVAL_CACHE.move_to_end(key)
    else:
        if len(_AVAL_CACHE) > _AVAL_CACHE_CAP:
            _AVAL_CACHE.popitem(last=False)
        try:
            out = jax.eval_shape(impl, *in_sds)
        except Exception:   # noqa: BLE001 - any trace failure => eager op
            _AVAL_CACHE[key] = got = _AVAL_BAD
        else:
            single = not isinstance(out, (tuple, list))
            outs = (out,) if single else tuple(out)
            if any(not isinstance(o, jax.ShapeDtypeStruct) for o in outs):
                _AVAL_CACHE[key] = got = _AVAL_BAD
            else:
                _AVAL_CACHE[key] = got = (outs, single)
    return got


# ---------------------------------------------------------------------------
# The dispatch hook
# ---------------------------------------------------------------------------

def _current_segment() -> Segment:
    seg = getattr(_TLS, "segment", None)
    if seg is None or seg.flushed:
        seg = _TLS.segment = Segment()
    return seg


def _flush_pending_inputs(inputs, reason: str) -> None:
    for x in inputs:
        buf = getattr(x, "_buf", None)   # sparse wrappers have no slot
        if type(buf) is PendingBuffer and buf.value is None:
            buf.segment.flush(reason)


def try_append(name: str, impl: Callable, token: Any,
               inputs: Sequence[Any], ctx: Any) -> Any:
    """Append one op to the calling thread's pending segment; returns
    the promised NDArray output(s), or NOT_BULKED when the op must take
    the per-op path (the caller then reads ``._data``, which flushes any
    pending inputs)."""
    if token is None:   # attrs hold arrays/objects: unjittable closure
        _flush_pending_inputs(inputs, "unjittable")
        return NOT_BULKED
    if (name, _token_head(token)) in _BULK_EAGER:
        _flush_pending_inputs(inputs, "unjittable")
        return NOT_BULKED

    recording = is_recording()
    if recording and _autograd_mode() != "fused":
        _flush_pending_inputs(inputs, "autograd")
        return NOT_BULKED

    seg = _current_segment()
    # resolve inputs: concrete ext captures vs in-segment node refs
    resolved: List[Tuple] = []   # ('e', wrapper, raw) | ('n', ni, oi)
    in_sds: List[Any] = []
    tainted = False
    for x in inputs:
        buf = getattr(x, "_buf", None)
        if buf is None:
            # sparse wrappers (no raw buffer slot): per-op path — their
            # dense fallback warning and storage handling stay intact
            _flush_pending_inputs(inputs, "unjittable")
            return NOT_BULKED
        if type(buf) is PendingBuffer:
            if buf.value is None and buf.segment is seg \
                    and not seg.flushed \
                    and not (recording and (x._ag_node is not None
                                            or x._grad_req != "null")):
                try:
                    node = seg.nodes[buf.ni]
                except IndexError:
                    # raced a cross-thread flush that cleared the node
                    # list — the promise now has (or will have) a value
                    node = None
                if node is not None:
                    if recording and node.tainted:
                        tainted = True
                    resolved.append(("n", buf.ni, buf.oi))
                    in_sds.append(node.out_sds[buf.oi])
                    continue
            # Materialize (raises if that segment failed): the value was
            # flushed earlier, is pending on another thread's segment, or
            # carries an out-of-band tape attachment (autograd.Function
            # output, attach_grad mid-chain) whose node/leaf status is
            # invisible to the segment — it must participate as a real
            # external tape input.  Any stale node-ref entries this
            # leaves in `resolved` are discarded by the flushed-segment
            # retry below.
            buf = buf.force("autograd" if recording else "cross_thread")
        if isinstance(buf, jax.core.Tracer):
            return NOT_BULKED   # inside a hybridize/jit trace: run inline
        if recording and x._on_tape:
            tainted = True
        resolved.append(("e", x, buf))
        in_sds.append(_sds_of(buf))

    if tainted:
        # a recorded op must not consume a pending un-recorded value:
        # the fused vjp would differentiate through ops the per-op tape
        # never recorded — flush those first (they become concrete
        # external inputs, where the gradient correctly stops)
        try:
            mixed = any(d[0] == "n" and not seg.nodes[d[1]].tainted
                        for d in resolved)
        except IndexError:      # raced a cross-thread flush
            mixed = True
        if mixed:
            seg.flush("autograd")
            return try_append(name, impl, token, inputs, ctx)

        # per-layer backward segmentation (MXNET_BULK_BACKWARD_SEGMENTS
        # =param): a recorded op consuming a FRESH attach_grad leaf (a
        # parameter this segment has not captured) marks a layer
        # boundary.  Once the segment holds a reduction bucket's worth
        # of parameter bytes (the coalescing floor), close it — the
        # fused vjp chain then replays backward layer-by-layer in
        # reverse, each sub-segment's parameter gradients materialize
        # individually, and the overlapped kvstore scheduler streams
        # their buckets while the rest of backward still runs.
        if seg.n_tainted:
            mode = seg.bwd_mode
            if mode is None:
                mode = seg.bwd_mode = backward_segments_mode()
                seg.bwd_floor = _segment_floor_bytes()
            if mode == "param":
                fresh = any(
                    d[0] == "e"
                    and getattr(d[1], "_grad_req", "null") != "null"
                    and id(d[1]) not in seg.leaf_ids
                    for d in resolved)
                if fresh:
                    if seg.param_bytes >= seg.bwd_floor:
                        _metrics.inc_backward_segment("param_boundary")
                        seg.flush("param_boundary")
                        return try_append(name, impl, token, inputs,
                                          ctx)
                    _metrics.inc_backward_segment("coalesced")

    got = _out_avals(name, impl, token, in_sds)
    if got is _AVAL_BAD:
        _flush_pending_inputs(inputs, "unjittable")
        return NOT_BULKED
    out_sds, single = got

    if ctx is None:
        # promised wrappers need a Context that does not require reading
        # the (not yet existing) buffer: derive it per NODE — from the
        # op's own first concrete input, else inherited from the
        # producing node of its first in-segment input (a per-segment
        # ctx would mislabel outputs of later ops whose inputs live on
        # a different device)
        for d in resolved:
            if d[0] == "e":
                if d[1]._ctx is not None:
                    ctx = d[1]._ctx
                else:
                    from .ndarray.ndarray import _ctx_from_data
                    ctx = _ctx_from_data(d[2])
                break
        else:
            for d in resolved:
                if d[0] == "n":
                    try:
                        ctx = seg.nodes[d[1]].ctx
                    except IndexError:   # raced a cross-thread flush
                        ctx = None
                    break

    NDArray = _ndarray_cls()
    with seg.lock:
        if seg.flushed:     # raced with a cross-thread flush: retry
            return try_append(name, impl, token, inputs, ctx)
        ins = tuple(("e", seg.ext_index(d[1], d[2])) if d[0] == "e"
                    else d for d in resolved)
        node = _Node(name, impl, token, ins, single, out_sds, tainted,
                     ctx=ctx)
        seg.nodes.append(node)
        if tainted:
            seg.n_tainted += 1
        ni = len(seg.nodes) - 1
        wrapped = []
        for oi, sds in enumerate(out_sds):
            ph = PendingBuffer(sds, seg, ni, oi)
            node.out_phs.append(weakref.ref(ph))
            w = NDArray(ph, ctx=ctx, _wrap=True)
            ph.wref = weakref.ref(w)
            wrapped.append(w)
        if ni + 1 >= max_ops():
            seg.flush("max_ops")
    return wrapped[0] if single else tuple(wrapped)


# ---------------------------------------------------------------------------
# Flush entry points / stats
# ---------------------------------------------------------------------------

def flush_current(reason: str = "host_read") -> None:
    """Flush the calling thread's pending segment, if any."""
    seg = getattr(_TLS, "segment", None)
    if seg is not None and not seg.flushed:
        seg.flush(reason)


def flush_all(reason: str = "waitall") -> None:
    """Flush every live segment across all threads (waitall, backward,
    and buffer-donation barriers)."""
    with _REG_LOCK:
        segs = list(_LIVE_SEGMENTS.values())
    for seg in segs:
        seg.flush(reason)


def flush_holding(arrays: Any, reason: str = "mutation") -> None:
    """Targeted donation barrier: flush only the live segments that
    captured any of ``arrays`` (raw device buffers, matched by identity)
    as an external input, plus the calling thread's own segment.

    The per-step donation barriers (``SPMDTrainer.step``/``run_steps``,
    the gluon trainer's fused update) used to ``flush_all``: sound, but
    it force-segmented EVERY thread's pending work once per step —
    with the async input pipeline that meant the prefetch thread's
    in-build preprocessing segment was cut mid-batch at step cadence
    (serializing exactly the work the pipeline exists to overlap, and
    churning the segment cache with truncated signatures).  A segment
    that never captured a donated buffer cannot read deleted memory, so
    it may keep building; the caller's own segment is always flushed —
    it is the one that traced through the params being donated, and the
    id-scan would miss a buffer captured between scan and donation on
    this same thread."""
    ids = {id(a) for a in arrays if a is not None}
    flush_current(reason)
    with _REG_LOCK:
        segs = list(_LIVE_SEGMENTS.values())
    own = getattr(_TLS, "segment", None)
    for seg in segs:
        if seg is own or seg.flushed:
            continue
        with seg.lock:
            if any(id(raw) in ids for raw in seg.ext):
                seg.flush(reason)


def flush_recorded(reason: str = "autograd") -> None:
    """Autograd barrier: flush the calling thread's segment plus every
    live segment holding a RECORDED (tainted) node — those must install
    their fused TapeNodes before the tape is walked.  An unrecorded
    segment on another thread (the prefetch thread's in-build
    preprocessing, a serving worker between requests) has nothing on
    the tape and may keep building; any value of theirs this thread's
    graph consumed was already forced at the cross-thread read."""
    flush_current(reason)
    with _REG_LOCK:
        segs = list(_LIVE_SEGMENTS.values())
    own = getattr(_TLS, "segment", None)
    for seg in segs:
        if seg is own or seg.flushed:
            continue
        with seg.lock:
            if any(n.tainted for n in seg.nodes):
                seg.flush(reason)


def bulk_stats() -> Dict[str, float]:
    """Snapshot of the bulking surface (exec_cache_stats feeds this into
    tools and the serving health endpoint)."""
    return {
        "bulk_cache_size": len(_SEG_CACHE),
        "bulk_cache_hits": _metrics.BULK_CACHE_HITS.value,
        "bulk_cache_misses": _metrics.BULK_CACHE_MISSES.value,
    }


def reset_caches() -> None:
    """Flush pending work and drop every compiled-segment / aval / churn
    cache (test isolation)."""
    flush_all("waitall")
    _SEG_CACHE.clear()
    _SEG_POISON.clear()
    _CHURN_COUNT.clear()
    _CHURN_SEEN.clear()
    _BULK_EAGER.clear()
    _AVAL_CACHE.clear()
    _metrics.BULK_CACHE_SIZE.set(0)
