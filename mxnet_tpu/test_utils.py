"""Testing toolkit — the framework's own test primitives.

Reference parity (leezu/mxnet): ``python/mxnet/test_utils.py`` —
``assert_almost_equal`` with per-dtype tolerances, ``check_numeric_gradient``
(finite differences vs autograd), ``check_consistency`` (cross-context
comparison: here cpu vs tpu), ``rand_ndarray``, ``default_context``.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as _np

from .base import register_env
from .context import Context, cpu, tpu
from .ndarray.ndarray import NDArray

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "rand_ndarray", "rand_shape_nd",
    "check_numeric_gradient", "check_consistency", "default_rtols",
]

register_env("MXNET_TEST_CTX", "cpu",
             "Default context the test suite runs on: 'cpu' (default) "
             "or 'tpu'/'gpu' for the accelerator ctx-flip gates "
             "(ci/run.sh tpu-sweep / tpu-core / tpu-unit — the "
             "reference's test_operator_gpu.py analog).")

_DEFAULT_CTX: Optional[Context] = None

# per-dtype tolerance maps (reference: test_utils.py default_rtols/atols)
_RTOLS: Dict[Any, float] = {
    _np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-6, _np.dtype(_np.int32): 0,
    _np.dtype(_np.int64): 0, _np.dtype(_np.bool_): 0,
}
_ATOLS: Dict[Any, float] = {
    _np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-5,
    _np.dtype(_np.float64): 1e-8, _np.dtype(_np.int32): 0,
    _np.dtype(_np.int64): 0, _np.dtype(_np.bool_): 0,
}


def default_rtols() -> Dict[Any, float]:
    return dict(_RTOLS)


def default_context() -> Context:
    """The context tests run on; switch via MXNET_TEST_CTX=tpu."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is None:
        name = os.environ.get("MXNET_TEST_CTX", "cpu")
        _DEFAULT_CTX = tpu() if name in ("tpu", "gpu") else cpu()
    return _DEFAULT_CTX


def set_default_context(ctx: Context) -> None:
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def _to_numpy(x: Any) -> _np.ndarray:
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def same(a: Any, b: Any) -> bool:
    return _np.array_equal(_to_numpy(a), _to_numpy(b))


def almost_equal(a: Any, b: Any, rtol: Optional[float] = None,
                 atol: Optional[float] = None, equal_nan: bool = False) -> bool:
    a, b = _to_numpy(a), _to_numpy(b)
    rtol = rtol if rtol is not None else _RTOLS.get(a.dtype, 1e-5)
    atol = atol if atol is not None else _ATOLS.get(a.dtype, 1e-6)
    return _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a: Any, b: Any, rtol: Optional[float] = None,
                        atol: Optional[float] = None,
                        names: Sequence[str] = ("a", "b"),
                        equal_nan: bool = False) -> None:
    """Assert allclose with per-dtype default tolerances."""
    a_np, b_np = _to_numpy(a), _to_numpy(b)
    rtol = rtol if rtol is not None else _RTOLS.get(a_np.dtype, 1e-5)
    atol = atol if atol is not None else _ATOLS.get(a_np.dtype, 1e-6)
    if _np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    diff = _np.abs(a_np.astype(_np.float64) - b_np.astype(_np.float64))
    denom = _np.abs(b_np.astype(_np.float64)) + atol
    idx = _np.unravel_index(_np.argmax(diff / denom), diff.shape)
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}: "
        f"max rel-violation at {idx}: {a_np[idx]} vs {b_np[idx]} "
        f"(abs diff {diff[idx]})")


def rand_shape_nd(ndim: int, dim: int = 10) -> tuple:
    return tuple(_np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape: Sequence[int], ctx: Optional[Context] = None,
                 dtype: Any = "float32", low: float = -1.0,
                 high: float = 1.0) -> NDArray:
    data = _np.random.uniform(low, high, size=tuple(shape)).astype(dtype)
    return NDArray(data, ctx=ctx or default_context())


def check_numeric_gradient(fn: Callable[..., NDArray],
                           inputs: Sequence[NDArray],
                           eps: float = 1e-3, rtol: float = 1e-2,
                           atol: float = 1e-3) -> None:
    """Compare autograd gradients against central finite differences.

    The reference's gatekeeper test for every op's FGradient
    (``python/mxnet/test_utils.py check_numeric_gradient``). ``fn`` maps
    NDArrays to a single NDArray output; gradients are checked for each
    input in float64-free finite differences with seed cotangent of ones.

    On TPU the matmul default precision is bfloat16, which swallows the
    ±eps perturbation entirely (numeric grads read as 0) — the whole
    check runs under ``jax.default_matmul_precision('highest')``. On an
    accelerator the central differences themselves carry extra fp32
    rounding noise (transcendental libm deviations scale by 1/eps), so
    tolerances floor at the reference's GPU-suite values (rtol=1e-2,
    atol=1e-2).
    """
    import jax
    # detect AFTER wrapping: raw numpy inputs land on the current default
    # context, which is the accelerator when one exists
    inputs = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
    accel = any(x.context.device_type != "cpu" for x in inputs)
    if accel:
        rtol, atol = max(rtol, 1e-2), max(atol, 1e-2)
    with jax.default_matmul_precision("highest"):
        _check_numeric_gradient_impl(fn, inputs, eps, rtol, atol)


def _check_numeric_gradient_impl(fn, inputs, eps, rtol, atol):
    from . import autograd

    inputs = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
    out.backward()
    analytic = [x.grad.asnumpy() for x in inputs]

    for i, x in enumerate(inputs):
        base = x.asnumpy().astype(_np.float64)
        num = _np.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = fn(*[NDArray(base.reshape(x.shape).astype(x.dtype))
                      if k == i else inputs[k]
                      for k in range(len(inputs))]).asnumpy().sum()
            flat[j] = orig - eps
            fm = fn(*[NDArray(base.reshape(x.shape).astype(x.dtype))
                      if k == i else inputs[k]
                      for k in range(len(inputs))]).asnumpy().sum()
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        assert_almost_equal(analytic[i], num, rtol=rtol, atol=atol,
                            names=(f"autograd_grad[{i}]", f"numeric_grad[{i}]"))


def check_consistency(fn: Callable[..., NDArray],
                      inputs_np: Sequence[_np.ndarray],
                      ctx_list: Optional[Sequence[Context]] = None,
                      dtypes: Sequence[str] = ("float32",),
                      rtol: Optional[float] = None,
                      atol: Optional[float] = None) -> None:
    """Run ``fn`` across contexts/dtypes and cross-compare outputs.

    The reference's THE cross-backend primitive (cpu/gpu/fp16 there;
    cpu/tpu/bf16 here).
    """
    ctxs = list(ctx_list or [cpu(), default_context()])
    results = []
    for ctx in ctxs:
        for dt in dtypes:
            args = [NDArray(a.astype(dt), ctx=ctx) for a in inputs_np]
            results.append((ctx, dt, fn(*args).asnumpy()))
    ref_ctx = ctxs[0]
    ref = results[0][2]
    for ctx, dt, out in results[1:]:
        r = rtol if rtol is not None else _RTOLS.get(_np.dtype(dt), 1e-3)
        a = atol if atol is not None else _ATOLS.get(_np.dtype(dt), 1e-4)
        if ctx.device_type != ref_ctx.device_type:
            # cross-BACKEND fp32 comparison: accelerator libm
            # (transcendental approximations) legitimately deviates from
            # host libm at the ~1e-4 level; the reference's
            # check_consistency used 1e-3-class tolerances for exactly
            # this cpu-vs-gpu case. Same-backend checks keep the tight
            # tolerance; each bound loosens only if the caller did not
            # set it explicitly.
            if rtol is None:
                r = max(r, 1e-3)
            if atol is None:
                a = max(a, 1e-4)
        assert_almost_equal(
            ref.astype(_np.float32), out.astype(_np.float32),
            rtol=r, atol=a, names=("reference", f"{ctx}/{dt}"))
