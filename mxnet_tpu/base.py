"""Base utilities: error types, env-var config tier, registry helpers.

Reference parity (leezu/mxnet):
  - ``python/mxnet/base.py`` (MXNetError, _LIB ctypes bootstrap)
  - ``3rdparty/dmlc-core`` env handling (``dmlc::GetEnv``) -> :func:`getenv`
  - ``src/c_api/c_api_error.cc`` error trampoline -> here errors are native
    Python exceptions; async device errors surface at sync points
    (see ``mxnet_tpu/engine.py``).

The env-var tier mirrors the reference's ``MXNET_*`` runtime config surface
(SURVEY.md section 5.6 tier 1).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "MXNetError",
    "NotImplementedForSymbol",
    "getenv",
    "register_env",
    "list_env",
    "classproperty",
    "join_distributed_job",
]


def _distributed_initialized(jax) -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax
    versions that predate it (<= 0.4.3x): the distributed global state
    holds a live client exactly when initialize() ran."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:   # noqa: BLE001 - private-API drift
        return False


def join_distributed_job() -> bool:
    """Join the multi-process job described by the launcher env
    (``tools/launch.py`` sets ``JAX_COORDINATOR_ADDRESS`` /
    ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` — the DMLC_* rendezvous
    analog). Idempotent; no-op (returns False) when the env is absent or
    ``MXNET_NO_AUTO_DISTRIBUTED=1``. Must run before anything touches
    the XLA backend; raises MXNetError with guidance if it is too late.

    ``MXNET_DIST_INIT_TIMEOUT`` (seconds, default 120) bounds the wait
    for the coordinator so a stale env cannot hang an import forever.
    """
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coord or os.environ.get("MXNET_NO_AUTO_DISTRIBUTED") == "1":
        return False
    import jax
    if _distributed_initialized(jax):
        return True
    too_late = MXNetError(
        "the XLA backend was initialized before joining the "
        "multi-process job; import mxnet_tpu (or call "
        "jax.distributed.initialize) before any jax computation "
        "when JAX_COORDINATOR_ADDRESS is set — or set "
        "MXNET_NO_AUTO_DISTRIBUTED=1 to opt out")
    # A live XLA backend means initialize() is guaranteed to be too late;
    # check the backend state directly rather than relying on jax's error
    # wording (which shifts across versions — string match kept below only
    # as a fallback).
    try:
        from jax._src import xla_bridge as _xb
        if getattr(_xb, "_backends", None):
            raise too_late
    except ImportError:
        pass
    # CPU multi-process jobs need a cross-process collective backend:
    # without one, XLA:CPU rejects any multiprocess computation
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"). Select gloo where this jax exposes the knob; harmless
    # before backend init, skipped for real accelerator jobs.
    try:
        platforms = (os.environ.get("JAX_PLATFORMS", "") or "").lower()
        if ("cpu" in platforms
                and "jax_cpu_collectives_implementation"
                in jax.config.values
                and jax.config.values[
                    "jax_cpu_collectives_implementation"]
                in (None, "none")):
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
    except Exception:   # noqa: BLE001 - version-dependent config surface
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("JAX_NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
            initialization_timeout=int(
                os.environ.get("MXNET_DIST_INIT_TIMEOUT", "120")))
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg:
            return True
        if "must be called before" in msg:
            raise too_late from e
        raise
    return True


class MXNetError(RuntimeError):
    """Default error thrown by framework operations.

    Mirrors ``mxnet.base.MXNetError``. Errors raised inside asynchronously
    dispatched device computations are re-raised from sync points
    (``wait_to_read`` / ``asnumpy`` / ``waitall``), matching the reference
    engine's rethrow-at-sync semantics
    (``src/engine/threaded_engine.cc`` exception handling).
    """


class NotImplementedForSymbol(MXNetError):
    """Raised when an imperative-only API is used under symbolic tracing."""

    def __init__(self, function: Any, *args: Any) -> None:
        super().__init__(
            f"Function {getattr(function, '__name__', function)} is not "
            f"supported under hybridize tracing."
        )


# ---------------------------------------------------------------------------
# Env-var config tier (reference: docs/.../env_var.md, ~80 MXNET_* vars)
# ---------------------------------------------------------------------------

_ENV_REGISTRY: Dict[str, Dict[str, Any]] = {}
_ENV_LOCK = threading.Lock()


def register_env(name: str, default: Any, doc: str = "") -> None:
    """Register a recognized ``MXNET_*`` environment variable with default+doc.

    Powers :func:`list_env` (the analog of the reference's env_var.md page).
    """
    with _ENV_LOCK:
        _ENV_REGISTRY[name] = {"default": default, "doc": doc}


def getenv(name: str, default: Any = None, typ: Optional[type] = None) -> Any:
    """Read an environment variable with type coercion (``dmlc::GetEnv``)."""
    if name in _ENV_REGISTRY and default is None:
        default = _ENV_REGISTRY[name]["default"]
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is None:
        typ = type(default) if default is not None else str
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    try:
        return typ(raw)
    except (TypeError, ValueError):
        return default


def list_env() -> Dict[str, Dict[str, Any]]:
    """Return the registered env-var config surface (name -> default/doc)."""
    with _ENV_LOCK:
        return {k: dict(v) for k, v in _ENV_REGISTRY.items()}


# Env-dependent TRACE knobs (modules whose env var changes the traced
# program) register a poller here; gluon's graph_epoch() runs them all so
# a toggle between calls bumps the epoch — and thus every cached
# executable's key — even though no trace (where the knob would be read)
# has run.  Lives in base because every module can import base without a
# cycle.
_GRAPH_KNOB_POLLERS: List[Any] = []


def register_graph_knob(poll) -> None:
    """Register a zero-arg callable polled by ``gluon.block.graph_epoch``.
    It should compare the knob's current value to its last seen value and
    call ``gluon.block.invalidate_cached_graphs()`` on change."""
    _GRAPH_KNOB_POLLERS.append(poll)


def poll_graph_knobs() -> None:
    for _poll in _GRAPH_KNOB_POLLERS:
        _poll()


# Core runtime vars (more are registered at their use sites).
register_env("MXNET_NO_AUTO_DISTRIBUTED", 0,
             "Set to 1 to skip the automatic jax.distributed.initialize "
             "at import even when JAX_COORDINATOR_ADDRESS is present in "
             "the environment (single-process debugging of a node from "
             "a launcher-described job).")
register_env("MXNET_DIST_INIT_TIMEOUT", 120,
             "Seconds the import-time join of a launcher-described "
             "multi-process job waits for the coordinator before "
             "failing loudly — a stale JAX_COORDINATOR_ADDRESS cannot "
             "hang an import forever.")
register_env("MXNET_SANITIZE", "",
             "Comma-separated runtime sanitizers to install at import. "
             "'locks' patches threading.Lock/RLock creation so every "
             "lock allocated from this repo records per-thread "
             "acquisition stacks and a global acquired-while-holding "
             "graph; a lock-order inversion (the A/B-B/A deadlock "
             "pattern) is reported with both stacks. CI enables it on "
             "the chaos and resilience smokes. See "
             "docs/static_analysis.md.")
register_env("MXNET_SANITIZE_LOCKS_ACTION", "raise",
             "What the lock-order sanitizer does on an inversion: "
             "'raise' (default) raises LockOrderViolation at the "
             "offending acquisition; 'warn' prints the report to "
             "stderr and continues (for surveying a long run).")
register_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice",
             "Execution mode: 'NaiveEngine' forces synchronous per-op "
             "execution (block_until_ready after every op) for debugging; "
             "anything else uses async XLA dispatch.")
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", 1,
             "Parity alias: the lazy bulking engine (mxnet_tpu/bulk.py, "
             "MXNET_BULK_MAX_OPS) is the load-bearing control for eager "
             "segment bulking; engine.set_bulk_size/engine.bulk scope it "
             "at runtime. This reference-named flag remains accepted but "
             "unread.")
register_env("MXNET_ENFORCE_DETERMINISM", 0,
             "Restrict to deterministic kernels.")


class classproperty:  # noqa: N801 - decorator naming
    """Read-only class-level property helper."""

    def __init__(self, fget: Callable[[Any], Any]) -> None:
        self.fget = fget

    def __get__(self, obj: Any, owner: Optional[type] = None) -> Any:
        return self.fget(owner)
