"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

A brand-new framework (NOT a port) with the API surface of Apache MXNet
(reference: leezu/mxnet), designed tpu-first on jax/XLA: the async
dependency engine maps to XLA's async dispatch, ``hybridize`` maps to a
jit-compiled executable cache, KVStore maps to SPMD collectives over a
device mesh. See SURVEY.md for the full blueprint.

Usage mirrors the reference::

    import mxnet_tpu as mx
    x = mx.np.ones((2, 3), ctx=mx.tpu())
    net = mx.gluon.nn.Dense(10)
    net.initialize()
    with mx.autograd.record():
        y = net(x)
"""
__version__ = "0.1.0"

# Install the runtime lock-order sanitizer BEFORE any submodule import
# allocates a lock (MXNET_SANITIZE=locks; see
# docs/static_analysis.md#lockdep) — lockdep tracks only locks created
# after the factories are patched, and analysis.lockdep is stdlib-only
# so this costs nothing when the env is unset.
import os as _os
_sanitizers = {t.strip()
               for t in _os.environ.get("MXNET_SANITIZE", "").split(",")
               if t.strip()}
if _sanitizers - {"locks"}:
    # a typo must not silently disarm a sanitizer the user asked for
    raise ValueError(
        f"unknown MXNET_SANITIZE value(s) {sorted(_sanitizers - {'locks'})}"
        " — supported: 'locks' (see docs/static_analysis.md)")
if "locks" in _sanitizers:
    from .analysis.lockdep import install as _lockdep_install
    _lockdep_install()
    del _lockdep_install
del _sanitizers

# Join a launcher-described multi-process job BEFORE anything touches the
# XLA backend (jax.distributed.initialize must run first) — the analog of
# the reference reading DMLC_* rendezvous env at import. No-op when the
# env is absent; see base.join_distributed_job for the knobs.
from .base import join_distributed_job as _join
_join()

from . import base
from .base import MXNetError
from .context import (Context, cpu, cpu_pinned, gpu, tpu, current_context,
                      num_gpus, num_tpus)
from . import engine
from . import bulk
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import numpy as np  # noqa: A004 - mirrors mx.np
from . import npx
from . import autograd
from .ndarray import random
from . import util
from .util import set_np, is_np_array, is_np_shape

# Subpackages that may import heavier deps load lazily via __getattr__.
_LAZY = {
    "gluon": ".gluon",
    "optimizer": ".optimizer",
    "metric": ".metric",
    "metrics": ".metrics",
    "initializer": ".initializer",
    "init": ".initializer",
    "kvstore": ".kvstore",
    "kv": ".kvstore",
    "io": ".io",
    "image": ".image",
    "recordio": ".recordio",
    "profiler": ".profiler",
    "amp": ".amp",
    "parallel": ".parallel",
    "test_utils": ".test_utils",
    "runtime": ".runtime",
    "lr_scheduler": ".lr_scheduler",
    "callback": ".callback",
    "model": ".model",
    "mod": ".module",
    "module": ".module",
    "operator": ".operator",
    "monitor": ".monitor",
    "mon": ".monitor",
    "symbol": ".symbol",
    "sym": ".symbol",
    "contrib": ".contrib",
    "subgraph": ".subgraph",
    "rtc": ".rtc",
    "serving": ".serving",
    "checkpoint": ".checkpoint",
    "faults": ".faults",
    "retry": ".retry",
    "preemption": ".preemption",
    "health": ".health",
    "name": ".name",
    "attribute": ".attribute",
    "visualization": ".visualization",
    "viz": ".visualization",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")


def waitall() -> None:
    """Block until all asynchronous device work completes."""
    engine.waitall()
