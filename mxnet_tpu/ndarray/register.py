"""Op invocation core and registry.

Reference parity (leezu/mxnet): the NNVM registry + imperative dispatch —
``NNVM_REGISTER_OP`` / ``Imperative::Invoke`` / ``PushFCompute``
(``src/imperative/imperative_utils.h``) and the Python generated-op layer
(``python/mxnet/ndarray/register.py``).

Design (tpu-first): every op is a pure function over jax arrays. Imperative
execution dispatches it directly (jax's C++ eager path + async device
streams stand in for the ThreadedEngine). When autograd is recording and an
input is on the tape, the op executes under ``jax.vjp`` and a TapeNode holds
the pullback. Under hybridize, the same Python op functions run with tracers
inside one ``jax.jit`` — the analog of CachedOp bulking, with XLA doing the
fusion the reference got from pointwise-fusion RTC codegen.
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .. import bulk as _bulk
from .. import engine
from .. import faults as _faults
from .. import metrics as _metrics
from .._tape import TapeNode, is_recording
from ..base import register_env

__all__ = ["invoke", "register_op", "get_op", "list_ops", "wrap_out",
           "exec_cache_stats"]

register_env("MXNET_IMPERATIVE_EXEC_CACHE", "auto",
             "Per-op executable cache for imperative dispatch: 1 "
             "forces it on (the exec-cache CI sanitizer), 0 forces it "
             "off, 'auto' (default) lets the runtime decide per op. "
             "Read once per process; the CI 'exec-cache' variant runs "
             "the core suite with it forced on.")

# name -> {"fn": public python fn, "doc": ...}
_OP_REGISTRY: Dict[str, Dict[str, Any]] = {}

# flipped by mxnet_tpu.amp.init()/disable(); checked on the hot dispatch
# path before importing the amp module at all
_amp_state = {"active": False}

# flipped by mxnet_tpu.profiler.set_state(); same hot-path pattern
_profiler_state = {"on": False}


# id -> hook fn; multiple Monitors may collect concurrently
_monitor_state = {"hooks": {}}

# flipped on while any multi-device-sharded array is alive (see
# mark_mesh_resident); single-device programs never pay the per-op
# sharding scan, and the flag drops back off once the last mesh-resident
# buffer is garbage-collected (a discarded GPTPipe doesn't tax every
# later eager op)
_mesh_state = {"active": False, "live": 0, "pinned": False}


def mark_mesh_resident(holder) -> None:
    """Track ``holder`` — an object whose lifetime upper-bounds some
    multi-device-sharded buffer (the NDArray wrapper of a mesh-placed
    parameter, a mesh-sharded op output, a raw mesh array): the per-op
    harmonization scan stays enabled only while at least one such holder
    is alive. Register wrappers rather than raw buffers when the buffer
    is swapped in place every step (SPMDTrainer parameters)."""
    _mesh_state["active"] = True
    try:
        weakref.finalize(holder, _mesh_release)
        _mesh_state["live"] += 1
    except TypeError:
        # not weakref-able: latch conservatively (previous behavior)
        _mesh_state["pinned"] = True


def _mesh_release() -> None:
    _mesh_state["live"] -= 1
    if _mesh_state["live"] <= 0 and not _mesh_state["pinned"]:
        _mesh_state["active"] = False

# ---------------------------------------------------------------------------
# TPU-resident imperative mode: per-op executable cache
# (reference: src/imperative/imperative.cc Imperative::Invoke → PushFCompute —
# the per-op kernel dispatch; here each op becomes ONE cached XLA executable
# instead of a chain of per-primitive eager dispatches, and its outputs are
# real device buffers, so eager ops run on the accelerator and hybridize/jit
# consumers need no host->device re-transfer)
# ---------------------------------------------------------------------------

# (op name, closure token, recording) -> jitted callable. jax.jit handles
# the per-shape/dtype executable keying internally; the closure token keys
# the op's attributes (closure cell values), so behaviorally-equal closures
# share one traced wrapper. LRU-bounded: evicting a wrapper releases its
# compiled executables.
from collections import OrderedDict  # noqa: E402

_EXEC_CACHE: "OrderedDict[Any, Callable]" = OrderedDict()
_EXEC_CACHE_CAP = 1024

# Ops whose attrs churn (e.g. an annealed python scalar bound into the
# closure every step) would otherwise pay a fresh trace+compile per call;
# after _CHURN_LIMIT distinct attr tokens for one (op, code) we stop
# caching that op and dispatch it eagerly.
_CHURN_COUNT: Dict[Any, int] = {}
_CHURN_EAGER: set = set()
_CHURN_LIMIT = 16

# MXNET_IMPERATIVE_EXEC_CACHE: "auto" (cache when an input lives on an
# accelerator device), "1" (always — also on CPU; used by tests), "0" (off)
_exec_mode = {"value": None}


class _UnhashableAttr(Exception):
    pass


def _attr_token(v: Any, depth: int = 0) -> Any:
    """A hashable token for a closure cell value, or raise."""
    if depth > 4:
        raise _UnhashableAttr
    if v is None or isinstance(v, (str, bytes)):
        return v
    if isinstance(v, slice):
        return ("slice", _attr_token(v.start, depth + 1),
                _attr_token(v.stop, depth + 1),
                _attr_token(v.step, depth + 1))
    if isinstance(v, (bool, int, float)):
        # dict-key equality conflates 0 == 0.0 == False; the numeric TYPE
        # is part of the op's behavior (output dtype), so key it too
        return (type(v).__name__, v)
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(
            _attr_token(x, depth + 1) for x in v)
    if isinstance(v, dict):
        try:
            return tuple(sorted(
                (k, _attr_token(x, depth + 1)) for k, x in v.items()))
        except TypeError:  # mixed-type keys don't sort
            raise _UnhashableAttr from None
    if isinstance(v, type) or hasattr(v, "dtype") and not hasattr(v, "shape"):
        return str(v)
    import numpy as _onp
    if isinstance(v, _onp.dtype):
        return str(v)
    if callable(v) and hasattr(v, "__code__"):
        return _closure_token(v, depth + 1)
    if callable(v):
        # code-less callable (jnp ufunc, builtin): stable object identity
        # is the token — the common case for scalar-operand binary ops
        try:
            hash(v)
            return v
        except TypeError:
            raise _UnhashableAttr from None
    raise _UnhashableAttr


def _closure_token(fn: Callable, depth: int = 0) -> Any:
    """Key an op impl closure by code object + attribute cell values.
    Cells holding arrays/objects (e.g. PRNG keys) are unhashable — such
    ops fall back to plain eager dispatch."""
    code = getattr(fn, "__code__", None)
    if code is None:
        # not a Python function (jnp ufunc, builtin): the stable callable
        # object itself is the token
        try:
            hash(fn)
        except TypeError:
            raise _UnhashableAttr from None
        return fn
    cells = fn.__closure__ or ()
    try:
        return (code,) + tuple(
            _attr_token(c.cell_contents, depth) for c in cells)
    except ValueError:  # empty (not-yet-bound) cell
        raise _UnhashableAttr from None


def _exec_cache_mode() -> str:
    mode = _exec_mode["value"]
    if mode is None:
        import os
        mode = os.environ.get("MXNET_IMPERATIVE_EXEC_CACHE", "auto")
        _exec_mode["value"] = mode
    return mode


def _should_use_exec_cache(arrays) -> bool:
    mode = _exec_cache_mode()
    if mode == "0":
        return False
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            return False  # inside a hybridize/jit trace: run inline
    if mode == "1":
        return True
    for a in arrays:
        if isinstance(a, jax.Array):
            try:
                devs = a.devices()
            except Exception:
                continue
            if any(d.platform != "cpu" for d in devs):
                return True
    return False


# Trace-failure poison, keyed by the FULL signature including input
# avals: a failure is often input-dependent (a weak-typed scalar, a
# shape-special-cased host check), so poisoning the (op, attrs) key
# alone would force ops eager forever even for inputs that trace fine.
# _EAGER_OPS is the cheap first-level guard so the hot path only builds
# an aval key for ops that have EVER failed.  Both are LRU-bounded
# (incremental eviction — a wholesale clear would make every known-bad
# signature re-pay a doomed trace at once); a stale _EAGER_OPS entry
# after its signatures evicted only costs an extra aval-key probe.
_EAGER_OPS: "OrderedDict[Any, None]" = OrderedDict()   # (name,tok,rec)
_EAGER_SIGS: "OrderedDict[Any, None]" = OrderedDict()  # (..., avalkey)
_EAGER_OPS_CAP = 1024
_EAGER_SIGS_CAP = 4096


def _aval_key(arrays) -> tuple:
    return tuple((tuple(getattr(a, "shape", ())),
                  str(getattr(a, "dtype", type(a).__name__)),
                  bool(getattr(a, "weak_type", False))) for a in arrays)


def _cached_exec(name: str, impl: Callable, arrays, record: bool):
    """Try the per-op executable cache; returns the raw result or None
    when the op must take the eager path."""
    try:
        token = _closure_token(impl)
    except _UnhashableAttr:
        return None  # attrs hold arrays/objects (e.g. PRNG keys)
    churn_key = (name, token[0] if isinstance(token, tuple) else token)
    if churn_key in _CHURN_EAGER:
        return None
    key = (name, token, record)
    if key in _EAGER_OPS and \
            (name, token, record, _aval_key(arrays)) in _EAGER_SIGS:
        return None     # this exact signature failed to trace before
    fn = _EXEC_CACHE.get(key)
    if fn is not None:
        _EXEC_CACHE.move_to_end(key)
        # a hit means attrs repeat — not the per-call-varying pattern the
        # churn guard targets
        _CHURN_COUNT.pop(churn_key, None)
        _metrics.COMPILE_HITS.inc()
    if fn is None:
        n = _CHURN_COUNT[churn_key] = _CHURN_COUNT.get(churn_key, 0) + 1
        if n > _CHURN_LIMIT:
            # attrs vary call-to-call (e.g. annealed scalars): caching
            # would trace+compile every step — stay eager from now on
            _CHURN_EAGER.add(churn_key)
            return None
        if record:
            # jax.vjp's pullback is a tree_util.Partial: its residuals
            # come back as device buffers and the pullback itself stays
            # jit-able for backward
            fn = jax.jit(lambda *xs: jax.vjp(impl, *xs))
        else:
            fn = jax.jit(impl)
        _EXEC_CACHE[key] = fn
        if len(_EXEC_CACHE) > _EXEC_CACHE_CAP:
            _EXEC_CACHE.popitem(last=False)
        _metrics.EXEC_CACHE_SIZE.set(len(_EXEC_CACHE))
    try:
        return fn(*arrays)
    except jax.errors.JAXTypeError:
        # op needs concrete values for THESE inputs (data-dependent host
        # checks, e.g. mode='raise' bounds validation on a weak-typed
        # scalar) — poison only this (op, attrs, avals) signature; other
        # input signatures keep using the cached wrapper
        _EAGER_OPS[key] = None
        if len(_EAGER_OPS) > _EAGER_OPS_CAP:
            _EAGER_OPS.popitem(last=False)
        _EAGER_SIGS[(name, token, record, _aval_key(arrays))] = None
        if len(_EAGER_SIGS) > _EAGER_SIGS_CAP:
            _EAGER_SIGS.popitem(last=False)
        return None


def _dispatch(name: str, impl: Callable, arrays, record: bool,
              eager_only: bool = False):
    """Run ``impl`` over raw arrays, through the per-op executable cache
    when eligible. Returns ``(outs, vjp_fn_or_None, cached)``."""
    if not eager_only and _should_use_exec_cache(arrays):
        result = _cached_exec(name, impl, arrays, record)
        if result is not None:
            outs = result[0] if record else result
            for o in (outs if isinstance(outs, (tuple, list)) else (outs,)):
                engine.mark_clean(o)
            if record:
                return result[0], result[1], True
            return result, None, True
    if record:
        outs, vjp_fn = jax.vjp(impl, *arrays)
        return outs, vjp_fn, False
    return impl(*arrays), None, False


def _harmonize_mesh_placement(arrays):
    """Eager ops mixing mesh-sharded operands (e.g. parameters placed by
    SPMDTrainer) with fresh single-device arrays: replicate the latter
    onto the same mesh so XLA can dispatch one program.  The mesh is one
    logical device in this framework's model (the reference instead
    *errors* on cross-context ops; here the mesh placement is an
    implementation detail the user never chose)."""
    mesh = None
    for a in arrays:
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer):
            sh = a.sharding
            if getattr(sh, "mesh", None) is not None \
                    and sh.num_devices > 1:
                mesh = sh.mesh
                break
    if mesh is None:
        return arrays
    out = []
    rep = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    for a in arrays:
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer) \
                and a.sharding.num_devices == 1:
            a = jax.device_put(a, rep)
        out.append(a)
    return out


# Re-entrancy guard for monitor hooks (per thread): a hook's own
# stat_func dispatches ops (abs/mean) through invoke(), and without the
# guard those instrumentation-internal dispatches re-fire every OTHER
# registered hook (Monitor._in_hook only protects the monitor against
# itself) — their stats then publish into mxnet_monitor_stat as if they
# were model ops.  Same rule the tracing layer follows by mirroring
# spans into the profiler via a direct event append instead of dispatch.
_monitor_tls = threading.local()


def _fire_monitor_hooks(name, outputs) -> None:
    if getattr(_monitor_tls, "active", False):
        return
    _monitor_tls.active = True
    try:
        for hook in list(_monitor_state["hooks"].values()):
            hook(name, outputs)
    finally:
        _monitor_tls.active = False


def exec_cache_stats() -> Dict[str, float]:
    """Snapshot of the compile-cache surface for tools and the serving
    health endpoint: per-op executable-cache size, eager-path hits, and
    process-wide XLA backend compiles (the jax.monitoring miss counter —
    covers hybridize/jit programs too, which is what serving warmup
    bounds)."""
    stats = {"size": len(_EXEC_CACHE),
             "hits": _metrics.COMPILE_HITS.value,
             "misses": _metrics.COMPILE_MISSES.value}
    stats.update(_bulk.bulk_stats())
    return stats


def register_op(name: str, fn: Callable, doc: str = "") -> Callable:
    """Register a public op under ``name`` (NNVM_REGISTER_OP analog)."""
    _OP_REGISTRY[name] = {"fn": fn, "doc": doc or (fn.__doc__ or "")}
    return fn


def get_op(name: str) -> Callable:
    """Look up a registered op by name (``mx.nd.op``-style access)."""
    return _OP_REGISTRY[name]["fn"]


def list_ops() -> List[str]:
    """All registered op names (``MXListAllOpNames`` analog)."""
    return sorted(_OP_REGISTRY)


def _ndarray_cls():
    from .ndarray import NDArray
    return NDArray


def wrap_out(data: Any, ctx=None) -> Any:
    """Wrap a raw jax array (or tracer) into an NDArray and track it."""
    NDArray = _ndarray_cls()
    out = NDArray(data, ctx=ctx, _wrap=True)
    engine.track(data)
    return out


def invoke_with_custom_vjp(name: str, impl: Callable,
                           inputs: Sequence[Any], vjp_fn: Callable,
                           ctx=None) -> Any:
    """Like :func:`invoke` but with a hand-written pullback instead of
    ``jax.vjp`` — for ops whose gradient is not a jax type (e.g. the
    row-sparse embedding grad). ``vjp_fn(out_cot) -> per-input cotangents``
    (None entries are skipped). Single-output ops only."""
    arrays = [x._data for x in inputs]
    _metrics.inc_op(name)
    if _faults._ARMED:
        _faults.maybe_fault("dispatch.op", op=name)
    if _mesh_state["active"]:
        arrays = _harmonize_mesh_placement(arrays)

    timer = None
    if _profiler_state["on"]:
        from ..profiler import op_timer
        timer = op_timer(name)
        if timer is not None:
            timer.__enter__()
    try:
        out = impl(*arrays)
    finally:
        if timer is not None:
            timer.__exit__()

    wrapped = wrap_out(out, ctx=ctx)
    if is_recording() and any(x._on_tape for x in inputs):
        node = TapeNode(name, vjp_fn, inputs,
                        [(tuple(out.shape), out.dtype)])
        node.out_arrays = [weakref.ref(wrapped)]
        wrapped._ag_node = node
        wrapped._ag_out_idx = 0

    if _monitor_state["hooks"]:
        _fire_monitor_hooks(name, (wrapped,))

    return wrapped


def invoke(name: str, impl: Callable, inputs: Sequence[Any],
           ctx=None, eager_only: bool = False) -> Any:
    """Execute op ``impl`` over NDArray ``inputs``; handle autograd.

    ``impl`` takes the raw arrays positionally (attrs must already be bound
    into the closure) and returns one array or a tuple of arrays.
    ``eager_only`` ops (data-dependent host-side behavior, e.g. bounds
    validation with mode='raise') bypass the per-op executable cache.
    """
    _metrics.inc_op(name)
    if _faults._ARMED:
        _faults.maybe_fault("dispatch.op", op=name)

    # Lazy bulking (mxnet_tpu/bulk.py): on the plain eager fast path the
    # op joins the pending segment and returns promised NDArrays without
    # dispatching anything. Paths that need per-op visibility or concrete
    # per-op arrays (amp casts, profiler timers, monitor hooks, mesh
    # harmonization, naive engine) keep per-op dispatch.
    # MXNET_IMPERATIVE_EXEC_CACHE=1 (the forced per-op-cache sanitizer
    # mode, ci/run.sh exec-cache) keeps per-op dispatch observable.
    if (not eager_only and not _amp_state["active"]
            and not _profiler_state["on"] and not _monitor_state["hooks"]
            and not _mesh_state["active"] and _exec_cache_mode() != "1"
            and _bulk.active()):
        try:
            token = _closure_token(impl)
        except _UnhashableAttr:
            token = None
        out = _bulk.try_append(name, impl, token, inputs, ctx)
        if out is not _bulk.NOT_BULKED:
            return out

    arrays = [x._data for x in inputs]
    if _mesh_state["active"]:
        arrays = _harmonize_mesh_placement(arrays)

    if _amp_state["active"]:
        from ..amp import apply_cast_policy
        arrays = apply_cast_policy(name, arrays)

    timer = None
    if _profiler_state["on"]:
        from ..profiler import op_timer
        timer = op_timer(name)
        if timer is not None:
            timer.__enter__()

    record = is_recording() and any(x._on_tape for x in inputs)
    try:
        outs, vjp_fn, cached = _dispatch(name, impl, arrays, record,
                                         eager_only)
    finally:
        if timer is not None:
            timer.__exit__()

    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)

    wrapped = [wrap_out(o, ctx=ctx) for o in outs_t]

    if _mesh_state["active"]:
        # mesh-sharded outputs keep the harmonization scan alive for as
        # long as THEY live (downstream eager ops still mix them with
        # fresh single-device arrays after the producing trainer/pipeline
        # is discarded)
        for w in wrapped:
            o = w._data
            if isinstance(o, jax.Array) and not isinstance(
                    o, jax.core.Tracer) \
                    and getattr(o.sharding, "num_devices", 1) > 1:
                mark_mesh_resident(w)

    if record:
        avals = [(tuple(o.shape), o.dtype) for o in outs_t]
        node = TapeNode(name, vjp_fn, inputs, avals, out_is_tuple=not single)
        node.jit_pull = cached
        node.out_arrays = [weakref.ref(w) for w in wrapped]
        for i, w in enumerate(wrapped):
            w._ag_node = node
            w._ag_out_idx = i

    if _monitor_state["hooks"]:
        _fire_monitor_hooks(name, tuple(wrapped))

    return wrapped[0] if single else tuple(wrapped)
