"""Op invocation core and registry.

Reference parity (leezu/mxnet): the NNVM registry + imperative dispatch —
``NNVM_REGISTER_OP`` / ``Imperative::Invoke`` / ``PushFCompute``
(``src/imperative/imperative_utils.h``) and the Python generated-op layer
(``python/mxnet/ndarray/register.py``).

Design (tpu-first): every op is a pure function over jax arrays. Imperative
execution dispatches it directly (jax's C++ eager path + async device
streams stand in for the ThreadedEngine). When autograd is recording and an
input is on the tape, the op executes under ``jax.vjp`` and a TapeNode holds
the pullback. Under hybridize, the same Python op functions run with tracers
inside one ``jax.jit`` — the analog of CachedOp bulking, with XLA doing the
fusion the reference got from pointwise-fusion RTC codegen.
"""
from __future__ import annotations

import functools
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .. import engine
from .._tape import TapeNode, is_recording

__all__ = ["invoke", "register_op", "get_op", "list_ops", "wrap_out"]

# name -> {"fn": public python fn, "doc": ...}
_OP_REGISTRY: Dict[str, Dict[str, Any]] = {}

# flipped by mxnet_tpu.amp.init()/disable(); checked on the hot dispatch
# path before importing the amp module at all
_amp_state = {"active": False}

# flipped by mxnet_tpu.profiler.set_state(); same hot-path pattern
_profiler_state = {"on": False}


# id -> hook fn; multiple Monitors may collect concurrently
_monitor_state = {"hooks": {}}

# flipped by SPMDTrainer once any parameter is placed on a multi-device
# mesh; single-device programs never pay the per-op sharding scan
_mesh_state = {"active": False}


def _harmonize_mesh_placement(arrays):
    """Eager ops mixing mesh-sharded operands (e.g. parameters placed by
    SPMDTrainer) with fresh single-device arrays: replicate the latter
    onto the same mesh so XLA can dispatch one program.  The mesh is one
    logical device in this framework's model (the reference instead
    *errors* on cross-context ops; here the mesh placement is an
    implementation detail the user never chose)."""
    mesh = None
    for a in arrays:
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer):
            sh = a.sharding
            if getattr(sh, "mesh", None) is not None \
                    and sh.num_devices > 1:
                mesh = sh.mesh
                break
    if mesh is None:
        return arrays
    out = []
    rep = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    for a in arrays:
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer) \
                and a.sharding.num_devices == 1:
            a = jax.device_put(a, rep)
        out.append(a)
    return out


def _fire_monitor_hooks(name, outputs) -> None:
    for hook in list(_monitor_state["hooks"].values()):
        hook(name, outputs)


def register_op(name: str, fn: Callable, doc: str = "") -> Callable:
    """Register a public op under ``name`` (NNVM_REGISTER_OP analog)."""
    _OP_REGISTRY[name] = {"fn": fn, "doc": doc or (fn.__doc__ or "")}
    return fn


def get_op(name: str) -> Callable:
    """Look up a registered op by name (``mx.nd.op``-style access)."""
    return _OP_REGISTRY[name]["fn"]


def list_ops() -> List[str]:
    """All registered op names (``MXListAllOpNames`` analog)."""
    return sorted(_OP_REGISTRY)


def _ndarray_cls():
    from .ndarray import NDArray
    return NDArray


def wrap_out(data: Any, ctx=None) -> Any:
    """Wrap a raw jax array (or tracer) into an NDArray and track it."""
    NDArray = _ndarray_cls()
    out = NDArray(data, ctx=ctx, _wrap=True)
    engine.track(data)
    return out


def invoke_with_custom_vjp(name: str, impl: Callable,
                           inputs: Sequence[Any], vjp_fn: Callable,
                           ctx=None) -> Any:
    """Like :func:`invoke` but with a hand-written pullback instead of
    ``jax.vjp`` — for ops whose gradient is not a jax type (e.g. the
    row-sparse embedding grad). ``vjp_fn(out_cot) -> per-input cotangents``
    (None entries are skipped). Single-output ops only."""
    arrays = [x._data for x in inputs]
    if _mesh_state["active"]:
        arrays = _harmonize_mesh_placement(arrays)

    timer = None
    if _profiler_state["on"]:
        from ..profiler import op_timer
        timer = op_timer(name)
        if timer is not None:
            timer.__enter__()
    try:
        out = impl(*arrays)
    finally:
        if timer is not None:
            timer.__exit__()

    wrapped = wrap_out(out, ctx=ctx)
    if is_recording() and any(x._on_tape for x in inputs):
        node = TapeNode(name, vjp_fn, inputs,
                        [(tuple(out.shape), out.dtype)])
        node.out_arrays = [weakref.ref(wrapped)]
        wrapped._ag_node = node
        wrapped._ag_out_idx = 0

    if _monitor_state["hooks"]:
        _fire_monitor_hooks(name, (wrapped,))

    return wrapped


def invoke(name: str, impl: Callable, inputs: Sequence[Any],
           ctx=None) -> Any:
    """Execute op ``impl`` over NDArray ``inputs``; handle autograd.

    ``impl`` takes the raw arrays positionally (attrs must already be bound
    into the closure) and returns one array or a tuple of arrays.
    """
    arrays = [x._data for x in inputs]
    if _mesh_state["active"]:
        arrays = _harmonize_mesh_placement(arrays)

    if _amp_state["active"]:
        from ..amp import apply_cast_policy
        arrays = apply_cast_policy(name, arrays)

    timer = None
    if _profiler_state["on"]:
        from ..profiler import op_timer
        timer = op_timer(name)
        if timer is not None:
            timer.__enter__()

    record = is_recording() and any(x._on_tape for x in inputs)
    try:
        if record:
            outs, vjp_fn = jax.vjp(impl, *arrays)
        else:
            outs = impl(*arrays)
    finally:
        if timer is not None:
            timer.__exit__()

    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)

    wrapped = [wrap_out(o, ctx=ctx) for o in outs_t]

    if record:
        avals = [(tuple(o.shape), o.dtype) for o in outs_t]
        node = TapeNode(name, vjp_fn, inputs, avals, out_is_tuple=not single)
        node.out_arrays = [weakref.ref(w) for w in wrapped]
        for i, w in enumerate(wrapped):
            w._ag_node = node
            w._ag_out_idx = i

    if _monitor_state["hooks"]:
        _fire_monitor_hooks(name, tuple(wrapped))

    return wrapped[0] if single else tuple(wrapped)
