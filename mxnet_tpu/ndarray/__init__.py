"""``mx.nd`` — the imperative NDArray namespace.

Reference parity: ``python/mxnet/ndarray/`` — NDArray class, generated op
namespace, random, legacy aliases. The numpy-semantics namespace ``mx.np``
reuses these same ops (see ``mxnet_tpu/numpy``).
"""
from .ndarray import NDArray, from_jax, waitall
from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all
from .ops_numpy import *  # noqa: F401,F403
from .ops_numpy import __all__ as _ops_np_all
from . import ops
from . import random
from . import linalg
from . import image
from . import sparse
from .sparse import RowSparseNDArray, CSRNDArray, BaseSparseNDArray
from .register import get_op, list_ops, register_op, invoke
from ..ndarray_io import save, load, save_params, load_params

__all__ = (["NDArray", "from_jax", "waitall", "random", "linalg",
            "get_op", "list_ops", "register_op"]
           + list(_ops_all) + list(_ops_np_all))


class _ContribNamespace:
    """``mx.nd.contrib`` — the reference's contrib op namespace. Accepts
    both plain and ``_contrib_``-prefixed spellings and resolves against
    the one op registry (quantize, interleaved attention matmuls, ...)."""

    def __getattr__(self, name: str):
        plain = name[len("_contrib_"):] if name.startswith("_contrib_") \
            else name
        if plain in list_ops():
            fn = get_op(plain)
            setattr(self, name, fn)
            return fn
        raise AttributeError(f"no contrib op {name!r}")


contrib = _ContribNamespace()


def __getattr__(name: str):
    """Resolve any registered op (and the reference's CamelCase aliases)
    as ``mx.nd.<name>`` — the analog of the generated-op namespace."""
    from ..symbol.symbol import _ALIASES
    canonical = _ALIASES.get(name, name)
    if canonical == "Custom":
        from .. import operator  # registers the Custom op on first touch
    if canonical in list_ops():
        fn = get_op(canonical)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute "
                         f"{name!r}")
