"""Linear-algebra op family — ``mx.np.linalg`` + ``mx.nd.linalg``.

Reference parity (leezu/mxnet): ``src/operator/tensor/la_op.{cc,cu,-inl.h}``
(gemm/potrf/trsm/trmm/syrk/... registered as ``_linalg_*``) and
``src/operator/numpy/linalg/`` (``np.linalg`` svd/inv/det/... semantics),
python surface ``python/mxnet/numpy/linalg.py`` / ``python/mxnet/ndarray/
linalg.py``.

Design (tpu-first): every routine is a composition of ``jax.numpy.linalg`` /
``jax.lax.linalg`` primitives, which XLA lowers to MXU-friendly blocked
factorizations; autograd comes uniformly from the vjp hook in
``register.invoke`` instead of per-op FGradient.
"""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as _np

from .ndarray import NDArray, from_jax
from .register import invoke, register_op

__all__ = [
    "norm", "svd", "svdvals", "inv", "pinv", "det", "slogdet", "cholesky",
    "qr", "eig", "eigh", "eigvals", "eigvalsh", "solve", "lstsq",
    "matrix_rank", "matrix_power", "multi_dot", "tensorinv", "tensorsolve",
    "cond", "matrix_norm", "vector_norm", "outer", "cross", "trace",
    "diagonal", "matmul", "matrix_transpose",
    # mxnet-style la_op family
    "gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "syrk",
    "sumlogdiag", "extractdiag", "makediag", "extracttrian", "maketrian",
]


def _as_nd(x: Any) -> NDArray:
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x), _wrap=True)


def _reg(fn, name=None):
    register_op("linalg_" + (name or fn.__name__), fn)
    return fn


# ---------------------------------------------------------------------------
# numpy.linalg semantics (reference: src/operator/numpy/linalg/)
# ---------------------------------------------------------------------------

@_reg
def norm(x, ord=None, axis=None, keepdims=False):  # noqa: A002
    o, ax, kd = ord, axis, keepdims
    return invoke("linalg_norm",
                  lambda a: jnp.linalg.norm(a, ord=o, axis=ax, keepdims=kd),
                  (_as_nd(x),))


@_reg
def matrix_norm(x, ord="fro", keepdims=False):  # noqa: A002
    o, kd = ord, keepdims
    return invoke("linalg_matrix_norm",
                  lambda a: jnp.linalg.norm(a, ord=o, axis=(-2, -1), keepdims=kd),
                  (_as_nd(x),))


@_reg
def vector_norm(x, ord=2, axis=None, keepdims=False):  # noqa: A002
    o, ax, kd = ord, axis, keepdims

    def impl(a):
        if ax is None:
            a = a.ravel()
            return jnp.linalg.norm(a, ord=o, keepdims=kd)
        return jnp.linalg.norm(a, ord=o, axis=ax, keepdims=kd)

    return invoke("linalg_vector_norm", impl, (_as_nd(x),))


@_reg
def svd(a, full_matrices=False, compute_uv=True):
    fm, cu = full_matrices, compute_uv
    nd = _as_nd(a)
    if not cu:
        return invoke("linalg_svdvals",
                      lambda x: jnp.linalg.svd(x, full_matrices=fm,
                                               compute_uv=False), (nd,))
    return invoke("linalg_svd",
                  lambda x: tuple(jnp.linalg.svd(x, full_matrices=fm)), (nd,))


@_reg
def svdvals(a):
    return svd(a, compute_uv=False)


@_reg
def inv(a):
    return invoke("linalg_inv", jnp.linalg.inv, (_as_nd(a),))


@_reg
def pinv(a, rcond=None, hermitian=False):
    rc, h = rcond, hermitian
    return invoke("linalg_pinv",
                  lambda x: jnp.linalg.pinv(x, rcond=rc, hermitian=h),
                  (_as_nd(a),))


@_reg
def det(a):
    return invoke("linalg_det", jnp.linalg.det, (_as_nd(a),))


@_reg
def slogdet(a):
    return invoke("linalg_slogdet",
                  lambda x: tuple(jnp.linalg.slogdet(x)), (_as_nd(a),))


@_reg
def cholesky(a, upper=False):
    up = upper

    def impl(x):
        l = jnp.linalg.cholesky(x)
        return jnp.swapaxes(l, -1, -2).conj() if up else l

    return invoke("linalg_cholesky", impl, (_as_nd(a),))


@_reg
def qr(a, mode="reduced"):
    m = mode
    return invoke("linalg_qr",
                  lambda x: tuple(jnp.linalg.qr(x, mode=m)), (_as_nd(a),))


@_reg
def eig(a):
    # jnp.linalg.eig is CPU-only in XLA; evaluate on host, return device arrays.
    nd = _as_nd(a)
    w, v = _np.linalg.eig(_np.asarray(nd.asnumpy()))
    return from_jax(jnp.asarray(w)), from_jax(jnp.asarray(v))


@_reg
def eigvals(a):
    nd = _as_nd(a)
    w = _np.linalg.eigvals(_np.asarray(nd.asnumpy()))
    return from_jax(jnp.asarray(w))


@_reg
def eigh(a, UPLO="L"):  # noqa: N803
    u = UPLO
    return invoke("linalg_eigh",
                  lambda x: tuple(jnp.linalg.eigh(x, UPLO=u)), (_as_nd(a),))


@_reg
def eigvalsh(a, UPLO="L"):  # noqa: N803
    u = UPLO
    return invoke("linalg_eigvalsh",
                  lambda x: jnp.linalg.eigvalsh(x, UPLO=u), (_as_nd(a),))


@_reg
def solve(a, b):
    return invoke("linalg_solve", jnp.linalg.solve, (_as_nd(a), _as_nd(b)))


@_reg
def lstsq(a, b, rcond="warn"):
    rc = None if rcond == "warn" else rcond
    nd_a, nd_b = _as_nd(a), _as_nd(b)
    x, res, rank, s = jnp.linalg.lstsq(nd_a._data, nd_b._data, rcond=rc)
    return from_jax(x), from_jax(res), int(rank), from_jax(s)


@_reg
def matrix_rank(a, tol=None, hermitian=False):
    t = tol
    nd = _as_nd(a)
    r = jnp.linalg.matrix_rank(nd._data, tol=t)
    return from_jax(r)


@_reg
def matrix_power(a, n):
    nn = n
    return invoke("linalg_matrix_power",
                  lambda x: jnp.linalg.matrix_power(x, nn), (_as_nd(a),))


@_reg
def multi_dot(arrays):
    nds = [_as_nd(a) for a in arrays]
    return invoke("linalg_multi_dot",
                  lambda *xs: jnp.linalg.multi_dot(list(xs)), nds)


@_reg
def tensorinv(a, ind=2):
    i = ind
    return invoke("linalg_tensorinv",
                  lambda x: jnp.linalg.tensorinv(x, ind=i), (_as_nd(a),))


@_reg
def tensorsolve(a, b, axes=None):
    ax = axes
    return invoke("linalg_tensorsolve",
                  lambda x, y: jnp.linalg.tensorsolve(x, y, axes=ax),
                  (_as_nd(a), _as_nd(b)))


@_reg
def cond(x, p=None):
    pp = p
    nd = _as_nd(x)
    return from_jax(jnp.linalg.cond(nd._data, p=pp))


@_reg
def outer(a, b):
    return invoke("linalg_outer",
                  lambda x, y: jnp.outer(x.ravel(), y.ravel()),
                  (_as_nd(a), _as_nd(b)))


@_reg
def cross(a, b, axis=-1):
    ax = axis
    return invoke("linalg_cross",
                  lambda x, y: jnp.cross(x, y, axis=ax),
                  (_as_nd(a), _as_nd(b)))


@_reg
def trace(a, offset=0):
    off = offset
    return invoke("linalg_trace",
                  lambda x: jnp.trace(x, offset=off, axis1=-2, axis2=-1),
                  (_as_nd(a),))


@_reg
def diagonal(a, offset=0):
    off = offset
    return invoke("linalg_diagonal",
                  lambda x: jnp.diagonal(x, offset=off, axis1=-2, axis2=-1),
                  (_as_nd(a),))


@_reg
def matmul(a, b):
    return invoke("linalg_matmul", jnp.matmul, (_as_nd(a), _as_nd(b)))


@_reg
def matrix_transpose(a):
    return invoke("linalg_matrix_transpose",
                  lambda x: jnp.swapaxes(x, -1, -2), (_as_nd(a),))


# ---------------------------------------------------------------------------
# mxnet la_op family (reference: src/operator/tensor/la_op.cc _linalg_*)
# ---------------------------------------------------------------------------

@_reg
def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):  # noqa: N803
    ta, tb, al, be = transpose_a, transpose_b, alpha, beta

    def impl(a, b, c):
        if ta:
            a = jnp.swapaxes(a, -1, -2)
        if tb:
            b = jnp.swapaxes(b, -1, -2)
        return al * jnp.matmul(a, b) + be * c

    return invoke("linalg_gemm", impl, (_as_nd(A), _as_nd(B), _as_nd(C)))


@_reg
def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):  # noqa: N803
    ta, tb, al = transpose_a, transpose_b, alpha

    def impl(a, b):
        if ta:
            a = jnp.swapaxes(a, -1, -2)
        if tb:
            b = jnp.swapaxes(b, -1, -2)
        return al * jnp.matmul(a, b)

    return invoke("linalg_gemm2", impl, (_as_nd(A), _as_nd(B)))


@_reg
def potrf(A, lower=True):  # noqa: N803
    lo = lower

    def impl(a):
        l = jnp.linalg.cholesky(a)
        return l if lo else jnp.swapaxes(l, -1, -2)

    return invoke("linalg_potrf", impl, (_as_nd(A),))


@_reg
def potri(A, lower=True):  # noqa: N803
    lo = lower

    def impl(a):
        l = a if lo else jnp.swapaxes(a, -1, -2)
        eye = jnp.eye(a.shape[-1], dtype=a.dtype)
        linv = jnp.linalg.solve(l, jnp.broadcast_to(eye, a.shape))
        return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)

    return invoke("linalg_potri", impl, (_as_nd(A),))


@_reg
def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):  # noqa: N803
    import jax.scipy.linalg as jsl
    tr, rs, lo, al = transpose, rightside, lower, alpha

    def impl(a, b):
        if rs:
            # solve X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
            x = jsl.solve_triangular(a, al * jnp.swapaxes(b, -1, -2),
                                     lower=lo, trans=0 if tr else 1)
            return jnp.swapaxes(x, -1, -2)
        return jsl.solve_triangular(a, al * b, lower=lo, trans=1 if tr else 0)

    return invoke("linalg_trsm", impl, (_as_nd(A), _as_nd(B)))


@_reg
def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):  # noqa: N803
    tr, rs, lo, al = transpose, rightside, lower, alpha

    def impl(a, b):
        t = jnp.tril(a) if lo else jnp.triu(a)
        if tr:
            t = jnp.swapaxes(t, -1, -2)
        return al * (jnp.matmul(b, t) if rs else jnp.matmul(t, b))

    return invoke("linalg_trmm", impl, (_as_nd(A), _as_nd(B)))


@_reg
def syrk(A, transpose=False, alpha=1.0):  # noqa: N803
    tr, al = transpose, alpha

    def impl(a):
        at = jnp.swapaxes(a, -1, -2)
        return al * (jnp.matmul(at, a) if tr else jnp.matmul(a, at))

    return invoke("linalg_syrk", impl, (_as_nd(A),))


@_reg
def sumlogdiag(A):  # noqa: N803
    return invoke("linalg_sumlogdiag",
                  lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)),
                                    axis=-1), (_as_nd(A),))


@_reg
def extractdiag(A, offset=0):  # noqa: N803
    off = offset
    return invoke("linalg_extractdiag",
                  lambda a: jnp.diagonal(a, offset=off, axis1=-2, axis2=-1),
                  (_as_nd(A),))


@_reg
def makediag(A, offset=0):  # noqa: N803
    off = offset
    return invoke("linalg_makediag",
                  lambda a: _batched_diag(a, off), (_as_nd(A),))


def _batched_diag(a, offset):
    import jax
    if a.ndim == 1:
        return jnp.diag(a, k=offset)
    fn = _batched_diag
    return jax.vmap(lambda x: fn(x, offset))(a)


@_reg
def extracttrian(A, offset=0, lower=True):  # noqa: N803
    off, lo = offset, lower

    def impl(a):
        n = a.shape[-1]
        rows, cols = _np.tril_indices(n, k=off) if lo else _np.triu_indices(n, k=off)
        return a[..., rows, cols]

    return invoke("linalg_extracttrian", impl, (_as_nd(A),))


@_reg
def maketrian(A, offset=0, lower=True):  # noqa: N803
    off, lo = offset, lower

    def impl(a):
        m = a.shape[-1]
        k = abs(off)
        strict = (lo and off < 0) or (not lo and off > 0)
        if strict:
            # strict triangle: m = (n-k)(n-k+1)/2 over an n x n matrix
            n = int((_np.sqrt(8 * m + 1) - 1) / 2) + k
        else:
            # widened triangle: m = n(n+1)/2 + sum of the k extra diagonals
            n = int((_np.sqrt(8 * m + (2 * k + 1) ** 2) - (2 * k + 1)) / 2) + k
        rows, cols = _np.tril_indices(n, k=off) if lo else _np.triu_indices(n, k=off)
        out = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
        return out.at[..., rows, cols].set(a)

    return invoke("linalg_maketrian", impl, (_as_nd(A),))
