"""Extended NumPy-semantics op surface (``mx.np`` beyond the core set).

Reference parity (leezu/mxnet): ``src/operator/numpy/*`` (np broadcast /
reduce / init / where / unique / einsum families) and
``python/mxnet/numpy/multiarray.py`` — the 2.x NumPy interface the leezu
fork's era standardized on (SURVEY.md section 2.2 "NumPy ops").

Design (tpu-first): thin pure-jax compositions; autograd via the vjp hook in
``register.invoke``. Stacking/combining helpers, nan-reductions, bitwise
ops, statistics, and index helpers that round out ``mx.np`` to practical
numpy drop-in coverage.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as _np

from .ndarray import NDArray, from_jax
from .register import invoke, register_op

__all__: list = []


def _public(fn, name=None):
    name = name or fn.__name__
    __all__.append(name)
    register_op(name, fn)
    return fn


def _as_nd(x: Any) -> NDArray:
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x), _wrap=True)


def _nds(seq) -> list:
    return [_as_nd(x) for x in seq]


# ---------------------------------------------------------------------------
# Stacking / combining
# ---------------------------------------------------------------------------

@_public
def vstack(tup):
    return invoke("vstack", lambda *xs: jnp.vstack(list(xs)), _nds(tup))


@_public
def hstack(tup):
    return invoke("hstack", lambda *xs: jnp.hstack(list(xs)), _nds(tup))


@_public
def dstack(tup):
    return invoke("dstack", lambda *xs: jnp.dstack(list(xs)), _nds(tup))


@_public
def column_stack(tup):
    return invoke("column_stack", lambda *xs: jnp.column_stack(list(xs)),
                  _nds(tup))


row_stack = _public(vstack, "row_stack")


@_public
def append(arr, values, axis=None):
    ax = axis
    return invoke("append", lambda a, v: jnp.append(a, v, axis=ax),
                  (_as_nd(arr), _as_nd(values)))


@_public
def insert(arr, obj, values, axis=None):
    o, ax = obj, axis
    return invoke("insert", lambda a, v: jnp.insert(a, o, v, axis=ax),
                  (_as_nd(arr), _as_nd(values)))


@_public
def delete(arr, obj, axis=None):
    o, ax = obj, axis
    return invoke("delete", lambda a: jnp.delete(a, o, axis=ax),
                  (_as_nd(arr),))


@_public
def resize(a, new_shape):
    ns = new_shape
    return invoke("resize", lambda x: jnp.resize(x, ns), (_as_nd(a),))


@_public
def trim_zeros(filt, trim="fb"):
    nd = _as_nd(filt)
    return from_jax(jnp.asarray(_np.trim_zeros(_np.asarray(nd.asnumpy()), trim)))


@_public
def rot90(m, k=1, axes=(0, 1)):
    kk, ax = k, axes
    return invoke("rot90", lambda x: jnp.rot90(x, k=kk, axes=ax), (_as_nd(m),))


@_public
def fliplr(m):
    return invoke("fliplr", jnp.fliplr, (_as_nd(m),))


@_public
def flipud(m):
    return invoke("flipud", jnp.flipud, (_as_nd(m),))


@_public
def broadcast_arrays(*args):
    arrs = _nds(args)
    outs = jnp.broadcast_arrays(*[a._data for a in arrs])
    return [from_jax(o) for o in outs]


# ---------------------------------------------------------------------------
# Statistics
# ---------------------------------------------------------------------------

@_public
def average(a, axis=None, weights=None, returned=False):
    ax, ret = axis, returned
    if weights is None:
        out = invoke("average", lambda x: jnp.mean(x, axis=ax), (_as_nd(a),))
        if ret:
            nd = _as_nd(a)
            n = nd.size if ax is None else nd.shape[ax]
            return out, from_jax(jnp.full_like(out._data, n))
        return out
    nd_a, nd_w = _as_nd(a), _as_nd(weights)
    out = invoke("average",
                 lambda x, w: jnp.average(x, axis=ax, weights=w),
                 (nd_a, nd_w))
    if ret:
        def sumw(x, w):
            if w.ndim != x.ndim:
                pos = (ax if ax is not None else 0) % x.ndim
                w = jnp.expand_dims(w, tuple(i for i in range(x.ndim)
                                             if i != pos))
            return jnp.sum(jnp.broadcast_to(w, x.shape), axis=ax)

        return out, invoke("average_sumw", sumw, (nd_a, nd_w))
    return out


@_public
def median(a, axis=None, keepdims=False):
    ax, kd = axis, keepdims
    return invoke("median",
                  lambda x: jnp.median(x, axis=ax, keepdims=kd), (_as_nd(a),))


@_public
def quantile(a, q, axis=None, keepdims=False, interpolation=None, method="linear"):
    ax, kd = axis, keepdims
    m = interpolation or method
    return invoke("quantile",
                  lambda x, qq: jnp.quantile(x, qq, axis=ax, keepdims=kd,
                                             method=m),
                  (_as_nd(a), _as_nd(q)))


@_public
def percentile(a, q, axis=None, keepdims=False, interpolation=None,
               method="linear"):
    ax, kd = axis, keepdims
    m = interpolation or method
    return invoke("percentile",
                  lambda x, qq: jnp.percentile(x, qq, axis=ax, keepdims=kd,
                                               method=m),
                  (_as_nd(a), _as_nd(q)))


@_public
def ptp(a, axis=None, keepdims=False):
    ax, kd = axis, keepdims
    return invoke("ptp", lambda x: jnp.ptp(x, axis=ax, keepdims=kd),
                  (_as_nd(a),))


@_public
def bincount(x, weights=None, minlength=0):
    ml = minlength
    if weights is None:
        return invoke("bincount",
                      lambda a: jnp.bincount(a, minlength=ml), (_as_nd(x),))
    return invoke("bincount",
                  lambda a, w: jnp.bincount(a, weights=w, minlength=ml),
                  (_as_nd(x), _as_nd(weights)))


@_public
def corrcoef(x, y=None):
    if y is None:
        return invoke("corrcoef", jnp.corrcoef, (_as_nd(x),))
    return invoke("corrcoef", jnp.corrcoef, (_as_nd(x), _as_nd(y)))


@_public
def cov(m, y=None, rowvar=True, bias=False, ddof=None):
    rv, b, dd = rowvar, bias, ddof
    if y is None:
        return invoke("cov",
                      lambda x: jnp.cov(x, rowvar=rv, bias=b, ddof=dd),
                      (_as_nd(m),))
    return invoke("cov",
                  lambda x, yy: jnp.cov(x, yy, rowvar=rv, bias=b, ddof=dd),
                  (_as_nd(m), _as_nd(y)))


@_public
def count_nonzero(a, axis=None, keepdims=False):
    ax, kd = axis, keepdims
    return invoke("count_nonzero",
                  lambda x: jnp.count_nonzero(x, axis=ax, keepdims=kd),
                  (_as_nd(a),))


@_public
def ediff1d(ary, to_end=None, to_begin=None):
    te, tb = to_end, to_begin
    return invoke("ediff1d",
                  lambda x: jnp.ediff1d(x, to_end=te, to_begin=tb),
                  (_as_nd(ary),))


# nan-reductions ------------------------------------------------------------

def _nanred(name, jfn):
    def fn(a, axis=None, keepdims=False):
        ax, kd = axis, keepdims
        return invoke(name, lambda x: jfn(x, axis=ax, keepdims=kd),
                      (_as_nd(a),))
    fn.__name__ = name
    return _public(fn)


nansum = _nanred("nansum", jnp.nansum)
nanprod = _nanred("nanprod", jnp.nanprod)
nanmean = _nanred("nanmean", jnp.nanmean)
nanmax = _nanred("nanmax", jnp.nanmax)
nanmin = _nanred("nanmin", jnp.nanmin)
nanstd = _nanred("nanstd", jnp.nanstd)
nanvar = _nanred("nanvar", jnp.nanvar)


@_public
def nanargmax(a, axis=None):
    ax = axis
    return invoke("nanargmax", lambda x: jnp.nanargmax(x, axis=ax),
                  (_as_nd(a),))


@_public
def nanargmin(a, axis=None):
    ax = axis
    return invoke("nanargmin", lambda x: jnp.nanargmin(x, axis=ax),
                  (_as_nd(a),))


@_public
def nancumsum(a, axis=None):
    ax = axis
    return invoke("nancumsum", lambda x: jnp.nancumsum(x, axis=ax),
                  (_as_nd(a),))


@_public
def nanmedian(a, axis=None, keepdims=False):
    ax, kd = axis, keepdims
    return invoke("nanmedian",
                  lambda x: jnp.nanmedian(x, axis=ax, keepdims=kd),
                  (_as_nd(a),))


# ---------------------------------------------------------------------------
# Bitwise / integer ops
# ---------------------------------------------------------------------------

def _binop(name, jfn):
    def fn(a, b):
        return invoke(name, jfn, (_as_nd(a), _as_nd(b)))
    fn.__name__ = name
    return _public(fn)


bitwise_and = _binop("bitwise_and", jnp.bitwise_and)
bitwise_or = _binop("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binop("bitwise_xor", jnp.bitwise_xor)
left_shift = _binop("left_shift", jnp.left_shift)
right_shift = _binop("right_shift", jnp.right_shift)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)
heaviside = _binop("heaviside", jnp.heaviside)
float_power = _binop("float_power", jnp.float_power)
ldexp = _binop("ldexp", jnp.ldexp)
nextafter = _binop("nextafter", jnp.nextafter)


@_public
def bitwise_not(a):
    return invoke("bitwise_not", jnp.bitwise_not, (_as_nd(a),))


invert = _public(bitwise_not, "invert")


@_public
def positive(a):
    return invoke("positive", jnp.positive, (_as_nd(a),))


@_public
def exp2(a):
    return invoke("exp2", jnp.exp2, (_as_nd(a),))


@_public
def signbit(a):
    return invoke("signbit", jnp.signbit, (_as_nd(a),))


@_public
def frexp(a):
    nd = _as_nd(a)
    m, e = jnp.frexp(nd._data)
    return from_jax(m), from_jax(e)


@_public
def modf(a):
    nd = _as_nd(a)
    frac, intg = jnp.modf(nd._data)
    return from_jax(frac), from_jax(intg)


@_public
def divmod(a, b):  # noqa: A001
    nd_a, nd_b = _as_nd(a), _as_nd(b)
    q, r = jnp.divmod(nd_a._data, nd_b._data)
    return from_jax(q), from_jax(r)


@_public
def deg2rad(a):
    return invoke("deg2rad", jnp.deg2rad, (_as_nd(a),))


@_public
def rad2deg(a):
    return invoke("rad2deg", jnp.rad2deg, (_as_nd(a),))


@_public
def around(a, decimals=0):
    d = decimals
    return invoke("around", lambda x: jnp.round(x, decimals=d), (_as_nd(a),))


@_public
def real(a):
    return invoke("real", jnp.real, (_as_nd(a),))


@_public
def imag(a):
    return invoke("imag", jnp.imag, (_as_nd(a),))


@_public
def conj(a):
    return invoke("conj", jnp.conj, (_as_nd(a),))


conjugate = _public(conj, "conjugate")


@_public
def angle(a, deg=False):
    d = deg
    return invoke("angle", lambda x: jnp.angle(x, deg=d), (_as_nd(a),))


@_public
def i0(a):
    return invoke("i0", jnp.i0, (_as_nd(a),))


@_public
def sinc(a):
    return invoke("sinc", jnp.sinc, (_as_nd(a),))


# ---------------------------------------------------------------------------
# Windows / ranges / grids
# ---------------------------------------------------------------------------

@_public
def hanning(M, dtype="float32"):  # noqa: N803
    return from_jax(jnp.hanning(M).astype(dtype))


@_public
def hamming(M, dtype="float32"):  # noqa: N803
    return from_jax(jnp.hamming(M).astype(dtype))


@_public
def blackman(M, dtype="float32"):  # noqa: N803
    return from_jax(jnp.blackman(M).astype(dtype))


@_public
def bartlett(M, dtype="float32"):  # noqa: N803
    return from_jax(jnp.bartlett(M).astype(dtype))


@_public
def kaiser(M, beta, dtype="float32"):  # noqa: N803
    return from_jax(jnp.kaiser(M, beta).astype(dtype))


@_public
def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    arr = jnp.logspace(start, stop, num=num, endpoint=endpoint, base=base,
                       dtype=dtype)
    return NDArray(arr, ctx=ctx)


@_public
def geomspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    arr = jnp.geomspace(start, stop, num=num, endpoint=endpoint, dtype=dtype)
    return NDArray(arr, ctx=ctx)


@_public
def indices(dimensions, dtype="int32", ctx=None):
    return NDArray(jnp.indices(dimensions, dtype=dtype), ctx=ctx)


@_public
def tri(N, M=None, k=0, dtype="float32", ctx=None):  # noqa: N803
    return NDArray(jnp.tri(N, M=M, k=k, dtype=dtype), ctx=ctx)


@_public
def vander(x, N=None, increasing=False):  # noqa: N803
    n, inc = N, increasing
    return invoke("vander",
                  lambda a: jnp.vander(a, N=n, increasing=inc), (_as_nd(x),))


@_public
def tril_indices(n, k=0, m=None):
    rows, cols = jnp.tril_indices(n, k=k, m=m)
    return from_jax(rows), from_jax(cols)


@_public
def triu_indices(n, k=0, m=None):
    rows, cols = jnp.triu_indices(n, k=k, m=m)
    return from_jax(rows), from_jax(cols)


@_public
def diag_indices(n, ndim=2):
    out = jnp.diag_indices(n, ndim=ndim)
    return tuple(from_jax(o) for o in out)


@_public
def unravel_index(indices, shape):  # noqa: A002
    sh = shape
    nd = _as_nd(indices)
    out = jnp.unravel_index(nd._data, sh)
    return tuple(from_jax(o) for o in out)


@_public
def ravel_multi_index(multi_index, dims, mode="raise"):
    m = mode
    nds = _nds(multi_index)
    out = jnp.ravel_multi_index(tuple(a._data for a in nds), dims, mode=m)
    return from_jax(out)


# ---------------------------------------------------------------------------
# Selection / comparison
# ---------------------------------------------------------------------------

@_public
def select(condlist, choicelist, default=0):
    d = default
    conds = _nds(condlist)
    choices = _nds(choicelist)
    n = len(conds)

    def impl(*xs):
        return jnp.select(list(xs[:n]), list(xs[n:]), default=d)

    return invoke("select", impl, conds + choices)


@_public
def extract(condition, arr):
    nd_c, nd_a = _as_nd(condition), _as_nd(arr)
    return from_jax(jnp.extract(nd_c._data, nd_a._data))


@_public
def compress(condition, a, axis=None):
    ax = axis
    nd_c, nd_a = _as_nd(condition), _as_nd(a)
    return from_jax(jnp.compress(nd_c._data, nd_a._data, axis=ax))


@_public
def choose(a, choices, mode="raise"):
    m = mode
    nd = _as_nd(a)
    ch = _nds(choices)

    def impl(x, *cs):
        # 'raise' needs a concrete index check, impossible under tracing —
        # fall back to numpy's documented alternative there.
        mm = m
        if mm == "raise" and isinstance(x, jax.core.Tracer):
            mm = "clip"
        return jnp.choose(x, list(cs), mode=mm)

    # mode='raise' validates indices against concrete values — the per-op
    # executable cache would silently degrade it to 'clip'
    return invoke("choose", impl, [nd] + ch, eager_only=(m == "raise"))


@_public
def argwhere(a):
    nd = _as_nd(a)
    return from_jax(jnp.argwhere(nd._data))


@_public
def flatnonzero(a):
    nd = _as_nd(a)
    return from_jax(jnp.flatnonzero(nd._data))


@_public
def array_equal(a1, a2):
    nd1, nd2 = _as_nd(a1), _as_nd(a2)
    return bool(jnp.array_equal(nd1._data, nd2._data))


@_public
def array_equiv(a1, a2):
    nd1, nd2 = _as_nd(a1), _as_nd(a2)
    return bool(jnp.array_equiv(nd1._data, nd2._data))


@_public
def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    nd1, nd2 = _as_nd(a), _as_nd(b)
    return bool(jnp.allclose(nd1._data, nd2._data, rtol=rtol, atol=atol,
                             equal_nan=equal_nan))


@_public
def isin(element, test_elements, invert=False):  # noqa: A002
    inv = invert
    return invoke("isin",
                  lambda e, t: jnp.isin(e, t, invert=inv),
                  (_as_nd(element), _as_nd(test_elements)))


@_public
def union1d(ar1, ar2):
    nd1, nd2 = _as_nd(ar1), _as_nd(ar2)
    return from_jax(jnp.union1d(nd1._data, nd2._data))


@_public
def intersect1d(ar1, ar2, assume_unique=False):
    au = assume_unique
    nd1, nd2 = _as_nd(ar1), _as_nd(ar2)
    return from_jax(jnp.intersect1d(nd1._data, nd2._data, assume_unique=au))


@_public
def setdiff1d(ar1, ar2, assume_unique=False):
    au = assume_unique
    nd1, nd2 = _as_nd(ar1), _as_nd(ar2)
    return from_jax(jnp.setdiff1d(nd1._data, nd2._data, assume_unique=au))


@_public
def in1d(ar1, ar2, invert=False):  # noqa: A002
    inv = invert
    nd1, nd2 = _as_nd(ar1), _as_nd(ar2)
    return from_jax(jnp.isin(nd1._data.ravel(), nd2._data, invert=inv))


# ---------------------------------------------------------------------------
# Polynomials / misc math
# ---------------------------------------------------------------------------

@_public
def polyval(p, x):
    return invoke("polyval", jnp.polyval, (_as_nd(p), _as_nd(x)))


@_public
def polyfit(x, y, deg):
    nd_x, nd_y = _as_nd(x), _as_nd(y)
    return from_jax(jnp.polyfit(nd_x._data.astype("float32"),
                                nd_y._data.astype("float32"), deg))


@_public
def roots(p):
    nd = _as_nd(p)
    return from_jax(jnp.asarray(_np.roots(_np.asarray(nd.asnumpy()))))


@_public
def convolve(a, v, mode="full"):
    m = mode
    return invoke("convolve", lambda x, y: jnp.convolve(x, y, mode=m),
                  (_as_nd(a), _as_nd(v)))


@_public
def correlate(a, v, mode="valid"):
    m = mode
    return invoke("correlate", lambda x, y: jnp.correlate(x, y, mode=m),
                  (_as_nd(a), _as_nd(v)))


@_public
def gradient(f, *varargs, axis=None):
    ax = axis
    nd = _as_nd(f)
    out = jnp.gradient(nd._data, *varargs, axis=ax)
    if isinstance(out, (tuple, list)):
        return [from_jax(o) for o in out]
    return from_jax(out)


@_public
def trapz(y, x=None, dx=1.0, axis=-1):
    d, ax = dx, axis
    if x is None:
        return invoke("trapz",
                      lambda yy: jnp.trapezoid(yy, dx=d, axis=ax), (_as_nd(y),))
    return invoke("trapz",
                  lambda yy, xx: jnp.trapezoid(yy, xx, axis=ax),
                  (_as_nd(y), _as_nd(x)))


@_public
def digitize(x, bins, right=False):
    r = right
    return invoke("digitize",
                  lambda a, b: jnp.digitize(a, b, right=r),
                  (_as_nd(x), _as_nd(bins)))


@_public
def piecewise(x, condlist, funclist):
    nd = _as_nd(x)
    conds = [_as_nd(c)._data for c in condlist]
    return from_jax(jnp.piecewise(nd._data, conds, funclist))


@_public
def apply_along_axis(func1d, axis, arr, *args, **kwargs):
    nd = _as_nd(arr)
    return from_jax(jnp.apply_along_axis(func1d, axis, nd._data, *args, **kwargs))


@_public
def may_share_memory(a, b):
    # functional XLA arrays: views share buffers only via jax aliasing,
    # which is not observable — mirror numpy's conservative False.
    return False


shares_memory = _public(may_share_memory, "shares_memory")


@_public
def result_type(*args):
    vals = [a._data if isinstance(a, NDArray) else a for a in args]
    return _np.dtype(jnp.result_type(*vals))


@_public
def promote_types(t1, t2):
    return _np.dtype(jnp.promote_types(t1, t2))


@_public
def can_cast(from_, to, casting="safe"):
    if isinstance(from_, NDArray):
        from_ = from_.dtype
    return _np.can_cast(from_, to, casting=casting)


@_public
def ndim(a):
    return _as_nd(a).ndim


@_public
def shape(a):
    return _as_nd(a).shape


@_public
def size(a, axis=None):
    nd = _as_nd(a)
    return nd.size if axis is None else nd.shape[axis]


@_public
def copy(a):
    return invoke("copy", lambda x: x + 0, (_as_nd(a),))


@_public
def require(a, dtype=None, requirements=None):
    nd = _as_nd(a)
    if dtype is not None:
        return from_jax(nd._data.astype(dtype))
    return nd


# ---------------------------------------------------------------------------
# Linear-algebra / index round-out (np.cross, diagonal, sorting variants)
# ---------------------------------------------------------------------------

@_public
def cross(a, b, axisa=-1, axisb=-1, axisc=-1, axis=None):
    if axis is not None:
        axisa = axisb = axisc = axis
    return invoke("cross",
                  lambda x, y: jnp.cross(x, y, axisa=axisa, axisb=axisb,
                                         axisc=axisc),
                  (_as_nd(a), _as_nd(b)))


@_public
def diagonal(a, offset=0, axis1=0, axis2=1):
    return invoke("diagonal",
                  lambda x: jnp.diagonal(x, offset=offset, axis1=axis1,
                                         axis2=axis2),
                  (_as_nd(a),))


@_public
def partition(a, kth, axis=-1):
    return invoke("partition",
                  lambda x: jnp.partition(x, kth=kth, axis=axis),
                  (_as_nd(a),))


@_public
def argpartition(a, kth, axis=-1):
    return invoke("argpartition",
                  lambda x: jnp.argpartition(x, kth=kth, axis=axis),
                  (_as_nd(a),))


@_public
def lexsort(keys, axis=-1):
    return invoke("lexsort",
                  lambda *ks: jnp.lexsort(list(ks), axis=axis),
                  _nds(list(keys)))


@_public
def packbits(a, axis=None, bitorder="big"):
    return invoke("packbits",
                  lambda x: jnp.packbits(x, axis=axis, bitorder=bitorder),
                  (_as_nd(a),))


@_public
def unpackbits(a, axis=None, count=None, bitorder="big"):
    return invoke("unpackbits",
                  lambda x: jnp.unpackbits(x, axis=axis, count=count,
                                           bitorder=bitorder),
                  (_as_nd(a),))


@_public
def atleast_3d(*arys):
    outs = [invoke("atleast_3d", jnp.atleast_3d, (_as_nd(a),))
            for a in arys]
    return outs[0] if len(outs) == 1 else outs


@_public
def hsplit(a, indices_or_sections):
    i = indices_or_sections
    i = tuple(i) if isinstance(i, (list, tuple)) else i
    return invoke("hsplit", lambda x: tuple(jnp.hsplit(x, i)),
                  (_as_nd(a),))


@_public
def vsplit(a, indices_or_sections):
    i = indices_or_sections
    i = tuple(i) if isinstance(i, (list, tuple)) else i
    return invoke("vsplit", lambda x: tuple(jnp.vsplit(x, i)),
                  (_as_nd(a),))


@_public
def dsplit(a, indices_or_sections):
    i = indices_or_sections
    i = tuple(i) if isinstance(i, (list, tuple)) else i
    return invoke("dsplit", lambda x: tuple(jnp.dsplit(x, i)),
                  (_as_nd(a),))


@_public
def put_along_axis(arr, indices, values, axis):
    """Out-of-place put_along_axis (arrays are immutable under XLA —
    returns the updated array rather than mutating, the np.put_along_axis
    semantics applied functionally)."""
    ax = axis
    return invoke(
        "put_along_axis",
        lambda a, i, v: jnp.put_along_axis(a, i.astype(jnp.int32), v, ax,
                                           inplace=False),
        (_as_nd(arr), _as_nd(indices), _as_nd(values)))


@_public
def fill_diagonal(a, val, wrap=False):
    """Out-of-place fill_diagonal (returns the filled array)."""
    w = bool(wrap)

    def impl(x, v):
        return jnp.fill_diagonal(x, v, wrap=w, inplace=False)

    return invoke("fill_diagonal", impl, (_as_nd(a), _as_nd(val)))


@_public
def histogram2d(x, y, bins=10, range=None, weights=None):
    b, r = bins, range
    if weights is not None:
        return invoke(
            "histogram2d",
            lambda xx, yy, ww: jnp.histogram2d(xx, yy, bins=b, range=r,
                                               weights=ww),
            (_as_nd(x), _as_nd(y), _as_nd(weights)))
    return invoke("histogram2d",
                  lambda xx, yy: jnp.histogram2d(xx, yy, bins=b, range=r),
                  (_as_nd(x), _as_nd(y)))


@_public
def block(arrays):
    """np.block over (possibly nested) lists of NDArrays."""
    # the nesting structure closes over the impl as HASHABLE nested
    # tuples of leaf indices (a PyTreeDef in the closure would defeat
    # the per-op executable cache's attr tokenization)
    leaves = []

    def index_of(node):
        if isinstance(node, list):
            return tuple(index_of(c) for c in node)
        leaves.append(node)
        return len(leaves) - 1

    struct = index_of(arrays)
    nds = tuple(_as_nd(v) for v in leaves)

    def impl(*xs):
        def rebuild(s):
            if isinstance(s, tuple):
                return [rebuild(c) for c in s]
            return xs[s]
        return jnp.block(rebuild(struct))

    return invoke("block", impl, nds)


def _ix_(*seqs):
    """np.ix_ open-mesh helper (host-side: returns reshaped index
    NDArrays, no compiled op needed)."""
    import numpy as _onp
    outs = _onp.ix_(*[_as_nd(s).asnumpy() for s in seqs])
    from .ndarray import NDArray as _ND
    return tuple(_ND(o) for o in outs)


ix_ = _public(_ix_, "ix_")
