"""``mx.nd.image`` operator namespace — image transform ops.

Reference parity (leezu/mxnet): ``src/operator/image/image_random.cc``,
``resize.cc``, ``crop.cc`` (``_image_to_tensor``, ``_image_normalize``,
``_image_resize``, ``_image_crop``, flips and color jitters) which back the
gluon vision transforms.

Design (tpu-first): every op is a pure jax function over HWC / NHWC arrays;
color jitter randomness uses numpy host RNG at call sites (augmentation is a
host-side pipeline stage feeding the device, like the reference's CPU-side
OpenCV augmenters), while the arithmetic itself is XLA-traceable so the same
ops can be fused on-device when composed under hybridize.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as _np

from .ndarray import NDArray, from_jax
from .register import invoke

__all__ = ["to_tensor", "normalize", "resize", "crop", "random_crop",
           "flip_left_right", "flip_top_bottom", "random_flip_left_right",
           "random_flip_top_bottom", "adjust_lighting", "random_lighting",
           "random_brightness", "random_contrast", "random_saturation",
           "random_hue", "random_color_jitter"]

_R, _G, _B = 0.299, 0.587, 0.114


def _as_jax(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _chan_axis(x) -> int:
    # HWC (3d) or NHWC (4d) — the reference's image ops use channels-last.
    return x.ndim - 1


def to_tensor(data) -> NDArray:
    """HWC/NHWC uint8 [0,255] -> CHW/NCHW float32 [0,1]
    (reference: ``_image_to_tensor``)."""
    def impl(x):
        x = x.astype(jnp.float32) / 255.0
        if x.ndim == 3:
            return jnp.transpose(x, (2, 0, 1))
        return jnp.transpose(x, (0, 3, 1, 2))
    return invoke("image_to_tensor", impl, (_wrap(data),))


def normalize(data, mean=0.0, std=1.0) -> NDArray:
    """Channel-wise normalize of CHW/NCHW float input
    (reference: ``_image_normalize``)."""
    def impl(x):
        c = x.shape[0] if x.ndim == 3 else x.shape[1]
        m = jnp.asarray(mean, dtype=x.dtype).reshape(-1)
        s = jnp.asarray(std, dtype=x.dtype).reshape(-1)
        m = jnp.broadcast_to(m, (c,))
        s = jnp.broadcast_to(s, (c,))
        shape = (c, 1, 1) if x.ndim == 3 else (1, c, 1, 1)
        return (x - m.reshape(shape)) / s.reshape(shape)
    return invoke("image_normalize", impl, (_wrap(data),))


def resize(data, size: Union[int, Sequence[int]], keep_ratio: bool = False,
           interp: int = 1) -> NDArray:
    """Resize HWC/NHWC image(s) (reference: ``_image_resize``).

    ``size`` is (w, h) or int; interp 0=nearest, 1=bilinear, 2=cubic."""
    x = _as_jax(data)
    if x.ndim == 3:
        h, w = x.shape[0], x.shape[1]
    else:
        h, w = x.shape[1], x.shape[2]
    if isinstance(size, int):
        if keep_ratio:
            if h > w:
                new_w, new_h = size, int(h * size / w)
            else:
                new_w, new_h = int(w * size / h), size
        else:
            new_w = new_h = size
    else:
        new_w, new_h = size
    method = {0: "nearest", 1: "linear", 2: "cubic"}.get(interp, "linear")

    def impl(x):
        dt = x.dtype
        xf = x.astype(jnp.float32)
        if x.ndim == 3:
            out = jax.image.resize(xf, (new_h, new_w, x.shape[2]), method)
        else:
            out = jax.image.resize(
                xf, (x.shape[0], new_h, new_w, x.shape[3]), method)
        if jnp.issubdtype(dt, jnp.integer):
            out = jnp.clip(jnp.round(out), 0, 255)
        return out.astype(dt)
    return invoke("image_resize", impl, (_wrap(data),))


def crop(data, x: int, y: int, width: int, height: int) -> NDArray:
    """Crop at (x, y) with (width, height), HWC/NHWC
    (reference: ``_image_crop``)."""
    def impl(a):
        if a.ndim == 3:
            return a[y:y + height, x:x + width, :]
        return a[:, y:y + height, x:x + width, :]
    return invoke("image_crop", impl, (_wrap(data),))


def random_crop(data, size: Tuple[int, int], rng: Optional[_np.random.RandomState] = None):
    """Random crop to (w, h); returns (cropped, (x, y, w, h))
    (reference: ``mx.image.random_crop``)."""
    rng = rng or _np.random
    x = _as_jax(data)
    h, w = (x.shape[0], x.shape[1]) if x.ndim == 3 else (x.shape[1], x.shape[2])
    cw, ch = size
    cw, ch = min(cw, w), min(ch, h)
    x0 = int(rng.randint(0, w - cw + 1))
    y0 = int(rng.randint(0, h - ch + 1))
    return crop(data, x0, y0, cw, ch), (x0, y0, cw, ch)


def flip_left_right(data) -> NDArray:
    def impl(x):
        return jnp.flip(x, axis=x.ndim - 2)
    return invoke("image_flip_lr", impl, (_wrap(data),))


def flip_top_bottom(data) -> NDArray:
    def impl(x):
        return jnp.flip(x, axis=x.ndim - 3)
    return invoke("image_flip_tb", impl, (_wrap(data),))


def random_flip_left_right(data, p: float = 0.5) -> NDArray:
    if _np.random.uniform() < p:
        return flip_left_right(data)
    return _wrap(data)


def random_flip_top_bottom(data, p: float = 0.5) -> NDArray:
    if _np.random.uniform() < p:
        return flip_top_bottom(data)
    return _wrap(data)


def random_brightness(data, min_factor: float, max_factor: float) -> NDArray:
    alpha = float(_np.random.uniform(min_factor, max_factor))
    def impl(x):
        out = x.astype(jnp.float32) * alpha
        if jnp.issubdtype(x.dtype, jnp.integer):
            out = jnp.clip(out, 0, 255)
        return out.astype(x.dtype)
    return invoke("image_brightness", impl, (_wrap(data),))


def random_contrast(data, min_factor: float, max_factor: float) -> NDArray:
    alpha = float(_np.random.uniform(min_factor, max_factor))
    def impl(x):
        xf = x.astype(jnp.float32)
        coef = jnp.asarray([_R, _G, _B], dtype=jnp.float32)
        gray = (xf * coef).sum(axis=-1, keepdims=True)
        mean = jnp.mean(gray, axis=(-3, -2), keepdims=True)
        out = xf * alpha + mean * (1.0 - alpha)
        if jnp.issubdtype(x.dtype, jnp.integer):
            out = jnp.clip(out, 0, 255)
        return out.astype(x.dtype)
    return invoke("image_contrast", impl, (_wrap(data),))


def random_saturation(data, min_factor: float, max_factor: float) -> NDArray:
    alpha = float(_np.random.uniform(min_factor, max_factor))
    def impl(x):
        xf = x.astype(jnp.float32)
        coef = jnp.asarray([_R, _G, _B], dtype=jnp.float32)
        gray = (xf * coef).sum(axis=-1, keepdims=True)
        out = xf * alpha + gray * (1.0 - alpha)
        if jnp.issubdtype(x.dtype, jnp.integer):
            out = jnp.clip(out, 0, 255)
        return out.astype(x.dtype)
    return invoke("image_saturation", impl, (_wrap(data),))


def random_hue(data, min_factor: float, max_factor: float) -> NDArray:
    alpha = float(_np.random.uniform(min_factor, max_factor))
    # YIQ rotation, matching the reference's hue jitter matrix
    # (src/operator/image/image_random-inl.h RandomHue).
    u = _np.cos(alpha * _np.pi)
    w = _np.sin(alpha * _np.pi)
    t_yiq = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], dtype=_np.float32)
    t_rgb = _np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], dtype=_np.float32)
    rot = _np.array([[1.0, 0.0, 0.0],
                     [0.0, u, -w],
                     [0.0, w, u]], dtype=_np.float32)
    m = jnp.asarray(t_rgb @ rot @ t_yiq)

    def impl(x):
        xf = x.astype(jnp.float32)
        out = jnp.einsum("...c,dc->...d", xf, m)
        if jnp.issubdtype(x.dtype, jnp.integer):
            out = jnp.clip(out, 0, 255)
        return out.astype(x.dtype)
    return invoke("image_hue", impl, (_wrap(data),))


def random_color_jitter(data, brightness: float = 0.0, contrast: float = 0.0,
                        saturation: float = 0.0, hue: float = 0.0) -> NDArray:
    augs = []
    if brightness > 0:
        augs.append(lambda d: random_brightness(d, 1 - brightness, 1 + brightness))
    if contrast > 0:
        augs.append(lambda d: random_contrast(d, 1 - contrast, 1 + contrast))
    if saturation > 0:
        augs.append(lambda d: random_saturation(d, 1 - saturation, 1 + saturation))
    if hue > 0:
        augs.append(lambda d: random_hue(d, -hue, hue))
    _np.random.shuffle(augs)
    out = _wrap(data)
    for a in augs:
        out = a(out)
    return out


def adjust_lighting(data, alpha, eigval=None, eigvec=None) -> NDArray:
    """AlexNet-style PCA lighting noise (reference: ``_image_adjust_lighting``);
    input HWC/NHWC RGB in [0,255] or [0,1]. ``eigval``/``eigvec`` default to
    the ImageNet PCA basis."""
    if eigval is None:
        eigval = _np.array([55.46, 4.794, 1.148], dtype=_np.float32)
    if eigvec is None:
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]], dtype=_np.float32)
    eigval = _np.asarray(eigval, dtype=_np.float32)
    eigvec = _np.asarray(eigvec, dtype=_np.float32)
    a = _np.asarray(alpha, dtype=_np.float32)
    delta = jnp.asarray(eigvec @ (a * eigval))

    def impl(x):
        xf = x.astype(jnp.float32)
        scale = 1.0 if jnp.issubdtype(x.dtype, jnp.integer) else 1.0 / 255.0
        out = xf + delta * scale
        if jnp.issubdtype(x.dtype, jnp.integer):
            out = jnp.clip(out, 0, 255)
        return out.astype(x.dtype)
    return invoke("image_lighting", impl, (_wrap(data),))


def random_lighting(data, alpha_std: float = 0.05, eigval=None,
                    eigvec=None) -> NDArray:
    alpha = _np.random.normal(0.0, alpha_std, size=(3,))
    return adjust_lighting(data, alpha, eigval=eigval, eigvec=eigvec)


def _wrap(x) -> NDArray:
    if isinstance(x, NDArray):
        return x
    return from_jax(jnp.asarray(x))
