"""Sparse storage types: ``row_sparse`` and ``csr``.

Reference parity (leezu/mxnet): ``include/mxnet/ndarray.h`` (storage types
kRowSparseStorage/kCSRStorage on NDArray::Chunk), the python surface
``python/mxnet/ndarray/sparse.py`` (RowSparseNDArray, CSRNDArray,
row_sparse_array, csr_matrix) and sparse FComputeEx kernels in
``src/operator/tensor/`` (dot, elemwise, cast_storage, sparse_retain).

Design (tpu-first): XLA has no first-class sparse tensors, so sparse
storage lives in the imperative layer as (indices, values) / CSR component
arrays on device; ops that have an efficient sparse formulation (dot,
retain, elemwise on aligned rows, row-sparse optimizer updates) work on
the components with gather/scatter/segment-sum primitives the MXU/VPU
handle well, and everything else falls back to dense with the reference's
"storage fallback" warning. Sparse is a host-driven (eager) feature —
under jit tracing, arrays densify.
"""
from __future__ import annotations

import warnings
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError

# jax arrays are int32 by default; row/col ids past this need the
# host-side int64 representation (the USE_INT64_TENSOR_SIZE analog)
_INT32_MAX = 2 ** 31 - 1
from ..context import Context, current_context
from .ndarray import NDArray
from .ops import _as_nd

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "zeros", "empty", "array",
           "dot", "add", "subtract", "multiply", "retain", "todense"]


def _warn_fallback(op: str, stype: str) -> None:
    warnings.warn(
        f"op {op!r} falling back to dense storage for a {stype} input "
        f"(the reference logs the same storage-fallback warning)",
        stacklevel=3)


class BaseSparseNDArray(NDArray):
    """Common base of the sparse storage classes.

    Accessing ``_data`` (i.e. using a dense-only op) densifies with a
    fallback warning, mirroring the reference's FComputeFallback path.
    """

    __slots__ = ("_sp_shape", "_sp_dtype", "_dense_cache")

    def __init__(self) -> None:  # components set by subclass
        self._dense_cache = None
        self._ag_node = None
        self._ag_out_idx = 0
        self._grad = None
        self._grad_req = "null"
        self._fresh_grad = False
        self._ctx = None

    # -- NDArray interface over components ---------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            _warn_fallback("<dense access>", self.stype)
            self._dense_cache = self._todense_impl()
        return self._dense_cache

    @_data.setter
    def _data(self, value) -> None:
        # A dense write re-encodes the value into this array's storage
        # format (the reference's storage-fallback cast on write), so
        # sparse readers (stype/asnumpy/optimizer FComputeEx paths) stay
        # consistent with dense ones.
        self._assign_dense(value)
        self._dense_cache = value

    @property
    def shape(self) -> tuple:
        return tuple(self._sp_shape)

    @property
    def dtype(self):
        return _np.dtype(self._sp_dtype)

    @property
    def ndim(self) -> int:
        # NDArray.ndim peeks at the dense _buf slot, which sparse
        # wrappers never populate
        return len(self._sp_shape)

    @property
    def context(self) -> Context:
        return self._ctx or current_context()

    ctx = context

    def asnumpy(self) -> _np.ndarray:
        return _np.asarray(self._todense_impl())

    def todense(self) -> NDArray:
        return NDArray(self._todense_impl(), ctx=self._ctx, _wrap=True)

    def wait_to_read(self) -> None:
        for c in self._components():
            c.block_until_ready()

    def __repr__(self) -> str:
        return (f"<{type(self).__name__} {self.shape} "
                f"@{self.context}>")

    # subclass hooks
    def _todense_impl(self):
        raise NotImplementedError

    def _components(self):
        raise NotImplementedError

    def _assign_dense(self, value):
        raise NotImplementedError


class RowSparseNDArray(BaseSparseNDArray):
    """Sparse tensor where only some leading-axis rows are stored
    (reference: kRowSparseStorage — the gradient format of Embedding with
    ``sparse_grad`` and of sparse optimizer updates).

    ``indices``: sorted int64 (nnz,) row ids; ``data``: (nnz,) + row shape.
    """

    __slots__ = ("_sp_indices", "_sp_values")

    def __init__(self, data: Any, indices: Any, shape: Tuple[int, ...],
                 ctx: Optional[Context] = None, dtype: Any = None) -> None:
        super().__init__()
        vals = jnp.asarray(data, dtype=dtype)
        if len(shape) and shape[0] > _INT32_MAX:
            # INT64 regime (reference: USE_INT64_TENSOR_SIZE builds).
            # jax arrays default to int32, which would silently WRAP row
            # ids past 2^31 — keep the ids host-side in exact int64;
            # a dense view is unmaterializable at this scale anyway.
            idx = _np.ascontiguousarray(indices, dtype=_np.int64)
        else:
            idx = jnp.asarray(indices, dtype=jnp.int32)
        if vals.ndim != len(shape):
            raise MXNetError(
                f"row_sparse data ndim {vals.ndim} must equal shape ndim "
                f"{len(shape)} (rows are stored whole)")
        if idx.shape[0] != vals.shape[0]:
            raise MXNetError(
                f"row_sparse: {idx.shape[0]} indices vs {vals.shape[0]} "
                f"value rows")
        self._sp_values = vals
        self._sp_indices = idx
        self._sp_shape = tuple(shape)
        self._sp_dtype = vals.dtype
        self._ctx = ctx

    @property
    def stype(self) -> str:
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._sp_indices, ctx=self._ctx, _wrap=True)

    @property
    def data(self) -> NDArray:
        return NDArray(self._sp_values, ctx=self._ctx, _wrap=True)

    def _components(self):
        return (self._sp_indices, self._sp_values)

    def _todense_impl(self):
        if isinstance(self._sp_indices, _np.ndarray):
            raise MXNetError(
                f"row_sparse with {self._sp_shape[0]} rows (> int32) "
                "cannot be densified — the dense view would exceed "
                "addressable element counts; keep it sparse")
        dense = jnp.zeros(self._sp_shape, dtype=self._sp_dtype)
        if self._sp_values.shape[0] == 0:
            return dense
        return dense.at[self._sp_indices].add(self._sp_values)

    def tostype(self, stype: str):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        if stype == "csr":
            return self.todense().tostype("csr")
        raise MXNetError(f"unknown stype {stype!r}")

    def retain(self, indices: Any) -> "RowSparseNDArray":
        return retain(self, indices)

    def copyto(self, other):
        if isinstance(other, Context):
            return RowSparseNDArray(self._sp_values, self._sp_indices,
                                    self._sp_shape, ctx=other)
        return super().copyto(other)

    def _assign_dense(self, value) -> None:
        # all-rows representation: indices = arange(nrows)
        v = jnp.asarray(value)
        self._sp_values = v
        self._sp_indices = jnp.arange(v.shape[0], dtype=jnp.int32)
        self._sp_shape = tuple(v.shape)
        self._sp_dtype = v.dtype

    def _canonical(self) -> "RowSparseNDArray":
        """Deduplicate + sort row ids (host-side; eager only)."""
        idx = _np.asarray(self._sp_indices)
        if idx.size == 0 or (_np.all(_np.diff(idx) > 0)):
            return self
        uniq, inv = _np.unique(idx, return_inverse=True)
        vals = jnp.zeros((len(uniq),) + tuple(self._sp_values.shape[1:]),
                         dtype=self._sp_values.dtype)
        vals = vals.at[jnp.asarray(inv)].add(self._sp_values)
        return RowSparseNDArray(vals, uniq.astype(_np.int32),
                                self._sp_shape, ctx=self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed-sparse-row tensor (reference: kCSRStorage; the input
    format of sparse linear models / libsvm data)."""

    __slots__ = ("_sp_data", "_sp_indices", "_sp_indptr")

    def __init__(self, data: Any, indices: Any, indptr: Any,
                 shape: Tuple[int, ...], ctx: Optional[Context] = None,
                 dtype: Any = None) -> None:
        super().__init__()
        if len(shape) != 2:
            raise MXNetError("csr arrays are 2-D")
        self._sp_data = jnp.asarray(data, dtype=dtype)
        if shape[1] > _INT32_MAX:
            # INT64 column regime: exact host-side ids (see RowSparse)
            self._sp_indices = _np.ascontiguousarray(indices,
                                                     dtype=_np.int64)
        else:
            self._sp_indices = jnp.asarray(indices, dtype=jnp.int32)
        self._sp_indptr = jnp.asarray(indptr, dtype=jnp.int32)
        if self._sp_indptr.shape[0] != shape[0] + 1:
            raise MXNetError(
                f"csr: indptr length {self._sp_indptr.shape[0]} != "
                f"rows+1 ({shape[0] + 1})")
        self._sp_shape = tuple(shape)
        self._sp_dtype = self._sp_data.dtype
        self._ctx = ctx

    @property
    def stype(self) -> str:
        return "csr"

    @property
    def data(self) -> NDArray:
        return NDArray(self._sp_data, ctx=self._ctx, _wrap=True)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._sp_indices, ctx=self._ctx, _wrap=True)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._sp_indptr, ctx=self._ctx, _wrap=True)

    def _components(self):
        return (self._sp_data, self._sp_indices, self._sp_indptr)

    def _row_ids(self) -> _np.ndarray:
        ptr = _np.asarray(self._sp_indptr)
        return _np.repeat(_np.arange(self._sp_shape[0]), _np.diff(ptr))

    def _assign_dense(self, value) -> None:
        arr = _np.asarray(value)
        if arr.ndim != 2:
            raise MXNetError("csr arrays are 2-D")
        mask = arr != 0
        self._sp_data = jnp.asarray(arr[mask])
        self._sp_indices = jnp.asarray(
            _np.nonzero(mask)[1].astype(_np.int32))
        self._sp_indptr = jnp.asarray(_np.concatenate(
            [[0], _np.cumsum(mask.sum(axis=1))]).astype(_np.int32))
        self._sp_shape = tuple(arr.shape)
        self._sp_dtype = self._sp_data.dtype

    def _todense_impl(self):
        dense = jnp.zeros(self._sp_shape, dtype=self._sp_dtype)
        if self._sp_data.shape[0] == 0:
            return dense
        rows = jnp.asarray(self._row_ids())
        return dense.at[rows, self._sp_indices].add(self._sp_data)

    def tostype(self, stype: str):
        if stype == "csr":
            return self
        if stype == "default":
            return self.todense()
        raise MXNetError(f"cannot convert csr to {stype!r} directly")

    def __getitem__(self, key):
        if isinstance(key, int):
            lo = int(self._sp_indptr[key])
            hi = int(self._sp_indptr[key + 1])
            row = jnp.zeros((self._sp_shape[1],), dtype=self._sp_dtype)
            row = row.at[self._sp_indices[lo:hi]].set(self._sp_data[lo:hi])
            return NDArray(row, ctx=self._ctx, _wrap=True)
        if isinstance(key, slice):
            start, stop, step = key.indices(self._sp_shape[0])
            if step != 1:
                raise MXNetError("csr slicing requires step 1")
            lo, hi = int(self._sp_indptr[start]), int(self._sp_indptr[stop])
            return CSRNDArray(self._sp_data[lo:hi],
                              self._sp_indices[lo:hi],
                              self._sp_indptr[start:stop + 1] -
                              self._sp_indptr[start],
                              (stop - start, self._sp_shape[1]),
                              ctx=self._ctx)
        raise MXNetError("csr supports int / contiguous-slice indexing")


# ---------------------------------------------------------------------------
# Creation (reference: python/mxnet/ndarray/sparse.py row_sparse_array etc.)
# ---------------------------------------------------------------------------

def row_sparse_array(arg1: Any, shape: Optional[tuple] = None,
                     ctx: Optional[Context] = None, dtype: Any = None
                     ) -> RowSparseNDArray:
    """Build from ``(data, indices)`` or densify-convert an array."""
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 2 and not isinstance(
            arg1[0], int):
        data, indices = arg1
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) needs shape")
        return RowSparseNDArray(data, indices, shape, ctx=ctx, dtype=dtype)
    dense = _as_nd(arg1) if not isinstance(arg1, NDArray) else arg1
    return _dense_to_rsp(dense, ctx=ctx)


def csr_matrix(arg1: Any, shape: Optional[tuple] = None,
               ctx: Optional[Context] = None, dtype: Any = None
               ) -> CSRNDArray:
    """Build from ``(data, indices, indptr)``, scipy-style triples, or a
    dense array."""
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("csr_matrix((data, indices, indptr)) needs "
                             "shape")
        return CSRNDArray(data, indices, indptr, shape, ctx=ctx, dtype=dtype)
    dense = _as_nd(arg1) if not isinstance(arg1, NDArray) else arg1
    return _dense_to_csr(dense, ctx=ctx)


def zeros(stype: str, shape: tuple, ctx: Optional[Context] = None,
          dtype: Any = "float32"):
    if stype == "row_sparse":
        row = (0,) + tuple(shape[1:])
        return RowSparseNDArray(_np.zeros(row, dtype=dtype), [], shape,
                                ctx=ctx)
    if stype == "csr":
        return CSRNDArray([], [], _np.zeros(shape[0] + 1, dtype=_np.int32),
                          shape, ctx=ctx, dtype=dtype)
    if stype == "default":
        from . import ops as _ops
        return _ops.zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown stype {stype!r}")


empty = zeros


def array(source, ctx: Optional[Context] = None, dtype: Any = None):
    """Sparse-aware ``mx.nd.sparse.array`` (scipy.sparse input supported
    when scipy is available)."""
    stype = getattr(source, "format", None)  # scipy sparse matrices
    if stype == "csr":
        return CSRNDArray(source.data, source.indices, source.indptr,
                          source.shape, ctx=ctx, dtype=dtype)
    if isinstance(source, BaseSparseNDArray):
        return source
    return NDArray(source, ctx=ctx, dtype=dtype)


def _dense_to_rsp(dense: NDArray, ctx=None) -> RowSparseNDArray:
    a = _np.asarray(dense.asnumpy())
    keep = _np.where(a.reshape(a.shape[0], -1).any(axis=1))[0]
    return RowSparseNDArray(a[keep], keep.astype(_np.int32), a.shape,
                            ctx=ctx or dense.context)


def _dense_to_csr(dense: NDArray, ctx=None) -> CSRNDArray:
    a = _np.asarray(dense.asnumpy())
    if a.ndim != 2:
        raise MXNetError("csr conversion requires a 2-D array")
    rows, cols = _np.nonzero(a)
    data = a[rows, cols]
    indptr = _np.zeros(a.shape[0] + 1, dtype=_np.int32)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr).astype(_np.int32)
    return CSRNDArray(data, cols.astype(_np.int32), indptr, a.shape,
                      ctx=ctx or dense.context)


def todense(a) -> NDArray:
    return a.todense() if isinstance(a, BaseSparseNDArray) else _as_nd(a)


# ---------------------------------------------------------------------------
# Sparse ops (reference: FComputeEx kernels — dot, elemwise, retain)
# ---------------------------------------------------------------------------

def retain(a: RowSparseNDArray, indices: Any) -> RowSparseNDArray:
    """Keep only the listed rows (reference: ``sparse_retain``)."""
    if not isinstance(a, RowSparseNDArray):
        raise MXNetError("retain expects a row_sparse array")
    want = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                       else indices).astype(_np.int64)
    have = _np.asarray(a._sp_indices)
    mask = _np.isin(have, want)
    keep = _np.where(mask)[0]
    return RowSparseNDArray(a._sp_values[jnp.asarray(keep)],
                            have[keep].astype(_np.int32), a.shape,
                            ctx=a._ctx)


def dot(a, b, transpose_a: bool = False, transpose_b: bool = False):
    """Sparse-aware dot: csr·dense, csrᵀ·dense (segment-sum formulation),
    dense·rspᵀ fall back where no sparse kernel applies."""
    if isinstance(a, CSRNDArray) and isinstance(b, NDArray) and \
            not isinstance(b, BaseSparseNDArray) and not transpose_b:
        rows = jnp.asarray(a._row_ids())
        if transpose_a:
            # out[k, :] = sum over nnz with col==k of data * b[row]
            m = a.shape[1]
            gathered = a._sp_data[:, None] * b._data[rows]
            out = jax.ops.segment_sum(gathered, a._sp_indices,
                                      num_segments=m)
            return NDArray(out.astype(a._sp_dtype), ctx=a._ctx, _wrap=True)
        # out[r, :] = sum over row-nnz of data * b[col]
        gathered = a._sp_data[:, None] * b._data[a._sp_indices]
        out = jax.ops.segment_sum(gathered, rows,
                                  num_segments=a.shape[0])
        return NDArray(out.astype(a._sp_dtype), ctx=a._ctx, _wrap=True)
    if isinstance(a, BaseSparseNDArray) or isinstance(b, BaseSparseNDArray):
        _warn_fallback("dot", a.stype if isinstance(a, BaseSparseNDArray)
                       else b.stype)
    from . import ops as _ops
    da = todense(a) if isinstance(a, BaseSparseNDArray) else a
    db = todense(b) if isinstance(b, BaseSparseNDArray) else b
    if transpose_a:
        da = da.T
    if transpose_b:
        db = db.T
    return _ops.dot(da, db)


def _rsp_elemwise(name: str, op, a, b):
    if isinstance(a, RowSparseNDArray) and isinstance(b, RowSparseNDArray) \
            and a.shape == b.shape:
        ca, cb = a._canonical(), b._canonical()
        ia, ib = _np.asarray(ca._sp_indices), _np.asarray(cb._sp_indices)
        union = _np.union1d(ia, ib)
        va = jnp.zeros((len(union),) + ca._sp_values.shape[1:],
                       dtype=ca._sp_values.dtype)
        pos_a = _np.searchsorted(union, ia)
        pos_b = _np.searchsorted(union, ib)
        va = va.at[jnp.asarray(pos_a)].set(ca._sp_values)
        vb = jnp.zeros_like(va).at[jnp.asarray(pos_b)].set(cb._sp_values)
        return RowSparseNDArray(op(va, vb), union.astype(_np.int32),
                                a.shape, ctx=a._ctx)
    _warn_fallback(name, a.stype if isinstance(a, BaseSparseNDArray)
                   else getattr(b, "stype", "default"))
    from . import ops as _ops
    return getattr(_ops, name)(todense(a), todense(b))


def add(a, b):
    return _rsp_elemwise("add", jnp.add, a, b)


def subtract(a, b):
    return _rsp_elemwise("subtract", jnp.subtract, a, b)


def multiply(a, b):
    return _rsp_elemwise("multiply", jnp.multiply, a, b)
