"""Random sampling ops over a splittable threefry PRNG.

Reference parity (leezu/mxnet): ``src/operator/random/sample_op.*`` and
``src/common/random_generator.*`` (philox/curand per-thread generators),
python ``mxnet/ndarray/random.py``.

Design (tpu-first): adopts jax's counter-based threefry keys (documented
break from philox — same statistical family, different streams). A global
key is held per process; every eager sample splits it (the analog of the
reference's per-op ``FResourceRequest::kParallelRandom`` states). Under
hybridize tracing, the key is threaded through the traced function as an
input so compiled graphs stay pure (see gluon/block.py CachedOp).
"""
from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..base import getenv, register_env
from .ndarray import NDArray, from_jax
from .register import invoke

__all__ = ["seed", "uniform", "normal", "randn", "randint", "gamma",
           "exponential", "poisson", "bernoulli", "multinomial", "choice",
           "shuffle", "beta", "laplace", "gumbel", "rand", "current_key",
           "split_key", "trace_key_scope", "chisquare", "rayleigh",
           "weibull", "pareto", "power", "logistic", "lognormal",
           "negative_binomial", "generalized_negative_binomial", "f", "t",
           "dirichlet", "binomial", "permutation", "randperm",
           "standard_normal", "random_sample", "sample"]

register_env("MXNET_RANDOM_SEED", 0, "Initial global PRNG seed.")


class _RngState(threading.local):
    def __init__(self) -> None:
        self.key = jax.random.PRNGKey(getenv("MXNET_RANDOM_SEED", 0))
        # During hybridize tracing, ops must draw subkeys from the traced
        # key input rather than the concrete global key.
        self.trace_key: Optional[Any] = None
        self.trace_count = 0


_STATE = _RngState()


def seed(seed_state: int, ctx: Any = "all") -> None:
    """Reset the global PRNG (``mx.random.seed``)."""
    _STATE.key = jax.random.PRNGKey(int(seed_state))


def current_key() -> Any:
    return _STATE.key


_split_jit = None


def split_key() -> Any:
    """Draw a fresh subkey (eager) or fold from the traced key (tracing).

    The eager split runs JITTED so the returned keys are clean compiled
    outputs — on the axon remote backend, eager-op-produced arrays are
    lazy handles that cost a tunnel round-trip per consuming jit call
    (see ``engine.launder``)."""
    if _STATE.trace_key is not None:
        _STATE.trace_count += 1
        return jax.random.fold_in(_STATE.trace_key, _STATE.trace_count)
    global _split_jit
    if _split_jit is None:
        _split_jit = jax.jit(lambda k: tuple(jax.random.split(k)))
    _STATE.key, sub = _split_jit(_STATE.key)
    return sub


class trace_key_scope:
    """Bind a traced PRNG key for the duration of a hybridize trace."""

    def __init__(self, key: Any) -> None:
        self._key = key

    def __enter__(self) -> None:
        self._prev = (_STATE.trace_key, _STATE.trace_count)
        _STATE.trace_key, _STATE.trace_count = self._key, 0

    def __exit__(self, *exc: Any) -> None:
        _STATE.trace_key, _STATE.trace_count = self._prev


def _shape(shape) -> tuple:
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _sample(name: str, fn, ctx=None) -> NDArray:
    out = fn(split_key())
    nd = from_jax(out)
    from .. import engine
    engine.track(out)
    return nd


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, **kw):
    """Uniform samples in [low, high) (``mx.nd.random.uniform``)."""
    shp = _shape(shape)
    return _sample("uniform",
                   lambda k: jax.random.uniform(k, shp, dtype=dtype,
                                                minval=low, maxval=high), ctx)


def rand(*shape, ctx=None, dtype="float32"):
    return uniform(0.0, 1.0, shape=shape, dtype=dtype, ctx=ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("normal",
                   lambda k: loc + scale * jax.random.normal(k, shp, dtype=dtype),
                   ctx)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape=shape, dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=None, dtype="int32", ctx=None, **kw):
    if high is None:
        low, high = 0, low
    shp = _shape(shape)
    return _sample("randint",
                   lambda k: jax.random.randint(k, shp, low, high, dtype=dtype),
                   ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("gamma",
                   lambda k: jax.random.gamma(k, alpha, shp, dtype=dtype) * beta,
                   ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("exponential",
                   lambda k: jax.random.exponential(k, shp, dtype=dtype) * scale,
                   ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("poisson",
                   lambda k: jax.random.poisson(k, lam, shp).astype(dtype), ctx)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("bernoulli",
                   lambda k: jax.random.bernoulli(k, prob, shp).astype(dtype),
                   ctx)


def beta(a=1.0, b=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("beta",
                   lambda k: jax.random.beta(k, a, b, shp).astype(dtype), ctx)


def laplace(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("laplace",
                   lambda k: loc + scale * jax.random.laplace(k, shp, dtype=dtype),
                   ctx)


def gumbel(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("gumbel",
                   lambda k: loc + scale * jax.random.gumbel(k, shp, dtype=dtype),
                   ctx)


def multinomial(data, shape=1, get_prob=False, dtype="int32", **kw):
    """Sample category indices from (batched) probability rows; with
    ``get_prob=True`` also return the log-probability of each draw
    (``mx.nd.random.multinomial`` — REINFORCE-style usage)."""
    n = shape if isinstance(shape, int) else int(jnp.prod(jnp.array(shape)))
    probs = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    logits = jnp.log(jnp.maximum(probs, 1e-38))
    k = split_key()
    squeeze = isinstance(shape, int) and shape == 1
    if logits.ndim == 1:
        out = jax.random.categorical(k, logits, shape=(n,))
        logp = jax.nn.log_softmax(logits)[out]
        if squeeze:
            out, logp = out[0], logp[0]
    else:
        out = jax.random.categorical(k, logits[:, None, :], axis=-1,
                                     shape=(logits.shape[0], n))
        logp = jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                                   out, axis=1)
        if squeeze:
            out, logp = out[:, 0], logp[:, 0]
    if get_prob:
        return from_jax(out.astype(dtype)), from_jax(logp)
    return from_jax(out.astype(dtype))


def choice(a, size=None, replace=True, p=None, ctx=None):
    aa = a._data if isinstance(a, NDArray) else a
    pp = p._data if isinstance(p, NDArray) else p
    shp = _shape(size)
    return _sample("choice",
                   lambda k: jax.random.choice(k, aa, shp, replace=replace, p=pp),
                   ctx)


_seed_jit = None


def split_seed():
    """Fresh (2,) uint32 seed words. Jitted end to end when eager — an
    eager key_data/reshape chain would produce lazy per-op handles that
    cost a tunnel round-trip per consuming jit call on the axon backend
    (the same trap ``split_key`` documents)."""
    key = split_key()
    if isinstance(key, jax.core.Tracer):
        return jax.random.key_data(key).reshape(-1)[:2].astype(jnp.uint32)
    global _seed_jit
    if _seed_jit is None:
        _seed_jit = jax.jit(lambda k: jax.random.key_data(k)
                            .reshape(-1)[:2].astype(jnp.uint32))
    return _seed_jit(key)


def shuffle(data):
    """Random permutation along the first axis (``mx.nd.random.shuffle``).

    Delegates to the registered ``shuffle`` op so the tape, AMP/profiler
    hooks and the per-op executable cache all apply (and the seed rides
    as an op input — compiled programs reshuffle every call)."""
    from . import ops as _ops
    return _ops.shuffle(data)


def chisquare(df=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("chisquare",
                   lambda k: jax.random.chisquare(k, df, shape=shp,
                                                  dtype=dtype), ctx)


def rayleigh(scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("rayleigh",
                   lambda k: jax.random.rayleigh(k, scale, shape=shp,
                                                 dtype=dtype), ctx)


def weibull(a=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("weibull",
                   lambda k: jax.random.weibull_min(k, 1.0, a, shape=shp,
                                                    dtype=dtype), ctx)


def pareto(a=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    # numpy's pareto is the Lomax form: samples of (X - 1) with X ~ Pareto(a)
    return _sample("pareto",
                   lambda k: jax.random.pareto(k, a, shape=shp,
                                               dtype=dtype) - 1.0, ctx)


def power(a=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("power",
                   lambda k: jax.random.uniform(k, shp, dtype=dtype)
                   ** (1.0 / a), ctx)


def logistic(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("logistic",
                   lambda k: loc + scale * jax.random.logistic(k, shp,
                                                               dtype=dtype),
                   ctx)


def lognormal(mean=0.0, sigma=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("lognormal",
                   lambda k: jnp.exp(mean + sigma * jax.random.normal(
                       k, shp, dtype=dtype)), ctx)


def negative_binomial(k=1, p=0.5, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    kk, pp = k, p

    def impl(key):
        k1, k2 = jax.random.split(key)
        # NB(k, p) == Poisson(Gamma(k, (1-p)/p))
        lam = jax.random.gamma(k1, kk, shp) * ((1.0 - pp) / pp)
        return jax.random.poisson(k2, lam, shp).astype(dtype)

    return _sample("negative_binomial", impl, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None, **kw):
    shp = _shape(shape)

    def impl(key):
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, r, shp) * (mu * alpha)
        return jax.random.poisson(k2, lam, shp).astype(dtype)

    return _sample("generalized_negative_binomial", impl, ctx)


def f(dfnum=1.0, dfden=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)

    def impl(key):
        k1, k2 = jax.random.split(key)
        num = jax.random.chisquare(k1, dfnum, shape=shp, dtype=dtype) / dfnum
        den = jax.random.chisquare(k2, dfden, shape=shp, dtype=dtype) / dfden
        return num / den

    return _sample("f", impl, ctx)


def t(df=1.0, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("t",
                   lambda k: jax.random.t(k, df, shape=shp, dtype=dtype), ctx)


def dirichlet(alpha, shape=None, dtype="float32", ctx=None, **kw):
    al = alpha._data if isinstance(alpha, NDArray) else jnp.asarray(
        alpha, dtype=dtype)
    shp = _shape(shape)
    return _sample("dirichlet",
                   lambda k: jax.random.dirichlet(k, al, shape=shp,
                                                  dtype=dtype), ctx)


def binomial(n=1, p=0.5, shape=None, dtype="float32", ctx=None, **kw):
    shp = _shape(shape)
    return _sample("binomial",
                   lambda k: jax.random.binomial(k, n, p, shape=shp).astype(
                       dtype), ctx)


def permutation(x, ctx=None):
    if isinstance(x, int):
        return _sample("permutation",
                       lambda k: jax.random.permutation(k, x), ctx)
    arr = x._data if isinstance(x, NDArray) else jnp.asarray(x)
    return _sample("permutation",
                   lambda k: jax.random.permutation(k, arr, axis=0), ctx)


def randperm(n, ctx=None):
    return permutation(n, ctx=ctx)


def standard_normal(shape=None, dtype="float32", ctx=None):
    return normal(0.0, 1.0, shape=shape, dtype=dtype, ctx=ctx)


def random_sample(shape=None, dtype="float32", ctx=None):
    return uniform(0.0, 1.0, shape=shape, dtype=dtype, ctx=ctx)


def sample(shape=None, dtype="float32", ctx=None):
    return uniform(0.0, 1.0, shape=shape, dtype=dtype, ctx=ctx)
