"""NDArray — the imperative tensor.

Reference parity (leezu/mxnet): ``include/mxnet/ndarray.h`` /
``src/ndarray/ndarray.cc`` (NDArray + Chunk) and
``python/mxnet/ndarray/ndarray.py`` (operator sugar, indexing, asnumpy).

Design (tpu-first): an NDArray wraps a ``jax.Array`` (device buffer with
async semantics) — the Chunk/engine-var machinery of the reference collapses
into PJRT buffer futures. ``wait_to_read`` == ``block_until_ready``;
``asnumpy`` is the sync point. Under ``hybridize`` tracing the same class
wraps jax tracers, so one op implementation serves both execution modes
(the reference's "one op set, two runtimes" shape, SURVEY.md section 0).

numpy semantics are adopted from day one (``mx.np``-style: zero-dim arrays,
elementwise ``__eq__``) per SURVEY.md section 7 step 2.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as _np

from .. import engine
from ..base import MXNetError
from ..bulk import PendingBuffer
from ..context import Context, cpu, current_context

__all__ = ["NDArray", "from_jax", "waitall"]


def _jax_device_of(data: Any):
    try:
        devs = data.devices()
        if len(devs) == 1:
            return next(iter(devs))
    except Exception:
        pass
    return None


def _ctx_from_data(data: Any) -> Context:
    dev = _jax_device_of(data)
    if dev is None:
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


def _raw(x: Any) -> Any:
    return x._data if isinstance(x, NDArray) else x


def _raw_key(key: Any) -> Any:
    if isinstance(key, tuple):
        return tuple(_raw(k) for k in key)
    return _raw(key)


class NDArray:
    """A multi-dimensional array on a device context.

    Create with ``mx.np.array`` / ``mx.np.zeros`` / etc.; direct construction
    from any array-like is also supported: ``NDArray([[1, 2], [3, 4]])``.
    """

    # _concrete_shadow: the concrete buffer while _data is temporarily a
    # tracer under gluon._bind_params (host-side layer logic — BatchNorm
    # virgin-stats resolution — inspects values mid-trace through it)
    # _grad_ready_cb: per-leaf grad-ready hook — backward_arrays calls
    # it (with this array) the moment this leaf's gradient finalizes
    # mid-backward; installed by gluon.Parameter.set_grad_ready_cb so
    # the overlapped kvstore scheduler can stream reduction buckets
    # while backward is still running
    __slots__ = ("_buf", "_ctx", "_ag_node", "_ag_out_idx", "_grad",
                 "_grad_req", "_fresh_grad", "_grad_ready_cb",
                 "_concrete_shadow", "__weakref__")

    # numpy interop priority (beats np.ndarray in mixed expressions)
    __array_priority__ = 1000.0

    # ------------------------------------------------------------------
    # The buffer slot. Under eager-op bulking (mxnet_tpu/bulk.py) _buf
    # may hold a PendingBuffer promise instead of a concrete jax array;
    # reading ._data is a materialization point (flushes the owning
    # segment), which is what makes bulking transparent to every
    # consumer in the codebase. Shape/dtype queries peek at _buf and
    # never force.
    # ------------------------------------------------------------------
    @property
    def _data(self) -> Any:
        d = self._buf
        if type(d) is PendingBuffer:
            d = d.force("host_read")
            self._buf = d
        return d

    @_data.setter
    def _data(self, value: Any) -> None:
        self._buf = value

    def _materialize(self, reason: str) -> Any:
        """Like reading ``._data`` but attributing the flush to
        ``reason`` (e.g. 'mutation' for in-place writes)."""
        d = self._buf
        if type(d) is PendingBuffer:
            d = d.force(reason)
            self._buf = d
        return d

    def _adopt(self, other: "NDArray") -> "NDArray":
        """In-place rebind to ``other``'s buffer WITHOUT forcing a
        pending promise (the in-place operator sugar: ``x += y`` stays
        bulked). Matches the historical ``self._data = other._data``
        contract exactly: only the buffer moves — autograd attachments
        of ``self`` are untouched.  A RECORDED pending value must
        materialize here: leaving it promised would let a later bulked
        consumer differentiate through the in-place op via the segment
        node ref, where per-op dispatch kept that node unreachable."""
        buf = other._buf
        if type(buf) is PendingBuffer and buf.value is None \
                and other._on_tape:
            buf.force("autograd")
        self._buf = other._buf
        return self

    def __init__(self, data: Any, ctx: Optional[Context] = None,
                 dtype: Any = None, _wrap: bool = False) -> None:
        if _wrap:
            self._data = data
            self._ctx = ctx
        else:
            if isinstance(data, NDArray):
                data = data._data
            ctx = ctx or current_context()
            if isinstance(data, _np.ndarray) and not isinstance(
                    data, jax.Array) and ctx.jax_device.platform != "cpu":
                # accelerator ingest may read the host buffer LAZILY
                # (the axon tunnel defers the transfer): snapshot it so
                # caller-side mutation after construction cannot change
                # the array's value (immutability contract)
                data = _np.array(data, dtype=dtype, copy=True)
            arr = jnp.asarray(data, dtype=dtype)
            if not _is_tracer(arr):
                arr = jax.device_put(arr, ctx.jax_device)
            self._data = arr
            self._ctx = ctx
        self._ag_node = None
        self._ag_out_idx = 0
        self._grad = None
        self._grad_req = "null"
        self._fresh_grad = False
        self._grad_ready_cb = None

    # ------------------------------------------------------------------
    # Basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> tuple:
        return tuple(self._buf.shape)   # peek: never forces a pending buf

    @property
    def dtype(self):
        return _np.dtype(self._buf.dtype)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def ndim(self) -> int:
        return self._buf.ndim

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        self._ctx = _ctx_from_data(self._data)
        return self._ctx

    ctx = context
    device = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    @property
    def grad(self) -> Optional["NDArray"]:
        """Gradient buffer attached via :meth:`attach_grad`."""
        return self._grad

    @property
    def _on_tape(self) -> bool:
        if self._ag_node is not None or self._grad_req != "null":
            return True
        # a promised buffer from a recorded bulked op joins the tape at
        # flush time — report it as recorded already
        buf = getattr(self, "_buf", None)   # sparse wrappers: no slot
        if type(buf) is PendingBuffer and buf.value is None:
            seg = buf.segment
            if not seg.flushed and buf.ni < len(seg.nodes):
                return seg.nodes[buf.ni].tainted
        return False

    # ------------------------------------------------------------------
    # Sync / transfer (reference: WaitToRead / asnumpy / CopyFromTo)
    # ------------------------------------------------------------------
    def wait_to_read(self) -> None:
        """Block until this array's value is computed (WaitForVar)."""
        engine._sync_and_translate(self._data)

    def asnumpy(self) -> _np.ndarray:
        """Copy to a numpy array — a synchronization point.

        Returns a WRITABLE, C-contiguous array (the reference's asnumpy
        copied into a fresh buffer): device arrays — in particular via
        the axon tunnel — can surface as read-only and/or non-C-ordered
        views, whose `.reshape()` silently COPIES and breaks the
        mutate-a-view pattern (e.g. finite-difference perturbation)."""
        out = _np.asarray(engine._sync_and_translate(self._data))
        if not (out.flags.writeable and out.flags.c_contiguous):
            out = _np.array(out, order="C")
        return out

    def item(self) -> Any:
        return self.asnumpy().item()

    def tolist(self):
        return self.asnumpy().tolist()

    def asscalar(self) -> Any:
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.item()

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        if not copy and _np.dtype(self._data.dtype) == _np.dtype(dtype):
            return self
        from .register import invoke
        dt = dtype
        return invoke("astype", lambda a: a.astype(dt), (self,))

    def copy(self) -> "NDArray":
        from .register import invoke
        return invoke("copy", lambda a: a + 0, (self,))

    def copyto(self, other) -> "NDArray":
        """Copy into another NDArray (in place) or onto a Context."""
        if isinstance(other, Context):
            return self.as_in_context(other)
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other.context.jax_device)
            return other
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, ctx: Context) -> "NDArray":
        """Return a copy on ``ctx`` (same array if already there)."""
        if self.context == ctx and not _is_tracer(self._buf):
            return self
        from .._tape import is_recording
        from .register import invoke
        if is_recording() and self._on_tape:
            # Route through the op layer so the transfer is a proper tape
            # node (device_put is differentiable under jax).
            dev = ctx.jax_device
            return invoke("as_in_context",
                          lambda a: jax.device_put(a, dev), (self,), ctx=ctx)
        data = self._data
        if not _is_tracer(data):
            data = jax.device_put(data, ctx.jax_device)
        return NDArray(data, ctx=ctx, _wrap=True)

    as_in_ctx = as_in_context
    to_device = as_in_context

    def as_nd_ndarray(self) -> "NDArray":
        return self

    def as_np_ndarray(self) -> "NDArray":
        return self

    def detach(self) -> "NDArray":
        """Return a view detached from the autograd graph."""
        return NDArray(self._data, ctx=self._ctx, _wrap=True)

    # ------------------------------------------------------------------
    # Autograd (reference: MXAutogradMarkVariables / NDArray::Backward)
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype: str = None) -> None:
        """Allocate a gradient buffer updated by ``backward()``."""
        if grad_req not in ("write", "add", "null"):
            raise ValueError(f"invalid grad_req {grad_req!r}")
        self._grad_req = grad_req
        if grad_req != "null":
            z = jnp.zeros(self.shape, dtype=self._data.dtype)
            if not _is_tracer(self._data):
                z = jax.device_put(z, self.context.jax_device)
            self._grad = NDArray(z, ctx=self._ctx, _wrap=True)
        else:
            self._grad = None

    def _write_grad(self, cot: Any) -> None:
        if self._grad_req == "null":
            return
        from .._tape import RowSparseCot
        if isinstance(cot, RowSparseCot):
            # sparse-grad leaf (Embedding sparse_grad): the grad buffer
            # becomes a fresh RowSparseNDArray each backward, as in the
            # reference's kRowSparseStorage gradient contract
            from .sparse import RowSparseNDArray
            rsp = RowSparseNDArray(cot.values, cot.indices, cot.shape,
                                   ctx=self._ctx)
            if self._grad_req == "add" and self._grad is not None and \
                    getattr(self._grad, "stype", "default") == "row_sparse":
                merged = RowSparseCot(
                    jnp.concatenate([self._grad._sp_indices, cot.indices]),
                    jnp.concatenate([self._grad._sp_values, cot.values]),
                    cot.shape)
                rsp = RowSparseNDArray(merged.values, merged.indices,
                                       cot.shape, ctx=self._ctx)
            self._grad = rsp._canonical()
            self._fresh_grad = True
            return
        if cot is None:
            cot = jnp.zeros(self.shape, dtype=self._data.dtype)
        if cot.dtype != self._data.dtype:
            cot = cot.astype(self._data.dtype)
        # Write INTO the buffer allocated by attach_grad (rebinding its
        # _data) so references held to ``x.grad`` stay live — the
        # reference's in-place grad contract that optimizers rely on.
        if self._grad is None:
            self._grad = NDArray(cot, ctx=self._ctx, _wrap=True)
        elif self._grad_req == "add":
            self._grad._data = self._grad._data + cot
        else:
            self._grad._data = cot
        self._fresh_grad = True  # staleness marker read by Trainer
        engine.track(self._grad._data)

    def backward(self, out_grad: Optional["NDArray"] = None,
                 retain_graph: bool = False, train_mode: bool = True) -> None:
        """Compute gradients of this array w.r.t. attached variables."""
        from .._tape import backward_arrays
        backward_arrays([self], [out_grad], retain_graph=retain_graph)

    # ------------------------------------------------------------------
    # Indexing
    # ------------------------------------------------------------------
    def __getitem__(self, key) -> "NDArray":
        from .register import invoke
        k = _raw_key(key)
        nd_keys = [x for x in (key if isinstance(key, tuple) else (key,))
                   if isinstance(x, NDArray)]
        if nd_keys:
            # advanced indexing with NDArray indices: pass them as real
            # inputs so gather is differentiable w.r.t. self only
            def impl(a, *idx):
                it = iter(idx)
                kk = tuple(next(it) if isinstance(x, NDArray) else _raw(x)
                           for x in (key if isinstance(key, tuple) else (key,)))
                return a[kk if isinstance(key, tuple) else kk[0]]
            return invoke("getitem", impl, (self, *nd_keys))
        return invoke("getitem", lambda a: a[k], (self,))

    def __setitem__(self, key, value) -> None:
        v = _raw(value)
        k = _raw_key(key)
        # in-place write to a promised buffer: a mutation hazard — the
        # pending segment flushes before the write lands
        d = self._materialize("mutation")
        if isinstance(k, slice) and k == slice(None) and not isinstance(v, (int, float, complex)):
            # x[:] = v  — full overwrite, keep dtype
            self._data = jnp.broadcast_to(jnp.asarray(v, dtype=d.dtype),
                                          self.shape)
        else:
            self._data = d.at[k].set(v)
        engine.track(self._data)

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self) -> bool:
        if self.size != 1:
            raise ValueError("The truth value of an array with more than one "
                             "element is ambiguous.")
        return bool(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __index__(self) -> int:
        return int(self.item())

    def __repr__(self) -> str:
        if _is_tracer(self._data):
            return f"NDArray(<traced {self.shape} {self._data.dtype}>)"
        return (f"{_np.array2string(self.asnumpy())}\n"
                f"<NDArray {self.shape} @{self.context}>")

    __hash__ = None  # elementwise __eq__ => unhashable, like numpy

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._data.__dlpack__(**kw)

    # ------------------------------------------------------------------
    # Arithmetic sugar (delegates to the op layer for autograd support)
    # ------------------------------------------------------------------
    def _binop(self, name, other, swap=False):
        from . import ops
        fn = getattr(ops, name)
        return fn(other, self) if swap else fn(self, other)

    def __add__(self, o): return self._binop("add", o)
    def __radd__(self, o): return self._binop("add", o, True)
    def __sub__(self, o): return self._binop("subtract", o)
    def __rsub__(self, o): return self._binop("subtract", o, True)
    def __mul__(self, o): return self._binop("multiply", o)
    def __rmul__(self, o): return self._binop("multiply", o, True)
    def __truediv__(self, o): return self._binop("divide", o)
    def __rtruediv__(self, o): return self._binop("divide", o, True)
    def __floordiv__(self, o): return self._binop("floor_divide", o)
    def __rfloordiv__(self, o): return self._binop("floor_divide", o, True)
    def __mod__(self, o): return self._binop("mod", o)
    def __rmod__(self, o): return self._binop("mod", o, True)
    def __pow__(self, o): return self._binop("power", o)
    def __rpow__(self, o): return self._binop("power", o, True)
    def __matmul__(self, o): return self._binop("matmul", o)
    def __rmatmul__(self, o): return self._binop("matmul", o, True)
    def __neg__(self): return self._binop("multiply", -1)
    def __pos__(self): return self
    def __abs__(self):
        from . import ops
        return ops.abs(self)

    def __eq__(self, o): return self._binop("equal", o)
    def __ne__(self, o): return self._binop("not_equal", o)
    def __lt__(self, o): return self._binop("less", o)
    def __le__(self, o): return self._binop("less_equal", o)
    def __gt__(self, o): return self._binop("greater", o)
    def __ge__(self, o): return self._binop("greater_equal", o)

    def __iadd__(self, o):
        return self._adopt(self._binop("add", o))

    def __isub__(self, o):
        return self._adopt(self._binop("subtract", o))

    def __imul__(self, o):
        return self._adopt(self._binop("multiply", o))

    def __itruediv__(self, o):
        return self._adopt(self._binop("divide", o))

    # ------------------------------------------------------------------
    # Method forms of common ops
    # ------------------------------------------------------------------
    def _op(self, name, *args, **kw):
        from . import ops
        return getattr(ops, name)(self, *args, **kw)

    def reshape(self, *shape, **kw):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self._op("reshape", shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return self._op("transpose", axes if axes else None)

    def swapaxes(self, a1, a2): return self._op("swapaxes", a1, a2)
    def flatten(self): return self.reshape(self.shape[0] if self.ndim else 1, -1) \
        if self.ndim > 1 else self.reshape(-1)
    def ravel(self): return self.reshape(-1)
    def expand_dims(self, axis): return self._op("expand_dims", axis)
    def squeeze(self, axis=None): return self._op("squeeze", axis)
    def broadcast_to(self, shape): return self._op("broadcast_to", shape)
    def broadcast_like(self, other): return self._op("broadcast_to", other.shape)
    def repeat(self, repeats, axis=None): return self._op("repeat", repeats, axis)
    def tile(self, reps): return self._op("tile", reps)
    def split(self, *a, **kw): return self._op("split", *a, **kw)
    def flip(self, axis=None): return self._op("flip", axis)
    def take(self, indices, axis=None, mode="clip"):
        return self._op("take", indices, axis, mode)
    def slice_axis(self, axis, begin, end):
        return self._op("slice_axis", axis=axis, begin=begin, end=end)

    def sum(self, axis=None, keepdims=False, dtype=None):
        return self._op("sum", axis=axis, keepdims=keepdims, dtype=dtype)
    def mean(self, axis=None, keepdims=False, dtype=None):
        return self._op("mean", axis=axis, keepdims=keepdims, dtype=dtype)
    def max(self, axis=None, keepdims=False):
        return self._op("max", axis=axis, keepdims=keepdims)
    def min(self, axis=None, keepdims=False):
        return self._op("min", axis=axis, keepdims=keepdims)
    def prod(self, axis=None, keepdims=False):
        return self._op("prod", axis=axis, keepdims=keepdims)
    def argmax(self, axis=None): return self._op("argmax", axis=axis)
    def argmin(self, axis=None): return self._op("argmin", axis=axis)
    def norm(self, ord=None, axis=None, keepdims=False):
        return self._op("norm", ord=ord, axis=axis, keepdims=keepdims)
    def cumsum(self, axis=None): return self._op("cumsum", axis=axis)
    def var(self, axis=None, keepdims=False):
        return self._op("var", axis=axis, keepdims=keepdims)
    def std(self, axis=None, keepdims=False):
        return self._op("std", axis=axis, keepdims=keepdims)

    def dot(self, other): return self._op("dot", other)
    def abs(self): return self._op("abs")
    def exp(self): return self._op("exp")
    def log(self): return self._op("log")
    def sqrt(self): return self._op("sqrt")
    def square(self): return self._op("square")
    def sign(self): return self._op("sign")
    def round(self, decimals=0): return self._op("round", decimals)
    def floor(self): return self._op("floor")
    def ceil(self): return self._op("ceil")
    def clip(self, a_min=None, a_max=None): return self._op("clip", a_min, a_max)
    def maximum(self, other): return self._op("maximum", other)
    def minimum(self, other): return self._op("minimum", other)
    def sigmoid(self): return self._op("sigmoid")
    def tanh(self): return self._op("tanh")
    def relu(self): return self._op("relu")
    def softmax(self, axis=-1): return self._op("softmax", axis=axis)
    def log_softmax(self, axis=-1): return self._op("log_softmax", axis=axis)
    def one_hot(self, depth, **kw): return self._op("one_hot", depth, **kw)
    def astype_like(self, other): return self.astype(other.dtype)
    def zeros_like(self): return self._op("zeros_like")
    def ones_like(self): return self._op("ones_like")

    def tostype(self, stype: str) -> "NDArray":
        if stype == "default":
            return self
        from . import sparse as _sparse
        if stype == "row_sparse":
            return _sparse._dense_to_rsp(self)
        if stype == "csr":
            return _sparse._dense_to_csr(self)
        raise MXNetError(f"unknown storage type {stype!r}")


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def from_jax(data: Any, ctx: Optional[Context] = None) -> NDArray:
    """Zero-copy wrap of an existing jax array / tracer."""
    return NDArray(data, ctx=ctx, _wrap=True)


def waitall() -> None:
    engine.waitall()
