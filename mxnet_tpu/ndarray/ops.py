"""Operator library: elementwise / broadcast / reduce / shape / linalg / init.

Reference parity (leezu/mxnet): ``src/operator/tensor/*`` (~150 unary/binary
ops, broadcast/reduce machinery, matrix ops, indexing, ordering) and the
``src/operator/numpy/*`` numpy-semantics ops — SURVEY.md section 2.2.

Design (tpu-first): each op is a pure function over jax arrays composed from
``jax.numpy``/``jax.lax``; XLA fuses elementwise chains automatically (the
reference needed NVRTC pointwise-fusion codegen for this —
``src/operator/fusion/``). Autograd is provided uniformly by the vjp hook in
``register.invoke``, replacing per-op ``FGradient`` registrations.

These functions accept NDArrays (plus python scalars) and return NDArrays.
They are also valid under jax tracing, which is how hybridize builds one XLA
program from the same implementations.
"""
from __future__ import annotations

import builtins
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as _np

from ..context import Context, current_context
from .ndarray import NDArray, from_jax
from .register import invoke, register_op
from builtins import slice as builtins_slice

__all__: list = []  # populated by _public


def _public(fn, name=None):
    name = name or fn.__name__
    __all__.append(name)
    register_op(name, fn)
    return fn


def _as_nd(x: Any, ref: Optional[NDArray] = None) -> NDArray:
    if isinstance(x, NDArray):
        return x
    dtype = None
    if isinstance(x, (bool, int, float)) and ref is not None:
        dtype = ref.dtype
    return NDArray(jnp.asarray(x, dtype=dtype), _wrap=True)


# ---------------------------------------------------------------------------
# Creation ops (reference: src/operator/tensor/init_op.cc)
# ---------------------------------------------------------------------------

def _create(data, ctx, dtype):
    return NDArray(data, ctx=ctx, dtype=dtype)


@_public
def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (``mx.nd.array``)."""
    if isinstance(source_array, NDArray):
        source_array = source_array._data
    return _create(source_array, ctx, dtype)


asarray = _public(array, "asarray")


@_public
def zeros(shape, ctx=None, dtype="float32") -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return _create(jnp.zeros(shape, dtype=dtype), ctx, None)


@_public
def ones(shape, ctx=None, dtype="float32") -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return _create(jnp.ones(shape, dtype=dtype), ctx, None)


@_public
def full(shape, val, ctx=None, dtype="float32") -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return _create(jnp.full(shape, val, dtype=dtype), ctx, None)


@_public
def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return zeros(shape, ctx, dtype)


@_public
def arange(start, stop=None, step=1.0, ctx=None, dtype="float32") -> NDArray:
    return _create(jnp.arange(start, stop, step, dtype=dtype), ctx, None)


@_public
def linspace(start, stop, num=50, endpoint=True, ctx=None, dtype="float32"):
    return _create(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=dtype), ctx, None)


@_public
def eye(N, M=None, k=0, ctx=None, dtype="float32") -> NDArray:
    return _create(jnp.eye(N, M, k=k, dtype=dtype), ctx, None)


@_public
def zeros_like(a: NDArray, dtype=None) -> NDArray:
    dt = dtype
    return invoke("zeros_like", lambda x: jnp.zeros_like(x, dtype=dt), (_as_nd(a),))


@_public
def ones_like(a: NDArray, dtype=None) -> NDArray:
    dt = dtype
    return invoke("ones_like", lambda x: jnp.ones_like(x, dtype=dt), (_as_nd(a),))


@_public
def full_like(a: NDArray, fill_value, dtype=None) -> NDArray:
    dt, v = dtype, fill_value
    return invoke("full_like", lambda x: jnp.full_like(x, v, dtype=dt), (_as_nd(a),))


# ---------------------------------------------------------------------------
# Generic unary ops
# ---------------------------------------------------------------------------

_UNARY_TABLE = {
    "negative": jnp.negative, "abs": jnp.abs, "absolute": jnp.abs,
    "sign": jnp.sign, "rint": jnp.rint, "floor": jnp.floor,
    "ceil": jnp.ceil, "trunc": jnp.trunc, "fix": jnp.trunc,
    "square": jnp.square, "sqrt": jnp.sqrt, "cbrt": jnp.cbrt,
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "reciprocal": jnp.reciprocal,
    "logical_not": jnp.logical_not,
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid, "relu": jax.nn.relu,
    "softsign": jax.nn.soft_sign,
}


def _make_unary(name, impl):
    def op(a, **kw):
        return invoke(name, impl, (_as_nd(a),))
    op.__name__ = name
    op.__doc__ = f"Elementwise ``{name}`` (src/operator/tensor/elemwise_unary_op)."
    return _public(op, name)


for _n, _f in _UNARY_TABLE.items():
    globals()[_n] = _make_unary(_n, _f)

rsqrt = _public(lambda a: invoke("rsqrt", jax.lax.rsqrt, (_as_nd(a),)), "rsqrt")
rcbrt = _public(lambda a: invoke("rcbrt", lambda x: 1.0 / jnp.cbrt(x), (_as_nd(a),)), "rcbrt")


@_public
def round(a, decimals=0):  # noqa: A001
    d = decimals
    return invoke("round", lambda x: jnp.round(x, d), (_as_nd(a),))


# ---------------------------------------------------------------------------
# Generic binary broadcast ops (scalar operands bound statically)
# ---------------------------------------------------------------------------

_BINARY_TABLE = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "true_divide": jnp.true_divide,
    "floor_divide": jnp.floor_divide, "mod": jnp.mod, "fmod": jnp.fmod,
    "remainder": jnp.remainder,
    "power": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin,
    "hypot": jnp.hypot, "arctan2": jnp.arctan2, "logaddexp": jnp.logaddexp,
    "copysign": jnp.copysign,
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "less": jnp.less, "less_equal": jnp.less_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}


def _make_binary(name, impl):
    def op(lhs, rhs, **kw):
        l_nd, r_nd = isinstance(lhs, NDArray), isinstance(rhs, NDArray)
        if l_nd and r_nd:
            return invoke(name, impl, (lhs, rhs))
        if l_nd:
            s = rhs
            return invoke(name, lambda a: impl(a, s), (lhs,))
        if r_nd:
            s = lhs
            return invoke(name, lambda b: impl(s, b), (rhs,))
        return NDArray(impl(jnp.asarray(lhs), jnp.asarray(rhs)), _wrap=True)
    op.__name__ = name
    op.__doc__ = (f"Broadcasting ``{name}`` "
                  f"(src/operator/tensor/elemwise_binary_broadcast_op).")
    return _public(op, name)


for _n, _f in _BINARY_TABLE.items():
    globals()[_n] = _make_binary(_n, _f)


@_public
def clip(a, a_min=None, a_max=None):
    lo, hi = a_min, a_max
    return invoke("clip", lambda x: jnp.clip(x, lo, hi), (_as_nd(a),))


@_public
def where(condition, x=None, y=None):
    if x is None and y is None:
        return invoke("where_idx", lambda c: jnp.where(c), (_as_nd(condition),))
    return invoke("where", lambda c, a, b: jnp.where(c, a, b),
                  (_as_nd(condition), _as_nd(x), _as_nd(y)))


# ---------------------------------------------------------------------------
# Reductions (reference: broadcast_reduce-inl, np reduce ops)
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _make_reduce(name, impl, has_dtype=True):
    def op(a, axis=None, keepdims=False, dtype=None, **kw):
        ax, kd, dt = _norm_axis(axis), keepdims, dtype
        if has_dtype:
            fn = lambda x: impl(x, axis=ax, keepdims=kd, dtype=dt)  # noqa: E731
        else:
            fn = lambda x: impl(x, axis=ax, keepdims=kd)  # noqa: E731
        return invoke(name, fn, (_as_nd(a),))
    op.__name__ = name
    op.__doc__ = f"Reduction ``{name}`` over axes (broadcast_reduce-inl)."
    return _public(op, name)


sum = _make_reduce("sum", jnp.sum)  # noqa: A001
mean = _make_reduce("mean", jnp.mean)
prod = _make_reduce("prod", jnp.prod)
max = _make_reduce("max", jnp.max, has_dtype=False)  # noqa: A001
min = _make_reduce("min", jnp.min, has_dtype=False)  # noqa: A001
amax, amin = max, min
_public(max, "amax"); _public(min, "amin")
all = _make_reduce("all", jnp.all, has_dtype=False)  # noqa: A001
any = _make_reduce("any", jnp.any, has_dtype=False)  # noqa: A001


@_public
def var(a, axis=None, ddof=0, keepdims=False, dtype=None):
    ax, kd, dd = _norm_axis(axis), keepdims, ddof
    return invoke("var", lambda x: jnp.var(x, axis=ax, ddof=dd, keepdims=kd),
                  (_as_nd(a),))


@_public
def std(a, axis=None, ddof=0, keepdims=False, dtype=None):
    ax, kd, dd = _norm_axis(axis), keepdims, ddof
    return invoke("std", lambda x: jnp.std(x, axis=ax, ddof=dd, keepdims=kd),
                  (_as_nd(a),))


@_public
def argmax(a, axis=None, keepdims=False):
    ax, kd = axis, keepdims
    return invoke("argmax", lambda x: jnp.argmax(x, axis=ax, keepdims=kd),
                  (_as_nd(a),))


@_public
def argmin(a, axis=None, keepdims=False):
    ax, kd = axis, keepdims
    return invoke("argmin", lambda x: jnp.argmin(x, axis=ax, keepdims=kd),
                  (_as_nd(a),))


@_public
def norm(a, ord=None, axis=None, keepdims=False):  # noqa: A002
    o, ax, kd = ord, _norm_axis(axis), keepdims
    def impl(x):
        if ax is None and x.ndim > 2:
            # flattened vector norm of the whole tensor (numpy semantics)
            flat = jnp.linalg.norm(x.reshape(-1), ord=o)
            return flat.reshape((1,) * x.ndim) if kd else flat
        return jnp.linalg.norm(x, ord=o, axis=ax, keepdims=kd)
    return invoke("norm", impl, (_as_nd(a),))


@_public
def cumsum(a, axis=None, dtype=None):
    ax, dt = axis, dtype
    return invoke("cumsum", lambda x: jnp.cumsum(x, axis=ax, dtype=dt),
                  (_as_nd(a),))


@_public
def cumprod(a, axis=None):
    ax = axis
    return invoke("cumprod", lambda x: jnp.cumprod(x, axis=ax), (_as_nd(a),))


@_public
def logsumexp(a, axis=None, keepdims=False):
    ax, kd = _norm_axis(axis), keepdims
    return invoke("logsumexp",
                  lambda x: jax.scipy.special.logsumexp(x, axis=ax, keepdims=kd),
                  (_as_nd(a),))


# ---------------------------------------------------------------------------
# Shape / layout ops (reference: matrix_op, np shape ops)
# ---------------------------------------------------------------------------

@_public
def reshape(a, newshape, order="C"):
    shp = tuple(newshape) if not isinstance(newshape, int) else (newshape,)
    return invoke("reshape", lambda x: jnp.reshape(x, shp), (_as_nd(a),))


@_public
def transpose(a, axes=None):
    ax = tuple(axes) if axes else None
    return invoke("transpose", lambda x: jnp.transpose(x, ax), (_as_nd(a),))


@_public
def swapaxes(a, axis1, axis2):
    a1, a2 = axis1, axis2
    return invoke("swapaxes", lambda x: jnp.swapaxes(x, a1, a2), (_as_nd(a),))


@_public
def moveaxis(a, source, destination):
    s, d = source, destination
    return invoke("moveaxis", lambda x: jnp.moveaxis(x, s, d), (_as_nd(a),))


@_public
def expand_dims(a, axis):
    ax = axis
    return invoke("expand_dims", lambda x: jnp.expand_dims(x, ax), (_as_nd(a),))


@_public
def squeeze(a, axis=None):
    ax = axis
    return invoke("squeeze", lambda x: jnp.squeeze(x, ax), (_as_nd(a),))


@_public
def broadcast_to(a, shape):
    shp = tuple(shape)
    return invoke("broadcast_to", lambda x: jnp.broadcast_to(x, shp), (_as_nd(a),))


@_public
def ravel(a):
    return reshape(a, (-1,))


@_public
def flatten(a):
    """Collapse all but the first axis (legacy ``Flatten`` semantics)."""
    nd = _as_nd(a)
    return reshape(nd, (nd.shape[0], -1))


@_public
def concatenate(seq, axis=0):
    ax = axis
    arrs = [_as_nd(s) for s in seq]
    return invoke("concatenate", lambda *xs: jnp.concatenate(xs, axis=ax), arrs)


@_public
def concat(*data, dim=0, axis=None):
    """Legacy ``concat`` (dim kwarg); also accepts a single list."""
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return concatenate(data, axis=dim if axis is None else axis)


@_public
def stack(seq, axis=0):
    ax = axis
    arrs = [_as_nd(s) for s in seq]
    return invoke("stack", lambda *xs: jnp.stack(xs, axis=ax), arrs)


@_public
def split(a, indices_or_sections, axis=0):
    i, ax = indices_or_sections, axis
    if isinstance(i, (list, tuple)):
        i = tuple(i)
    return invoke("split", lambda x: tuple(jnp.split(x, i, axis=ax)),
                  (_as_nd(a),))


@_public
def slice_channel(data, num_outputs, axis=1, squeeze_axis=False):
    """Split along ``axis`` into ``num_outputs`` equal parts (reference:
    ``SliceChannel`` in src/operator/slice_channel.cc; default axis=1)."""
    n, ax, sq = num_outputs, axis, squeeze_axis

    def impl(x):
        parts = jnp.split(x, n, axis=ax)
        if sq:
            parts = [jnp.squeeze(p, axis=ax) for p in parts]
        return tuple(parts)

    return invoke("slice_channel", impl, (_as_nd(data),))


@_public
def array_split(a, indices_or_sections, axis=0):
    i, ax = indices_or_sections, axis
    return invoke("array_split",
                  lambda x: tuple(jnp.array_split(x, i, axis=ax)),
                  (_as_nd(a),))


@_public
def tile(a, reps):
    r = reps
    return invoke("tile", lambda x: jnp.tile(x, r), (_as_nd(a),))


@_public
def repeat(a, repeats, axis=None):
    r, ax = repeats, axis
    return invoke("repeat", lambda x: jnp.repeat(x, r, axis=ax), (_as_nd(a),))


@_public
def flip(a, axis=None):
    ax = axis
    return invoke("flip", lambda x: jnp.flip(x, axis=ax), (_as_nd(a),))


@_public
def roll(a, shift, axis=None):
    s, ax = shift, axis
    return invoke("roll", lambda x: jnp.roll(x, s, axis=ax), (_as_nd(a),))


@_public
def pad(a, pad_width, mode="constant", constant_values=0):
    pw, m, cv = pad_width, mode, constant_values
    def impl(x):
        if m == "constant":
            return jnp.pad(x, pw, mode=m, constant_values=cv)
        return jnp.pad(x, pw, mode=m)
    return invoke("pad", impl, (_as_nd(a),))


@_public
def slice_axis(a, axis, begin, end):
    ax, b, e = axis, begin, end
    def impl(x):
        idx = [builtins.slice(None)] * x.ndim
        idx[ax] = builtins.slice(b, e)
        return x[tuple(idx)]
    return invoke("slice_axis", impl, (_as_nd(a),))


@_public
def slice_like(a, b, axes=None):
    axs = axes
    bshape = _as_nd(b).shape
    def impl(x):
        idx = [builtins.slice(None)] * x.ndim
        rng = axs if axs is not None else range(x.ndim)
        for ax in rng:
            idx[ax] = builtins.slice(0, bshape[ax])
        return x[tuple(idx)]
    return invoke("slice_like", impl, (_as_nd(a),))


@_public
def atleast_1d(a):
    return invoke("atleast_1d", jnp.atleast_1d, (_as_nd(a),))


@_public
def atleast_2d(a):
    return invoke("atleast_2d", jnp.atleast_2d, (_as_nd(a),))


@_public
def tril(a, k=0):
    kk = k
    return invoke("tril", lambda x: jnp.tril(x, kk), (_as_nd(a),))


@_public
def triu(a, k=0):
    kk = k
    return invoke("triu", lambda x: jnp.triu(x, kk), (_as_nd(a),))


@_public
def diag(a, k=0):
    kk = k
    return invoke("diag", lambda x: jnp.diag(x, kk), (_as_nd(a),))


# ---------------------------------------------------------------------------
# Indexing / gather-scatter (reference: indexing_op.cc)
# ---------------------------------------------------------------------------

@_public
def take(a, indices, axis=None, mode="clip"):
    ax, md = axis, mode
    idx = _as_nd(indices)
    return invoke("take",
                  lambda x, i: jnp.take(x, i.astype(jnp.int32), axis=ax,
                                        mode=md if md != "raise" else "clip"),
                  (_as_nd(a), idx))


@_public
def take_along_axis(a, indices, axis):
    ax = axis
    return invoke("take_along_axis",
                  lambda x, i: jnp.take_along_axis(x, i.astype(jnp.int32), axis=ax),
                  (_as_nd(a), _as_nd(indices)))


@_public
def gather_nd(data, indices):
    """Gather with leading index tensor (src/operator/tensor/indexing_op.cc)."""
    def impl(x, i):
        i = i.astype(jnp.int32)
        idx = tuple(i[k] for k in range(i.shape[0]))
        return x[idx]
    return invoke("gather_nd", impl, (_as_nd(data), _as_nd(indices)))


@_public
def scatter_nd(data, indices, shape):
    shp = tuple(shape)
    def impl(d, i):
        i = i.astype(jnp.int32)
        idx = tuple(i[k] for k in range(i.shape[0]))
        return jnp.zeros(shp, d.dtype).at[idx].add(d)
    return invoke("scatter_nd", impl, (_as_nd(data), _as_nd(indices)))


@_public
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    d, on, off, dt = depth, on_value, off_value, dtype
    return invoke("one_hot",
                  lambda i: jax.nn.one_hot(i.astype(jnp.int32), d, dtype=dt) *
                  (on - off) + off,
                  (_as_nd(indices),))


@_public
def unique(a, return_index=False, return_inverse=False, return_counts=False):
    nd = _as_nd(a)
    res = _np.unique(nd.asnumpy(), return_index=return_index,
                     return_inverse=return_inverse, return_counts=return_counts)
    if isinstance(res, tuple):
        return tuple(NDArray(r) for r in res)
    return NDArray(res)


@_public
def nonzero(a):
    nd = _as_nd(a)
    res = _np.nonzero(nd.asnumpy())
    return tuple(NDArray(r) for r in res)


@_public
def boolean_mask(data, mask):
    nd, m = _as_nd(data), _as_nd(mask)
    return NDArray(nd.asnumpy()[m.asnumpy().astype(bool)])


# ---------------------------------------------------------------------------
# Ordering (reference: ordering_op.cc — topk/sort/argsort via cub)
# ---------------------------------------------------------------------------

@_public
def sort(a, axis=-1, is_ascend=True):
    ax, asc = axis, is_ascend
    def impl(x):
        s = jnp.sort(x, axis=ax)
        return s if asc else jnp.flip(s, axis=ax)
    return invoke("sort", impl, (_as_nd(a),))


@_public
def argsort(a, axis=-1, is_ascend=True, dtype="float32"):
    ax, asc, dt = axis, is_ascend, dtype
    def impl(x):
        s = jnp.argsort(x, axis=ax)
        if not asc:
            s = jnp.flip(s, axis=ax)
        return s.astype(dt)
    return invoke("argsort", impl, (_as_nd(a),))


@_public
def topk(a, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    ax, kk, rt, asc, dt = axis, k, ret_typ, is_ascend, dtype
    def impl(x):
        xm = jnp.moveaxis(x, ax, -1)
        vals, idx = jax.lax.top_k(-xm if asc else xm, kk)
        if asc:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, ax)
        idx = jnp.moveaxis(idx, -1, ax)
        if rt == "value":
            return vals
        if rt == "indices":
            return idx.astype(dt)
        return (vals, idx.astype(dt))
    return invoke("topk", impl, (_as_nd(a),))


@_public
def searchsorted(a, v, side="left"):
    s = side
    return invoke("searchsorted",
                  lambda x, q: jnp.searchsorted(x, q, side=s),
                  (_as_nd(a), _as_nd(v)))


# ---------------------------------------------------------------------------
# Linear algebra (reference: dot.cc, la_op.cc, np_matmul)
# ---------------------------------------------------------------------------

@_public
def dot(a, b):
    """MXNet ``dot``: inner product over last axis of a / first axis of b."""
    def impl(x, y):
        if x.ndim == 1 and y.ndim == 1:
            return jnp.dot(x, y)
        return jnp.tensordot(x, y, axes=([-1], [0]))
    return invoke("dot", impl, (_as_nd(a), _as_nd(b)))


@_public
def matmul(a, b):
    return invoke("matmul", jnp.matmul, (_as_nd(a), _as_nd(b)))


@_public
def batch_dot(a, b, transpose_a=False, transpose_b=False):
    ta, tb = transpose_a, transpose_b
    def impl(x, y):
        if ta:
            x = jnp.swapaxes(x, -1, -2)
        if tb:
            y = jnp.swapaxes(y, -1, -2)
        return jnp.matmul(x, y)
    return invoke("batch_dot", impl, (_as_nd(a), _as_nd(b)))


@_public
def tensordot(a, b, axes=2):
    ax = axes
    return invoke("tensordot", lambda x, y: jnp.tensordot(x, y, axes=ax),
                  (_as_nd(a), _as_nd(b)))


@_public
def einsum(subscripts, *operands, optimize=True):
    sub = subscripts
    arrs = [_as_nd(o) for o in operands]
    return invoke("einsum",
                  lambda *xs: jnp.einsum(sub, *xs,
                                         optimize="optimal" if optimize else False),
                  arrs)


@_public
def inner(a, b):
    return invoke("inner", jnp.inner, (_as_nd(a), _as_nd(b)))


@_public
def outer(a, b):
    return invoke("outer", jnp.outer, (_as_nd(a), _as_nd(b)))


@_public
def kron(a, b):
    return invoke("kron", jnp.kron, (_as_nd(a), _as_nd(b)))


@_public
def vdot(a, b):
    return invoke("vdot", jnp.vdot, (_as_nd(a), _as_nd(b)))


@_public
def trace(a, offset=0, axis1=0, axis2=1):
    o, a1, a2 = offset, axis1, axis2
    return invoke("trace", lambda x: jnp.trace(x, o, a1, a2), (_as_nd(a),))


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------

@_public
def cast(a, dtype):
    dt = dtype
    return invoke("cast", lambda x: x.astype(dt), (_as_nd(a),))


astype = _public(cast, "astype")


@_public
def identity(a):
    return invoke("identity", lambda x: x + 0, (_as_nd(a),))


@_public
def stop_gradient(a):
    return invoke("stop_gradient", jax.lax.stop_gradient, (_as_nd(a),))


BlockGrad = _public(stop_gradient, "BlockGrad")


@_public
def add_n(*args):
    """Sum of a list of arrays (reference: ElementwiseSum)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    arrs = [_as_nd(a) for a in args]
    return invoke("add_n", lambda *xs: jax.tree_util.tree_reduce(jnp.add, list(xs)),
                  arrs)


ElementWiseSum = _public(add_n, "ElementWiseSum")


@_public
def maximum_n(*args):
    arrs = [_as_nd(a) for a in args]
    return invoke("maximum_n",
                  lambda *xs: jax.tree_util.tree_reduce(jnp.maximum, list(xs)), arrs)


@_public
def isclose(a, b, rtol=1e-5, atol=1e-8):
    rt, at = rtol, atol
    return invoke("isclose", lambda x, y: jnp.isclose(x, y, rt, at),
                  (_as_nd(a), _as_nd(b)))


@_public
def nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    n, p, ng = nan, posinf, neginf
    return invoke("nan_to_num",
                  lambda x: jnp.nan_to_num(x, nan=n, posinf=p, neginf=ng),
                  (_as_nd(a),))


@_public
def diff(a, n=1, axis=-1):
    nn, ax = n, axis
    return invoke("diff", lambda x: jnp.diff(x, n=nn, axis=ax), (_as_nd(a),))


@_public
def meshgrid(*xs, indexing="xy"):
    ind = indexing
    arrs = [_as_nd(x) for x in xs]
    outs = jnp.meshgrid(*[a._data for a in arrs], indexing=ind)
    return tuple(from_jax(o) for o in outs)


@_public
def histogram(a, bins=10, range=None):  # noqa: A002
    nd = _as_nd(a)
    h, e = jnp.histogram(nd._data, bins=bins, range=range)
    return from_jax(h), from_jax(e)


@_public
def interp(x, xp, fp):
    return invoke("interp", jnp.interp, (_as_nd(x), _as_nd(xp), _as_nd(fp)))


@_public
def waitall():
    from .. import engine as _e
    _e.waitall()


# ---------------------------------------------------------------------------
# Legacy 1.x op-name aliases + remaining tensor ops (reference:
# src/operator/tensor/elemwise_binary_broadcast_op*, matrix_op*,
# src/operator/bilinear_sampler.cc, grid_generator.cc). The broadcast_*/
# elemwise_* spellings share one implementation — XLA broadcasts either
# way; keeping both names preserves the reference's public surface.
# ---------------------------------------------------------------------------

broadcast_add = _public(globals()["add"], "broadcast_add")
broadcast_plus = _public(globals()["add"], "broadcast_plus")
broadcast_sub = _public(globals()["subtract"], "broadcast_sub")
broadcast_minus = _public(globals()["subtract"], "broadcast_minus")
broadcast_mul = _public(globals()["multiply"], "broadcast_mul")
broadcast_div = _public(globals()["divide"], "broadcast_div")
broadcast_mod = _public(globals()["mod"], "broadcast_mod")
broadcast_power = _public(globals()["power"], "broadcast_power")
broadcast_maximum = _public(globals()["maximum"], "broadcast_maximum")
broadcast_minimum = _public(globals()["minimum"], "broadcast_minimum")
broadcast_equal = _public(globals()["equal"], "broadcast_equal")
broadcast_not_equal = _public(globals()["not_equal"], "broadcast_not_equal")
broadcast_greater = _public(globals()["greater"], "broadcast_greater")
broadcast_greater_equal = _public(globals()["greater_equal"],
                                  "broadcast_greater_equal")
broadcast_lesser = _public(globals()["less"], "broadcast_lesser")
broadcast_lesser_equal = _public(globals()["less_equal"],
                                 "broadcast_lesser_equal")
broadcast_logical_and = _public(globals()["logical_and"],
                                "broadcast_logical_and")
broadcast_logical_or = _public(globals()["logical_or"],
                               "broadcast_logical_or")
broadcast_logical_xor = _public(globals()["logical_xor"],
                                "broadcast_logical_xor")
elemwise_add = _public(globals()["add"], "elemwise_add")
elemwise_sub = _public(globals()["subtract"], "elemwise_sub")
elemwise_mul = _public(globals()["multiply"], "elemwise_mul")
elemwise_div = _public(globals()["divide"], "elemwise_div")


@_public
def broadcast_axis(data, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    if len(axes) != len(sizes):
        raise ValueError(f"broadcast_axis: axis {axes} and size {sizes} "
                         "must have the same length")

    def impl(x):
        shape = list(x.shape)
        for ax, s in zip(axes, sizes):
            shape[ax] = s
        return jnp.broadcast_to(x, shape)

    return invoke("broadcast_axis", impl, (_as_nd(data),))


broadcast_axes = _public(globals()["broadcast_axis"], "broadcast_axes")


@_public
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    l, r = _as_nd(lhs), _as_nd(rhs)
    if lhs_axes is None:
        return invoke("broadcast_like",
                      lambda a, b: jnp.broadcast_to(a, b.shape), (l, r))
    if rhs_axes is None or len(tuple(lhs_axes)) != len(tuple(rhs_axes)):
        raise ValueError("broadcast_like: lhs_axes and rhs_axes must be "
                         "given together with equal length")
    l_axes, r_axes = tuple(lhs_axes), tuple(rhs_axes)

    def impl(a, b):
        shape = list(a.shape)
        for la, ra in zip(l_axes, r_axes):
            shape[la] = b.shape[ra]
        return jnp.broadcast_to(a, shape)

    return invoke("broadcast_like", impl, (l, r))


@_public
def reshape_like(lhs, rhs):
    return invoke("reshape_like",
                  lambda a, b: jnp.reshape(a, b.shape),
                  (_as_nd(lhs), _as_nd(rhs)))


@_public
def reverse(data, axis):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return invoke("reverse", lambda x: jnp.flip(x, axis=axes),
                  (_as_nd(data),))


@_public
def slice(data, begin, end, step=None):  # noqa: A001
    b, e = tuple(begin), tuple(end)
    st = tuple(step) if step is not None else (1,) * len(b)
    if len(b) != len(e) or len(st) != len(b):
        raise ValueError(f"slice: begin {b}, end {e}, step {st} must have "
                         "equal lengths")
    if 0 in st:  # NB: module-level `any` is the reduction op, not builtin
        raise ValueError("slice: step cannot be 0")
    sl = tuple(builtins_slice(bb, ee, ss)
               for bb, ee, ss in zip(b, e, st))
    return invoke("slice", lambda x: x[sl], (_as_nd(data),))


@_public
def softmin(data, axis=-1):
    return invoke("softmin",
                  lambda x: jax.nn.softmax(-x.astype(jnp.float32), axis=axis)
                  .astype(x.dtype), (_as_nd(data),))


@_public
def moments(data, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None

    def impl(x):
        m = jnp.mean(x, axis=ax, keepdims=keepdims)
        v = jnp.var(x, axis=ax, keepdims=keepdims)
        return m, v

    out = invoke("moments", impl, (_as_nd(data),))
    return out


@_public
def shape_array(data):
    nd = _as_nd(data)
    return from_jax(jnp.asarray(nd.shape, dtype=jnp.int32))


@_public
def size_array(data):
    nd = _as_nd(data)
    return from_jax(jnp.asarray([nd.size], dtype=jnp.int32))


@_public
def batch_take(a, indices):
    return invoke("batch_take",
                  lambda x, idx: jnp.take_along_axis(
                      x, idx[:, None].astype(jnp.int32), axis=1)[:, 0],
                  (_as_nd(a), _as_nd(indices)))


@_public
def grid_generator(data, transform_type="affine", target_shape=None):
    """Sampling-grid generation for spatial transformers (reference:
    src/operator/grid_generator.cc). 'affine': data is (N, 6) affine
    params; 'warp': data is (N, 2, H, W) flow offsets. Output grid is
    (N, 2, H, W) with x/y in [-1, 1]."""
    th, tw = (target_shape if transform_type == "affine"
              else _as_nd(data).shape[2:])

    def impl(d):
        if transform_type == "affine":
            n = d.shape[0]
            ys = jnp.linspace(-1.0, 1.0, th)
            xs = jnp.linspace(-1.0, 1.0, tw)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            ones = jnp.ones_like(gx)
            base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # 3,HW
            theta = d.reshape(n, 2, 3).astype(jnp.float32)
            out = jnp.einsum("nij,jk->nik", theta, base)  # n,2,HW
            return out.reshape(n, 2, th, tw)
        # warp: offsets are in pixels; normalize to [-1, 1]
        n, _, h, w = d.shape
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        fx = (gx + d[:, 0].astype(jnp.float32)) * 2.0 / \
            jnp.maximum(w - 1, 1) - 1.0
        fy = (gy + d[:, 1].astype(jnp.float32)) * 2.0 / \
            jnp.maximum(h - 1, 1) - 1.0
        return jnp.stack([fx, fy], axis=1)

    return invoke("grid_generator", impl, (_as_nd(data),))


@_public
def bilinear_sampler(data, grid, cudnn_off=None):
    """Bilinear sampling of (N, C, H, W) data at grid locations
    (reference: src/operator/bilinear_sampler.cc; the spatial-transformer
    sampler). ``grid`` is (N, 2, Ho, Wo) with x/y in [-1, 1]; out-of-
    range samples read zero (border handled by clamping the gather and
    masking the weight)."""

    def impl(x, g):
        n, c, h, w = x.shape
        gx = (g[:, 0].astype(jnp.float32) + 1.0) * (w - 1) / 2.0
        gy = (g[:, 1].astype(jnp.float32) + 1.0) * (h - 1) / 2.0
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = gx - x0
        wy = gy - y0

        def gather(yy, xx):
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            valid = ((yy >= 0) & (yy <= h - 1) &
                     (xx >= 0) & (xx <= w - 1)).astype(jnp.float32)
            vals = jax.vmap(
                lambda img, yj, xj: img[:, yj, xj])(x, yi, xi)  # n,c,Ho,Wo?
            return vals, valid

        v00, m00 = gather(y0, x0)
        v01, m01 = gather(y0, x0 + 1)
        v10, m10 = gather(y0 + 1, x0)
        v11, m11 = gather(y0 + 1, x0 + 1)
        w00 = ((1 - wy) * (1 - wx) * m00)[:, None]
        w01 = ((1 - wy) * wx * m01)[:, None]
        w10 = (wy * (1 - wx) * m10)[:, None]
        w11 = (wy * wx * m11)[:, None]
        out = (v00.astype(jnp.float32) * w00 +
               v01.astype(jnp.float32) * w01 +
               v10.astype(jnp.float32) * w10 +
               v11.astype(jnp.float32) * w11)
        return out.astype(x.dtype)

    return invoke("bilinear_sampler", impl, (_as_nd(data), _as_nd(grid)))


@_public
def depth_to_space(data, block_size: int):
    """Rearrange depth blocks into spatial blocks, NCHW (reference:
    src/operator/tensor/matrix_op DepthToSpace — the DCR layout the
    reference documents: reshape (N, b, b, C/b², H, W) → transpose →
    (N, C/b², H·b, W·b))."""
    b = int(block_size)

    def impl(x):
        n, c, h, w = x.shape
        t = x.reshape(n, b, b, c // (b * b), h, w)
        t = jnp.transpose(t, (0, 3, 4, 1, 5, 2))
        return t.reshape(n, c // (b * b), h * b, w * b)

    nd = _as_nd(data)
    if b <= 0 or nd.ndim != 4 or nd.shape[1] % (b * b):
        raise ValueError(
            f"depth_to_space: need NCHW with C divisible by block² and "
            f"a positive block (got shape {nd.shape}, block {b})")
    return invoke("depth_to_space", impl, (nd,))


@_public
def space_to_depth(data, block_size: int):
    """Inverse of :func:`depth_to_space` (reference SpaceToDepth)."""
    b = int(block_size)

    def impl(x):
        n, c, h, w = x.shape
        t = x.reshape(n, c, h // b, b, w // b, b)
        t = jnp.transpose(t, (0, 3, 5, 1, 2, 4))
        return t.reshape(n, c * b * b, h // b, w // b)

    nd = _as_nd(data)
    if b <= 0 or nd.ndim != 4 or nd.shape[2] % b or nd.shape[3] % b:
        raise ValueError(
            f"space_to_depth: need NCHW with H, W divisible by block "
            f"and a positive block (got shape {nd.shape}, block {b})")
    return invoke("space_to_depth", impl, (nd,))


@_public
def shuffle(data):
    """Random permutation along the first axis (reference:
    mx.nd.random.shuffle / src/operator/random/shuffle_op.cc). Draws
    from the framework RNG stream; rides as an op input so compiled
    programs reshuffle every call."""
    from . import random as _random
    seed = _random.split_seed()   # jitted: no eager key ops on the tunnel

    def impl(x, s):
        k = jax.random.wrap_key_data(s, impl="threefry2x32")
        return jax.random.permutation(k, x, axis=0)

    return invoke("shuffle", impl,
                  (_as_nd(data), _as_nd(seed)))


@_public
def spatial_transformer(data, loc, target_shape=None,
                        transform_type: str = "affine",
                        sampler_type: str = "bilinear"):
    """Spatial transformer network op (reference:
    src/operator/spatial_transformer.cc): affine grid from ``loc``
    (N, 6) + bilinear sampling of ``data`` — the composition of
    :func:`grid_generator` and :func:`bilinear_sampler`."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("spatial_transformer supports transform_type="
                         "'affine' with sampler_type='bilinear'")
    if target_shape is None:
        target_shape = _as_nd(data).shape[2:]
    grid = grid_generator(loc, "affine", tuple(target_shape))
    return bilinear_sampler(data, grid)


@_public
def khatri_rao(*matrices):
    """Column-wise Kronecker product (reference:
    src/operator/contrib/krprod.cc — mx.nd.khatri_rao). All inputs are
    (r_i, k); output ((Πr_i), k)."""
    if not matrices:
        raise ValueError("khatri_rao needs at least one matrix")
    nds = tuple(_as_nd(m) for m in matrices)
    bad = False
    for m in nds:                      # ndim first: 0-d has no shape[-1]
        bad = bad or m.ndim != 2
    if not bad:
        bad = len({m.shape[-1] for m in nds}) != 1
    if bad:
        raise ValueError(
            "khatri_rao needs 2-D matrices with a COMMON column count; "
            f"got shapes {[m.shape for m in nds]}")

    def impl(*ms):
        out = ms[0]
        for m in ms[1:]:
            k = out.shape[1]
            out = (out[:, None, :] * m[None, :, :]).reshape(-1, k)
        return out

    return invoke("khatri_rao", impl, nds)
