"""ctypes bindings for the native runtime (libmxtpu.so).

Reference parity (leezu/mxnet): ``python/mxnet/base.py`` (``_LIB`` ctypes
loading, ``check_call`` + ``MXGetLastError`` error trampoline).  The
native library provides the host-side runtime: dependency engine, pooled
storage, RecordIO and the threaded prefetcher (see ``src/mxtpu.h``).

Everything degrades gracefully: if the library is absent and cannot be
built (no toolchain), ``LIB`` is ``None`` and pure-Python fallbacks are
used by callers.
"""
from __future__ import annotations

import atexit
import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, List, Optional, Sequence

from .base import MXNetError, register_env

register_env("MXNET_NATIVE_BUILD", 1,
             "Set to 0 to skip the automatic 'make -C src' rebuild of "
             "libmxtpu.so when the shared library is missing; the "
             "native engine then stays unavailable and pure-Python "
             "paths serve instead.")
register_env("MXNET_CPU_WORKER_NTHREADS", 0,
             "Worker threads for the native C++ engine's CPU pool "
             "(libmxtpu.so). 0 (default) sizes the pool from the "
             "machine; mirrors the reference's knob of the same name.")

__all__ = ["LIB", "check_call", "NativeEngine", "NativeRecordWriter",
           "NativeRecordReader", "NativePrefetcher", "storage_stats",
           "storage_release_all", "native_features"]

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_PKG_DIR, "libmxtpu.so")
_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "src")


def _try_build() -> bool:
    if os.environ.get("MXNET_NATIVE_BUILD", "1") == "0":
        return False
    if not os.path.isfile(os.path.join(_SRC_DIR, "Makefile")):
        return False
    try:
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True, timeout=300)
        return os.path.isfile(_LIB_PATH)
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    if not os.path.isfile(_LIB_PATH) and not _try_build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXLibInfoFeatures.restype = ctypes.c_char_p
    return lib


LIB = _load()

# Engine callback signatures (src/mxtpu.h MXEngineFn / MXEngineOnComplete).
_ENGINE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_ON_COMPLETE = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int)


def check_call(ret: int) -> None:
    """Raise MXNetError with the native message on nonzero return."""
    if ret != 0:
        msg = LIB.MXGetLastError().decode("utf-8", "replace")
        raise MXNetError(msg or "native call failed")


def native_features() -> List[str]:
    if LIB is None:
        return []
    return LIB.MXLibInfoFeatures().decode().split(",")


def storage_stats() -> Dict[str, int]:
    """Pooled-allocator counters (storage/pooled_storage_manager.h)."""
    if LIB is None:
        return {}
    vals = [ctypes.c_uint64() for _ in range(4)]
    check_call(LIB.MXStorageStats(*[ctypes.byref(v) for v in vals]))
    keys = ("bytes_in_use", "bytes_pooled", "pool_hits", "pool_misses")
    return dict(zip(keys, (v.value for v in vals)))


def storage_release_all() -> None:
    if LIB is not None:
        check_call(LIB.MXStorageReleaseAll())


class NativeEngine:
    """Asynchronous host-work engine with read/write var dependencies.

    Mirrors ``Engine::PushAsync`` semantics (include/mxnet/engine.h):
    callables pushed with var lists execute on worker threads once all
    dependencies clear; writers are exclusive, readers concurrent.
    """

    def __init__(self, num_workers: int = 0, naive: bool = False) -> None:
        if LIB is None:
            raise MXNetError("native library unavailable")
        self.handle = ctypes.c_void_p()
        check_call(LIB.MXEngineCreate(num_workers, int(naive),
                                      ctypes.byref(self.handle)))
        self._lock = threading.Lock()
        self._inflight: Dict[int, Callable[[], None]] = {}
        self._token = 0
        # static trampolines: the engine invokes _fn then _done exactly
        # once per op, so the closure registry cannot leak
        self._fn_cb = _ENGINE_FN(self._fn)
        self._done_cb = _ON_COMPLETE(self._done)
        self._closed = False

    def _fn(self, ctx) -> None:
        with self._lock:
            fn = self._inflight.get(int(ctx or 0))
        if fn is not None:
            try:
                fn()
            except Exception:   # noqa: BLE001 — worker threads must survive
                import traceback
                traceback.print_exc()

    def _done(self, ctx, _cancelled) -> None:
        with self._lock:
            self._inflight.pop(int(ctx or 0), None)

    def new_var(self) -> int:
        out = ctypes.c_void_p()
        check_call(LIB.MXEngineNewVar(self.handle, ctypes.byref(out)))
        return out.value

    def free_var(self, var: int) -> None:
        check_call(LIB.MXEngineFreeVar(self.handle,
                                       ctypes.c_void_p(var)))

    def push(self, fn: Callable[[], None],
             read_vars: Sequence[int] = (),
             write_vars: Sequence[int] = (),
             priority: int = 0, name: str = "") -> None:
        with self._lock:
            self._token += 1
            token = self._token
            self._inflight[token] = fn
        n_r, n_w = len(read_vars), len(write_vars)
        r_arr = (ctypes.c_void_p * max(n_r, 1))(*read_vars)
        w_arr = (ctypes.c_void_p * max(n_w, 1))(*write_vars)
        try:
            check_call(LIB.MXEnginePushAsync(
                self.handle, self._fn_cb, ctypes.c_void_p(token),
                self._done_cb, r_arr, n_r, w_arr, n_w, priority,
                name.encode() if name else None))
        except Exception:
            with self._lock:
                self._inflight.pop(token, None)
            raise

    def wait_for_var(self, var: int) -> None:
        check_call(LIB.MXEngineWaitForVar(self.handle,
                                          ctypes.c_void_p(var)))

    def wait_all(self) -> None:
        check_call(LIB.MXEngineWaitAll(self.handle))

    def set_profiling(self, enabled: bool) -> None:
        check_call(LIB.MXEngineSetProfiling(self.handle, int(enabled)))

    def dump_profile(self) -> str:
        out = ctypes.c_char_p()
        check_call(LIB.MXEngineDumpProfile(self.handle,
                                           ctypes.byref(out)))
        try:
            return (out.value or b"[]").decode()
        finally:
            LIB.MXFreeString(out)

    def close(self) -> None:
        if not self._closed and self.handle:
            self._closed = True
            check_call(LIB.MXEngineFree(self.handle))
            self.handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass


_GLOBAL_ENGINE: Optional[NativeEngine] = None
_GLOBAL_ENGINE_LOCK = threading.Lock()


def global_engine() -> Optional[NativeEngine]:
    """Lazily-created shared engine (CreateEngine in engine/engine.cc);
    honors MXNET_ENGINE_TYPE=NaiveEngine."""
    global _GLOBAL_ENGINE
    if LIB is None:
        return None
    with _GLOBAL_ENGINE_LOCK:
        if _GLOBAL_ENGINE is None:
            naive = os.environ.get("MXNET_ENGINE_TYPE") == "NaiveEngine"
            nthreads = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "0"))
            _GLOBAL_ENGINE = NativeEngine(nthreads, naive)
        return _GLOBAL_ENGINE


@atexit.register
def _shutdown() -> None:
    global _GLOBAL_ENGINE
    with _GLOBAL_ENGINE_LOCK:
        if _GLOBAL_ENGINE is not None:
            try:
                _GLOBAL_ENGINE.wait_all()
                _GLOBAL_ENGINE.close()
            except Exception:   # noqa: BLE001
                pass
            _GLOBAL_ENGINE = None


class NativeRecordWriter:
    def __init__(self, path: str) -> None:
        if LIB is None:
            raise MXNetError("native library unavailable")
        self.handle = ctypes.c_void_p()
        check_call(LIB.MXRecordIOWriterCreate(path.encode(),
                                              ctypes.byref(self.handle)))

    def write(self, buf: bytes) -> int:
        pos = ctypes.c_uint64()
        check_call(LIB.MXRecordIOWriterWrite(
            self.handle, buf, ctypes.c_uint64(len(buf)),
            ctypes.byref(pos)))
        return pos.value

    def tell(self) -> int:
        pos = ctypes.c_uint64()
        check_call(LIB.MXRecordIOWriterTell(self.handle,
                                            ctypes.byref(pos)))
        return pos.value

    def close(self) -> None:
        # LIB may already be torn down at interpreter shutdown
        if self.handle and LIB is not None:
            check_call(LIB.MXRecordIOWriterFree(self.handle))
            self.handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001
            pass


class NativeRecordReader:
    def __init__(self, path: str) -> None:
        if LIB is None:
            raise MXNetError("native library unavailable")
        self.handle = ctypes.c_void_p()
        check_call(LIB.MXRecordIOReaderCreate(path.encode(),
                                              ctypes.byref(self.handle)))

    def read(self) -> Optional[bytes]:
        data = ctypes.c_void_p()
        size = ctypes.c_uint64()
        check_call(LIB.MXRecordIOReaderNext(
            self.handle, ctypes.byref(data), ctypes.byref(size)))
        if data.value is None:
            return None
        return ctypes.string_at(data.value, size.value)

    def seek(self, pos: int) -> None:
        check_call(LIB.MXRecordIOReaderSeek(self.handle,
                                            ctypes.c_uint64(pos)))

    def tell(self) -> int:
        pos = ctypes.c_uint64()
        check_call(LIB.MXRecordIOReaderTell(self.handle,
                                            ctypes.byref(pos)))
        return pos.value

    def scan_index(self) -> List[int]:
        buf = ctypes.POINTER(ctypes.c_uint64)()
        count = ctypes.c_uint64()
        check_call(LIB.MXRecordIOReaderScanIndex(
            self.handle, ctypes.byref(buf), ctypes.byref(count)))
        try:
            return [buf[i] for i in range(count.value)]
        finally:
            LIB.MXFreeBuffer(buf)

    def close(self) -> None:
        # LIB may already be torn down at interpreter shutdown
        if self.handle and LIB is not None:
            check_call(LIB.MXRecordIOReaderFree(self.handle))
            self.handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001
            pass


class NativePrefetcher:
    """Background-thread record batches (src/io/iter_prefetcher.h)."""

    def __init__(self, path: str, batch_size: int, capacity: int = 4,
                 index: Optional[Sequence[int]] = None) -> None:
        if LIB is None:
            raise MXNetError("native library unavailable")
        self.batch_size = batch_size
        self.handle = ctypes.c_void_p()
        n = len(index) if index else 0
        idx_arr = (ctypes.c_uint64 * max(n, 1))(*(index or ()))
        check_call(LIB.MXPrefetcherCreate(
            path.encode(), batch_size, capacity,
            idx_arr if n else None, ctypes.c_uint64(n),
            ctypes.byref(self.handle)))
        # c_void_p (not c_char_p): records are binary; c_char_p getitem
        # would truncate at the first NUL byte
        self._data = (ctypes.c_void_p * batch_size)()
        self._sizes = (ctypes.c_uint64 * batch_size)()

    def next_batch(self) -> List[bytes]:
        """Returns the next list of records; [] at epoch end."""
        n = ctypes.c_int()
        check_call(LIB.MXPrefetcherNext(self.handle, self._data,
                                        self._sizes, ctypes.byref(n)))
        return [ctypes.string_at(self._data[i], self._sizes[i])
                for i in range(n.value)]

    def reset(self) -> None:
        check_call(LIB.MXPrefetcherReset(self.handle))

    def close(self) -> None:
        if self.handle:
            check_call(LIB.MXPrefetcherFree(self.handle))
            self.handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:   # noqa: BLE001
            pass
