"""``mx.mod`` — the legacy Module training API.

Reference parity: ``python/mxnet/module/`` (BaseModule.fit epoch loop,
Module bind/init/forward/backward/update, BucketingModule).
"""
from .module import BaseModule, Module, BucketingModule

__all__ = ["BaseModule", "Module", "BucketingModule"]
