"""Module — the legacy symbolic-style trainer.

Reference parity (leezu/mxnet): ``python/mxnet/module/base_module.py``
(``BaseModule.fit`` epoch loop), ``module.py`` (bind / init_params /
init_optimizer / forward / backward / update / predict / score /
save_checkpoint), ``bucketing_module.py`` (per-bucket executors sharing
weights — the era's variable-length answer).

Design (tpu-first): the reference's Symbol is replaced by a gluon
(Hybrid)Block plus a loss — under XLA the "symbolic executor" and the
hybridized block are the same compiled-program machinery, so Module is a
thin training harness over Block + Trainer. BucketingModule exploits the
jit cache directly: one shared block, per-shape executables appear
automatically per bucket key (the reference needed explicit per-length
executor groups, ``DataParallelExecutorGroup``).
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from .. import autograd
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..io.io import DataBatch, DataDesc
from ..metric import EvalMetric, create as metric_create
from ..model import BatchEndParam, load_checkpoint, save_checkpoint
from ..ndarray.ndarray import NDArray

__all__ = ["BaseModule", "Module", "BucketingModule"]


def _as_list(x: Any) -> list:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class BaseModule:
    """Shared fit/score/predict loops (reference ``BaseModule``)."""

    def __init__(self, logger: Any = logging) -> None:
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # subclass interface ----------------------------------------------------
    def forward(self, data_batch: DataBatch, is_train: Optional[bool] = None
                ) -> None:
        raise NotImplementedError

    def backward(self) -> None:
        raise NotImplementedError

    def update(self) -> None:
        raise NotImplementedError

    def get_outputs(self) -> List[NDArray]:
        raise NotImplementedError

    def update_metric(self, eval_metric: EvalMetric,
                      labels: Sequence[NDArray]) -> None:
        raise NotImplementedError

    # shared loops ----------------------------------------------------------
    def forward_backward(self, data_batch: DataBatch) -> None:
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data: Any, eval_metric: Union[str, EvalMetric],
              num_batch: Optional[int] = None, reset: bool = True,
              epoch: int = 0, batch_end_callback: Any = None) -> list:
        if not isinstance(eval_metric, EvalMetric):
            eval_metric = metric_create(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            for cb in _as_list(batch_end_callback):
                cb(BatchEndParam(epoch, nbatch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data: Any, num_batch: Optional[int] = None,
                reset: bool = True) -> Union[NDArray, List[NDArray]]:
        if reset:
            eval_data.reset()
        outputs: List[List[NDArray]] = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            pad = batch.pad or 0
            if pad:
                outs = [o[:o.shape[0] - pad] for o in outs]
            outputs.append(outs)
        if not outputs:
            return []
        n_out = len(outputs[0])
        from ..ndarray.ops import concatenate
        cat = [concatenate([row[i] for row in outputs], axis=0)
               for i in range(n_out)]
        return cat[0] if n_out == 1 else cat

    def fit(self, train_data: Any, eval_data: Any = None,
            eval_metric: Union[str, EvalMetric] = "acc",
            epoch_end_callback: Any = None, batch_end_callback: Any = None,
            kvstore: str = "local", optimizer: str = "sgd",
            optimizer_params: Optional[dict] = None,
            eval_end_callback: Any = None,
            eval_batch_end_callback: Any = None,
            initializer: Any = None, arg_params: Optional[dict] = None,
            aux_params: Optional[dict] = None,
            allow_missing: bool = False, force_init: bool = False,
            begin_epoch: int = 0, num_epoch: Optional[int] = None,
            validation_metric: Any = None, monitor: Any = None) -> None:
        """The classic epoch loop (reference ``BaseModule.fit``)."""
        if num_epoch is None:
            raise MXNetError("fit: num_epoch must be given")
        if not self.binded:
            self.bind(data_shapes=train_data.provide_data,
                      label_shapes=train_data.provide_label, for_training=True)
        if not self.params_initialized or force_init:
            self.init_params(initializer=initializer, arg_params=arg_params,
                             aux_params=aux_params,
                             allow_missing=allow_missing,
                             force_init=force_init)
        if not self.optimizer_initialized:
            self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params)
        if not isinstance(eval_metric, EvalMetric):
            eval_metric = metric_create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch, nbatch, eval_metric))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            arg, aux = self.get_params()
            for cb in _as_list(epoch_end_callback):
                cb(epoch, self.symbol, arg, aux)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 epoch=epoch + 1,
                                 batch_end_callback=eval_batch_end_callback)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)


class Module(BaseModule):
    """Train a block through the classic Module workflow.

    ``symbol`` is a gluon (Hybrid)Block producing network outputs;
    ``loss`` maps (output, label) -> per-sample loss (defaults to softmax
    cross-entropy, the reference's ``SoftmaxOutput`` head).
    """

    def __init__(self, symbol: Any, data_names: Sequence[str] = ("data",),
                 label_names: Sequence[str] = ("softmax_label",),
                 logger: Any = logging,
                 context: Optional[Union[Context, Sequence[Context]]] = None,
                 loss: Any = None) -> None:
        super().__init__(logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._sym_mode = False
        self._head_op = None
        self._root: Optional[NDArray] = None
        if hasattr(symbol, "_heads"):      # mx.sym.Symbol: wrap in a block
            from ..gluon.block import SymbolBlock
            from ..symbol import Variable
            self._sym_mode = True
            # any head may be the loss head (Group([features, loss]) is a
            # standard reference pattern)
            self._head_op = None
            self._loss_head_idx = None
            for i, (node, _) in enumerate(symbol._heads):
                if node.op in self._LOSS_HEADS:
                    self._head_op = node.op
                    self._loss_head_idx = i
                    self._head_normalization = node.attrs.get(
                        "normalization", "null")
                    break
            sym_args = set(symbol.list_arguments())
            # only wire label inputs the graph actually consumes
            self._used_labels = [n for n in self._label_names
                                 if n in sym_args]
            in_syms = [Variable(n) for n in self._data_names
                       + self._used_labels]
            self._block = SymbolBlock(symbol, in_syms)
        else:
            self._block = symbol
        ctxs = context if context is not None else [current_context()]
        self._contexts = list(ctxs) if isinstance(ctxs, (list, tuple)) \
            else [ctxs]
        if loss is None:
            from ..gluon.loss import SoftmaxCrossEntropyLoss
            loss = SoftmaxCrossEntropyLoss()
        self._loss_fn = loss
        self._trainer = None
        self._outputs: List[NDArray] = []
        self._loss_val: Optional[NDArray] = None
        self._cur_batch_size = 0

    # -- binding / params ---------------------------------------------------
    @property
    def symbol(self) -> Any:
        return self._block

    def bind(self, data_shapes: Any, label_shapes: Any = None,
             for_training: bool = True, inputs_need_grad: bool = False,
             force_rebind: bool = False, **kwargs: Any) -> None:
        if self.binded and not force_rebind:
            return
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self.binded = True

    def init_params(self, initializer: Any = None, arg_params: Any = None,
                    aux_params: Any = None, allow_missing: bool = False,
                    force_init: bool = False, allow_extra: bool = False
                    ) -> None:
        if not self.binded:
            raise MXNetError("call bind before init_params")
        self._block.initialize(init=initializer, ctx=self._contexts[0],
                               force_reinit=force_init)
        # materialize deferred shapes with one dummy forward — on the
        # MODULE's context (the accelerator default ctx would mix
        # devices with cpu-bound parameters)
        def _desc_to_dummy(desc):
            shape = tuple(desc.shape) if hasattr(desc, "shape") else \
                tuple(desc[1])
            dtype = getattr(desc, "dtype", _np.float32)
            return NDArray(_np.zeros(shape, dtype=dtype),
                           ctx=self._contexts[0])

        dummies = [_desc_to_dummy(d) for d in self._data_shapes]
        if self._sym_mode and self._used_labels:
            # pick label descs by name (DataDesc.name) where available so
            # only the consumed labels are fed, in graph-input order
            by_name = {}
            for j, d in enumerate(self._label_shapes or []):
                nm = getattr(d, "name", None) or \
                    (d[0] if isinstance(d, (tuple, list)) else None)
                by_name[nm] = d
            batch = dummies[0].shape[0] if dummies else 1
            for n in self._used_labels:
                desc = by_name.get(n)
                if desc is not None:
                    dummies.append(_desc_to_dummy(desc))
                else:
                    dummies.append(NDArray(_np.zeros((batch,),
                                                     dtype=_np.float32),
                                           ctx=self._contexts[0]))
        self._block(*dummies)
        if arg_params or aux_params:
            merged = dict(arg_params or {})
            merged.update(aux_params or {})
            params = self._block.collect_params()
            for k, v in merged.items():
                if k in params:
                    params[k].set_data(v)
                elif not allow_extra:
                    raise MXNetError(f"init_params: unknown param {k!r}")
        self.params_initialized = True

    def get_params(self) -> Tuple[Dict[str, NDArray], Dict[str, NDArray]]:
        arg: Dict[str, NDArray] = {}
        aux: Dict[str, NDArray] = {}
        for name, p in self._block.collect_params().items():
            if not p.is_initialized:
                continue
            (aux if p.grad_req == "null" else arg)[name] = p.data()
        return arg, aux

    def set_params(self, arg_params: dict, aux_params: dict,
                   allow_missing: bool = False, force_init: bool = True,
                   allow_extra: bool = False) -> None:
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=False,
                         allow_extra=allow_extra)

    def init_optimizer(self, kvstore: str = "local", optimizer: str = "sgd",
                       optimizer_params: Optional[dict] = None,
                       force_init: bool = False) -> None:
        if not self.params_initialized:
            raise MXNetError("call init_params before init_optimizer")
        from ..gluon.trainer import Trainer
        self._trainer = Trainer(self._block.collect_params(), optimizer,
                                optimizer_params or {"learning_rate": 0.01},
                                kvstore=kvstore)
        self.optimizer_initialized = True

    # -- execution ----------------------------------------------------------
    def forward(self, data_batch: DataBatch,
                is_train: Optional[bool] = None) -> None:
        # batches land on the MODULE's context — under the accelerator
        # default-ctx, iterator-produced arrays would otherwise mix
        # devices with a cpu-bound module's parameters
        ctx = self._contexts[0]
        data = [(d if isinstance(d, NDArray) else NDArray(d, ctx=ctx))
                .as_in_context(ctx) for d in _as_list(data_batch.data)]
        labels = [(l if isinstance(l, NDArray) else NDArray(l, ctx=ctx))
                  .as_in_context(ctx)
                  for l in _as_list(data_batch.label)]
        is_train = self.binded if is_train is None else is_train
        self._cur_batch_size = data[0].shape[0] if data else 0
        if self._sym_mode:
            self._forward_symbol(data, labels, is_train)
            return
        if is_train:
            with autograd.record():
                out = self._block(*data)
                outs = _as_list(out)
                if labels:
                    loss = self._loss_fn(outs[0], *labels)
                    self._loss_val = loss.mean() if loss.ndim > 0 else loss
                else:
                    self._loss_val = None
            self._outputs = outs
        else:
            out = self._block(*data)
            self._outputs = _as_list(out)
            self._loss_val = None

    # ops whose backward injects the loss gradient directly (reference:
    # SoftmaxOutput & the regression output heads)
    _LOSS_HEADS = frozenset([
        "softmax_output", "linear_regression_output",
        "logistic_regression_output", "mae_regression_output", "make_loss"])

    def _forward_symbol(self, data: List[NDArray], labels: List[NDArray],
                        is_train: bool) -> None:
        """Forward for a wrapped mx.sym.Symbol: loss-head graphs carry
        their own gradient, so the root of backward is the head output."""
        feeds = list(data)
        if self._used_labels:
            if labels:
                # labels arrive ordered by label_names; select by name so
                # a non-prefix consumed subset still lines up
                for n in self._used_labels:
                    pos = self._label_names.index(n)
                    if pos >= len(labels):
                        raise MXNetError(
                            f"label {n!r} (position {pos} of "
                            f"{self._label_names}) not provided: batch "
                            f"has only {len(labels)} label array(s)")
                    feeds.append(labels[pos])
            else:   # inference without labels: heads ignore label values
                feeds += [NDArray(_np.zeros((self._cur_batch_size,),
                                            dtype=_np.float32),
                                  ctx=self._contexts[0])
                          for _ in self._used_labels]
        if is_train and self._head_op is not None:
            with autograd.record():
                out = self._block(*feeds)
            self._outputs = _as_list(out)
            self._root = self._outputs[self._loss_head_idx]
            self._loss_val = None
            if self._head_op == "softmax_output" and labels:
                from ..ops.nn import pick
                p = pick(self._root.detach(), labels[0])
                self._loss_val = -(p + 1e-12).log().mean()
        elif is_train:
            with autograd.record():
                out = self._block(*feeds)
                outs = _as_list(out)
                if labels:
                    loss = self._loss_fn(outs[0], *labels)
                    self._loss_val = loss.mean() if loss.ndim > 0 else loss
                else:
                    self._loss_val = None
                self._root = self._loss_val
            self._outputs = outs
        else:
            out = self._block(*feeds)
            self._outputs = _as_list(out)
            self._root = None
            self._loss_val = None

    def backward(self) -> None:
        if self._sym_mode and self._root is not None and \
                self._head_op is not None:
            self._root.backward()
            return
        if self._loss_val is None:
            raise MXNetError("backward: no training forward recorded "
                             "(labels missing or is_train=False)")
        self._loss_val.backward()

    def update(self) -> None:
        if self._trainer is None:
            raise MXNetError("call init_optimizer before update")
        if self._sym_mode and self._head_op is not None:
            # With normalization='null' the loss-head grads are per-sample
            # sums; the reference's Module sets rescale_grad=1/batch — do
            # that here. Heads that normalize themselves need no rescale.
            scale = 1 if self._head_normalization in ("batch", "valid") \
                else max(1, self._cur_batch_size)
            self._trainer.step(scale, ignore_stale_grad=True)
            return
        # loss was averaged over the batch already
        self._trainer.step(1, ignore_stale_grad=True)

    def get_outputs(self, merge_multi_context: bool = True) -> List[NDArray]:
        return self._outputs

    def update_metric(self, eval_metric: EvalMetric,
                      labels: Sequence[NDArray]) -> None:
        outputs = self._outputs
        if self._sym_mode and self._head_op is not None and \
                len(outputs) > 1:
            # metrics score the loss head's prediction, not extra outputs
            outputs = [outputs[self._loss_head_idx]]
        eval_metric.update(_as_list(labels), outputs)

    # -- checkpointing ------------------------------------------------------
    def export(self, prefix: str, epoch: int = 0,
               dynamic_batch: bool = False) -> Tuple[str, str]:
        """Write the serving/deploy artifact for this module's network
        (``prefix-symbol.json`` + ``prefix-NNNN.params``) — the
        inference-bind half of the classic workflow, aimed at
        ``mxnet_tpu.serving.load_served`` / ``tools/serve.py``.  The
        input signature comes from the bound data shapes;
        ``dynamic_batch=True`` makes the artifact batch-polymorphic so
        the serving batch buckets all run one program."""
        if not self.params_initialized:
            raise MXNetError("bind + init_params before export")
        from ..gluon.block import HybridBlock
        if not isinstance(self._block, HybridBlock):
            raise MXNetError(
                f"export needs a HybridBlock network; this module wraps "
                f"a {type(self._block).__name__}")
        sig = []
        for d in self._data_shapes:
            shape = tuple(d.shape) if hasattr(d, "shape") else tuple(d[1])
            sig.append((shape, getattr(d, "dtype", _np.float32)))
        return self._block.export(prefix, epoch, input_signature=sig,
                                  dynamic_batch=dynamic_batch)

    def save_checkpoint(self, prefix: str, epoch: int,
                        save_optimizer_states: bool = False) -> None:
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._block, arg, aux)
        if save_optimizer_states and self._trainer is not None:
            self._trainer.save_states(f"{prefix}-{epoch:04d}.states")

    @staticmethod
    def load(prefix: str, epoch: int, load_optimizer_states: bool = False,
             symbol: Any = None, **kwargs: Any) -> "Module":
        """Rebuild a Module from a checkpoint; ``symbol`` (the block) must
        be supplied since python code is not serialized (the reference
        reconstructed the graph from symbol.json)."""
        if symbol is None:
            raise MXNetError(
                "Module.load: pass symbol=<block instance> (architecture "
                "is python code in this build)")
        _, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._pending_params = (arg, aux)
        mod._load_prefix_epoch = (prefix, epoch, load_optimizer_states)
        return mod

    def _apply_pending(self) -> None:
        pending = getattr(self, "_pending_params", None)
        if pending is not None:
            arg, aux = pending
            self.init_params(arg_params=arg, aux_params=aux,
                             allow_extra=True)
            self._pending_params = None


class BucketingModule(BaseModule):
    """Variable-length training over bucketed batches.

    ``sym_gen(bucket_key) -> (block, data_names, label_names)`` as in the
    reference; parameters are shared by returning the same underlying
    block (weights live on the block, executables are cached per input
    shape by hybridize/jit — no explicit executor sharing needed).
    """

    def __init__(self, sym_gen: Callable,
                 default_bucket_key: Any = None, logger: Any = logging,
                 context: Any = None, loss: Any = None) -> None:
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_key = default_bucket_key
        self._context = context
        self._loss = loss
        self._modules: Dict[Any, Module] = {}
        self._curr_key = default_bucket_key

    def _get_module(self, key: Any) -> Module:
        if key not in self._modules:
            block, data_names, label_names = self._sym_gen(key)
            mod = Module(block, data_names, label_names, self.logger,
                         self._context, loss=self._loss)
            self._modules[key] = mod
        return self._modules[key]

    @property
    def symbol(self) -> Any:
        return self._get_module(self._curr_key).symbol

    def bind(self, data_shapes: Any, label_shapes: Any = None,
             for_training: bool = True, **kwargs: Any) -> None:
        mod = self._get_module(self._default_key)
        mod.bind(data_shapes, label_shapes, for_training, **kwargs)
        self.binded = True

    def init_params(self, **kwargs: Any) -> None:
        self._get_module(self._default_key).init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs: Any) -> None:
        self._get_module(self._default_key).init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def switch_bucket(self, bucket_key: Any, data_shapes: Any = None,
                      label_shapes: Any = None) -> None:
        mod = self._get_module(bucket_key)
        if not mod.binded and data_shapes is not None:
            mod.bind(data_shapes, label_shapes)
        # share trainer/optimizer state with the default module
        default = self._modules[self._default_key]
        mod._trainer = default._trainer
        mod.params_initialized = True
        mod.optimizer_initialized = default.optimizer_initialized
        self._curr_key = bucket_key

    def forward(self, data_batch: DataBatch,
                is_train: Optional[bool] = None) -> None:
        key = getattr(data_batch, "bucket_key", self._default_key)
        self.switch_bucket(key, getattr(data_batch, "provide_data", None),
                           getattr(data_batch, "provide_label", None))
        self._modules[key].forward(data_batch, is_train)

    def backward(self) -> None:
        self._modules[self._curr_key].backward()

    def update(self) -> None:
        self._modules[self._curr_key].update()

    def get_outputs(self) -> List[NDArray]:
        return self._modules[self._curr_key].get_outputs()

    def update_metric(self, eval_metric: EvalMetric,
                      labels: Sequence[NDArray]) -> None:
        self._modules[self._curr_key].update_metric(eval_metric, labels)

    def get_params(self):
        return self._modules[self._default_key].get_params()
