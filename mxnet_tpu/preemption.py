"""Preemption handling — SIGTERM/SIGINT as routine training events.

TPU capacity is economically preemptible (PAPERS.md: the serving
comparison's spot-capacity arithmetic); a production trainer must treat
"the scheduler wants this host back" as a normal control path, not a
crash.  :class:`PreemptionGuard` converts the first SIGTERM/SIGINT into
a cooperative flag the training loop polls between steps: the in-flight
step finishes, a final checkpoint is written, and the process exits
cleanly so the next incarnation auto-resumes (see
``SPMDTrainer.fit(checkpoint_manager=...)`` and
``Estimator.fit(checkpoint_manager=...)``).

A SECOND signal escalates: the original handler runs (normally: die) —
the operator mashing Ctrl-C twice must still win over a wedged step.

Signal handlers only install from the main thread (a Python
constraint); elsewhere the guard degrades to an inert flag so library
code can use it unconditionally.
"""
from __future__ import annotations

import signal
import threading
from typing import Any, Iterable, Optional

from . import metrics as _metrics

__all__ = ["PreemptionGuard"]

PREEMPTION_SIGNALS = _metrics.counter(
    "mxnet_preemption_signals_total",
    "SIGTERM/SIGINT deliveries converted into cooperative shutdown "
    "requests by PreemptionGuard, by signal name.", labels=("signal",))


class PreemptionGuard:
    """Context manager: convert termination signals into a poll-able
    flag for the duration of a training loop.

    ::

        with PreemptionGuard() as guard:
            for step in ...:
                trainer.step(...)
                if guard.requested:
                    manager.save(trainer, step=...)
                    break
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT)) -> None:
        self._signals = tuple(signals)
        self._previous: dict = {}
        self._installed = False
        self._event = threading.Event()
        self.signal_name: Optional[str] = None

    @property
    def requested(self) -> bool:
        """True once a termination signal arrived (sticky)."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _handler(self, signum: int, frame: Any) -> None:
        if self._event.is_set():
            # second signal: escalate to the pre-existing behavior —
            # a wedged loop must still be killable
            prev = self._previous.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            if signum == signal.SIGINT:
                raise KeyboardInterrupt
            raise SystemExit(128 + signum)
        try:
            self.signal_name = signal.Signals(signum).name
        except ValueError:
            self.signal_name = str(signum)
        PREEMPTION_SIGNALS.labels(signal=self.signal_name).inc()
        self._event.set()

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handler)
            self._installed = True
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._installed:
            for s, prev in self._previous.items():
                try:
                    signal.signal(s, prev)
                except (ValueError, TypeError):
                    pass
            self._previous.clear()
            self._installed = False
