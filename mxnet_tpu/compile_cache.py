"""Crash-safe persistent compile cache — compiled XLA executables as
durable, verified artifacts that survive restarts.

PRs 7-8 made process death routine (replica supervisors, elastic PS,
rank restarts), but every restarted worker or serving replica still
re-traced and re-compiled every executable from scratch: recovery was
survivable but slow, and a restart storm multiplies warmup cost across
the fleet.  Following the Julia->TPU full-compilation direction
(PAPERS.md) — a training step / serving bucket is ONE ahead-of-time
compiled program — this module makes those programs durable the same
way PR-3 made checkpoints durable:

* entries are serialized AOT executables
  (``jax.jit(...).lower(...).compile()`` ->
  ``jax.experimental.serialize_executable``), written with the shared
  :mod:`mxnet_tpu._durable` recipe (same-directory staging + fsync +
  atomic rename + SHA-256 manifest + orphan-staging sweep);
* the key covers the **program signature** (SHA-256 of the lowered
  StableHLO module) and the **whole toolchain fingerprint**
  (jax/jaxlib/XLA platform version, backend platform + device kind +
  topology, library version) — a restart on a different toolchain or
  mesh is a clean miss, never a wrong executable;
* corrupted, truncated, or version-mismatched entries are
  **quarantined** (renamed aside, counted in
  ``mxnet_compile_cache_corrupt_total``) and silently recompiled —
  cache failure can NEVER fail a step or a request;
* concurrent multi-process access is safe with **no locks on the read
  path**: readers see either a complete entry or a miss (atomic
  rename; the manifest written last is the commit point), and
  concurrent writers of the same key both stage privately — the last
  rename wins wholesale (single-writer dedupe);
* total size is bounded (``MXNET_COMPILE_CACHE_MAX_BYTES``) with
  oldest-first LRU eviction (mtime refreshed on every hit) that never
  evicts entries **pinned** by live servers (the serving surfaces pin
  their bucket-grid programs; pins are mirrored as on-disk marker
  files so a COOPERATING process — e.g. a trainer sharing the
  directory — honors another process's live grid too).

Compile surfaces wired through :class:`PersistentlyCached` (each falls
back to its plain ``jax.jit`` path on ANY cache trouble):

* ``bulk`` — fused eager-op segment executables (non-recorded
  segments; a recorded segment's vjp closure is not serializable);
* ``spmd.step`` / ``spmd.multi`` — the SPMDTrainer compiled train
  step and the K-step fused program;
* ``serving.export`` / ``serving.decode`` / ``serving.kv`` — the
  one-shot bucket grid, the continuous-batching prefill/decode
  programs, and the KV-cache row-write/grow helpers.

Chaos: ``compile_cache.read`` / ``compile_cache.write`` fault sites
(docs/fault_tolerance.md) prove the degrade-to-recompile path under
``tools/cache_smoke.py``.

Enable by setting ``MXNET_COMPILE_CACHE_DIR`` (every cooperating
process — workers, serving replicas, their supervised restarts — points
at the same directory); ``MXNET_COMPILE_CACHE_DISABLE=1`` is the
kill-switch that wins over a set directory.
"""
from __future__ import annotations

import json
import os
import pickle
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .base import getenv, register_env
from . import metrics as _metrics
from . import faults as _faults
from ._durable import (ORPHAN_MIN_AGE_S, sha256_bytes, sweep_orphans,
                       write_bytes_durable)

__all__ = ["CompileCache", "PersistentlyCached", "default_cache",
           "persistently_cached", "cache_stats", "reset_default_cache"]

register_env(
    "MXNET_COMPILE_CACHE_DIR", "",
    "Directory of the crash-safe persistent compile cache: compiled "
    "XLA executables (train steps, serving bucket grids, fused eager "
    "segments) are serialized here with checkpoint-grade durability "
    "and reloaded by restarted processes, so a supervisor- or "
    "launch-restarted worker/replica rejoins with zero steady-state "
    "recompiles. Empty (default) disables persistence. Point every "
    "cooperating process at the same directory.")
register_env(
    "MXNET_COMPILE_CACHE_MAX_BYTES", 2 << 30,
    "Size bound of the persistent compile cache directory; exceeding "
    "it evicts the least-recently-used entries (mtime refreshed on "
    "every hit) that no live server has pinned. 0 disables eviction.")
register_env(
    "MXNET_COMPILE_CACHE_DISABLE", 0,
    "Kill-switch for the persistent compile cache: 1 disables reads "
    "AND writes even when MXNET_COMPILE_CACHE_DIR is set (every "
    "surface falls back to its in-memory jax.jit path).")

CACHE_HITS = _metrics.counter(
    "mxnet_compile_cache_hits_total",
    "Persistent compile-cache lookups that loaded a verified "
    "serialized executable instead of compiling, by surface.",
    labels=("surface",))
CACHE_MISSES = _metrics.counter(
    "mxnet_compile_cache_misses_total",
    "Persistent compile-cache lookups that found no usable entry and "
    "compiled (then wrote back), by surface. A restarted process in "
    "steady state should report 0.", labels=("surface",))
CACHE_WRITES = _metrics.counter(
    "mxnet_compile_cache_writes_total",
    "Entries durably written to the persistent compile cache (staged "
    "+ fsynced + renamed + manifest), by surface.", labels=("surface",))
CACHE_CORRUPT = _metrics.counter(
    "mxnet_compile_cache_corrupt_total",
    "Persistent compile-cache entries quarantined as unusable, by "
    "reason: manifest (unreadable/garbled manifest), missing (payload "
    "gone), digest (SHA-256 mismatch: truncated or bit-flipped), "
    "version (toolchain fingerprint drift under the same key), "
    "deserialize (payload unpickles/loads poisonously). Every one is "
    "silently recompiled.", labels=("reason",))
CACHE_EVICTIONS = _metrics.counter(
    "mxnet_compile_cache_evictions_total",
    "Persistent compile-cache entries removed by LRU size eviction "
    "(pinned entries are never evicted).")
CACHE_BYTES = _metrics.gauge(
    "mxnet_compile_cache_bytes",
    "Bytes held by the persistent compile cache (payloads + "
    "manifests), as of this process's last scan.")
CACHE_ENTRIES = _metrics.gauge(
    "mxnet_compile_cache_entries",
    "Complete entries in the persistent compile cache, as of this "
    "process's last scan.")

_ENTRY_PREFIX = "cc-"
_STAGING_PREFIX = "cc-staging-"
_QUARANTINE_PREFIX = "quarantine-"
_PIN_PREFIX = "ccpin-"

# A pin marker younger than this marks its entry as held by a live
# server SOMEWHERE in the fleet (pin sets are process memory; markers
# make them visible to every cooperating evictor).  Markers are
# refreshed on pin and on every load of their entry; older ones are
# presumed to belong to dead processes and are swept at init.
PIN_TTL_S = 86400.0

_FP_LOCK = threading.Lock()
_FP: Dict[str, str] = {}


def _fingerprint() -> Dict[str, str]:
    """The toolchain/topology identity baked into every key AND
    double-checked against the manifest on load (defense in depth for
    a hash collision or a hand-edited manifest)."""
    with _FP_LOCK:
        if _FP:
            return dict(_FP)
        import jax
        import jaxlib
        try:
            backend = jax.devices()[0].client
            platform = str(getattr(backend, "platform", "?"))
            platform_version = str(getattr(backend, "platform_version",
                                           "?"))
            device_kind = str(jax.devices()[0].device_kind)
        except Exception:   # noqa: BLE001 - no backend: fingerprint
            platform = platform_version = device_kind = "?"
        import mxnet_tpu
        _FP.update({
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": platform,
            "platform_version": platform_version,
            "device_kind": device_kind,
            "devices": str(jax.device_count()),
            "processes": str(jax.process_count()),
            "library": getattr(mxnet_tpu, "__version__", "?"),
        })
        return dict(_FP)


def _sig_of(args: Tuple[Any, ...]) -> Tuple[Any, Any]:
    """Hashable input-signature of a call: pytree structure + per-leaf
    (shape, dtype, weak_type, sharding).  Shardings participate because
    the same avals under a different placement lower to a different
    program."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig: List[Any] = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            aval = getattr(leaf, "aval", None)
            sig.append((tuple(leaf.shape), str(leaf.dtype),
                        bool(getattr(aval, "weak_type", False)),
                        getattr(leaf, "sharding", None)))
        else:
            # python scalars trace as weak-typed value-independent
            # avals: one memo entry covers every value
            sig.append(("py", type(leaf).__name__))
    return treedef, tuple(sig)


class CompileCache:
    """One cache directory: verified load, durable store, LRU+pin
    eviction.  All methods are safe to call from any thread and any
    number of cooperating processes."""

    def __init__(self, directory: str,
                 max_bytes: Optional[int] = None) -> None:
        self.directory = directory
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else getenv("MXNET_COMPILE_CACHE_MAX_BYTES", 2 << 30))
        os.makedirs(directory, exist_ok=True)
        # crash debris from dead writers (staged payloads) and old
        # quarantined entries; age-guarded so live writers survive
        sweep_orphans(directory, (_STAGING_PREFIX, _QUARANTINE_PREFIX))
        # pin markers from long-dead servers (a live server's markers
        # stay fresh: loads and the wrapper's periodic refresh re-touch
        # them)
        sweep_orphans(directory, (_PIN_PREFIX,), min_age_s=PIN_TTL_S)
        # payloads whose manifest never landed (crash between store()'s
        # two durable writes): invisible to readers AND to the size
        # accounting, so reclaim them here — age-guarded, a live
        # writer's rename-to-rename window is milliseconds
        self._sweep_unreferenced()
        self._pinned: set = set()
        self._lock = threading.Lock()
        self._store_broken = False
        self._update_gauges()

    # -- keys ----------------------------------------------------------
    def key_for(self, lowered: Any, extra: Sequence[Any] = ()) -> str:
        """SHA-256 over (toolchain fingerprint, lowered StableHLO
        module text, caller extras) — the full version key."""
        import hashlib
        h = hashlib.sha256()
        fp = _fingerprint()
        for k in sorted(fp):
            h.update(f"{k}={fp[k]}\n".encode())
        h.update(lowered.as_text().encode())
        for e in extra:
            h.update(repr(e).encode())
        return h.hexdigest()

    def _exe_path(self, key: str) -> str:
        return os.path.join(self.directory, f"{_ENTRY_PREFIX}{key}.exe")

    def _man_path(self, key: str) -> str:
        return os.path.join(self.directory, f"{_ENTRY_PREFIX}{key}.json")

    def _pin_path(self, key: str) -> str:
        return os.path.join(self.directory, f"{_PIN_PREFIX}{key}")

    # -- pinning -------------------------------------------------------
    def pin(self, key: str) -> None:
        """Mark ``key`` as held by a live server: eviction will never
        remove it — not this process's eviction (the in-memory set) and
        not a cooperating process's (the on-disk marker)."""
        with self._lock:
            self._pinned.add(key)
        path = self._pin_path(key)
        try:
            with open(path, "a"):
                pass
            os.utime(path, None)
        except OSError:
            pass    # marker failed: the pin stays process-local

    def pinned(self) -> set:
        with self._lock:
            return set(self._pinned)

    def _disk_pins(self) -> set:
        """Keys pinned by ANY cooperating process: fresh-mtime markers
        (a dead server's markers age out past PIN_TTL_S)."""
        out: set = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        now = time.time()
        for name in names:
            if not name.startswith(_PIN_PREFIX):
                continue
            try:
                mtime = os.path.getmtime(
                    os.path.join(self.directory, name))
            except OSError:
                continue
            if now - mtime <= PIN_TTL_S:
                out.add(name[len(_PIN_PREFIX):])
        return out

    # -- read path (lock-free) -----------------------------------------
    def load(self, key: str, surface: str = "unknown") -> Optional[Any]:
        """A loaded, callable executable for ``key``, or None (miss).
        Any unusable entry is quarantined and reported as a miss —
        this method never raises for cache reasons."""
        try:
            _faults.maybe_fault("compile_cache.read", key=key[:12],
                                surface=surface)
        except Exception:   # noqa: BLE001 - injected read failure:
            return None     # degrade to a miss (recompile), by design
        man, exe = self._man_path(key), self._exe_path(key)
        try:
            with open(man, "r") as f:
                meta = json.load(f)
        except FileNotFoundError:
            return None                          # clean miss
        except Exception:   # noqa: BLE001 - unreadable/garbled manifest
            self._quarantine(key, "manifest")
            return None
        if meta.get("fingerprint") != _fingerprint():
            self._quarantine(key, "version")
            return None
        try:
            with open(exe, "rb") as f:
                blob = f.read()
        except OSError:
            self._quarantine(key, "missing")
            return None
        if sha256_bytes(blob) != meta.get("sha256"):
            self._quarantine(key, "digest")
            return None
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = pickle.loads(blob)
            fn = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:   # noqa: BLE001 - verified bytes that still
            self._quarantine(key, "deserialize")  # refuse to load
            return None
        # LRU recency for the shared evictor (best effort: another
        # process may be evicting this very entry — still a valid
        # load); an existing pin marker is refreshed too, so a live
        # server's grid never ages past PIN_TTL_S while in use
        for path in (exe, man, self._pin_path(key)):
            try:
                os.utime(path, None)
            except OSError:
                pass
        return fn

    def _quarantine(self, key: str, reason: str) -> None:
        """Move a poisoned entry aside so the next lookup is a clean
        miss (recompile + overwrite) instead of re-reading poison every
        step.  Quarantined files are reclaimed by the init sweep."""
        CACHE_CORRUPT.labels(reason=reason).inc()
        stamp = f"{_QUARANTINE_PREFIX}{reason}-{_ENTRY_PREFIX}{key}"
        for src, suffix in ((self._exe_path(key), ".exe"),
                            (self._man_path(key), ".json")):
            try:
                os.replace(src, os.path.join(self.directory,
                                             stamp + suffix))
            except OSError:
                pass    # already quarantined/evicted by someone else
        self._update_gauges()

    # -- write path ----------------------------------------------------
    def store(self, key: str, compiled: Any,
              surface: str = "unknown") -> bool:
        """Durably persist ``compiled`` under ``key``; returns True on
        a completed (or already-present) entry.  Never raises for
        cache reasons."""
        if self._store_broken:
            return False
        man, exe = self._man_path(key), self._exe_path(key)
        if os.path.exists(man) and os.path.exists(exe):
            return True     # another writer won the rename: dedupe
        try:
            _faults.maybe_fault("compile_cache.write", key=key[:12],
                                surface=surface)
        except Exception:   # noqa: BLE001 - ANY injected write fault
            # (error/timeout/...) abandons THIS write only — the next
            # program still persists
            return False
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:   # noqa: BLE001 - backend cannot serialize
            # (or the out-tree holds unpicklable closures): stop paying
            # the serialization attempt per program
            self._store_broken = True
            return False
        try:
            # payload first, manifest last: the manifest is the commit
            # point a reader requires, so a crash between the two
            # renames leaves an invisible (unreferenced) payload the
            # next writer simply overwrites
            digest = write_bytes_durable(exe, blob, _STAGING_PREFIX)
            meta = {
                "key": key,
                "sha256": digest,
                "size": len(blob),
                "surface": surface,
                "fingerprint": _fingerprint(),
                "created": time.time(),
            }
            write_bytes_durable(
                man, json.dumps(meta, sort_keys=True).encode(),
                _STAGING_PREFIX)
        except Exception:   # noqa: BLE001 - disk full / perms: degrade
            return False
        CACHE_WRITES.labels(surface=surface).inc()
        # a write never evicts itself: under a budget tighter than one
        # entry the freshly persisted program must still survive long
        # enough for its own process's restart to matter
        self._evict_if_needed(keep={key})
        return True

    def _sweep_unreferenced(self) -> None:
        """Remove aged cc-*.exe payloads with no manifest — crash
        debris a reader can never see and ``_entries`` never counts."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        now = time.time()
        for name in names:
            if not (name.startswith(_ENTRY_PREFIX)
                    and name.endswith(".exe")):
                continue
            key = name[len(_ENTRY_PREFIX):-len(".exe")]
            if os.path.exists(self._man_path(key)):
                continue
            path = os.path.join(self.directory, name)
            try:
                if now - os.path.getmtime(path) < ORPHAN_MIN_AGE_S:
                    continue
                os.remove(path)
            except OSError:
                pass

    # -- size bound ----------------------------------------------------
    def _entries(self) -> List[Tuple[str, float, int]]:
        """(key, mtime, bytes) per COMPLETE entry (manifest present)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(_ENTRY_PREFIX)
                    and name.endswith(".json")):
                continue
            key = name[len(_ENTRY_PREFIX):-len(".json")]
            size = 0
            mtime = 0.0
            try:
                for path in (self._man_path(key), self._exe_path(key)):
                    st = os.stat(path)
                    size += st.st_size
                    mtime = max(mtime, st.st_mtime)
            except OSError:
                continue        # half-evicted by a peer: skip
            out.append((key, mtime, size))
        return out

    def _update_gauges(self,
                       entries: Optional[List[Tuple[str, float, int]]]
                       = None) -> None:
        if entries is None:
            entries = self._entries()
        CACHE_ENTRIES.set(len(entries))
        CACHE_BYTES.set(sum(e[2] for e in entries))

    def _evict_if_needed(self, keep: Optional[set] = None) -> int:
        """Oldest-first LRU eviction down to ``max_bytes``; pinned
        entries (and ``keep``) survive regardless.  Returns entries
        evicted."""
        if self.max_bytes <= 0:
            self._update_gauges()
            return 0
        entries = self._entries()
        total = sum(e[2] for e in entries)
        if total <= self.max_bytes:
            self._update_gauges(entries)
            return 0
        pinned = self.pinned() | self._disk_pins() | (keep or set())
        evicted = 0
        for key, _mtime, size in sorted(entries, key=lambda e: e[1]):
            if total <= self.max_bytes:
                break
            if key in pinned:
                continue
            # manifest first: readers see a clean miss, never a
            # manifest-without-payload corruption event; any stale pin
            # marker goes with the entry
            for path in (self._man_path(key), self._exe_path(key),
                         self._pin_path(key)):
                try:
                    os.remove(path)
                except OSError:
                    pass
            total -= size
            evicted += 1
            CACHE_EVICTIONS.inc()
        self._update_gauges()
        return evicted

    def stats(self) -> Dict[str, Any]:
        entries = self._entries()
        return {
            "directory": self.directory,
            "entries": len(entries),
            "bytes": sum(e[2] for e in entries),
            "max_bytes": self.max_bytes,
            "pinned": len(self.pinned() | self._disk_pins()),
        }


# ---------------------------------------------------------------------------
# The process-default cache (env-configured)
# ---------------------------------------------------------------------------

_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Dict[str, Any] = {"env": None, "cache": None, "gen": 0}


def default_cache() -> Optional[CompileCache]:
    """The env-configured cache, or None when disabled.  Re-reads the
    env tier on every call (cheap), so tests and tools can point a
    process at a directory without import-order gymnastics."""
    d = str(getenv("MXNET_COMPILE_CACHE_DIR", "") or "")
    dis = str(getenv("MXNET_COMPILE_CACHE_DISABLE", 0))
    mb = str(getenv("MXNET_COMPILE_CACHE_MAX_BYTES", 2 << 30))
    env = (d, dis, mb)
    if _DEFAULT["env"] == env:
        return _DEFAULT["cache"]
    with _DEFAULT_LOCK:
        if _DEFAULT["env"] == env:
            return _DEFAULT["cache"]
        cache = None
        if d and dis.strip().lower() not in ("1", "true", "yes"):
            try:
                cache = CompileCache(d, max_bytes=int(float(mb)))
            except Exception:   # noqa: BLE001 - unusable dir: disabled
                cache = None
        _DEFAULT["env"] = env
        _DEFAULT["cache"] = cache
        # a changed env invalidates every wrapper's latched resolution
        # too — the first default_cache() call that notices the change
        # (a new wrapper, cache_stats, /v1/model) propagates it
        _DEFAULT["gen"] += 1
    return cache


def reset_default_cache() -> None:
    """Forget the memoized default cache and invalidate every
    :class:`PersistentlyCached` wrapper's latched resolution (the
    wrappers re-read the env on their next call).  Call after changing
    the ``MXNET_COMPILE_CACHE_*`` env mid-process (tests, tools); this
    also drops the in-process pin set."""
    with _DEFAULT_LOCK:
        _DEFAULT["env"] = None
        _DEFAULT["cache"] = None
        _DEFAULT["gen"] += 1


def _family_total(family: Any) -> float:
    return sum(child.value for _vals, child in family._series())


def cache_stats() -> Dict[str, Any]:
    """Stats of the default cache ({} when disabled) — serving /v1/model
    and tools surface this.  Counter totals are THIS process's
    (directory-level entries/bytes are shared)."""
    cache = default_cache()
    if cache is None:
        return {}
    s = cache.stats()
    s.update(
        hits=_family_total(CACHE_HITS),
        misses=_family_total(CACHE_MISSES),
        writes=_family_total(CACHE_WRITES),
        corrupt=_family_total(CACHE_CORRUPT),
        evictions=CACHE_EVICTIONS.value,
    )
    return s


# ---------------------------------------------------------------------------
# PersistentlyCached — the surface wrapper
# ---------------------------------------------------------------------------

class PersistentlyCached:
    """Wrap a ``jax.jit``-wrapped callable with per-input-signature AOT
    compilation through the persistent cache.

    First call at a signature: lower (trace only), derive the version
    key, try the cache — a verified hit loads the serialized executable
    (zero XLA compile), a miss compiles AOT and durably writes back.
    Later calls dispatch the memoized executable directly.  With no
    cache configured, or on ANY cache/AOT trouble, the call degrades to
    the wrapped ``jax.jit`` path — bit-identical semantics, never a new
    failure mode.
    """

    _MEMO_CAP = 64
    # pinned wrappers re-touch their on-disk markers at this cadence
    # (steady-state traffic hits the memo, never load()/pin(), so
    # without it a busy server's markers would age past PIN_TTL_S and
    # lose eviction protection against cooperating processes)
    _PIN_REFRESH_S = PIN_TTL_S / 8.0

    __slots__ = ("_jitted", "_surface", "_extra", "_pin", "_memo",
                 "_lock", "_cache", "_cache_gen", "_pin_keys",
                 "_pin_refresh_t")

    def __init__(self, jitted: Callable, surface: str,
                 extra_key: Sequence[Any] = (),
                 pin: bool = False) -> None:
        self._jitted = jitted
        self._surface = surface
        self._extra = tuple(extra_key)
        self._pin = bool(pin)
        self._memo: "OrderedDict[Any, Callable]" = OrderedDict()
        self._lock = threading.Lock()
        self._cache: Optional[CompileCache] = None
        self._cache_gen = -1        # unresolved: first call latches
        self._pin_keys: List[str] = []
        self._pin_refresh_t = time.monotonic()

    def lower(self, *args: Any, **kwargs: Any) -> Any:
        """Delegate AOT inspection to the wrapped ``jax.jit`` (tests
        and tools lower the step to read its StableHLO)."""
        return self._jitted.lower(*args, **kwargs)

    def __call__(self, *args: Any) -> Any:
        # the env resolution is latched per wrapper (reset_default_cache
        # invalidates): the disabled case — most processes — costs one
        # int compare per call, not three env reads
        if self._cache_gen != _DEFAULT["gen"]:
            self._cache = default_cache()
            self._cache_gen = _DEFAULT["gen"]
        cache = self._cache
        if cache is None:
            return self._jitted(*args)
        try:
            sig = _sig_of(args)
        except Exception:   # noqa: BLE001 - unhashable exotic leaf
            return self._jitted(*args)
        with self._lock:
            fn = self._memo.get(sig)
            if fn is not None:
                self._memo.move_to_end(sig)
        if self._pin and self._pin_keys and \
                time.monotonic() - self._pin_refresh_t \
                > self._PIN_REFRESH_S:
            self._refresh_pins(cache)
        if fn is None:
            fn = self._acquire(cache, args)
            with self._lock:
                self._memo[sig] = fn
                if len(self._memo) > self._MEMO_CAP:
                    self._memo.popitem(last=False)
        if fn is self._jitted:
            return fn(*args)
        try:
            return fn(*args)
        except Exception:   # noqa: BLE001
            # a loaded executable rejected these args (e.g. placement
            # drift the signature missed): degrade this signature to
            # the jit path — unless the executable already consumed
            # donated inputs, where a retry would read deleted buffers
            # (then the original error IS the truthful one)
            import jax
            for leaf in jax.tree_util.tree_leaves(args):
                if getattr(leaf, "is_deleted", None) is not None \
                        and leaf.is_deleted():
                    raise
            with self._lock:
                self._memo[sig] = self._jitted
            return self._jitted(*args)

    def _refresh_pins(self, cache: CompileCache) -> None:
        """Re-touch this wrapper's pin markers so a busy server's grid
        never ages out of the fleet-wide eviction protection."""
        with self._lock:
            if time.monotonic() - self._pin_refresh_t \
                    <= self._PIN_REFRESH_S:
                return              # another thread just did it
            self._pin_refresh_t = time.monotonic()
            keys = list(self._pin_keys)
        for key in keys:
            cache.pin(key)

    def _acquire(self, cache: CompileCache,
                 args: Tuple[Any, ...]) -> Callable:
        try:
            lowered = self._jitted.lower(*args)
            key = cache.key_for(lowered, self._extra)
        except Exception:   # noqa: BLE001 - a lower failure is a real
            # trace problem: the jit path will surface it faithfully
            return self._jitted
        fn = cache.load(key, surface=self._surface)
        if fn is not None:
            CACHE_HITS.labels(surface=self._surface).inc()
            if self._pin:
                self._remember_pin(cache, key)
            return fn
        CACHE_MISSES.labels(surface=self._surface).inc()
        try:
            compiled = lowered.compile()
        except Exception:   # noqa: BLE001 - real compile error: let
            return self._jitted     # the jit path raise it
        if self._pin:
            self._remember_pin(cache, key)  # before store: its own
            #                     eviction pass must already see the pin
        cache.store(key, compiled, surface=self._surface)
        return compiled

    def _remember_pin(self, cache: CompileCache, key: str) -> None:
        cache.pin(key)
        with self._lock:
            if key not in self._pin_keys:
                self._pin_keys.append(key)


def persistently_cached(jitted: Callable, surface: str,
                        extra_key: Sequence[Any] = (),
                        pin: bool = False) -> PersistentlyCached:
    """Convenience constructor (the call sites read better)."""
    return PersistentlyCached(jitted, surface, extra_key=extra_key,
                              pin=pin)
