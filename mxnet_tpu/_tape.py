"""Autograd tape internals (shared by ndarray and autograd packages).

Reference parity (leezu/mxnet): ``src/imperative/imperative.cc``
(``Imperative::RecordOp`` / ``Imperative::Backward``) and the ``AGInfo``
node attachments. The reference records an NNVM node per imperative op and
builds a backward graph with the nnvm Gradient pass; here each recorded op
stores the ``jax.vjp`` pullback of its functional form, and ``backward``
walks the tape in reverse topological order accumulating cotangents.

This module holds only the tape data structures and thread-local mode state;
the user-facing API (``record``/``pause``/``backward``/``grad``) lives in
``mxnet_tpu/autograd``.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _onp

__all__ = [
    "TapeNode", "is_recording", "is_training", "set_recording",
    "set_training", "backward_arrays",
]


class _ModeState(threading.local):
    def __init__(self) -> None:
        self.recording = False
        self.training = False


_MODE = _ModeState()


def is_recording() -> bool:
    return _MODE.recording


def is_training() -> bool:
    return _MODE.training


def set_recording(flag: bool) -> bool:
    prev, _MODE.recording = _MODE.recording, flag
    return prev


def set_training(flag: bool) -> bool:
    prev, _MODE.training = _MODE.training, flag
    return prev


class RowSparseCot:
    """A row-sparse cotangent produced by ops with ``sparse_grad``
    (reference: Embedding's kRowSparseStorage gradient). Travels through
    the tape only as a LEAF gradient; any arithmetic with a dense
    cotangent densifies it."""

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices: Any, values: Any,
                 shape: Tuple[int, ...]) -> None:
        self.indices = indices      # (nnz,) int32 row ids (may repeat)
        self.values = values        # (nnz,) + row shape
        self.shape = tuple(shape)

    def dense(self):
        out = jnp.zeros(self.shape, dtype=self.values.dtype)
        return out.at[self.indices].add(self.values)

    def merge(self, other: "RowSparseCot") -> "RowSparseCot":
        return RowSparseCot(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]), self.shape)


def add_cotangents(a: Any, b: Any) -> Any:
    """Accumulate two cotangents; handles row-sparse values and the
    float0 zeros jax emits for integer-dtype inputs (absorbing)."""
    if isinstance(a, RowSparseCot) and isinstance(b, RowSparseCot):
        return a.merge(b)
    if isinstance(a, RowSparseCot):
        return b + a.dense()
    if isinstance(b, RowSparseCot):
        return a + b.dense()
    if getattr(a, "dtype", None) == jax.dtypes.float0:
        return a
    if getattr(b, "dtype", None) == jax.dtypes.float0:
        return b
    return a + b


class TapeNode:
    """One recorded op: inputs, output metadata, and the vjp pullback.

    ``vjp_fn`` maps output cotangents -> input cotangents (the analog of the
    reference's per-op ``FGradient`` subgraph, but computed by jax).
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "out_arrays",
                 "out_is_tuple", "consumed", "jit_pull")

    def __init__(self, name: str, vjp_fn: Callable,
                 inputs: Sequence[Any],
                 out_avals: Sequence[Tuple[Tuple[int, ...], Any]],
                 out_is_tuple: bool = False) -> None:
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)          # NDArray refs (keep alive)
        self.out_avals = list(out_avals)    # [(shape, dtype), ...]
        self.out_arrays: List[Any] = []     # weakrefs to output NDArrays
        self.out_is_tuple = out_is_tuple    # fwd returned a tuple (any arity)
        self.consumed = False
        # True when the forward ran through the per-op executable cache:
        # vjp_fn is then a jit-able tree_util.Partial with device-resident
        # residuals, and backward dispatches it as ONE compiled program
        self.jit_pull = False

    def n_out(self) -> int:
        return len(self.out_avals)


_PULL_JIT: dict = {"fn": None}


def _pullback_jit() -> Callable:
    fn = _PULL_JIT["fn"]
    if fn is None:
        fn = _PULL_JIT["fn"] = jax.jit(lambda vjp, ct: vjp(ct))
    return fn


def _toposort(heads: Sequence[Any]) -> List[TapeNode]:
    """Reverse-topological order of tape nodes reachable from ``heads``."""
    order: List[TapeNode] = []
    seen = set()

    # Iterative DFS (deep models overflow Python recursion otherwise).
    stack: List[Tuple[TapeNode, int]] = []
    for h in heads:
        node = h._ag_node
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            stack.append((node, 0))
        while stack:
            node, idx = stack.pop()
            children = [x._ag_node for x in node.inputs
                        if getattr(x, "_ag_node", None) is not None]
            if idx < len(children):
                stack.append((node, idx + 1))
                child = children[idx]
                if id(child) not in seen:
                    seen.add(id(child))
                    stack.append((child, 0))
            else:
                order.append(node)
    return order[::-1]  # heads-first


def backward_arrays(heads: Sequence[Any],
                    head_grads: Optional[Sequence[Any]] = None,
                    retain_graph: bool = False,
                    variables: Optional[Sequence[Any]] = None
                    ) -> Optional[List[Any]]:
    """Run reverse-mode accumulation from ``heads``.

    When ``variables`` is None, gradients are written into each attached
    leaf's ``.grad`` honoring ``grad_req`` ('write' overwrites, 'add'
    accumulates) — the reference's ``Imperative::Backward`` contract. When
    ``variables`` is given, returns grads w.r.t. those arrays instead
    (``autograd.grad``).
    """
    from .base import MXNetError
    from . import bulk as _bulk

    # the autograd boundary: pending bulked segments holding RECORDED
    # ops must materialize (and install their fused TapeNodes) before
    # the tape is walked.  Targeted, not flush_all: an unrecorded
    # segment on another thread (async input prefetch, serving workers)
    # has nothing on this tape and keeps building — cutting it at step
    # cadence re-serialized exactly the work it overlaps
    _bulk.flush_recorded("autograd")

    heads = list(heads)
    for h in heads:
        if h._ag_node is None:
            raise MXNetError(
                "cannot differentiate a head that was not computed while "
                "autograd was recording (did you forget autograd.record()?)")

    # Seed cotangents.
    cots: dict = {}  # id(NDArray._data-slot key) -> jax array; keyed by array wrapper id

    def _add_cot(arr: Any, value: Any) -> None:
        key = id(arr)
        if key in cots:
            cots[key] = add_cotangents(cots[key], value)
        else:
            cots[key] = value

    if head_grads is None:
        head_grads = [None] * len(heads)
    for h, hg in zip(heads, head_grads):
        if hg is None:
            seed = jnp.ones(h.shape, dtype=h.dtype)
        else:
            seed = hg._data if hasattr(hg, "_data") else jnp.asarray(hg)
        _add_cot(h, seed)

    order = _toposort(heads)

    # Incremental leaf finalization (leaf-write mode only): count the
    # remaining tape uses of every attached leaf so its gradient can be
    # written — and its grad-ready hook fired — the moment the LAST
    # node consuming it has contributed, instead of after the whole
    # walk.  With per-layer backward segmentation
    # (MXNET_BULK_BACKWARD_SEGMENTS=param) the tape is a chain of
    # per-layer fused nodes walked heads-first, so parameter gradients
    # finalize in reverse registration order WHILE later pullbacks are
    # still dispatching — the window the overlapped kvstore scheduler's
    # event-driven enqueue (Parameter._grad_ready_cb -> Round.offer)
    # streams reduction buckets into.  The written value is identical
    # to the end-of-walk write: zero remaining uses means no further
    # cotangent can accumulate.
    # Error-path caveat: a pullback raising MID-walk now leaves the
    # already-finalized leaves written (and their hooks fired), where
    # the end-of-walk write left none — the tape is equally consumed
    # either way (retry requires a fresh forward+backward), but
    # grad_req='add' users retrying after a mid-backward error should
    # zero_grad first to avoid double-accumulating the partial walk.
    leaf_uses: dict = {}
    if variables is None:
        for node in order:
            for x in node.inputs:
                if x._grad_req != "null":
                    leaf_uses[id(x)] = leaf_uses.get(id(x), 0) + 1
    written: set = set()

    def _finalize_leaf(x: Any) -> None:
        written.add(id(x))
        x._write_grad(cots.get(id(x)))
        cb = getattr(x, "_grad_ready_cb", None)
        if cb is not None:
            cb(x)

    # Map node -> the output NDArrays it produced. Outputs hold a reference
    # to their node; we need the reverse to gather cotangents, so each
    # NDArray carries (_ag_node, _ag_out_idx) and nodes carry weak output
    # list via the arrays seen at accumulation time. We reconstruct from
    # heads + node input links: every cotangent is keyed by the NDArray
    # wrapper, and nodes learn their outputs when those wrappers were
    # created (stored on the node).
    for node in order:
        if node.consumed:
            raise MXNetError(
                f"tape node {node.name} was already consumed by a previous "
                f"backward; pass retain_graph=True to backward() to allow "
                f"multiple backward passes over the same graph")
        outs = node.out_arrays
        out_cots = []
        for arr_ref, (shape, dtype) in zip(outs, node.out_avals):
            arr = arr_ref() if callable(arr_ref) else arr_ref
            c = cots.get(id(arr)) if arr is not None else None
            if isinstance(c, RowSparseCot):
                c = c.dense()   # only leaf grads stay sparse
            _is_int_out = jnp.issubdtype(_onp.dtype(dtype), jnp.integer) or \
                _onp.dtype(dtype) == jnp.bool_
            if c is None:
                # integer/bool outputs take float0 cotangents (jax.vjp
                # contract for non-differentiable dtypes)
                c = _onp.zeros(shape, jax.dtypes.float0) if _is_int_out \
                    else jnp.zeros(shape, dtype=dtype)
            elif c.dtype == jax.dtypes.float0 or _is_int_out:
                # zero-tangent for an int-valued output (e.g. argmax feeding
                # one_hot): pass through as float0, never cast
                c = _onp.zeros(shape, jax.dtypes.float0)
            elif c.dtype != dtype:
                # cotangents accumulated in a wider dtype (e.g. amp widest-
                # cast) must match the recorded output aval for jax.vjp
                try:
                    c = c.astype(dtype)
                except (TypeError, ValueError) as e:
                    raise MXNetError(
                        f"backward of op {node.name!r}: cannot cast "
                        f"cotangent dtype {c.dtype} to recorded output "
                        f"dtype {dtype!r}: {e}") from e
            out_cots.append(c)
        payload = tuple(out_cots) if node.out_is_tuple else out_cots[0]
        if node.jit_pull and not any(
                getattr(c, "dtype", None) == jax.dtypes.float0
                for c in out_cots):
            # one compiled pullback dispatch (jax.jit caches per pullback
            # structure + cotangent avals); float0 cotangents can't cross
            # a jit boundary, those nodes stay eager
            in_cots = _pullback_jit()(node.vjp_fn, payload)
        else:
            in_cots = node.vjp_fn(payload)
        if not retain_graph:
            node.vjp_fn = None
            node.consumed = True
        for x, c in zip(node.inputs, in_cots):
            if c is None:
                continue
            _add_cot(x, c)
        if variables is None:
            for x in node.inputs:
                if x._grad_req != "null":
                    n = leaf_uses[id(x)] - 1
                    leaf_uses[id(x)] = n
                    if n == 0 and id(x) not in written:
                        _finalize_leaf(x)

    if variables is not None:
        result = []
        for v in variables:
            c = cots.get(id(v))
            if isinstance(c, RowSparseCot):
                c = c.dense()
            if c is None:
                c = jnp.zeros(v.shape, dtype=v.dtype)
            result.append(c)
        return result

    # Every node-input leaf was finalized incrementally above (its use
    # count reached zero when its last consumer contributed); what
    # remains is a head that is itself an attached leaf feeding no
    # node — its gradient is just the accumulated seed.
    for h in heads:
        if h._grad_req != "null" and id(h) not in written:
            _finalize_leaf(h)
    return None
