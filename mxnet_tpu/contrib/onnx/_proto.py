"""Minimal protobuf wire-format codec for ONNX messages.

The image has no ``onnx`` package; ONNX's wire format is plain protobuf,
which is stable and simple (varint/length-delimited fields), so the
exporter/importer encode it directly.  Field numbers follow onnx.proto3
(IR version 8 era — they are frozen by protobuf compatibility rules).

Only the messages the converters need are modeled: ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto,
TypeProto(.Tensor), TensorShapeProto(.Dimension), OperatorSetIdProto.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple, Union

import numpy as onp

# -- wire primitives --------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def field_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def field_string(field: int, value: str) -> bytes:
    return field_bytes(field, value.encode("utf-8"))


def field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def field_double(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def field_packed_double(field: int, values) -> bytes:
    return field_bytes(field, b"".join(struct.pack("<d", float(v))
                                       for v in values))


def field_packed_int64(field: int, values) -> bytes:
    payload = b"".join(_varint(int(v)) for v in values)
    return field_bytes(field, payload)


def field_packed_float(field: int, values) -> bytes:
    payload = b"".join(struct.pack("<f", float(v)) for v in values)
    return field_bytes(field, payload)


# -- decoder (generic: field number -> list of raw values) ------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def decode(buf: bytes) -> Dict[int, List[Any]]:
    """Parse one protobuf message into {field_number: [values...]}.
    Length-delimited fields come back as bytes (decode nested messages by
    calling :func:`decode` again); varints as int; fixed32 as raw bytes."""
    out: Dict[int, List[Any]] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(val)
    return out


def _signed64(v: int) -> int:
    """Protobuf int64 varints are two's complement; recover the sign."""
    return v - (1 << 64) if v >= (1 << 63) else v


def decode_packed_int64(raw: bytes) -> List[int]:
    vals, pos = [], 0
    while pos < len(raw):
        v, pos = _read_varint(raw, pos)
        vals.append(_signed64(v))
    return vals


# -- ONNX dtype mapping -----------------------------------------------------

# onnx.TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64 = 1, 2, 3, 6, 7
BOOL, FLOAT16, DOUBLE, BFLOAT16 = 9, 10, 11, 16

_NP2ONNX = {
    onp.dtype("float32"): FLOAT, onp.dtype("uint8"): UINT8,
    onp.dtype("int8"): INT8, onp.dtype("int32"): INT32,
    onp.dtype("int64"): INT64, onp.dtype("bool"): BOOL,
    onp.dtype("float16"): FLOAT16, onp.dtype("float64"): DOUBLE,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def np_to_onnx_dtype(dt) -> int:
    try:
        return _NP2ONNX[onp.dtype(dt)]
    except KeyError:
        raise ValueError(f"no ONNX dtype for {dt}") from None


def onnx_to_np_dtype(code: int):
    return _ONNX2NP[code]


# -- message builders -------------------------------------------------------

def tensor(name: str, array: onp.ndarray) -> bytes:
    """TensorProto via raw_data."""
    array = onp.ascontiguousarray(array)
    msg = b""
    msg += field_packed_int64(1, array.shape) if array.ndim else b""
    msg += field_varint(2, np_to_onnx_dtype(array.dtype))
    msg += field_string(8, name)
    msg += field_bytes(9, array.tobytes())
    return msg


def parse_tensor(raw: bytes) -> Tuple[str, onp.ndarray]:
    f = decode(raw)
    dims = decode_packed_int64(f[1][0]) if 1 in f else []
    dtype = onnx_to_np_dtype(f[2][0])
    name = f[8][0].decode() if 8 in f else ""
    if 9 in f:
        arr = onp.frombuffer(f[9][0], dtype=dtype).reshape(dims)
    elif 4 in f:        # float_data (packed)
        arr = onp.array(struct.unpack(f"<{len(f[4][0]) // 4}f", f[4][0]),
                        dtype=onp.float32).reshape(dims)
    elif 7 in f:        # int64_data
        arr = onp.array(decode_packed_int64(f[7][0]),
                        dtype=onp.int64).reshape(dims)
    else:
        arr = onp.zeros(dims, dtype=dtype)
    return name, arr


# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR = 1, 2, 3, 4
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


def attribute(name: str, value: Any) -> bytes:
    msg = field_string(1, name)
    if isinstance(value, bool):
        msg += field_varint(3, int(value)) + field_varint(20, A_INT)
    elif isinstance(value, int):
        msg += field_varint(3, value) + field_varint(20, A_INT)
    elif isinstance(value, float):
        msg += field_float(2, value) + field_varint(20, A_FLOAT)
    elif isinstance(value, str):
        msg += field_bytes(4, value.encode()) + field_varint(20, A_STRING)
    elif isinstance(value, onp.ndarray):
        msg += field_bytes(5, tensor("", value)) + field_varint(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, int) for v in value):
            msg += field_packed_int64(8, value) + field_varint(20, A_INTS)
        elif all(isinstance(v, float) for v in value):
            msg += field_packed_float(7, value) + field_varint(20, A_FLOATS)
        else:
            raise ValueError(f"mixed attribute list {name}: {value}")
    else:
        raise ValueError(f"unsupported attribute {name}: {type(value)}")
    return msg


def parse_attribute(raw: bytes) -> Tuple[str, Any]:
    f = decode(raw)
    name = f[1][0].decode()
    atype = f[20][0] if 20 in f else None
    if atype == A_INT or (atype is None and 3 in f):
        return name, _signed64(f[3][0])
    if atype == A_FLOAT or (atype is None and 2 in f):
        return name, struct.unpack("<f", f[2][0])[0]
    if atype == A_STRING or (atype is None and 4 in f):
        return name, f[4][0].decode()
    if atype == A_TENSOR or (atype is None and 5 in f):
        return name, parse_tensor(f[5][0])[1]
    if atype == A_INTS or (atype is None and 8 in f):
        return name, decode_packed_int64(f[8][0])
    if atype == A_FLOATS or (atype is None and 7 in f):
        raw7 = f[7][0]
        return name, list(struct.unpack(f"<{len(raw7) // 4}f", raw7))
    return name, None


def node(op_type: str, inputs: List[str], outputs: List[str],
         name: str = "", attrs: Dict[str, Any] = None) -> bytes:
    msg = b""
    for i in inputs:
        msg += field_string(1, i)
    for o in outputs:
        msg += field_string(2, o)
    if name:
        msg += field_string(3, name)
    msg += field_string(4, op_type)
    for k, v in (attrs or {}).items():
        msg += field_bytes(5, attribute(k, v))
    return msg


def parse_node(raw: bytes) -> Dict[str, Any]:
    f = decode(raw)
    return {
        "inputs": [b.decode() for b in f.get(1, [])],
        "outputs": [b.decode() for b in f.get(2, [])],
        "name": f[3][0].decode() if 3 in f else "",
        "op_type": f[4][0].decode() if 4 in f else "",
        "attrs": dict(parse_attribute(a) for a in f.get(5, [])),
    }


def value_info(name: str, dtype, shape) -> bytes:
    dim_msgs = b""
    for d in shape:
        if isinstance(d, int):
            dim_msgs += field_bytes(1, field_varint(1, d))
        else:
            dim_msgs += field_bytes(1, field_string(2, str(d)))
    ttype = field_varint(1, np_to_onnx_dtype(dtype)) \
        + field_bytes(2, dim_msgs)
    return field_string(1, name) + field_bytes(2, field_bytes(1, ttype))


def parse_value_info(raw: bytes) -> Tuple[str, Any, List[int]]:
    f = decode(raw)
    name = f[1][0].decode()
    ttype = decode(decode(f[2][0])[1][0])
    dtype = onnx_to_np_dtype(ttype[1][0]) if 1 in ttype else None
    shape = []
    if 2 in ttype:
        for draw in decode(ttype[2][0]).get(1, []):
            df = decode(draw)
            shape.append(df[1][0] if 1 in df
                         else df[2][0].decode() if 2 in df else None)
    return name, dtype, shape


def graph(nodes: List[bytes], name: str, initializers: List[bytes],
          inputs: List[bytes], outputs: List[bytes]) -> bytes:
    msg = b""
    for n in nodes:
        msg += field_bytes(1, n)
    msg += field_string(2, name)
    for t in initializers:
        msg += field_bytes(5, t)
    for i in inputs:
        msg += field_bytes(11, i)
    for o in outputs:
        msg += field_bytes(12, o)
    return msg


def model(graph_msg: bytes, opset: int = 13,
          producer: str = "mxnet_tpu") -> bytes:
    msg = field_varint(1, 8)                     # ir_version
    msg += field_string(2, producer)
    msg += field_bytes(8, field_varint(2, opset))   # opset_import
    msg += field_bytes(7, graph_msg)
    return msg


def parse_model(raw: bytes) -> Dict[str, Any]:
    f = decode(raw)
    g = decode(f[7][0])
    opsets = []
    for o in f.get(8, []):
        of = decode(o)
        opsets.append(of.get(2, [0])[0])
    return {
        "ir_version": f.get(1, [None])[0],
        "producer": f[2][0].decode() if 2 in f else "",
        "opset": max(opsets) if opsets else 0,
        "graph": {
            "name": g[2][0].decode() if 2 in g else "",
            "nodes": [parse_node(n) for n in g.get(1, [])],
            "initializers": dict(parse_tensor(t) for t in g.get(5, [])),
            "inputs": [parse_value_info(v) for v in g.get(11, [])],
            "outputs": [parse_value_info(v) for v in g.get(12, [])],
        },
    }
