"""ONNX interop (reference: ``python/mxnet/contrib/onnx/``).

``export_model`` writes standard ONNX protobuf files;
``import_model`` loads them back into a Symbol + params.
The codec is self-contained (``_proto.py``) — no ``onnx`` dependency.
"""
from .mx2onnx import export_model
from .onnx2mx import import_model

__all__ = ["export_model", "import_model"]
