"""ONNX → Symbol-graph importer.

Reference parity (leezu/mxnet): ``python/mxnet/contrib/onnx/onnx2mx/`` —
``import_model(onnx_file) -> (sym, arg_params, aux_params)`` with a
per-op translation table (``_import_helper.py``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as onp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...symbol import symbol as S
from . import _proto as P

__all__ = ["import_model"]


def _pads(attrs, ndim):
    pads = attrs.get("pads", [0] * ndim * 2)
    begin, end = pads[:ndim], pads[ndim:]
    if list(begin) != list(end):
        raise MXNetError(f"asymmetric ONNX pads {pads} unsupported")
    return tuple(int(p) for p in begin)


class _Importer:
    def __init__(self, model: Dict[str, Any]):
        self.graph = model["graph"]
        self.inits: Dict[str, onp.ndarray] = self.graph["initializers"]
        self.syms: Dict[str, Any] = {}
        self.aux_names: set = set()

    def sym(self, name: str):
        if name not in self.syms:
            if name in self.inits:
                self.syms[name] = S.Variable(name)
            else:
                raise MXNetError(f"undefined ONNX tensor {name!r}")
        return self.syms[name]

    def const_value(self, name: str) -> onp.ndarray:
        if name not in self.inits:
            raise MXNetError(f"ONNX input {name!r} must be an initializer")
        return self.inits[name]

    def run(self):
        for name, _, _ in self.graph["inputs"]:
            if name not in self.inits:
                self.syms[name] = S.Variable(name)
        for node in self.graph["nodes"]:
            conv = _IMPORTERS.get(node["op_type"])
            if conv is None:
                raise MXNetError(
                    f"no importer for ONNX op {node['op_type']!r}")
            conv(self, node)
        heads = [self.syms[name] for name, _, _ in self.graph["outputs"]]
        out = heads[0] if len(heads) == 1 else S.Group(heads)
        arg_params, aux_params = {}, {}
        for k, v in self.inits.items():
            if k in self._used_inits:
                (aux_params if k in self.aux_names
                 else arg_params)[k] = NDArray(v)
        return out, arg_params, aux_params

    _used_inits: set

    def mark_used(self, *names):
        for n in names:
            if n in self.inits:
                self._used_inits.add(n)


def _imp_gemm(imp, n):
    a = n["attrs"]
    if a.get("transA", 0):
        raise MXNetError("Gemm transA=1 unsupported")
    x, w = n["inputs"][0], n["inputs"][1]
    bias = n["inputs"][2] if len(n["inputs"]) > 2 else None
    if not a.get("transB", 0):
        # weight is (in, out): transpose the initializer to mx layout
        imp.inits[w] = onp.ascontiguousarray(imp.const_value(w).T)
    num_hidden = imp.inits[w].shape[0] if w in imp.inits else 0
    args = [imp.sym(x), imp.sym(w)]
    kw = dict(num_hidden=int(num_hidden), flatten=False,
              name=n["name"] or None)
    if bias:
        args.append(imp.sym(bias))
    else:
        kw["no_bias"] = True
    imp.mark_used(w, bias or "")
    imp.syms[n["outputs"][0]] = S._apply_op("fully_connected", *args, **kw)


def _imp_conv(imp, n):
    a = n["attrs"]
    kernel = tuple(int(k) for k in a["kernel_shape"])
    ndim = len(kernel)
    args = [imp.sym(i) for i in n["inputs"]]
    w = imp.const_value(n["inputs"][1])
    kw = dict(kernel=kernel,
              stride=tuple(int(s) for s in a.get("strides", [1] * ndim)),
              pad=_pads(a, ndim),
              dilate=tuple(int(d) for d in a.get("dilations",
                                                 [1] * ndim)),
              num_filter=int(w.shape[0]),
              num_group=int(a.get("group", 1)),
              name=n["name"] or None)
    if len(args) < 3:
        kw["no_bias"] = True
    imp.mark_used(*n["inputs"][1:])
    imp.syms[n["outputs"][0]] = S._apply_op("convolution", *args, **kw)


def _imp_act(act):
    def conv(imp, n):
        imp.syms[n["outputs"][0]] = S._apply_op(
            "activation", imp.sym(n["inputs"][0]), act_type=act,
            name=n["name"] or None)
    return conv


def _imp_pool(ptype, global_pool=False):
    def conv(imp, n):
        a = n["attrs"]
        kw = dict(pool_type=ptype, name=n["name"] or None)
        if global_pool:
            kw["global_pool"] = True
        else:
            kernel = tuple(int(k) for k in a["kernel_shape"])
            ndim = len(kernel)
            kw.update(kernel=kernel,
                      stride=tuple(int(s) for s in
                                   a.get("strides", kernel)),
                      pad=_pads(a, ndim))
            if ptype == "avg":
                kw["count_include_pad"] = bool(
                    a.get("count_include_pad", 1))
        imp.syms[n["outputs"][0]] = S._apply_op(
            "pooling", imp.sym(n["inputs"][0]), **kw)
    return conv


def _imp_bn(imp, n):
    a = n["attrs"]
    x, gamma, beta, mean, var = n["inputs"][:5]
    imp.aux_names.update([mean, var])
    imp.mark_used(gamma, beta, mean, var)
    imp.syms[n["outputs"][0]] = S._apply_op(
        "batch_norm", imp.sym(x), imp.sym(gamma), imp.sym(beta),
        imp.sym(mean), imp.sym(var),
        eps=float(a.get("epsilon", 1e-5)),
        momentum=float(a.get("momentum", 0.9)), name=n["name"] or None)


def _imp_ln(imp, n):
    a = n["attrs"]
    ins = [imp.sym(i) for i in n["inputs"][:3]]
    imp.mark_used(*n["inputs"][1:3])
    imp.syms[n["outputs"][0]] = S._apply_op(
        "layer_norm", *ins, axis=int(a.get("axis", -1)),
        eps=float(a.get("epsilon", 1e-5)), name=n["name"] or None)


def _imp_softmax(imp, n):
    imp.syms[n["outputs"][0]] = S._apply_op(
        "softmax", imp.sym(n["inputs"][0]),
        axis=int(n["attrs"].get("axis", -1)), name=n["name"] or None)


def _imp_flatten(imp, n):
    imp.syms[n["outputs"][0]] = S._apply_op(
        "flatten", imp.sym(n["inputs"][0]), name=n["name"] or None)


def _imp_dropout(imp, n):
    # inference import: identity (reference does the same)
    for out in n["outputs"]:
        imp.syms[out] = imp.sym(n["inputs"][0])
    imp.mark_used(*n["inputs"][1:])
    for extra in n["inputs"][1:]:
        imp.inits.pop(extra, None)


def _imp_reshape(imp, n):
    shape = tuple(int(s) for s in imp.const_value(n["inputs"][1]))
    imp.inits.pop(n["inputs"][1], None)
    imp.syms[n["outputs"][0]] = S._apply_op(
        "reshape", imp.sym(n["inputs"][0]), shape, name=n["name"] or None)


def _imp_concat(imp, n):
    ins = [imp.sym(i) for i in n["inputs"]]
    imp.syms[n["outputs"][0]] = S._apply_op(
        "concat", *ins, axis=int(n["attrs"].get("axis", 1)),
        name=n["name"] or None)


def _imp_binop(op):
    def conv(imp, n):
        imp.mark_used(*n["inputs"])
        imp.syms[n["outputs"][0]] = S._apply_op(
            op, imp.sym(n["inputs"][0]), imp.sym(n["inputs"][1]),
            name=n["name"] or None)
    return conv


def _imp_gather(imp, n):
    if int(n["attrs"].get("axis", 0)) != 0:
        raise MXNetError("Gather axis != 0 unsupported")
    imp.mark_used(n["inputs"][0])
    imp.syms[n["outputs"][0]] = S._apply_op(
        "take", imp.sym(n["inputs"][0]), imp.sym(n["inputs"][1]),
        axis=0, name=n["name"] or None)


def _imp_cast(imp, n):
    dt = P.onnx_to_np_dtype(int(n["attrs"]["to"]))
    imp.syms[n["outputs"][0]] = S._apply_op(
        "cast", imp.sym(n["inputs"][0]), dtype=onp.dtype(dt).name,
        name=n["name"] or None)


def _imp_transpose(imp, n):
    perm = n["attrs"].get("perm")
    kw = {"axes": tuple(int(p) for p in perm)} if perm else {}
    imp.syms[n["outputs"][0]] = S._apply_op(
        "transpose", imp.sym(n["inputs"][0]), name=n["name"] or None,
        **kw)


def _imp_identity(imp, n):
    imp.syms[n["outputs"][0]] = imp.sym(n["inputs"][0])


_IMPORTERS = {
    "Gemm": _imp_gemm, "Conv": _imp_conv,
    "Relu": _imp_act("relu"), "Sigmoid": _imp_act("sigmoid"),
    "Tanh": _imp_act("tanh"), "Softplus": _imp_act("softrelu"),
    "Elu": _imp_act("elu"), "Selu": _imp_act("selu"),
    "Gelu": _imp_act("gelu"),
    "MaxPool": _imp_pool("max"), "AveragePool": _imp_pool("avg"),
    "GlobalMaxPool": _imp_pool("max", True),
    "GlobalAveragePool": _imp_pool("avg", True),
    "BatchNormalization": _imp_bn, "LayerNormalization": _imp_ln,
    "Softmax": _imp_softmax, "Flatten": _imp_flatten,
    "Dropout": _imp_dropout, "Reshape": _imp_reshape,
    "Concat": _imp_concat,
    "Add": _imp_binop("add"), "Sub": _imp_binop("subtract"),
    "Mul": _imp_binop("multiply"), "Div": _imp_binop("divide"),
    "Max": _imp_binop("maximum"), "Min": _imp_binop("minimum"),
    "Pow": _imp_binop("power"), "MatMul": _imp_binop("dot"),
    "Gather": _imp_gather, "Cast": _imp_cast,
    "Transpose": _imp_transpose, "Identity": _imp_identity,
}


def import_model(onnx_file_path: str):
    """Load an ONNX file -> ``(sym, arg_params, aux_params)``
    (reference ``onnx_mxnet.import_model``)."""
    with open(onnx_file_path, "rb") as f:
        model = P.parse_model(f.read())
    imp = _Importer(model)
    imp._used_inits = set()
    return imp.run()
