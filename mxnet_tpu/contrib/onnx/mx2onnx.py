"""Symbol-graph → ONNX exporter.

Reference parity (leezu/mxnet): ``python/mxnet/contrib/onnx/mx2onnx/`` —
``export_model(sym, params, in_shapes, in_types, onnx_file)`` with a
per-op converter table (``_op_translations.py``).

The protobuf encoding is hand-rolled (``_proto.py``) since the image has
no ``onnx`` package; files produced here load in onnxruntime/netron.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ...base import MXNetError
from ...symbol.symbol import Symbol, _topo_order
from . import _proto as P

__all__ = ["export_model"]


def _pair(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Ctx:
    def __init__(self, params, dtype):
        self.params = params
        self.dtype = dtype
        self.nodes: List[bytes] = []
        self.initializers: Dict[str, onp.ndarray] = {}
        self.renames: Dict[str, str] = {}
        self._uid = 0

    def out(self, node, idx=0):
        base = node.name if idx == 0 else f"{node.name}_out{idx}"
        return self.renames.get(base, base)

    def tmp(self, hint):
        self._uid += 1
        return f"{hint}_{self._uid}"

    def add(self, op_type, inputs, outputs, name="", **attrs):
        self.nodes.append(P.node(op_type, inputs, outputs, name, attrs))

    def const(self, name, array):
        self.initializers[name] = onp.asarray(array)
        return name


# --- converters: fn(ctx, node, in_names) appends ONNX nodes ---------------

def _conv_fc(ctx, n, ins):
    a = n.attrs
    flatten = a.get("flatten", True)
    out = ctx.out(n)
    x, w = ins[0], ins[1]
    bias = ins[2] if len(ins) > 2 and not a.get("no_bias", False) else None
    if flatten:
        fl = ctx.tmp(f"{n.name}_flat")
        ctx.add("Flatten", [x], [fl], axis=1)
        x = fl
    gemm_in = [x, w] + ([bias] if bias else [])
    ctx.add("Gemm", gemm_in, [out], n.name, alpha=1.0, beta=1.0,
            transA=0, transB=1)


def _conv_convolution(ctx, n, ins):
    a = n.attrs
    if a.get("layout", "NCHW") not in ("NCHW", "NCW", "NCDHW"):
        raise MXNetError("ONNX export supports channel-first conv layouts")
    kernel = _pair(a.get("kernel"), len(_pair(a.get("kernel"))))
    ndim = len(kernel)
    stride = _pair(a.get("stride") or 1, ndim)
    pad = _pair(a.get("pad") if a.get("pad") is not None else 0, ndim)
    dilate = _pair(a.get("dilate") or 1, ndim)
    inputs = list(ins)
    if a.get("no_bias", False) and len(inputs) > 2:
        inputs = inputs[:2]
    ctx.add("Conv", inputs, [ctx.out(n)], n.name,
            kernel_shape=list(kernel), strides=list(stride),
            pads=list(pad) * 2, dilations=list(dilate),
            group=int(a.get("num_group", 1)))


_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softrelu": "Softplus", "softsign": "Softsign",
            "gelu": "Gelu", "elu": "Elu", "selu": "Selu"}


def _conv_activation(ctx, n, ins):
    act = n.attrs.get("act_type", "relu")
    if act not in _ACT_MAP:
        raise MXNetError(f"no ONNX mapping for activation {act!r}")
    ctx.add(_ACT_MAP[act], [ins[0]], [ctx.out(n)], n.name)


def _conv_pooling(ctx, n, ins):
    a = n.attrs
    ptype = a.get("pool_type", "max")
    out = ctx.out(n)
    if a.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.add(op, [ins[0]], [out], n.name)
        return
    kernel = _pair(a.get("kernel"), len(_pair(a.get("kernel"))))
    ndim = len(kernel)
    stride = _pair(a.get("stride") or kernel, ndim)
    pad = _pair(a.get("pad") if a.get("pad") is not None else 0, ndim)
    op = {"max": "MaxPool", "avg": "AveragePool"}[ptype]
    attrs = dict(kernel_shape=list(kernel), strides=list(stride),
                 pads=list(pad) * 2)
    if ptype == "avg":
        attrs["count_include_pad"] = int(a.get("count_include_pad", True))
    ctx.add(op, [ins[0]], [out], n.name, **attrs)


def _conv_batch_norm(ctx, n, ins):
    a = n.attrs
    # inputs: data gamma beta mean var
    ctx.add("BatchNormalization", list(ins[:5]), [ctx.out(n)], n.name,
            epsilon=float(a.get("eps", 1e-5)),
            momentum=float(a.get("momentum", 0.9)))


def _conv_layer_norm(ctx, n, ins):
    a = n.attrs
    ctx.add("LayerNormalization", list(ins[:3]), [ctx.out(n)], n.name,
            axis=int(a.get("axis", -1)),
            epsilon=float(a.get("eps", 1e-5)))


def _conv_softmax(ctx, n, ins):
    ctx.add("Softmax", [ins[0]], [ctx.out(n)], n.name,
            axis=int(n.attrs.get("axis", -1)))


def _conv_flatten(ctx, n, ins):
    ctx.add("Flatten", [ins[0]], [ctx.out(n)], n.name, axis=1)


def _conv_dropout(ctx, n, ins):
    ratio = ctx.const(ctx.tmp(f"{n.name}_ratio"),
                      onp.float32(n.attrs.get("p", 0.5)))
    ctx.add("Dropout", [ins[0], ratio], [ctx.out(n)], n.name)


def _conv_reshape(ctx, n, ins):
    shape = n.attrs.get("shape")
    cname = ctx.const(ctx.tmp(f"{n.name}_shape"),
                      onp.asarray(shape, dtype=onp.int64))
    ctx.add("Reshape", [ins[0], cname], [ctx.out(n)], n.name)


def _conv_concat(ctx, n, ins):
    axis = n.attrs.get("dim", n.attrs.get("axis", 1))
    ctx.add("Concat", list(ins), [ctx.out(n)], n.name, axis=int(axis))


def _binop(op_type):
    def conv(ctx, n, ins):
        ctx.add(op_type, list(ins[:2]), [ctx.out(n)], n.name)
    return conv


def _conv_embedding(ctx, n, ins):
    # mx embedding(data, weight) -> Gather(weight, indices)
    idx = ctx.tmp(f"{n.name}_idx")
    ctx.add("Cast", [ins[0]], [idx], to=P.INT64)
    ctx.add("Gather", [ins[1], idx], [ctx.out(n)], n.name, axis=0)


def _conv_cast(ctx, n, ins):
    dt = P.np_to_onnx_dtype(n.attrs.get("dtype", "float32"))
    ctx.add("Cast", [ins[0]], [ctx.out(n)], n.name, to=dt)


def _conv_transpose(ctx, n, ins):
    axes = n.attrs.get("axes")
    kw = {"perm": [int(x) for x in axes]} if axes else {}
    ctx.add("Transpose", [ins[0]], [ctx.out(n)], n.name, **kw)


def _conv_stopgrad(ctx, n, ins):
    ctx.add("Identity", [ins[0]], [ctx.out(n)], n.name)


_CONVERTERS = {
    "fully_connected": _conv_fc,
    "convolution": _conv_convolution,
    "activation": _conv_activation,
    "pooling": _conv_pooling,
    "batch_norm": _conv_batch_norm,
    "layer_norm": _conv_layer_norm,
    "softmax": _conv_softmax,
    "flatten": _conv_flatten,
    "dropout": _conv_dropout,
    "reshape": _conv_reshape,
    "concat": _conv_concat,
    "add": _binop("Add"), "subtract": _binop("Sub"),
    "multiply": _binop("Mul"), "divide": _binop("Div"),
    "maximum": _binop("Max"), "minimum": _binop("Min"),
    "power": _binop("Pow"),
    "dot": _binop("MatMul"),
    "embedding": _conv_embedding,
    "cast": _conv_cast,
    "transpose": _conv_transpose,
    "stop_gradient": _conv_stopgrad,
    "relu": lambda ctx, n, ins: ctx.add("Relu", [ins[0]], [ctx.out(n)],
                                        n.name),
    "sigmoid": lambda ctx, n, ins: ctx.add("Sigmoid", [ins[0]],
                                           [ctx.out(n)], n.name),
    "tanh": lambda ctx, n, ins: ctx.add("Tanh", [ins[0]], [ctx.out(n)],
                                        n.name),
    "exp": lambda ctx, n, ins: ctx.add("Exp", [ins[0]], [ctx.out(n)],
                                       n.name),
}


def export_model(sym: Symbol, params: Dict[str, Any],
                 input_shapes: Sequence[Tuple[int, ...]],
                 input_types: Any = "float32",
                 onnx_file_path: str = "model.onnx",
                 opset: int = 13, verbose: bool = False) -> str:
    """Export a Symbol + params dict to an ONNX file.

    params values may be NDArray or numpy; keys may carry the reference's
    ``arg:``/``aux:`` prefixes.  Returns ``onnx_file_path``.
    """
    clean_params = {}
    for k, v in params.items():
        k = k.split(":", 1)[-1]
        clean_params[k] = onp.asarray(
            v.asnumpy() if hasattr(v, "asnumpy") else v)

    order = _topo_order(sym._heads)
    data_inputs = [n for n in order
                   if n.op == "null" and n.name not in clean_params]
    if len(data_inputs) != len(input_shapes):
        raise MXNetError(
            f"{len(data_inputs)} graph inputs "
            f"({[n.name for n in data_inputs]}) but "
            f"{len(input_shapes)} input_shapes given")
    if isinstance(input_types, (str, onp.dtype, type)):
        input_types = [input_types] * len(data_inputs)

    ctx = _Ctx(clean_params, input_types)
    for name, arr in clean_params.items():
        ctx.initializers[name] = arr

    for n in order:
        if n.op == "null":
            continue
        conv = _CONVERTERS.get(n.op)
        if conv is None:
            raise MXNetError(f"no ONNX converter for op {n.op!r} "
                             f"(node {n.name!r})")
        ins = [ctx.out(m, idx) for m, idx in n.inputs]
        conv(ctx, n, ins)

    inits = [P.tensor(k, v) for k, v in ctx.initializers.items()]
    vi_in = [P.value_info(n.name, dt, list(shape))
             for n, shape, dt in zip(data_inputs, input_shapes,
                                     input_types)]
    heads = [(n, idx) for n, idx in sym._heads]
    vi_out = [P.value_info(ctx.out(n, idx), input_types[0], [])
              for n, idx in heads]
    g = P.graph(ctx.nodes, "mxnet_tpu_graph", inits, vi_in, vi_out)
    blob = P.model(g, opset=opset)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    if verbose:
        print(f"exported {len(ctx.nodes)} nodes, "
              f"{len(inits)} initializers -> {onnx_file_path}")
    return onnx_file_path
