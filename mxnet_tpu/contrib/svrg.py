"""SVRG — stochastic variance-reduced gradient training.

Reference parity (leezu/mxnet): ``python/mxnet/contrib/svrg_optimization/``
(``SVRGModule`` + ``_SVRGOptimizer``) — every ``update_freq`` epochs a
full-pass gradient is snapshotted; minibatch updates use
``g_i(w) - g_i(w_snap) + mu`` to cut gradient variance.

Design (tpu-first): a gluon-level trainer (the reference's Module API
equivalent lives in ``mxnet_tpu.module``); the corrected gradient is
formed on device with plain ops so the whole update stays on-chip.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..base import MXNetError
from ..gluon.trainer import Trainer
from ..ndarray.ndarray import NDArray

__all__ = ["SVRGTrainer"]


class SVRGTrainer:
    """Variance-reduced wrapper around :class:`gluon.Trainer`.

    Usage per epoch::

        trainer.update_snapshot(full_data_iter, loss_fn)   # full-pass mu
        for X, y in batches:
            trainer.step_svrg(X, y, loss_fn)
    """

    def __init__(self, net: Any, optimizer: str = "sgd",
                 optimizer_params: Optional[Dict[str, Any]] = None) -> None:
        self.net = net
        self._params = [p for p in net.collect_params().values()
                        if p.grad_req != "null"]
        self.trainer = Trainer(net.collect_params(), optimizer,
                               optimizer_params or {})
        self._snapshot: Optional[List[NDArray]] = None
        self._mu: Optional[List[NDArray]] = None

    def update_snapshot(self, data_iter, loss_fn: Callable) -> None:
        """Snapshot current weights and the full-pass gradient mu."""
        from .. import autograd
        acc: Optional[List[NDArray]] = None
        n_batches = 0
        for batch in data_iter:
            X, y = batch
            for p in self._params:
                p.zero_grad()
            with autograd.record():
                loss = loss_fn(self.net(X), y).mean()
            loss.backward()
            grads = [p.grad() for p in self._params]
            acc = [g.copy() for g in grads] if acc is None \
                else [a + g for a, g in zip(acc, grads)]
            n_batches += 1
        if n_batches == 0:
            raise MXNetError("empty data_iter for SVRG snapshot")
        self._mu = [a / float(n_batches) for a in acc]
        self._snapshot = [p.data().copy() for p in self._params]
        for p in self._params:
            p.zero_grad()

    def step_svrg(self, X: Any, y: Any, loss_fn: Callable) -> NDArray:
        """One variance-reduced step; returns the minibatch loss."""
        if self._snapshot is None:
            raise MXNetError("call update_snapshot before step_svrg")
        from .. import autograd

        # grad at current weights
        for p in self._params:
            p.zero_grad()
        with autograd.record():
            loss = loss_fn(self.net(X), y).mean()
        loss.backward()
        g_cur = [p.grad().copy() for p in self._params]

        # grad at snapshot weights (swap raw buffers in, eval, swap back —
        # set_data would alias the live NDArray and break the restore; the
        # snapshot is swapped in as a COPY so the optimizer's later
        # buffer donation can never invalidate it)
        current = [p.data()._data for p in self._params]
        for p, w in zip(self._params, self._snapshot):
            p._data._data = w._data.copy() if hasattr(w._data, "copy") \
                else w._data
        for p in self._params:
            p.zero_grad()
        with autograd.record():
            snap_loss = loss_fn(self.net(X), y).mean()
        snap_loss.backward()
        g_snap = [p.grad().copy() for p in self._params]
        for p, arr in zip(self._params, current):
            p._data._data = arr

        # corrected gradient into .grad, then a normal optimizer step;
        # grads already carry the 1/batch mean scale, so rescale=1
        for p, gc, gs, mu in zip(self._params, g_cur, g_snap, self._mu):
            p.grad()._data = (gc - gs + mu)._data
            p.data()._fresh_grad = True
        self.trainer.step(1)
        return loss
