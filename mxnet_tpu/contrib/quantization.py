"""INT8 post-training quantization driver.

Reference parity (leezu/mxnet): ``python/mxnet/contrib/quantization.py`` —
``quantize_net`` / ``quantize_model`` with naive (min/max) and entropy
(KL-divergence) calibration, excluded-layer control, and per-layer
quantized replacements (``quantized_conv`` / ``quantized_fully_connected``
in ``src/operator/quantization/``).

Design (tpu-first): calibration observes the float net eagerly (no graph
surgery pass — layers are swapped in the Block child registry), and the
quantized layers execute int8 ``lax`` dots/convs with int32 accumulation
(``mxnet_tpu/ops/quantization.py``).  Under ``hybridize()`` the whole
quantized net still traces into one XLA program, which is where the win
comes from on TPU (int8 MXU passes + fused requantize arithmetic).
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon.nn.basic_layers import Dense
from ..gluon.nn.conv_layers import _Conv
from ..ndarray.ndarray import NDArray
from ..ops import quantization as qop

__all__ = ["quantize_net", "QuantizedDense", "QuantizedConv",
           "optimal_threshold_entropy"]


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------

_NBINS = 2048
_QLEVELS = 255


def optimal_threshold_entropy(hist: onp.ndarray, edges: onp.ndarray
                              ) -> float:
    """KL-optimal |threshold| from an abs-value histogram (reference:
    ``_get_optimal_threshold`` / ``_LayerHistogramCollector``).

    Sweeps candidate clip points; for each, P = clipped distribution,
    Q = P re-binned to 255 int8 levels; picks argmin KL(P||Q).
    """
    total = hist.sum()
    if total == 0:
        return float(edges[-1])
    best_kl, best_t = onp.inf, float(edges[-1])
    # sweep from 128 bins up (finer than int8 makes no sense)
    for i in range(_QLEVELS, len(hist) + 1, 8):
        p = hist[:i].astype(onp.float64).copy()
        p[i - 1] += hist[i:].sum()          # clip mass onto the edge bin
        num_merged = i // _QLEVELS
        if num_merged == 0:
            continue
        q = onp.zeros(i, dtype=onp.float64)
        for j in range(_QLEVELS):
            start = j * num_merged
            stop = i if j == _QLEVELS - 1 else (j + 1) * num_merged
            chunk = hist[start:stop]
            nz = (chunk > 0).sum()
            if nz:
                q[start:stop] = onp.where(chunk > 0, chunk.sum() / nz, 0)
        p /= p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        kl = float((p[mask] * onp.log(
            p[mask] / onp.maximum(q[mask], 1e-12))).sum())
        if kl < best_kl:
            best_kl, best_t = kl, float(edges[i])
    return best_t


class _Observer:
    """Records a layer's input range during calibration forwards."""

    def __init__(self, mode: str) -> None:
        self.mode = mode
        self.mn = onp.inf
        self.mx = -onp.inf
        self.hist = onp.zeros(_NBINS)
        self.absmax = 0.0

    def update(self, x: onp.ndarray) -> None:
        self.mn = min(self.mn, float(x.min()))
        self.mx = max(self.mx, float(x.max()))
        if self.mode == "entropy":
            a = onp.abs(x).ravel()
            amax = float(a.max()) if a.size else 0.0
            if amax > self.absmax and self.absmax > 0:
                # rescale old histogram onto the wider range
                old_edges = onp.linspace(0, self.absmax, _NBINS + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                new_hist, _ = onp.histogram(
                    centers, bins=_NBINS, range=(0, amax),
                    weights=self.hist)
                self.hist = new_hist
                self.absmax = amax
            self.absmax = max(self.absmax, amax)
            h, _ = onp.histogram(a, bins=_NBINS, range=(0, self.absmax or 1))
            self.hist += h

    def range(self) -> Tuple[float, float]:
        if self.mode == "entropy":
            edges = onp.linspace(0, self.absmax or 1.0, _NBINS + 1)
            t = optimal_threshold_entropy(self.hist, edges)
            return -t, t
        return self.mn, self.mx


# ---------------------------------------------------------------------------
# Quantized layers
# ---------------------------------------------------------------------------

def _q_weight(w: NDArray):
    q, mn, mx = qop.quantize_v2(w, out_type="int8")
    return q, float(mn.asnumpy()), float(mx.asnumpy())


class QuantizedDense(HybridBlock):
    """int8 replacement for ``gluon.nn.Dense`` (inference only)."""

    def __init__(self, layer: Dense, in_range: Tuple[float, float],
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._units = layer._units
        self._flatten = layer._flatten
        self._activation = layer._activation
        self._in_min, self._in_max = in_range
        self.wq, self._wmin, self._wmax = _q_weight(layer.weight.data())
        if layer.bias is not None:
            self.bq, self._bmin, self._bmax = _q_weight(layer.bias.data())
        else:
            self.bq = None

    def forward(self, x: NDArray) -> NDArray:
        q, mn, mx = qop.quantize_v2(x, self._in_min, self._in_max,
                                    out_type="int8")
        from .. import np as _np
        wmin, wmax = _np.array(self._wmin), _np.array(self._wmax)
        if self.bq is not None:
            y, mn_o, mx_o = qop.quantized_fully_connected(
                q, self.wq, self.bq, mn, mx, wmin, wmax,
                _np.array(self._bmin), _np.array(self._bmax),
                num_hidden=self._units, flatten=self._flatten)
        else:
            y, mn_o, mx_o = qop.quantized_fully_connected(
                q, self.wq, None, mn, mx, wmin, wmax,
                num_hidden=self._units, no_bias=True,
                flatten=self._flatten)
        out = qop.dequantize(y, mn_o, mx_o)
        if self._activation:
            from ..ops import nn as npx
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self) -> str:
        return f"QuantizedDense(-> {self._units}, int8)"


class QuantizedConv(HybridBlock):
    """int8 replacement for ``gluon.nn.Conv*D`` (inference only)."""

    def __init__(self, layer: _Conv, in_range: Tuple[float, float],
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if layer._transpose:
            raise MXNetError("transpose conv has no int8 path")
        self._cfg = dict(kernel=layer._kernel, stride=layer._strides,
                         pad=layer._padding, dilate=layer._dilation,
                         num_filter=layer._channels,
                         num_group=layer._groups, layout=layer._layout)
        self._activation = layer._activation
        self._in_min, self._in_max = in_range
        self.wq, self._wmin, self._wmax = _q_weight(layer.weight.data())
        if layer.bias is not None:
            self.bq, self._bmin, self._bmax = _q_weight(layer.bias.data())
        else:
            self.bq = None

    def forward(self, x: NDArray) -> NDArray:
        q, mn, mx = qop.quantize_v2(x, self._in_min, self._in_max,
                                    out_type="int8")
        from .. import np as _np
        wmin, wmax = _np.array(self._wmin), _np.array(self._wmax)
        if self.bq is not None:
            y, mn_o, mx_o = qop.quantized_conv(
                q, self.wq, self.bq, mn, mx, wmin, wmax,
                _np.array(self._bmin), _np.array(self._bmax), **self._cfg)
        else:
            y, mn_o, mx_o = qop.quantized_conv(
                q, self.wq, None, mn, mx, wmin, wmax, no_bias=True,
                **self._cfg)
        out = qop.dequantize(y, mn_o, mx_o)
        if self._activation:
            from ..ops import nn as npx
            out = npx.activation(out, self._activation)
        return out

    def __repr__(self) -> str:
        return f"QuantizedConv({self._cfg['num_filter']}, int8)"


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def _target_layers(net: HybridBlock, exclude: Sequence[str]
                   ) -> List[Tuple[HybridBlock, str, HybridBlock]]:
    """(parent, child_name, layer) for every quantizable layer."""
    out = []

    def walk(block, prefix):
        for name, child in list(block._children.items()):
            path = f"{prefix}{name}"
            quantizable = (isinstance(child, Dense) or
                           (isinstance(child, _Conv)
                            and not child._transpose))
            if quantizable and path not in exclude \
                    and child.weight.is_initialized:
                out.append((block, name, child, path))
            else:
                walk(child, path + ".")

    walk(net, "")
    return out


def quantize_net(net: HybridBlock, quantized_dtype: str = "int8",
                 exclude_layers: Optional[Sequence[str]] = None,
                 calib_data: Any = None, calib_mode: str = "naive",
                 num_calib_batches: Optional[int] = None,
                 logger: Optional[logging.Logger] = None) -> HybridBlock:
    """Post-training-quantize a gluon net for int8 inference.

    calib_mode: 'naive' (observed min/max), 'entropy' (KL-optimal
    threshold), or 'none' (per-batch dynamic ranges).  ``calib_data``
    iterates input batches (NDArray, tuple, or DataLoader yielding
    (data, label)).  The net is modified IN PLACE (quantizable children
    are swapped) and also returned.
    """
    if quantized_dtype != "int8":
        raise MXNetError("only quantized_dtype='int8' is supported on TPU")
    if calib_mode not in ("naive", "entropy", "none"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    log = logger or logging.getLogger(__name__)
    targets = _target_layers(net, tuple(exclude_layers or ()))
    if not targets:
        raise MXNetError("no quantizable (Dense/Conv) layers found — "
                         "run a forward pass first so shapes are inferred")

    ranges: Dict[str, Tuple[float, float]] = {}
    if calib_mode == "none":
        # dynamic: quantize_v2 falls back to runtime min/max
        ranges = {path: (None, None) for _, _, _, path in targets}
    else:
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} requires "
                             "calib_data")
        observers = {path: _Observer(calib_mode)
                     for _, _, _, path in targets}
        hooks = []
        for _, _, layer, path in targets:
            obs = observers[path]
            orig = layer.forward

            def hooked(x, _orig=orig, _obs=obs):
                _obs.update(onp.asarray(x.asnumpy()))
                return _orig(x)

            layer.forward = hooked
            hooks.append((layer, orig))
        # calibration must run EAGERLY: a hybridized net would execute
        # its cached compiled graph and the observer hooks would never
        # fire (silent garbage ranges)
        was_active = bool(getattr(net, "_active", False))
        if was_active:
            net.hybridize(active=False)
        try:
            for i, batch in enumerate(calib_data):
                if num_calib_batches is not None \
                        and i >= num_calib_batches:
                    break
                data = batch[0] if isinstance(batch, (tuple, list)) \
                    else batch
                net(data)
        finally:
            for layer, orig in hooks:
                layer.forward = orig
            if was_active:
                net.hybridize(active=True)
        ranges = {p: obs.range() for p, obs in observers.items()}
        for p, r in ranges.items():
            log.info("calibrated %s: range (%.4g, %.4g)", p, *r)

    for parent, name, layer, path in targets:
        rng = ranges[path]
        if isinstance(layer, Dense):
            qlayer = QuantizedDense(layer, rng)
        else:
            qlayer = QuantizedConv(layer, rng)
        parent._children[name] = qlayer
        # attribute-registered children also live in __dict__
        if parent.__dict__.get(name) is layer:
            object.__setattr__(parent, name, qlayer)
        # any compiled cache of the parent now traces the old children
        if hasattr(parent, "_cached_graph"):
            parent._cached_graph.clear()
    if hasattr(net, "_cached_graph"):
        net._cached_graph.clear()
    return net
