"""Text utilities: vocabulary + token embeddings.

Reference parity (leezu/mxnet): ``python/mxnet/contrib/text/`` —
``vocab.Vocabulary``, ``embedding.TokenEmbedding`` (GloVe/fastText
loaders, CustomEmbedding from local files), ``utils.count_tokens_from_str``.

Pretrained downloads are out (zero egress); the file-format loaders read
local GloVe/fastText-style text files, which is what the reference's
loaders do after download.
"""
from __future__ import annotations

import collections
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["Vocabulary", "CustomEmbedding", "count_tokens_from_str",
           "register_embedding", "create"]


def count_tokens_from_str(source_str: str, token_delim: str = " ",
                          seq_delim: str = "\n", to_lower: bool = False,
                          counter_to_update: Optional[
                              collections.Counter] = None
                          ) -> collections.Counter:
    """Count tokens (reference ``text.utils.count_tokens_from_str``)."""
    source_str = re.sub(f"[{token_delim}{seq_delim}]+", " ", source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(t for t in source_str.split(" ") if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with unknown + reserved tokens
    (reference ``text.vocab.Vocabulary``)."""

    def __init__(self, counter: Optional[collections.Counter] = None,
                 most_freq_count: Optional[int] = None, min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[Sequence[str]] = None) -> None:
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token must not be in reserved_tokens")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._idx_to_token: List[str] = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq:
                    break
                if tok != unknown_token and tok not in reserved_tokens:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i
                              for i, t in enumerate(self._idx_to_token)}

    def __len__(self) -> int:
        return len(self._idx_to_token)

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def reserved_tokens(self) -> List[str]:
        return self._reserved_tokens

    def to_indices(self, tokens: Union[str, Sequence[str]]):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices: Union[int, Sequence[int]]):
        single = isinstance(indices, int)
        idxs = [indices] if single else list(indices)
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError(f"token index {i} out of range")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


class _TokenEmbedding:
    """Base: vocabulary-aligned embedding matrix with unknown fallback."""

    def __init__(self, vocabulary: Optional[Vocabulary] = None) -> None:
        self._vocab = vocabulary
        self._idx_to_vec: Optional[onp.ndarray] = None

    @property
    def vec_len(self) -> int:
        return 0 if self._idx_to_vec is None else self._idx_to_vec.shape[1]

    @property
    def idx_to_vec(self) -> NDArray:
        return NDArray(self._idx_to_vec)

    def _load_embedding_file(self, path: str, elem_delim: str = " ",
                             encoding: str = "utf-8"
                             ) -> Dict[str, onp.ndarray]:
        vecs: Dict[str, onp.ndarray] = {}
        with open(path, encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2 \
                        and parts[0].isdigit() and parts[1].isdigit():
                    continue        # fastText header "count dim"
                tok, elems = parts[0], parts[1:]
                if len(elems) <= 1:
                    continue        # malformed line (reference warns)
                vecs[tok] = onp.asarray([float(e) for e in elems],
                                        dtype=onp.float32)
        if not vecs:
            raise MXNetError(f"no embedding vectors parsed from {path}")
        return vecs

    def _build(self, token_vecs: Dict[str, onp.ndarray],
               init_unknown_vec) -> None:
        dim = len(next(iter(token_vecs.values())))
        if self._vocab is None:
            counter = collections.Counter(
                {t: 1 for t in token_vecs})
            self._vocab = Vocabulary(counter)
        n = len(self._vocab)
        mat = onp.stack([init_unknown_vec(dim)] * n)
        for tok, vec in token_vecs.items():
            i = self._vocab.token_to_idx.get(tok)
            if i is not None and len(vec) == dim:
                mat[i] = vec
        self._idx_to_vec = mat.astype(onp.float32)

    # vocabulary passthroughs
    def __len__(self) -> int:
        return len(self._vocab)

    @property
    def token_to_idx(self):
        return self._vocab.token_to_idx

    @property
    def idx_to_token(self):
        return self._vocab.idx_to_token

    def get_vecs_by_tokens(self, tokens: Union[str, Sequence[str]],
                           lower_case_backup: bool = False) -> NDArray:
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        t2i = self._vocab.token_to_idx
        idx = []
        for t in toks:
            i = t2i.get(t)
            if i is None and lower_case_backup:
                i = t2i.get(t.lower())
            idx.append(0 if i is None else i)
        out = self._idx_to_vec[idx]
        return NDArray(out[0] if single else out)

    def update_token_vectors(self, tokens: Union[str, Sequence[str]],
                             new_vectors: Any) -> None:
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        arr = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else onp.asarray(new_vectors)
        arr = arr.reshape(len(toks), -1)
        for t, v in zip(toks, arr):
            i = self._vocab.token_to_idx.get(t)
            if i is None:
                raise MXNetError(f"token {t!r} not in vocabulary")
            self._idx_to_vec[i] = v


class CustomEmbedding(_TokenEmbedding):
    """Embedding from a local GloVe/fastText-style text file
    (reference ``text.embedding.CustomEmbedding``)."""

    def __init__(self, pretrained_file_path: str, elem_delim: str = " ",
                 encoding: str = "utf-8",
                 init_unknown_vec=onp.zeros,
                 vocabulary: Optional[Vocabulary] = None) -> None:
        super().__init__(vocabulary)
        vecs = self._load_embedding_file(pretrained_file_path, elem_delim,
                                         encoding)
        self._build(vecs, init_unknown_vec)


_EMBED_REGISTRY: Dict[str, type] = {"custom": CustomEmbedding}


def register_embedding(name: str, cls: type) -> type:
    """Register an embedding loader (reference ``TokenEmbedding.register``)."""
    _EMBED_REGISTRY[name.lower()] = cls
    return cls


def create(embedding_name: str, **kwargs: Any):
    """Create a registered embedding (reference ``text.embedding.create``).
    Note: 'glove'/'fasttext' pretrained downloads need network access —
    point CustomEmbedding at a local vector file instead."""
    try:
        cls = _EMBED_REGISTRY[embedding_name.lower()]
    except KeyError:
        raise MXNetError(
            f"unknown embedding {embedding_name!r} (registered: "
            f"{sorted(_EMBED_REGISTRY)}); pretrained downloads are "
            "unavailable offline — use 'custom' with a local file") \
            from None
    return cls(**kwargs)
