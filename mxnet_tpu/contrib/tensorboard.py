"""TensorBoard logging — the mxboard analog.

Reference parity (leezu/mxnet): the external ``mxboard`` package
(SURVEY.md 5.5) — ``SummaryWriter.add_scalar/add_histogram`` writing
TensorFlow event files.  The event-file format is TFRecord framing
(length + masked CRC32C) around ``Event`` protobufs; both are encoded
directly here (no tensorflow dependency), and the files load in a stock
TensorBoard.
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Any, Dict, Optional

import numpy as onp

from ..base import MXNetError

__all__ = ["SummaryWriter"]


# -- CRC32C (Castagnoli), table-driven; TFRecord masking ---------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# protobuf wire helpers shared with the ONNX codec (one implementation)
from .onnx._proto import (field_bytes as _f_bytes,       # noqa: E402
                          field_string as _f_str,
                          field_float as _f_float,
                          field_varint as _f_varint,
                          field_double as _f_double,
                          field_packed_double as _f_packed_double)


# Event: wall_time(1,double), step(2,int64), file_version(3,str),
#        summary(5,msg)
# Summary.Value: tag(1,str), simple_value(2,float), histo(7,HistogramProto)
# HistogramProto: min(1,d) max(2,d) num(3,d) sum(4,d) sum_squares(5,d)
#                 bucket_limit(6,packed d) bucket(7,packed d)

def _event(payload: bytes) -> bytes:
    return _f_double(1, time.time()) + payload


def _record(event: bytes) -> bytes:
    header = struct.pack("<Q", len(event))
    return (header + struct.pack("<I", _masked_crc(header))
            + event + struct.pack("<I", _masked_crc(event)))


class SummaryWriter:
    """Writes TensorBoard event files (``add_scalar`` /
    ``add_histogram`` / ``flush`` / ``close`` — the mxboard surface)."""

    def __init__(self, logdir: str, filename_suffix: str = "") -> None:
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}{filename_suffix}")
        self._path = os.path.join(logdir, name)
        self._f = open(self._path, "wb")
        self._f.write(_record(_event(_f_str(3, "brain.Event:2"))))

    def add_scalar(self, tag: str, value: Any,
                   global_step: int = 0) -> None:
        value = float(value.asnumpy() if hasattr(value, "asnumpy")
                      else value)
        val = _f_str(1, tag) + _f_float(2, value)
        summary = _f_bytes(1, val)
        self._f.write(_record(_event(
            _f_varint(2, global_step) + _f_bytes(5, summary))))

    def add_histogram(self, tag: str, values: Any, global_step: int = 0,
                      bins: int = 30) -> None:
        arr = onp.asarray(values.asnumpy() if hasattr(values, "asnumpy")
                          else values, dtype=onp.float64).ravel()
        if arr.size == 0:
            raise MXNetError("add_histogram: empty value array")
        counts, edges = onp.histogram(arr, bins=bins)
        histo = (_f_double(1, float(arr.min()))
                 + _f_double(2, float(arr.max()))
                 + _f_double(3, float(arr.size))
                 + _f_double(4, float(arr.sum()))
                 + _f_double(5, float((arr ** 2).sum()))
                 + _f_packed_double(6, edges[1:])
                 + _f_packed_double(7, counts))
        val = _f_str(1, tag) + _f_bytes(7, histo)
        self._f.write(_record(_event(
            _f_varint(2, global_step) + _f_bytes(5, _f_bytes(1, val)))))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
