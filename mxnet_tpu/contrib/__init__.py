"""Contrib namespace — experimental / auxiliary subsystems.

Reference parity (leezu/mxnet): ``python/mxnet/contrib/`` (quantization
driver, onnx, tensorboard hooks, …).
"""
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from . import svrg  # noqa: F401
from . import tensorboard  # noqa: F401
