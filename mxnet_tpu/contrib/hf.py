"""HuggingFace transformers interop — convert GPT-2 / BERT checkpoints
into mxnet_tpu model-zoo models.

The reference model zoo shipped pretrained weights for its
architectures; the modern equivalent of that capability is loading the
de-facto checkpoint format. ``convert_gpt2`` / ``convert_bert`` map a
``transformers`` torch model's state into the corresponding
``model_zoo`` block with exact numerical parity (pinned by
``tests/test_hf.py``: logits match to float32 tolerance on random
weights, so the mapping is verified architecture-wide, not just
shape-wide).

Usage (no network needed if the HF model is already local):

    from transformers import GPT2LMHeadModel
    hf = GPT2LMHeadModel.from_pretrained("/path/to/gpt2")
    net = mxnet_tpu.contrib.hf.convert_gpt2(hf)
    out = net.generate(prompt, 50)

Weight-layout notes (the whole conversion, really):

* HF GPT-2 uses ``Conv1D`` layers storing ``(in, out)`` — transposed
  relative to ``Dense``'s ``(out, in)``.
* HF splits q/k/v projections in BERT; our layers fuse them — concat
  along the output axis.
* GPT-2's activation is the tanh GELU approximation ("gelu_new") —
  ``GPTModel(gelu_approximate=True)``.
"""
from __future__ import annotations

from typing import Any

import numpy as onp

from ..base import MXNetError

__all__ = ["convert_gpt2", "convert_bert"]


def _t(tensor) -> onp.ndarray:
    return tensor.detach().cpu().numpy().astype("float32")


def _set(param, value: onp.ndarray) -> None:
    from ..ndarray.ops import array
    if not param.is_initialized:
        param._finish_deferred_init(tuple(value.shape))
    if tuple(param.shape) != tuple(value.shape):
        raise MXNetError(
            f"shape mismatch for {param.name}: ours {tuple(param.shape)} "
            f"vs checkpoint {tuple(value.shape)}")
    param.set_data(array(onp.ascontiguousarray(value)))


def convert_gpt2(hf_model, dropout: float = 0.0):
    """``transformers.GPT2LMHeadModel`` (or ``GPT2Model``) -> GPTModel."""
    from ..gluon.model_zoo.gpt import GPTModel

    tr = getattr(hf_model, "transformer", hf_model)   # LMHead or bare
    cfg = hf_model.config
    if getattr(cfg, "activation_function", "gelu_new") not in (
            "gelu_new", "gelu", "gelu_pytorch_tanh"):
        raise MXNetError(
            f"unsupported GPT-2 activation {cfg.activation_function!r}")
    approx = cfg.activation_function in ("gelu_new", "gelu_pytorch_tanh")
    # config variants that change the math without changing shapes must
    # refuse loudly — a silent conversion would be numerically wrong
    if getattr(cfg, "scale_attn_by_inverse_layer_idx", False):
        raise MXNetError(
            "scale_attn_by_inverse_layer_idx checkpoints are not "
            "supported (per-layer attention scaling not implemented)")
    if getattr(cfg, "reorder_and_upcast_attn", False):
        raise MXNetError(
            "reorder_and_upcast_attn checkpoints are not supported")

    net = GPTModel(vocab_size=cfg.vocab_size, num_layers=cfg.n_layer,
                   units=cfg.n_embd,
                   hidden_size=cfg.n_inner or 4 * cfg.n_embd,
                   num_heads=cfg.n_head, max_length=cfg.n_positions,
                   dropout=dropout,
                   layer_norm_eps=cfg.layer_norm_epsilon,
                   gelu_approximate=approx)
    net.initialize()

    _set(net.word_embed.weight, _t(tr.wte.weight))
    _set(net.position_weight, _t(tr.wpe.weight))
    for blk, h in zip(net.blocks._children.values(), tr.h):
        _set(blk.ln1.gamma, _t(h.ln_1.weight))
        _set(blk.ln1.beta, _t(h.ln_1.bias))
        # Conv1D stores (in, out): transpose into Dense's (out, in)
        _set(blk.attn_qkv.weight, _t(h.attn.c_attn.weight).T)
        _set(blk.attn_qkv.bias, _t(h.attn.c_attn.bias))
        _set(blk.attn_out.weight, _t(h.attn.c_proj.weight).T)
        _set(blk.attn_out.bias, _t(h.attn.c_proj.bias))
        _set(blk.ln2.gamma, _t(h.ln_2.weight))
        _set(blk.ln2.beta, _t(h.ln_2.bias))
        _set(blk.ffn1.weight, _t(h.mlp.c_fc.weight).T)
        _set(blk.ffn1.bias, _t(h.mlp.c_fc.bias))
        _set(blk.ffn2.weight, _t(h.mlp.c_proj.weight).T)
        _set(blk.ffn2.bias, _t(h.mlp.c_proj.bias))
    _set(net.ln_f.gamma, _t(tr.ln_f.weight))
    _set(net.ln_f.beta, _t(tr.ln_f.bias))
    # the LM head is weight-tied to wte in both frameworks — nothing to
    # copy (HF's lm_head.weight IS wte.weight)
    return net


def convert_bert(hf_model, dropout: float = 0.0):
    """``transformers.BertModel`` / ``BertForPreTraining`` -> BERTModel."""
    from ..gluon.model_zoo.bert import BERTModel

    bert = getattr(hf_model, "bert", hf_model)
    cfg = hf_model.config
    if getattr(cfg, "hidden_act", "gelu") != "gelu":
        raise MXNetError(
            f"unsupported BERT activation {cfg.hidden_act!r}")
    cls = getattr(hf_model, "cls", None)   # pretraining heads, if any

    net = BERTModel(vocab_size=cfg.vocab_size,
                    num_layers=cfg.num_hidden_layers,
                    units=cfg.hidden_size,
                    hidden_size=cfg.intermediate_size,
                    num_heads=cfg.num_attention_heads,
                    max_length=cfg.max_position_embeddings,
                    token_type_vocab_size=cfg.type_vocab_size,
                    dropout=dropout,
                    use_pooler=bert.pooler is not None,
                    use_decoder=cls is not None,
                    use_classifier=cls is not None,
                    layer_norm_eps=cfg.layer_norm_eps)
    net.initialize()

    emb = bert.embeddings
    _set(net.word_embed.weight, _t(emb.word_embeddings.weight))
    _set(net.token_type_embed.weight,
         _t(emb.token_type_embeddings.weight))
    _set(net.encoder.position_weight, _t(emb.position_embeddings.weight))
    _set(net.encoder.ln.gamma, _t(emb.LayerNorm.weight))
    _set(net.encoder.ln.beta, _t(emb.LayerNorm.bias))

    for lyr, h in zip(net.encoder.layers._children.values(),
                      bert.encoder.layer):
        a = h.attention
        # separate q/k/v Linears fuse into one qkv Dense: concat on the
        # OUTPUT axis (Dense weight is (out, in))
        _set(lyr.attn_qkv.weight, onp.concatenate(
            [_t(a.self.query.weight), _t(a.self.key.weight),
             _t(a.self.value.weight)], axis=0))
        _set(lyr.attn_qkv.bias, onp.concatenate(
            [_t(a.self.query.bias), _t(a.self.key.bias),
             _t(a.self.value.bias)], axis=0))
        _set(lyr.attn_out.weight, _t(a.output.dense.weight))
        _set(lyr.attn_out.bias, _t(a.output.dense.bias))
        _set(lyr.ln1.gamma, _t(a.output.LayerNorm.weight))
        _set(lyr.ln1.beta, _t(a.output.LayerNorm.bias))
        _set(lyr.ffn1.weight, _t(h.intermediate.dense.weight))
        _set(lyr.ffn1.bias, _t(h.intermediate.dense.bias))
        _set(lyr.ffn2.weight, _t(h.output.dense.weight))
        _set(lyr.ffn2.bias, _t(h.output.dense.bias))
        _set(lyr.ln2.gamma, _t(h.output.LayerNorm.weight))
        _set(lyr.ln2.beta, _t(h.output.LayerNorm.bias))

    if bert.pooler is not None and net.pooler is not None:
        _set(net.pooler.weight, _t(bert.pooler.dense.weight))
        _set(net.pooler.bias, _t(bert.pooler.dense.bias))
    if cls is not None and net.mlm_transform is not None:
        pred = cls.predictions
        _set(net.mlm_transform.weight, _t(pred.transform.dense.weight))
        _set(net.mlm_transform.bias, _t(pred.transform.dense.bias))
        _set(net.mlm_ln.gamma, _t(pred.transform.LayerNorm.weight))
        _set(net.mlm_ln.beta, _t(pred.transform.LayerNorm.bias))
        _set(net.mlm_bias, _t(pred.decoder.bias))
        if net.classifier is not None and hasattr(cls,
                                                  "seq_relationship"):
            _set(net.classifier.weight, _t(cls.seq_relationship.weight))
            _set(net.classifier.bias, _t(cls.seq_relationship.bias))
    return net
