"""Jittered exponential backoff with deadline — the retry substrate the
distributed stack shares.

Reference parity (leezu/mxnet): ps-lite's van retried sends with a fixed
schedule buried in C++; here retry policy is one auditable helper with a
uniform env tier and per-site metrics, used by the dist_async client
(reconnects, RPC replays) and available to anything else that talks to a
peer that can die.

Policy: attempt ``fn``; on a retryable exception sleep
``min(max_ms, base_ms * 2**attempt)`` scaled by a random jitter factor
in ``[1 - jitter, 1]`` (decorrelates a fleet of workers hammering a
restarting server), then try again — up to ``attempts`` total tries or
until ``deadline_s`` of wall time has elapsed, whichever comes first.
The LAST exception is re-raised, so call sites keep their structured
errors.

Metrics (PR-1 registry): ``mxnet_retry_attempts_total{site}`` counts
retries (not first tries), ``mxnet_retry_backoff_seconds{site}``
observes each sleep, ``mxnet_retry_exhausted_total{site}`` counts
giving up.  A healthy system shows zeros; a flapping dependency shows up
as a marching per-site counter before anyone reads a log.
"""
from __future__ import annotations

import random
import time
from typing import Any, Callable, Iterator, Optional, Tuple, Type

from .base import MXNetError, getenv, register_env
from . import metrics as _metrics

__all__ = ["retry_call", "backoff_delays", "RETRY_ATTEMPTS",
           "RETRY_EXHAUSTED", "RETRY_BACKOFF_SECONDS"]

register_env(
    "MXNET_RETRY_MAX_ATTEMPTS", 4,
    "Default total tries (first try + retries) for retry_call sites "
    "(dist_async reconnect/RPC replay) when the call site does not pass "
    "its own budget.")
register_env(
    "MXNET_RETRY_BASE_MS", 50,
    "First-retry backoff for retry_call sites; doubles per retry up to "
    "MXNET_RETRY_MAX_MS, scaled by a random jitter factor.")
register_env(
    "MXNET_RETRY_MAX_MS", 2000,
    "Backoff ceiling per retry for retry_call sites.")

RETRY_ATTEMPTS = _metrics.counter(
    "mxnet_retry_attempts_total",
    "Retries taken (excludes first tries), by retry site.",
    labels=("site",))
RETRY_EXHAUSTED = _metrics.counter(
    "mxnet_retry_exhausted_total",
    "retry_call gave up (attempt or deadline budget spent) and "
    "re-raised, by retry site.", labels=("site",))
RETRY_BACKOFF_SECONDS = _metrics.histogram(
    "mxnet_retry_backoff_seconds",
    "Backoff sleeps between retries, by retry site.", labels=("site",))

_JITTER_RNG = random.Random()


def backoff_delays(attempts: Optional[int] = None,
                   base_ms: Optional[float] = None,
                   max_ms: Optional[float] = None,
                   jitter: float = 0.5,
                   rng: Optional[random.Random] = None
                   ) -> Iterator[float]:
    """Yield the sleep (seconds) before retry 1, 2, ... — at most
    ``attempts - 1`` values (one fewer sleep than tries)."""
    if attempts is None:
        attempts = int(getenv("MXNET_RETRY_MAX_ATTEMPTS", 4))
    if base_ms is None:
        base_ms = float(getenv("MXNET_RETRY_BASE_MS", 50))
    if max_ms is None:
        max_ms = float(getenv("MXNET_RETRY_MAX_MS", 2000))
    if attempts < 1:
        raise MXNetError(f"retry attempts must be >= 1, got {attempts}")
    r = rng if rng is not None else _JITTER_RNG
    for i in range(max(0, attempts - 1)):
        d = min(max_ms, base_ms * (2.0 ** i)) / 1e3
        yield d * (1.0 - jitter * r.random())


def retry_call(fn: Callable[[], Any], *, site: str,
               retryable: Tuple[Type[BaseException], ...] = (
                   ConnectionError, OSError),
               attempts: Optional[int] = None,
               base_ms: Optional[float] = None,
               max_ms: Optional[float] = None,
               deadline_s: Optional[float] = None,
               jitter: float = 0.5,
               on_retry: Optional[Callable[[BaseException, int, float],
                                           Any]] = None,
               rng: Optional[random.Random] = None) -> Any:
    """Call ``fn()`` under the backoff policy; re-raise the last
    retryable exception once the budget is spent.  ``site`` labels the
    retry metrics; ``on_retry(exc, attempt_index, delay_s)`` observes
    each retry decision (diagnostics/logging hooks)."""
    deadline = (time.monotonic() + deadline_s
                if deadline_s is not None else None)
    delays = backoff_delays(attempts=attempts, base_ms=base_ms,
                            max_ms=max_ms, jitter=jitter, rng=rng)
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as e:
            delay = next(delays, None)
            if delay is None or (deadline is not None
                                 and time.monotonic() >= deadline):
                RETRY_EXHAUSTED.labels(site=site).inc()
                raise
            if deadline is not None:
                delay = min(delay, max(0.0,
                                       deadline - time.monotonic()))
            RETRY_ATTEMPTS.labels(site=site).inc()
            RETRY_BACKOFF_SECONDS.labels(site=site).observe(delay)
            if on_retry is not None:
                on_retry(e, attempt, delay)
            time.sleep(delay)
            attempt += 1
