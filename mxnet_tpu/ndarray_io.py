"""Binary parameter serialization — the ``.params`` file format.

Reference parity (leezu/mxnet): ``NDArray::Save/Load``
(``src/ndarray/ndarray.cc`` — dmlc::Stream binary with magic + payload;
C API ``MXNDArraySave/Load``). This is a fresh TPU-era container with the
same role and usage pattern (named dense tensors, one file, mmap-friendly
aligned payloads); the reference's exact on-disk layout is CUDA-era
internal and is NOT reproduced.

Format (little-endian):
  magic:   8 bytes  b"MXTPU001"
  count:   uint64
  per tensor:
    name_len uint32, name utf-8
    dtype_len uint32, dtype utf-8 (numpy dtype str, e.g. "<f4", "bfloat16")
    ndim uint32, shape int64 * ndim
    pad to 64-byte alignment
    data raw bytes (C-order)
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as _np

from .base import MXNetError
from .context import Context
from .ndarray.ndarray import NDArray

__all__ = ["save_params", "load_params", "save", "load"]

_MAGIC = b"MXTPU001"
_ALIGN = 64


def _np_of(arr: Any) -> _np.ndarray:
    if isinstance(arr, NDArray):
        # bfloat16 has no numpy dtype; view as uint16 with tagged dtype
        data = arr._data
        if str(data.dtype) == "bfloat16":
            import ml_dtypes
            return _np.asarray(data).view(_np.uint16), "bfloat16"
        return arr.asnumpy(), None
    return _np.asarray(arr), None


def save_params(filename: str, params: Dict[str, Any]) -> None:
    """Save a dict of name->NDArray to ``filename`` (.params format)."""
    with open(filename, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(params)))
        for name, arr in params.items():
            npa = arr.asnumpy() if isinstance(arr, NDArray) else _np.asarray(arr)
            dtype_str = str(npa.dtype.str) if npa.dtype != _np.dtype("V2") \
                else "bfloat16"
            if isinstance(arr, NDArray) and "bfloat16" in str(arr.dtype):
                import ml_dtypes  # noqa: F401 - numpy gains bfloat16 support
                npa = _np.asarray(arr._data)
                dtype_str = "bfloat16"
            nb = name.encode("utf-8")
            db = dtype_str.encode("utf-8")
            f.write(struct.pack("<I", len(nb))); f.write(nb)
            f.write(struct.pack("<I", len(db))); f.write(db)
            f.write(struct.pack("<I", npa.ndim))
            for s in npa.shape:
                f.write(struct.pack("<q", s))
            pos = f.tell()
            pad = (-pos) % _ALIGN
            f.write(b"\0" * pad)
            f.write(npa.tobytes(order="C"))


def load_params(filename: str, ctx: Optional[Context] = None
                ) -> Dict[str, NDArray]:
    """Load a .params file into a dict of name->NDArray."""
    with open(filename, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise MXNetError(
                f"{filename} is not a mxnet_tpu .params file "
                f"(bad magic {magic!r})")
        (count,) = struct.unpack("<Q", f.read(8))
        out: Dict[str, NDArray] = {}
        for _ in range(count):
            (nlen,) = struct.unpack("<I", f.read(4))
            name = f.read(nlen).decode("utf-8")
            (dlen,) = struct.unpack("<I", f.read(4))
            dtype_str = f.read(dlen).decode("utf-8")
            (ndim,) = struct.unpack("<I", f.read(4))
            shape = tuple(struct.unpack("<q", f.read(8))[0]
                          for _ in range(ndim))
            pos = f.tell()
            f.read((-pos) % _ALIGN)
            if dtype_str == "bfloat16":
                import ml_dtypes
                dt = _np.dtype(ml_dtypes.bfloat16)
            else:
                dt = _np.dtype(dtype_str)
            n_items = 1
            for s in shape:
                n_items *= s
            buf = f.read(n_items * dt.itemsize)
            npa = _np.frombuffer(buf, dtype=dt).reshape(shape)
            out[name] = NDArray(npa, ctx=ctx)
        return out


def save(filename: str,
         data: Union[NDArray, Sequence[NDArray], Dict[str, NDArray]]) -> None:
    """``mx.nd.save`` parity: save list (keys "arg:0"...) or dict."""
    if isinstance(data, NDArray):
        data = {"0": data}
    elif isinstance(data, (list, tuple)):
        data = {str(i): a for i, a in enumerate(data)}
    save_params(filename, data)


def load(filename: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    """``mx.nd.load`` parity: returns a list when keys are 0..n-1."""
    d = load_params(filename)
    keys = list(d)
    if keys and all(k.isdigit() for k in keys):
        return [d[str(i)] for i in range(len(keys))]
    return d
