"""AMP op classification lists.

Reference parity (leezu/mxnet): ``python/mxnet/amp/lists/symbol_fp16.py``
(FP16_FUNCS / FP32_FUNCS / WIDEST_TYPE_CASTS / CONDITIONAL_FP32_FUNCS).

Design (tpu-first): the target low-precision dtype is **bfloat16** (the
MXU's native format) rather than float16 — bf16 keeps fp32's exponent
range so the loss-scaling machinery is optional (still provided for
parity and for fp16 use). Names here are op-registry names as passed to
``register.invoke``; the cast hook in ``amp/__init__.py`` consults these
centrally, replacing the reference's per-namespace monkey-patching.
"""

# MXU-bound ops: run in the low-precision target dtype.
TARGET_DTYPE_FUNCS = [
    "fully_connected", "convolution", "deconvolution", "dot", "batch_dot",
    # fused BN/ReLU->1x1-conv junctions: the GEMM runs at the data dtype
    # (stats/prologue are f32 internally regardless — ops/pallas/
    # conv_fused.py), so they cast like 'convolution'
    "batch_norm_relu_conv1x1", "relu_conv1x1",
    "matmul", "linalg_gemm", "linalg_gemm2", "linalg_matmul", "tensordot",
    "inner", "outer", "kron", "einsum",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "multi_head_attention", "dot_product_attention",
    "rnn", "embedding",
]

# Numerically sensitive ops: always run in float32.
FP32_FUNCS = [
    "softmax", "log_softmax", "masked_softmax", "masked_log_softmax",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "l2_normalization", "lrn", "norm", "logsumexp",
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "erfinv", "reciprocal", "rsqrt", "rcbrt",
    "linalg_potrf", "linalg_potri", "linalg_trsm", "linalg_cholesky",
    "linalg_inv", "linalg_det", "linalg_slogdet", "linalg_svd",
    "linalg_sumlogdiag", "linalg_norm",
    "mean", "sum", "prod", "cumsum", "var", "std",
    "quantile", "percentile", "median",
    "smooth_l1", "pick",
]

# Per-operand refinement for TARGET_DTYPE_FUNCS ops whose operand list
# MIXES MXU data with normalization statistics (ADVICE r5): the fused
# BN->ReLU->1x1-conv junction takes (data, gamma, beta, running_mean,
# running_var, weight[, conv_bias][, shift]) — casting the five
# BN-statistics vectors to bf16 would accrue rounding in the running
# stats and eval-mode normalization that the UNFUSED chain (batch_norm
# in FP32_FUNCS) never sees, breaking the fusion's numerically-invisible
# contract under amp.init().  The predicate gets (operand_index, ndim)
# and returns True for operands that cast to the target dtype; ndim >= 2
# selects exactly the tensor operands (NCHW data, the conv weight) and
# keeps every per-channel statistics/bias vector f32 (the kernel reads
# scale/shift/bias in f32 regardless — ops/pallas/conv_fused.py).
TARGET_DTYPE_OPERAND_POLICY = {
    "batch_norm_relu_conv1x1": lambda idx, ndim: ndim >= 2,
}

# Elementwise combiners: promote all float inputs to the widest dtype.
WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "true_divide", "divide", "mod",
    "power", "maximum", "minimum", "fmax", "fmin", "hypot", "arctan2",
    "add_n", "ElementWiseSum", "maximum_n", "where", "clip",
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logaddexp", "copysign",
]
