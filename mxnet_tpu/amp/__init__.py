"""Automatic mixed precision.

Reference parity (leezu/mxnet): ``python/mxnet/amp/amp.py`` —
``amp.init()`` (op-level cast insertion per curated lists),
``amp.init_trainer`` / ``amp.scale_loss`` / ``amp.unscale`` (dynamic loss
scaling with skip-on-overflow), ``amp.convert_model`` /
``convert_hybrid_block`` (inference conversion).

Design (tpu-first): the default target dtype is **bfloat16** (MXU native;
fp32 exponent range, so loss scaling is rarely needed — kept for fp16 and
API parity). Instead of monkey-patching generated op namespaces, the cast
policy hooks the single dispatch point ``ndarray.register.invoke``: ops in
``TARGET_DTYPE_FUNCS`` get float32 inputs cast down (MXU-bound matmuls),
ops in ``FP32_FUNCS`` get low-precision inputs cast up, and
``WIDEST_TYPE_CASTS`` promote mixed inputs. Because the hook also runs
under hybridize tracing, the casts land inside the compiled XLA program —
the analog of the reference's symbol-pass cast insertion, with XLA fusing
the casts into neighbours for free.
"""
from __future__ import annotations

import contextlib
import warnings
from typing import Any, Iterable, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, from_jax
from ..ndarray.register import invoke, register_op
from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "convert_model",
           "convert_hybrid_block", "amp_cast", "amp_multicast",
           "DynamicLossScaler", "is_enabled", "disable"]

_STATE = {
    "active": False,
    "target_dtype": None,      # jnp dtype
    "target_funcs": frozenset(),
    "fp32_funcs": frozenset(),
    "widest_funcs": frozenset(),
    # op -> (idx, ndim) -> bool: which operands of a TARGET_DTYPE op
    # cast down (fused ops mixing data with BN statistics)
    "operand_policy": {},
}


def is_enabled() -> bool:
    return _STATE["active"]


def _float_like(a) -> bool:
    return jnp.issubdtype(a.dtype, jnp.floating)


def apply_cast_policy(name: str, arrays: List[Any]) -> List[Any]:
    """Cast hook consulted by ``register.invoke`` on every op dispatch."""
    if not _STATE["active"]:
        return arrays
    tgt = _STATE["target_dtype"]
    if name in _STATE["target_funcs"]:
        pol = _STATE["operand_policy"].get(name)
        return [a.astype(tgt)
                if _float_like(a) and a.dtype == jnp.float32
                and (pol is None or pol(i, a.ndim)) else a
                for i, a in enumerate(arrays)]
    if name in _STATE["fp32_funcs"]:
        return [a.astype(jnp.float32)
                if _float_like(a) and a.dtype in (tgt, jnp.float16) else a
                for a in arrays]
    if name in _STATE["widest_funcs"]:
        fdts = [a.dtype for a in arrays if _float_like(a)]
        if len(set(map(str, fdts))) > 1:
            widest = jnp.result_type(*fdts)
            return [a.astype(widest) if _float_like(a) else a
                    for a in arrays]
    return arrays


def init(target_dtype: Union[str, Any] = "bfloat16",
         target_dtype_ops: Optional[Iterable[str]] = None,
         fp32_ops: Optional[Iterable[str]] = None,
         widest_dtype_ops: Optional[Iterable[str]] = None) -> None:
    """Enable mixed precision globally (reference: ``amp.init()``).

    Optional op-name lists override the curated defaults in
    ``amp/lists.py``.
    """
    dt = jnp.bfloat16 if str(target_dtype) in ("bfloat16", "bf16") else \
        jnp.dtype(target_dtype)
    if dt not in (jnp.bfloat16, jnp.float16):
        raise MXNetError(
            f"amp target_dtype must be bfloat16 or float16, got {dt}")
    from ..ndarray import register as _reg
    _reg._amp_state["active"] = True
    _STATE.update(
        active=True,
        target_dtype=dt,
        target_funcs=frozenset(target_dtype_ops
                               if target_dtype_ops is not None
                               else lists.TARGET_DTYPE_FUNCS),
        fp32_funcs=frozenset(fp32_ops if fp32_ops is not None
                             else lists.FP32_FUNCS),
        widest_funcs=frozenset(widest_dtype_ops
                               if widest_dtype_ops is not None
                               else lists.WIDEST_TYPE_CASTS),
        operand_policy=dict(lists.TARGET_DTYPE_OPERAND_POLICY),
    )


def disable() -> None:
    """Turn the cast policy off (no reference analog; useful in tests)."""
    _STATE["active"] = False
    from ..ndarray import register as _reg
    _reg._amp_state["active"] = False


# ---------------------------------------------------------------------------
# Cast ops (reference: src/operator/tensor/amp_cast.cc)
# ---------------------------------------------------------------------------

def amp_cast(data: Any, dtype: Any) -> NDArray:
    """Gradient-transparent cast (reference ``amp_cast``: dtype changes do
    not block gradient flow)."""
    dt = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") else \
        jnp.dtype(dtype)
    nd = data if isinstance(data, NDArray) else NDArray(data)
    return invoke("amp_cast", lambda x: x.astype(dt), (nd,))


def amp_multicast(*data: Any, num_outputs: Optional[int] = None):
    """Cast all inputs to the widest of their dtypes (``amp_multicast``)."""
    nds = [d if isinstance(d, NDArray) else NDArray(d) for d in data]
    widest = jnp.result_type(*[n._data.dtype for n in nds])
    return tuple(invoke("amp_cast", lambda x: x.astype(widest), (n,))
                 for n in nds)


register_op("amp_cast", amp_cast)
register_op("amp_multicast", amp_multicast)


# ---------------------------------------------------------------------------
# Dynamic loss scaling (reference: amp.py LossScaler)
# ---------------------------------------------------------------------------

class DynamicLossScaler:
    """Dynamic loss scale: halve on overflow, double every
    ``scale_window`` clean steps (the reference's fp16 recipe)."""

    def __init__(self, init_scale: float = 2.0 ** 16,
                 scale_factor: float = 2.0, scale_window: int = 2000) -> None:
        self.loss_scale = float(init_scale)
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    def has_overflow(self, grads: Iterable[NDArray]) -> bool:
        for g in grads:
            if g is None:
                continue
            arr = g._data if isinstance(g, NDArray) else g
            if not bool(jnp.isfinite(arr).all()):
                return True
        return False

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0

    def decay(self) -> None:
        """Treat the current step as an overflow: halve the scale and
        reset the clean-step window (the health guard's skip policy
        calls this so a dropped fp16 step also backs the scale off)."""
        self.update_scale(True)


def init_trainer(trainer: Any, init_scale: float = 2.0 ** 16,
                 scale_window: int = 2000) -> None:
    """Attach dynamic loss scaling to a Trainer: ``trainer.step`` divides
    grads by the live scale and skips the update on overflow (reference:
    ``amp.init_trainer``)."""
    scaler = DynamicLossScaler(init_scale=init_scale,
                               scale_window=scale_window)
    trainer._amp_scaler = scaler
    orig_update = trainer._update

    def _update(ignore_stale_grad: bool = False) -> None:
        grads = [p.data().grad for p in trainer._params
                 if p.grad_req != "null" and p.is_initialized]
        overflow = scaler.has_overflow(grads)
        scaler.update_scale(overflow)
        if overflow:
            for p in trainer._params:
                if p.is_initialized and p.data().grad is not None:
                    p.data()._fresh_grad = False
            warnings.warn(
                f"amp: gradient overflow, skipping step "
                f"(loss scale -> {scaler.loss_scale})")
            return
        orig_update(ignore_stale_grad)

    trainer._update = _update


@contextlib.contextmanager
def scale_loss(loss: Any, trainer: Any):
    """Multiply the loss by the live scale inside the context; trainer.step
    un-scales gradients automatically (reference: ``amp.scale_loss``)."""
    scaler: Optional[DynamicLossScaler] = getattr(trainer, "_amp_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) before scale_loss")
    # trainer.step multiplies grads by _scale/batch_size — set the inverse
    # so gradients come out un-scaled
    trainer._scale = 1.0 / scaler.loss_scale
    try:
        if isinstance(loss, (list, tuple)):
            yield type(loss)(l * scaler.loss_scale for l in loss)
        else:
            yield loss * scaler.loss_scale
    finally:
        pass


def unscale(trainer: Any) -> None:
    """Divide current grads by the loss scale (for grad clipping before
    step; reference: ``amp.unscale``)."""
    scaler: Optional[DynamicLossScaler] = getattr(trainer, "_amp_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p.is_initialized:
            w = p.data()
            if w.grad is not None and w._fresh_grad:
                w._grad = from_jax(w.grad._data * inv)
    trainer._scale = 1.0


# ---------------------------------------------------------------------------
# Model conversion (reference: amp.convert_model / convert_hybrid_block)
# ---------------------------------------------------------------------------

def convert_model(block: Any, target_dtype: Union[str, Any] = "bfloat16",
                  excluded_sym_names: Optional[Iterable[str]] = None) -> Any:
    """Cast a trained block's parameters to the target dtype for
    low-precision inference, keeping norm-layer params in fp32 (the
    reference keeps FP32_FUNCS ops in fp32)."""
    excluded = set(excluded_sym_names or ())
    dt = "bfloat16" if str(target_dtype) in ("bfloat16", "bf16") else \
        str(_np.dtype(target_dtype))
    for name, p in block.collect_params().items():
        if name in excluded:
            continue
        lname = name.lower()
        if any(t in lname for t in ("norm", "gamma", "beta",
                                    "running_mean", "running_var")):
            continue
        if p.is_initialized and jnp.issubdtype(p.data()._data.dtype,
                                               jnp.floating):
            p.set_data(from_jax(p.data()._data.astype(dt)))
            p._dtype = dt
    return block


convert_hybrid_block = convert_model
