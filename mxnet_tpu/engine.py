"""Execution-engine semantics over JAX's asynchronous dispatch.

Reference parity (leezu/mxnet): ``src/engine/threaded_engine*.cc``,
``include/mxnet/engine.h``. The reference's dependency engine exists so that
Python returns immediately while kernels run on device streams, with
correctness enforced by read/write var lists. XLA/PJRT gives the same
contract natively: every dispatched computation is asynchronous, ordered per
device stream, with data dependencies tracked by buffer futures. The
"engine" therefore shrinks to:

  * :func:`waitall`  — barrier on all outstanding device work
    (``Engine::WaitForAll`` / ``mx.nd.waitall``).
  * per-array ``wait_to_read`` — ``block_until_ready``
    (``Engine::WaitForVar``).
  * :func:`is_naive` — ``MXNET_ENGINE_TYPE=NaiveEngine`` forces a block
    after every op, the reference's standard first debugging step for
    suspected async races (SURVEY.md section 5.2).

Async errors: XLA poisons dependent buffers; blocking re-raises the original
error. :func:`_sync_and_translate` converts those into :class:`MXNetError`
at sync points, matching the reference's rethrow-at-sync behavior
(``src/engine/threaded_engine.cc`` OnCompleteStatic exception path).
"""
from __future__ import annotations

import time
import weakref
from typing import Any, Dict, Iterable

import jax

from . import metrics as _metrics
from .base import MXNetError, getenv

__all__ = ["waitall", "is_naive", "set_bulk_size", "bulk",
           "native_engine", "push_host_async"]

# Weak registry of live device arrays so waitall() can provide a true
# barrier. jax arrays are weakref-able but unhashable, so this is an
# id-keyed dict of weakrefs, swept when it grows past a bound.
_LIVE: Dict[int, "weakref.ref"] = {}
_SWEEP_AT = 4096


def is_naive() -> bool:
    """True when MXNET_ENGINE_TYPE=NaiveEngine (fully synchronous mode)."""
    return getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice") == "NaiveEngine"


def _weak_register(registry: Dict[int, "weakref.ref"], arr: Any) -> None:
    """Insert ``arr`` into an id-keyed weakref registry, sweeping dead
    entries past the size bound."""
    try:
        registry[id(arr)] = weakref.ref(arr)
    except TypeError:  # plain numpy scalars etc. need no tracking
        pass
    if len(registry) > _SWEEP_AT:
        for k in [k for k, r in registry.items() if r() is None]:
            del registry[k]
        _metrics.ENGINE_SWEEPS.inc()
    if registry is _LIVE:
        _metrics.ENGINE_LIVE_BUFFERS.set(len(registry))


def track(arr: Any) -> Any:
    """Register a device array with the engine; blocks if in naive mode."""
    _weak_register(_LIVE, arr)
    if is_naive():
        _sync_and_translate(arr)
    return arr


def _sync_and_translate(arr: Any) -> Any:
    """Block on ``arr``; translate device-side errors into MXNetError."""
    try:
        return jax.block_until_ready(arr)
    except MXNetError:
        raise
    except Exception as exc:  # XLA raises XlaRuntimeError and friends
        _metrics.ENGINE_SYNC_ERRORS.inc()
        raise MXNetError(str(exc)) from exc


_LAUNDER_CACHE: dict = {}

# Weak id-registry of arrays known to be accelerator-resident compiled-
# program outputs (launder results, trainer write-backs). launder() skips
# these, so repeated hybridized calls with already-clean buffers cost no
# extra copy dispatch. id() reuse is guarded by identity-checking the
# weakref target.
_CLEAN: Dict[int, "weakref.ref"] = {}


def mark_clean(arrays) -> None:
    """Record compiled-executable outputs so ``launder`` passes them
    through untouched."""
    arrs = arrays if isinstance(arrays, (list, tuple)) else [arrays]
    for a in arrs:
        _weak_register(_CLEAN, a)


def _is_clean(a: Any) -> bool:
    ref = _CLEAN.get(id(a))
    return ref is not None and ref() is a


def launder(arrays):
    """Re-materialize eager-produced buffers as accelerator-resident
    compiled-program outputs before they become jit arguments.

    Eager dispatch runs on the eager backend (host CPU under the axon
    remote-TPU tunnel), so eager-produced arrays consumed by a compiled
    program re-pay their host->device transfer on EVERY call (measured
    60-80s/call for a 267-parameter ResNet forward vs 37ms laundered;
    ~1s/step for a re-used 19MB input batch).  One jitted identity copy
    moves them onto the accelerator once.  No-op when the default
    platform IS the cpu backend (tests / virtual mesh).
    """
    single = not isinstance(arrays, (list, tuple))
    arrs = [arrays] if single else list(arrays)
    try:
        if jax.devices()[0].platform == "cpu":
            return arrays
    except Exception:
        return arrays
    # skip buffers already known to be compiled-program outputs — repeated
    # calls with clean inputs dispatch nothing
    dirty = [i for i, a in enumerate(arrs) if not _is_clean(a)]
    if not dirty:
        return arrays
    n = len(dirty)
    fn = _LAUNDER_CACHE.get(n)
    if fn is None:
        import jax.numpy as _jnp
        fn = jax.jit(lambda xs: [_jnp.asarray(a).copy() for a in xs])
        _LAUNDER_CACHE[n] = fn
    out = fn([arrs[i] for i in dirty])
    for i, a in zip(dirty, out):
        arrs[i] = a
    mark_clean(arrs)
    return arrs[0] if single else arrs


def waitall() -> None:
    """Block until all pushed device work completes (``mx.nd.waitall``)."""
    from . import bulk as _bulk   # lazy: bulk imports engine
    _bulk.flush_all("waitall")
    t0 = time.perf_counter()
    try:
        for key, ref in list(_LIVE.items()):
            arr = ref()
            if arr is not None:
                _sync_and_translate(arr)
            _LIVE.pop(key, None)
    finally:
        _metrics.ENGINE_WAITALL.inc()
        _metrics.ENGINE_WAITALL_SECONDS.observe(time.perf_counter() - t0)
        _metrics.ENGINE_LIVE_BUFFERS.set(len(_LIVE))


def wait(arrs: Iterable[Any]) -> None:
    for a in arrs:
        _sync_and_translate(a)


# ---------------------------------------------------------------------------
# Native host-work engine (src/engine.cc — ThreadedEngine analog).
# Device ordering belongs to XLA; this engine schedules *host* work (IO
# decode, custom ops, checkpoint writes) with the reference's read/write
# var dependency discipline.
# ---------------------------------------------------------------------------

def native_engine():
    """The shared native dependency engine, or None if libmxtpu.so is
    unavailable (``Engine::Get()`` analog; ``MXNET_ENGINE_TYPE`` and
    ``MXNET_CPU_WORKER_NTHREADS`` are honored at creation)."""
    from ._native import global_engine
    return global_engine()


def push_host_async(fn, read_vars=(), write_vars=(), priority: int = 0,
                    name: str = "") -> bool:
    """Push host work with var dependencies (``Engine::PushAsync``).

    Returns True if scheduled on the native engine, False if executed
    inline (no native library)."""
    eng = native_engine()
    if eng is None:
        fn()
        return False
    eng.push(fn, read_vars=read_vars, write_vars=write_vars,
             priority=priority, name=name)
    return True


# ---------------------------------------------------------------------------
# Bulking knobs (reference: MXNET_EXEC_BULK_EXEC_* + Engine::bulk_size).
# Since the lazy bulking engine (mxnet_tpu/bulk.py) these are LOAD-BEARING:
# the size is the pending-segment cap (MXNET_BULK_MAX_OPS at runtime).
# ---------------------------------------------------------------------------

def set_bulk_size(size: int) -> int:
    """Set the bulk-execution segment size — how many eager ops the lazy
    bulking engine fuses into one compiled dispatch; returns the previous
    value. ``size <= 1`` disables bulking (per-op dispatch)."""
    from . import bulk as _bulk_mod
    return _bulk_mod.set_max_ops(size)


class bulk:
    """Context manager scoping the bulk segment size (``mx.engine.bulk``).
    Exiting the scope flushes any segment still pending under it, so
    promised buffers never outlive the requested bulking window."""

    def __init__(self, size: int) -> None:
        self._size = size
        self._prev = None

    def __enter__(self) -> "bulk":
        self._prev = set_bulk_size(self._size)
        return self

    def __exit__(self, *exc: Any) -> None:
        from . import bulk as _bulk_mod
        _bulk_mod.flush_current("waitall")
        set_bulk_size(self._prev)
