"""Subgraph accelerator backends — the ``optimize_for`` registry.

Reference parity (leezu/mxnet): ``src/operator/subgraph/subgraph_property.h``
+ ``build_subgraph.cc`` — pluggable backends (MKLDNN fusion, TensorRT)
selected via ``HybridBlock.optimize_for(backend)`` or the
``MXNET_SUBGRAPH_BACKEND`` env var.

Design (tpu-first): XLA already does the fusion the reference's MKLDNN/
TensorRT properties existed for, so a backend here is a whole-block
transform applied before compilation rather than a C++ graph-partition
pass.  Built-ins:

- ``'xla'``    — hybridize + warm the jit cache (the default accelerator;
                 equivalent to the reference's default partitioner).
- ``'int8'``   — post-training int8 quantization via
                 ``contrib.quantization.quantize_net`` (MKLDNN/TensorRT
                 int8 analog), calibrating on the sample input.
- ``'bf16'``   — AMP bf16 cast policy over the block's compiled program
                 (the reference's AMP-convert-model analog).

Custom backends: ``register_backend(name, fn)`` with
``fn(block, sample_inputs, **kwargs) -> block``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .base import MXNetError, getenv, register_env

__all__ = ["register_backend", "get_backend", "list_backends"]

register_env("MXNET_SUBGRAPH_BACKEND", "xla",
             "Default backend applied by HybridBlock.optimize_for when "
             "none is given ('xla', 'int8', 'bf16', or a registered name).")

_BACKENDS: Dict[str, Callable] = {}


def register_backend(name: str, fn: Callable) -> Callable:
    """Register ``fn(block, sample_inputs, **kwargs) -> block`` under
    ``name`` (SubgraphProperty registration analog)."""
    _BACKENDS[name] = fn
    return fn


def get_backend(name: Optional[str] = None) -> Callable:
    name = name or getenv("MXNET_SUBGRAPH_BACKEND")
    try:
        return _BACKENDS[name]
    except KeyError:
        raise MXNetError(
            f"unknown subgraph backend {name!r}; registered: "
            f"{sorted(_BACKENDS)}") from None


def list_backends() -> List[str]:
    return sorted(_BACKENDS)


# ---------------------------------------------------------------------------
# Built-ins
# ---------------------------------------------------------------------------

def _xla_backend(block, sample_inputs, static_alloc: bool = False,
                 static_shape: bool = False, **kwargs: Any):
    block.hybridize(static_alloc=static_alloc, static_shape=static_shape)
    block(*sample_inputs)
    return block


def _int8_backend(block, sample_inputs, calib_mode: str = "naive",
                  exclude_layers=None, calib_data=None, **kwargs: Any):
    from .contrib.quantization import quantize_net
    if calib_data is None and calib_mode != "none":
        calib_data = [sample_inputs[0]]
    block = quantize_net(block, calib_mode=calib_mode,
                         calib_data=calib_data,
                         exclude_layers=exclude_layers)
    block.hybridize()
    block(*sample_inputs)
    return block


def _bf16_backend(block, sample_inputs, **kwargs: Any):
    from . import amp
    amp.init(target_dtype="bfloat16")
    block.hybridize()
    block(*sample_inputs)
    return block


register_backend("xla", _xla_backend)
register_backend("int8", _int8_backend)
register_backend("bf16", _bf16_backend)
