"""``mx.monitor`` — per-op output statistics for debugging.

Reference parity (leezu/mxnet): ``python/mxnet/monitor.py`` — ``Monitor``
installs a callback on executor op outputs and prints a stat (default
|x|/size) per matching op every ``interval`` batches; the standard tool for
chasing exploding activations.

Design (tpu-first): rather than executor install-hooks, the monitor taps
the imperative dispatch layer (``ndarray.register.invoke``) — every op the
framework executes flows through it, eager or under Block.__call__, so one
hook covers Gluon and Module paths alike.  Stats are computed lazily as XLA
reductions and only synced to host at ``toc()``.
"""
from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Tuple

from .ndarray import register as _register
from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Collect per-op output statistics (reference: ``mx.mon.Monitor``).

    Parameters mirror the reference: ``interval`` (batches between
    collections), ``stat_func`` (NDArray -> NDArray stat, default mean
    |x|), ``pattern`` (regex on op/output name), ``sort`` (sort results
    by name at ``toc``).
    """

    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable[[NDArray], NDArray]] = None,
                 pattern: str = ".*", sort: bool = False) -> None:
        if stat_func is None:
            def stat_func(x: NDArray) -> NDArray:
                return x.abs().mean()
        self.stat_func = stat_func
        self.interval = interval
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, NDArray]] = []
        self._exes: List[Any] = []
        self._in_hook = False

    # -- gluon/imperative path --------------------------------------------
    def _hook(self, name: str, outputs: Tuple[NDArray, ...]) -> None:
        # stat_func itself runs ops through the same dispatch layer;
        # guard against recursing into our own stat computation
        if self._in_hook or not self.pattern.match(name):
            return
        import jax
        from . import autograd
        self._in_hook = True
        try:
            # stats are a debugging side-channel: never tape them, and skip
            # abstract tracers (ops running under a hybridize/jit trace)
            with autograd.pause():
                for i, out in enumerate(outputs):
                    if isinstance(out._data, jax.core.Tracer):
                        continue
                    oname = name if len(outputs) == 1 else f"{name}_output{i}"
                    try:
                        self.queue.append(
                            (self.step, oname, self.stat_func(out)))
                    except Exception:   # noqa: BLE001 - stat on odd dtypes
                        pass
        finally:
            self._in_hook = False

    def install(self, exe: Any) -> None:
        """Attach to a symbol Executor (reference: ``Monitor.install``).
        The executor runs ops through the same dispatch layer, so this
        just remembers the exe for interface parity."""
        self._exes.append(exe)

    def tic(self) -> None:
        """Start collecting for this batch if the interval hits
        (reference: ``Monitor.tic``)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
            _register._monitor_state["hooks"][id(self)] = self._hook
        self.step += 1

    def toc(self) -> List[Tuple[int, str, str]]:
        """Stop collecting; return [(step, name, stat_str)]
        (reference: ``Monitor.toc``).

        Scalar stats are additionally published to the runtime metrics
        registry as ``mxnet_monitor_stat{name=...}`` gauges, so the last
        collected value per op output is queryable alongside the rest of
        the runtime metrics (docs/observability.md)."""
        if not self.activated:
            return []
        from . import metrics as _metrics
        _register._monitor_state["hooks"].pop(id(self), None)
        self.activated = False
        res = []
        for step, name, stat in self.queue:
            arr = stat.asnumpy() if isinstance(stat, NDArray) else stat
            try:
                if getattr(arr, "size", 0) == 1:
                    _metrics.MONITOR_STAT.labels(name=name).set(
                        float(arr))
            except (TypeError, ValueError):
                pass   # non-numeric stat: exposition keeps the string only
            res.append((step, name, str(arr)))
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self) -> None:
        """Collect and log results (reference: ``Monitor.toc_print``)."""
        import logging
        for step, name, stat in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, stat)

    def __enter__(self) -> "Monitor":
        self.tic()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.toc_print()
