"""Functional neural-net op library (``mx.npx``-style extensions).

Reference parity: ``src/operator/nn/*`` — see ``nn.py``.
"""
from .nn import *  # noqa: F401,F403
from .nn import __all__ as _nn_all
from .transformer import *  # noqa: F401,F403
from .transformer import __all__ as _tr_all
from .quantization import *  # noqa: F401,F403
from .quantization import __all__ as _q_all
from .boxes import *  # noqa: F401,F403
from .boxes import __all__ as _box_all

__all__ = list(_nn_all) + list(_tr_all) + list(_q_all) + list(_box_all)
