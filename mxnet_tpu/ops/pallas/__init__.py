"""Pallas TPU kernels — the replacement for the reference's CUDA specials.

Reference parity: where leezu/mxnet hand-wrote ``.cu`` kernels
(``src/operator/contrib/transformer.cu``, fused softmax/layernorm paths),
this package holds Mosaic kernels authored with ``jax.experimental.pallas``
(SURVEY.md section 7 design stance).
"""
from .attention import flash_attention

__all__ = ["flash_attention"]
