"""Flash attention — blockwise online-softmax Pallas kernel.

Reference parity (leezu/mxnet): the reference's attention is full O(T²)
materialized scores (``src/operator/contrib/transformer.cu``); this kernel
is the TPU-native upgrade (SURVEY.md 5.7): tiles of Q stream over tiles of
K/V held in VMEM with a running max/denominator, so scores never hit HBM.

Forward is the Pallas kernel (grid B×H×Tq-blocks×Tk-blocks, sequential
accumulation over the last grid axis in VMEM scratch), emitting the
per-row log-sum-exp. Backward is blockwise too (standard flash-attention
recipe): a dq kernel streams K/V blocks against the saved LSE and
``delta = rowsum(dO·O)``, and a dk/dv kernel streams Q/dO blocks — scores
are recomputed per tile and never hit HBM in either direction.

Surface (round-2): additive bias/mask blocks stream like K/V (broadcast
(1|B, 1|H, Tq, Tk) accepted; the bias gradient materializes the softmax
cotangent ds, O(B·H·T²) — the price of a dense bias); probability dropout
uses the TPU PRNG seeded per (batch, head, q-block, k-block) tile so the
backward kernels regenerate the identical mask; block sizes are tunable
per call. On CPU the kernels run in interpret mode, except dropout which
takes a dense XLA path (pltpu PRNG is TPU-only).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as onp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# CompilerParams was named TPUCompilerParams before jax 0.5
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# all three kernels accumulate over their LAST grid axis only; telling
# Mosaic the rest are parallel lets it pipeline/reorder grid steps
_GRID_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _prec(dtype):
    """Explicit dot precision per operand dtype: the kernel's contract is
    bf16 MXU passes for low-precision inputs and exact fp32 for f32 —
    INDEPENDENT of the global jax_default_matmul_precision (a global
    'highest' would otherwise request an fp32 contract on bf16 operands,
    which Mosaic rejects with 'Bad lhs type')."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _bias_spec(bias_shape, block_q, block_k, kv_major: bool = False):
    """Bias streams like K/V. A Tq-broadcast bias (B/1, H/1, 1, Tk) —
    the canonical BERT key-padding mask — ships as (1, block_k) rows
    that broadcast over the q tile inside the kernel; a full bias ships
    (block_q, block_k) tiles. ``kv_major`` flips the grid argument
    order for the dkv kernel's (b, h, ik, iq) grid."""
    Bb, Hb, Tqb = bias_shape[0], bias_shape[1], bias_shape[2]

    def idx(b, h, x, y):
        i, j = (y, x) if kv_major else (x, y)
        return (b if Bb > 1 else 0, h if Hb > 1 else 0,
                0 if Tqb == 1 else i, j)

    if Tqb == 1:
        return pl.BlockSpec((1, 1, 1, block_k), idx)
    return pl.BlockSpec((1, 1, block_q, block_k), idx)


def _dropout_keep(seed_ref, b, h, iq, ik, rate, shape):
    """Regenerable keep-mask for one (q-block, k-block) tile: seeding is a
    pure function of (user seed, batch, head, q-block, k-block), so the
    dq/dkv kernels rebuild the identical mask. Mosaic caps prng_seed at
    two words, so the tile coordinates fold in arithmetically (int32
    wraparound is deterministic)."""
    mix0 = seed_ref[0] + b * jnp.int32(1000003) + h * jnp.int32(7919)
    mix1 = seed_ref[1] + iq * jnp.int32(65537) + ik
    pltpu.prng_seed(mix0, mix1)
    bits = pltpu.prng_random_bits(shape)
    threshold = jnp.uint32(min(0xFFFFFFFF, int(rate * 4294967296.0)))
    return bits.astype(jnp.uint32) >= threshold


def _causal_branches(causal, iq, ik, block_q, block_k, kv_len, tile,
                     skipped=None):
    """Dispatch one grid step to the right specialization of ``tile``:

    - fully-masked tiles (above the causal diagonal) execute NOTHING —
      at T=1024/128-blocks this halves the kernel's matmul work, the
      reason a causal flash kernel can beat XLA's full-T² attention;
    - interior tiles (fully below the diagonal, inside kv range) skip
      the iota/compare/where masking entirely;
    - only diagonal-straddling or kv-padded tiles pay the masked path.
    All conditions are scalar functions of the grid ids, so Mosaic
    executes exactly one branch per step."""
    need_kv = (ik + 1) * block_k > kv_len
    if causal:
        live = ik * block_k <= (iq + 1) * block_q - 1
        need_mask = jnp.logical_or(
            (ik + 1) * block_k - 1 > iq * block_q, need_kv)

        @pl.when(jnp.logical_and(live, jnp.logical_not(need_mask)))
        def _fast():
            tile(False)

        @pl.when(jnp.logical_and(live, need_mask))
        def _masked():
            tile(True)

        if skipped is not None:
            @pl.when(jnp.logical_not(live))
            def _skip():
                skipped()
    else:
        @pl.when(jnp.logical_not(need_kv))
        def _fast():
            tile(False)

        @pl.when(need_kv)
        def _masked():
            tile(True)


def _flash_fwd_kernel(*refs, scale: float, causal: bool, block_q: int,
                      block_k: int, kv_len: int, num_k_blocks: int,
                      has_bias: bool, rate: float):
    i = 0
    q_ref, kt_ref, v_ref = refs[0], refs[1], refs[2]
    i = 3
    bias_ref = refs[i] if has_bias else None
    i += 1 if has_bias else 0
    seed_ref = refs[i] if rate > 0 else None
    i += 1 if rate > 0 else 0
    o_ref, lse_ref = refs[i], refs[i + 1]
    if num_k_blocks > 1:
        acc_ref, m_ref, l_ref = refs[i + 2:i + 5]

    b = pl.program_id(0)
    h = pl.program_id(1)
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    if num_k_blocks == 1:
        # single-block specialization (every T <= block_k): the whole K
        # is in this tile, so the online-softmax carry (acc rescale,
        # running m/l scratch reads/writes) is pure overhead — a plain
        # row softmax computes the exact same result ~15% faster.
        def tile1(apply_mask):
            q = q_ref[0, 0]
            kt = kt_ref[0, 0]
            v = v_ref[0, 0]
            s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=_prec(q.dtype)) * scale
            if has_bias:
                s = s + bias_ref[0, 0].astype(jnp.float32)
            if apply_mask:
                col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
                mask = col < kv_len
                if causal:
                    row = iq * block_q + jax.lax.broadcasted_iota(
                        jnp.int32, s.shape, 0)
                    mask = jnp.logical_and(mask, col <= row)
                s = jnp.where(mask, s, _NEG_INF)
            m = jnp.max(s, axis=1, keepdims=True)
            p = jnp.exp(s - m)
            l = jnp.sum(p, axis=1, keepdims=True)
            if rate > 0:
                keep = _dropout_keep(seed_ref, b, h, iq, ik, rate,
                                     p.shape)
                p = jnp.where(keep, p / (1.0 - rate), 0.0)
            acc = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(v.dtype))
            denom = jnp.maximum(l, 1e-30)
            o_ref[0, 0] = (acc / denom).astype(o_ref.dtype)
            lse_ref[0, 0] = m + jnp.log(denom)

        _causal_branches(causal, iq, ik, block_q, block_k, kv_len, tile1)
        return

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def tile(apply_mask):
        q = q_ref[0, 0]                               # (bq, d) input dtype
        kt = kt_ref[0, 0]                             # (d, bk) PRE-transposed
        v = v_ref[0, 0]                               # (bk, d)
        # matmuls run in the INPUT dtype (bf16 MXU rate is 2-4x f32) with
        # f32 accumulation; scale applies to the f32 product.  K arrives
        # PRE-TRANSPOSED (r5): contracting over the rhs's LANE dim (the
        # q@k^T 'nt' form) makes Mosaic transpose k inside every grid
        # step — a measured 27% of the whole fwd kernel at BERT shapes;
        # the one XLA-side swapaxes outside the kernel costs ~0.2 ms
        # and every step's matmul becomes MXU-native.
        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype)) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if apply_mask:
            col = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            mask = col < kv_len
            if causal:
                row = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                mask = jnp.logical_and(mask, col <= row)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]                           # (bq, 1)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # (bq, bk) f32
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if rate > 0:
            keep = _dropout_keep(seed_ref, b, h, iq, ik, rate, p.shape)
            p = jnp.where(keep, p / (1.0 - rate), 0.0)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(v.dtype))
        m_ref[...] = m_new

    _causal_branches(causal, iq, ik, block_q, block_k, kv_len, tile)

    @pl.when(ik == num_k_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)
        # lse rides as (B, H, T, 1): a trailing unit dim keeps the block
        # shape (block_q, 1) legal under TPU (8, 128) tiling rules
        lse_ref[0, 0] = m_ref[...] + jnp.log(denom)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _legal_blocks(block_q, block_k, Tq, Tk, interpret):
    """TPU tiling legality: a block's trailing dim must be a multiple
    of 128 or the whole (padded) axis, second-to-last a multiple of 8
    or whole.  Since r5 the K/V operands ship PRE-TRANSPOSED, putting
    ``block_k`` on the LANE dim of the (D, block_k) kT/vT blocks — so
    the constraint applies to EVERY call, not only blocked-bias ones:
    odd tunable blocks collapse to whole-axis blocks (same math, one
    block).  Interpret mode (CPU) keeps the requested blocks for
    multi-block coverage."""
    if not interpret:
        if block_k % 128:
            block_k = Tk
        if block_q % 8:
            block_q = Tq
    return block_q, block_k


def _pad_bias(bias, block_q, block_k):
    if bias.shape[2] == 1:          # Tq-broadcast row bias: pad Tk only
        return _pad_to(bias, 3, block_k)
    return _pad_to(_pad_to(bias, 2, block_q), 3, block_k)


def _flash_forward(q, k, v, bias, seed, scale: float, causal: bool,
                   block_q: int, block_k: int, rate: float,
                   interpret: bool):
    """q/k/v: (B, H, T, D). Returns ((B, H, Tq, D), lse (B, H, Tq, 1))."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    has_bias = bias is not None
    block_q, block_k = _legal_blocks(block_q, block_k, Tq, Tk,
                                     interpret)
    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    Tq_p, Tk_p = qp.shape[2], kp.shape[2]
    n_q, n_k = Tq_p // block_q, Tk_p // block_k
    # K ships PRE-TRANSPOSED (one XLA copy) so the in-kernel q@k^T is an
    # MXU-native 'nn' contraction — see _flash_fwd_kernel
    ktp = jnp.swapaxes(kp, 2, 3)                      # (B, H, D, Tk_p)

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tk, num_k_blocks=n_k, has_bias=has_bias,
        rate=rate)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, D, block_k), lambda b, h, i, j: (b, h, 0, j)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
    ]
    args = [qp, ktp, vp]
    if has_bias:
        bp = _pad_bias(bias, block_q, block_k)
        in_specs.append(_bias_spec(bias.shape, block_q, block_k))
        args.append(bp)
    if rate > 0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)

    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq_p, 1), jnp.float32),
        ],
        # the single-block specialization needs no online-softmax carry —
        # don't reserve VMEM it never touches
        scratch_shapes=([] if n_k == 1 else [
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
        ]),
        interpret=interpret,
        compiler_params=_GRID_SEMANTICS,
    )(*args)
    return out[:, :, :Tq], lse[:, :, :Tq]


def _flash_bwd_dq_kernel(*refs, scale: float, causal: bool, block_q: int,
                         block_k: int, kv_len: int, num_k_blocks: int,
                         has_bias: bool, rate: float, emit_ds: bool):
    i = 0
    (q_ref, k_ref, kt_ref, vt_ref, do_ref, lse_ref,
     delta_ref) = refs[:7]
    i = 7
    bias_ref = refs[i] if has_bias else None
    i += 1 if has_bias else 0
    seed_ref = refs[i] if rate > 0 else None
    i += 1 if rate > 0 else 0
    dq_ref = refs[i]
    ds_ref = refs[i + 1] if emit_ds else None
    dq_acc = refs[i + 2] if emit_ds else refs[i + 1]

    b = pl.program_id(0)
    h = pl.program_id(1)
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def tile(apply_mask):
        q = q_ref[0, 0]                                # (bq, d) input dtype
        k = k_ref[0, 0]                                # (bk, d)
        kt = kt_ref[0, 0]                              # (d, bk)
        vt = vt_ref[0, 0]                              # (d, bk)
        do = do_ref[0, 0]                              # (bq, d)
        lse = lse_ref[0, 0]                            # (bq, 1)
        delta = delta_ref[0, 0]                        # (bq, 1)

        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype)) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        p = jnp.exp(s - lse)                           # (bq, bk) f32
        if apply_mask:
            col = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            mask = col < kv_len
            if causal:
                row = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                mask = jnp.logical_and(mask, col <= row)
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(do, vt, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_prec(vt.dtype))
        if rate > 0:
            keep = _dropout_keep(seed_ref, b, h, iq, ik, rate, p.shape)
            dp = jnp.where(keep, dp / (1.0 - rate), 0.0)
        ds0 = p * (dp - delta)                         # dsoftmax (no scale)
        if emit_ds:
            ds_ref[0, 0] = ds0.astype(ds_ref.dtype)
        ds = (ds0 * scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(k.dtype))

    def skipped():
        if emit_ds:
            ds_ref[0, 0] = jnp.zeros_like(ds_ref[0, 0])

    _causal_branches(causal, iq, ik, block_q, block_k, kv_len, tile,
                     skipped=skipped if emit_ds else None)

    @pl.when(ik == num_k_blocks - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_fused_kernel(*refs, scale: float, causal: bool,
                            block_q: int, block_k: int, kv_len: int,
                            num_q_blocks: int, has_bias: bool,
                            rate: float, emit_ds: bool):
    """Single-pass backward for the n_k == 1 regime (Tk fits one k-block
    — every T <= block_k, i.e. all BERT/GPT headline shapes under the
    default 1024 block).  The two-pass recipe pays two kernel launches
    that each re-read q/k/v and re-compute the probabilities; here one
    grid (B, H, n_q) computes s and p ONCE per q-tile, emits dq directly
    (the whole K is resident, so dq needs no cross-block accumulation),
    and accumulates dk/dv in VMEM scratch over the sequential q axis.
    K/V block specs are constant in iq, so Mosaic keeps them in VMEM
    across the whole (b, h) pass — q/k/v stream exactly once.  K rides
    twice (original for ds@k, pre-transposed for q@k^T) and V rides
    only pre-transposed (do@v^T) — r5: shipping the transposed forms
    keeps every matmul MXU-native instead of paying an in-kernel
    transpose per grid step."""
    (q_ref, k_ref, kt_ref, vt_ref, do_ref, lse_ref,
     delta_ref) = refs[:7]
    i = 7
    bias_ref = refs[i] if has_bias else None
    i += 1 if has_bias else 0
    seed_ref = refs[i] if rate > 0 else None
    i += 1 if rate > 0 else 0
    dq_ref, dk_ref, dv_ref = refs[i:i + 3]
    i += 3
    ds_ref = refs[i] if emit_ds else None
    i += 1 if emit_ds else 0
    dk_acc, dv_acc = refs[i:i + 2]

    b = pl.program_id(0)
    h = pl.program_id(1)
    iq = pl.program_id(2)
    ik = 0                          # the single k block

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def tile(apply_mask):
        q = q_ref[0, 0]                                # (bq, d) input dtype
        k = k_ref[0, 0]                                # (Tk, d)
        kt = kt_ref[0, 0]                              # (d, Tk)
        vt = vt_ref[0, 0]                              # (d, Tk)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                            # (bq, 1)
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype)) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        p = jnp.exp(s - lse)                           # (bq, Tk) f32
        if apply_mask:
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = col < kv_len
            if causal:
                row = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                mask = jnp.logical_and(mask, col <= row)
            p = jnp.where(mask, p, 0.0)
        p_drop = p
        dp = jax.lax.dot_general(do, vt, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_prec(vt.dtype))
        if rate > 0:
            keep = _dropout_keep(seed_ref, b, h, iq, ik, rate, p.shape)
            inv = 1.0 / (1.0 - rate)
            p_drop = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        # dv += p_drop^T do
        dv_acc[...] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(do.dtype))
        ds0 = p * (dp - delta)                         # dsoftmax (no scale)
        if emit_ds:
            ds_ref[0, 0] = ds0.astype(ds_ref.dtype)
        ds = (ds0 * scale).astype(k.dtype)
        # dq for this q-tile is COMPLETE (all of K is here): write direct
        dq_ref[0, 0] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(k.dtype)).astype(dq_ref.dtype)
        # dk += ds^T q
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q.dtype))

    # every q-tile is live against the single k block (causal row 0 still
    # sees column 0), so no skipped branch exists — dq/ds are written on
    # every grid step.  ik rides as a traced 0 so the branch predicates
    # stay scalar-traced like the two-pass kernels'.
    _causal_branches(causal, iq, jnp.int32(0), block_q, block_k, kv_len,
                     tile)

    @pl.when(iq == num_q_blocks - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, scale: float, causal: bool, block_q: int,
                          block_k: int, kv_len: int, num_q_blocks: int,
                          has_bias: bool, rate: float):
    q_ref, kt_ref, vt_ref, do_ref, lse_ref, delta_ref = refs[:6]
    i = 6
    bias_ref = refs[i] if has_bias else None
    i += 1 if has_bias else 0
    seed_ref = refs[i] if rate > 0 else None
    i += 1 if rate > 0 else 0
    dk_ref, dv_ref, dk_acc, dv_acc = refs[i:i + 4]

    b = pl.program_id(0)
    h = pl.program_id(1)
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def tile(apply_mask):
        q = q_ref[0, 0]                                # (bq, d) input dtype
        kt = kt_ref[0, 0]                              # (d, bk)
        vt = vt_ref[0, 0]                              # (d, bk)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype)) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        p = jnp.exp(s - lse)                           # (bq, bk) f32
        if apply_mask:
            col = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            mask = col < kv_len
            if causal:
                row = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0)
                mask = jnp.logical_and(mask, col <= row)
            p = jnp.where(mask, p, 0.0)
        p_drop = p
        dp = jax.lax.dot_general(do, vt, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=_prec(vt.dtype))
        if rate > 0:
            keep = _dropout_keep(seed_ref, b, h, iq, ik, rate, p.shape)
            inv = 1.0 / (1.0 - rate)
            p_drop = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        # dv += p_drop^T do
        dv_acc[...] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(do.dtype))
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        # dk += ds^T q
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q.dtype))

    _causal_branches(causal, iq, ik, block_q, block_k, kv_len, tile)

    @pl.when(iq == num_q_blocks - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, bias, seed, o, lse, g, scale: float,
                    causal: bool, block_q: int, block_k: int, rate: float,
                    interpret: bool, bias_grad: bool = True):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    has_bias = bias is not None
    block_q, block_k = _legal_blocks(block_q, block_k, Tq, Tk,
                                     interpret)
    # a non-learned mask bias skips the O(B*H*T^2) ds materialization —
    # the whole point of a flash kernel for long contexts
    want_dbias = has_bias and bias_grad
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # (B, H, Tq, 1)
    qp = _pad_to(q, 2, block_q)
    dop = _pad_to(g, 2, block_q)
    lsep = _pad_to(lse, 2, block_q)
    deltap = _pad_to(delta, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    Tq_p, Tk_p = qp.shape[2], kp.shape[2]
    n_q, n_k = Tq_p // block_q, Tk_p // block_k
    # pre-transposed K/V (one XLA copy each): every s = q@k^T and
    # dp = do@v^T inside the kernels becomes an MXU-native 'nn'
    # contraction instead of paying a per-grid-step Mosaic transpose
    ktp = jnp.swapaxes(kp, 2, 3)                      # (B, H, D, Tk_p)
    vtp = jnp.swapaxes(vp, 2, 3)

    if n_k == 1:
        # single k-block regime (every T <= block_k): ONE fused pass
        # computes dq/dk/dv — halves the backward's kernel launches,
        # q/k/v reads, and probability recomputes.  This is what moves
        # the flash-vs-XLA crossover down to BERT fine-tuning lengths
        # (VERDICT r4 directive 3).
        fused_in_specs = [
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),      # q
            pl.BlockSpec((1, 1, Tk_p, D),
                         lambda b, h, i: (b, h, 0, 0)),      # k (resident)
            pl.BlockSpec((1, 1, D, Tk_p),
                         lambda b, h, i: (b, h, 0, 0)),      # k^T (resident)
            pl.BlockSpec((1, 1, D, Tk_p),
                         lambda b, h, i: (b, h, 0, 0)),      # v^T (resident)
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),      # do
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i: (b, h, i, 0)),      # lse
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i: (b, h, i, 0)),      # delta
        ]
        fused_args = [qp, kp, ktp, vtp, dop, lsep, deltap]
        if has_bias:
            Bb, Hb, Tqb = bias.shape[0], bias.shape[1], bias.shape[2]
            bshape = ((1, 1, 1, Tk_p) if Tqb == 1
                      else (1, 1, block_q, Tk_p))
            fused_in_specs.append(pl.BlockSpec(
                bshape,
                lambda b, h, i, Bb=Bb, Hb=Hb, Tqb=Tqb: (
                    b if Bb > 1 else 0, h if Hb > 1 else 0,
                    0 if Tqb == 1 else i, 0)))
            fused_args.append(_pad_bias(bias, block_q, block_k))
        if rate > 0:
            fused_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            fused_args.append(seed)

        fused_out_specs = [
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),      # dq
            pl.BlockSpec((1, 1, Tk_p, D),
                         lambda b, h, i: (b, h, 0, 0)),      # dk
            pl.BlockSpec((1, 1, Tk_p, D),
                         lambda b, h, i: (b, h, 0, 0)),      # dv
        ]
        fused_out_shape = [
            jax.ShapeDtypeStruct((B, H, Tq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tk_p, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Tk_p, D), v.dtype),
        ]
        if want_dbias:
            fused_out_specs.append(pl.BlockSpec(
                (1, 1, block_q, Tk_p), lambda b, h, i: (b, h, i, 0)))
            fused_out_shape.append(
                jax.ShapeDtypeStruct((B, H, Tq_p, Tk_p), jnp.float32))

        outs = pl.pallas_call(
            functools.partial(
                _flash_bwd_fused_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, kv_len=Tk,
                num_q_blocks=n_q, has_bias=has_bias, rate=rate,
                emit_ds=want_dbias),
            grid=(B, H, n_q),
            in_specs=fused_in_specs,
            out_specs=fused_out_specs,
            out_shape=fused_out_shape,
            scratch_shapes=[pltpu.VMEM((Tk_p, D), jnp.float32),   # dk acc
                            pltpu.VMEM((Tk_p, D), jnp.float32)],  # dv acc
            interpret=interpret,
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
        )(*fused_args)
        if want_dbias:
            dq, dk, dv, ds_full = outs
            ds_full = ds_full[:, :, :Tq, :Tk]
            red = tuple(ax for ax, size in enumerate(bias.shape[:3])
                        if size == 1)
            d_bias = (ds_full.sum(axis=red, keepdims=True) if red
                      else ds_full).astype(bias.dtype)
        else:
            dq, dk, dv = outs
            d_bias = None
        return dq[:, :, :Tq], dk[:, :, :Tk], dv[:, :, :Tk], d_bias

    # two-pass path (n_k > 1): dq kernel then dkv kernel
    q_spec = pl.BlockSpec((1, 1, block_q, D),
                          lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, i, j: (b, h, j, 0))
    kt_spec = pl.BlockSpec((1, 1, D, block_k),
                           lambda b, h, i, j: (b, h, 0, j))
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b, h, i, j: (b, h, i, 0))

    in_specs = [q_spec, k_spec, kt_spec, kt_spec, q_spec,
                row_spec, row_spec]
    args = [qp, kp, ktp, vtp, dop, lsep, deltap]
    if has_bias:
        bp = _pad_bias(bias, block_q, block_k)
        in_specs.append(_bias_spec(bias.shape, block_q, block_k))
        args.append(bp)
    if rate > 0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)

    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct((B, H, Tq_p, D), q.dtype)]
    if want_dbias:
        # the softmax cotangent, materialized so d_bias can reduce over
        # broadcast dims — O(B*H*T^2), the price of a LEARNED dense bias
        out_specs.append(pl.BlockSpec((1, 1, block_q, block_k),
                                      lambda b, h, i, j: (b, h, i, j)))
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, Tq_p, Tk_p), jnp.float32))

    dq_out = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          kv_len=Tk, num_k_blocks=n_k, has_bias=has_bias,
                          rate=rate, emit_ds=want_dbias),
        grid=(B, H, n_q, n_k),
        in_specs=in_specs,
        out_specs=out_specs if want_dbias else out_specs[0],
        out_shape=out_shape if want_dbias else out_shape[0],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
        compiler_params=_GRID_SEMANTICS,
    )(*args)
    if want_dbias:
        dq, ds_full = dq_out
    else:
        dq, ds_full = dq_out, None

    # dk/dv: swap the roles — kv blocks on the parallel axis, q blocks
    # sequential
    qs_spec = pl.BlockSpec((1, 1, block_q, D),
                           lambda b, h, j, i: (b, h, i, 0))
    ks_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, j, i: (b, h, j, 0))
    kts_spec = pl.BlockSpec((1, 1, D, block_k),
                            lambda b, h, j, i: (b, h, 0, j))
    rows_spec = pl.BlockSpec((1, 1, block_q, 1),
                             lambda b, h, j, i: (b, h, i, 0))
    in_specs2 = [qs_spec, kts_spec, kts_spec, qs_spec,
                 rows_spec, rows_spec]
    args2 = [qp, ktp, vtp, dop, lsep, deltap]
    if has_bias:
        in_specs2.append(_bias_spec(bias.shape, block_q, block_k,
                                    kv_major=True))
        args2.append(_pad_bias(bias, block_q, block_k))
    if rate > 0:
        in_specs2.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args2.append(seed)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          kv_len=Tk, num_q_blocks=n_q, has_bias=has_bias,
                          rate=rate),
        grid=(B, H, n_k, n_q),
        in_specs=in_specs2,
        out_specs=[ks_spec, ks_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, Tk_p, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Tk_p, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
        compiler_params=_GRID_SEMANTICS,
    )(*args2)

    d_bias = None
    if want_dbias:
        ds_full = ds_full[:, :, :Tq, :Tk]
        # reduce over broadcast dims (incl. a Tq-broadcast row bias's
        # query axis) back to the bias shape
        red = tuple(ax for ax, size in enumerate(bias.shape[:3])
                    if size == 1)
        d_bias = ds_full.sum(axis=red, keepdims=True) if red else ds_full
        d_bias = d_bias.astype(bias.dtype)
    return dq[:, :, :Tq], dk[:, :, :Tk], dv[:, :, :Tk], d_bias


def _dense_reference(q, k, v, scale: float, causal: bool, bias=None):
    """O(T^2) reference in plain XLA."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        # top-left alignment (col <= row), matching the kernel's mask
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _interpret_for(*arrays) -> bool:
    """Should the kernels run in interpret mode?

    Concrete (eager) operands: decide by their committed device — under
    the axon tunnel, eager default-ctx arrays live on XLA:CPU even though
    ``jax.default_backend()`` says tpu, and a Mosaic lowering there would
    fail. Tracers: jit compiles for the default backend. (A jit whose
    ARGUMENTS are host-committed still lowers for CPU with tracers inside
    — callers targeting the chip must device_put their args, as
    __graft_entry__.entry does.)"""
    for a in arrays:
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer):
            try:
                return next(iter(a.devices())).platform == "cpu"
            except Exception:
                continue
    return jax.default_backend() == "cpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash2(q, k, v, bias, seed, rate, scale, causal, block_q, block_k,
            bias_grad=True):
    out, _ = _flash_forward(q, k, v, bias, seed, scale, causal, block_q,
                            block_k, rate, _interpret_for(q))
    return out


def _flash2_fwd(q, k, v, bias, seed, rate, scale, causal, block_q,
                block_k, bias_grad=True):
    out, lse = _flash_forward(q, k, v, bias, seed, scale, causal, block_q,
                              block_k, rate, _interpret_for(q))
    return out, (q, k, v, bias, seed, out, lse)


def _flash2_bwd(rate, scale, causal, block_q, block_k, bias_grad, res, g):
    q, k, v, bias, seed, o, lse = res
    dq, dk, dv, d_bias = _flash_backward(
        q, k, v, bias, seed, o, lse, g, scale, causal, block_q, block_k,
        rate, _interpret_for(q, g), bias_grad=bias_grad)
    if d_bias is None and bias is not None:
        d_bias = jnp.zeros_like(bias)
    d_seed = None if seed is None else \
        onp.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, d_bias, d_seed


_flash2.defvjp(_flash2_fwd, _flash2_bwd)


# measured optimum on v5e (benchmark/attn_probe.py sweep, r3): tall
# q-blocks over full-width k-blocks, clamped to T per call. Single source
# of truth — ops/transformer.py's env-var defaults read these too.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 1024


def flash_attention(q, k, v, scale: Optional[float] = None,
                    causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    bias=None, dropout: float = 0.0,
                    dropout_seed=None, bias_grad: bool = True):
    """Flash attention over (B, T, H, D) inputs (jax layout convention).

    bias: additive score bias/mask of shape (1|B, 1|H, Tq, Tk) — the two
    leading dims may broadcast, the trailing two must be full-size.
    bias_grad=False marks a non-learned mask: its gradient is skipped,
    avoiding the O(B*H*T^2) softmax-cotangent materialization.
    dropout: probability-dropout rate on the attention weights;
    dropout_seed: int32 array of shape (2,) (derive from a threefry key);
    required when dropout > 0. On CPU, dropout falls back to the dense
    XLA path (the TPU PRNG has no interpret-mode implementation).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if bias is not None and (bias.ndim != 4 or
                             bias.shape[2] not in (1, q.shape[1]) or
                             bias.shape[3] != k.shape[1]):
        raise ValueError(
            f"flash_attention bias must be (1|B, 1|H, 1|Tq, Tk); got "
            f"{bias.shape} for Tq={q.shape[1]}, Tk={k.shape[1]} — "
            "the trailing key dim must be full-size")
    # kernel blocks over (B, H, T, D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    block_q = min(block_q, max(qt.shape[2], 8))
    block_k = min(block_k, max(kt.shape[2], 8))
    rate = float(dropout)
    if rate > 0 and dropout_seed is None:
        raise ValueError("flash_attention: dropout > 0 needs dropout_seed")
    if rate > 0 and _interpret_for(qt):
        # dense differentiable fallback with jax-level dropout — same
        # platform decision as the kernels (the TPU PRNG has no
        # interpret-mode implementation)
        out = dense_dropout_attention_bhtd(
            qt, kt, vt, bias, jnp.asarray(dropout_seed, jnp.int32), rate,
            float(scale), bool(causal))
        return jnp.swapaxes(out, 1, 2)
    seed = None if rate == 0 else jnp.asarray(dropout_seed, jnp.int32)
    out = _flash2(qt, kt, vt, bias, seed, rate, float(scale), bool(causal),
                  int(block_q), int(block_k), bool(bias_grad))
    return jnp.swapaxes(out, 1, 2)


def dense_dropout_attention_bhtd(q, k, v, bias, seed, rate, scale, causal):
    """Plain-XLA attention with probability dropout over (B, H, T, D)
    operands — the shared differentiable fallback for platforms/paths
    without the Pallas kernel. ``seed`` is a (2,) int32 array."""
    key = jax.random.wrap_key_data(
        jnp.asarray(seed, jnp.uint32).reshape(2,), impl="threefry2x32")
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        m = jnp.tril(jnp.ones((Tq, Tk), bool))
        s = jnp.where(m, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    keep = jax.random.bernoulli(key, 1.0 - rate, p.shape)
    p = jnp.where(keep, p / (1.0 - rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
