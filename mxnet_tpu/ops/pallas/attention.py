"""Flash attention — blockwise online-softmax Pallas kernel.

Reference parity (leezu/mxnet): the reference's attention is full O(T²)
materialized scores (``src/operator/contrib/transformer.cu``); this kernel
is the TPU-native upgrade (SURVEY.md 5.7): tiles of Q stream over tiles of
K/V held in VMEM with a running max/denominator, so scores never hit HBM.

Forward is the Pallas kernel (grid B×H×Tq-blocks×Tk-blocks, sequential
accumulation over the last grid axis in VMEM scratch), emitting the
per-row log-sum-exp. Backward is blockwise too (standard flash-attention
recipe): a dq kernel streams K/V blocks against the saved LSE and
``delta = rowsum(dO·O)``, and a dk/dv kernel streams Q/dO blocks — scores
are recomputed per tile and never hit HBM in either direction. On CPU the
kernels run in interpret mode, keeping tests meaningful.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *,
                      scale: float, causal: bool, block_q: int,
                      block_k: int, kv_len: int, num_k_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0].astype(jnp.float32) * scale       # (bq, d)
    k = k_ref[0, 0].astype(jnp.float32)               # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)               # (bk, d)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bk)

    # mask out-of-range (padded) kv columns, and the future when causal
    col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = col < kv_len
    if causal:
        row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        mask = jnp.logical_and(mask, col <= row)
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[...]                               # (bq, 1)
    l_prev = l_ref[...]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                            # (bq, bk)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(ik == num_k_blocks - 1)
    def _finish():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)
        # lse rides as (B, H, T, 1): a trailing unit dim keeps the block
        # shape (block_q, 1) legal under TPU (8, 128) tiling rules
        lse_ref[0, 0] = m_ref[...] + jnp.log(denom)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, scale: float, causal: bool,
                   block_q: int, block_k: int, interpret: bool):
    """q/k/v: (B, H, T, D). Returns ((B, H, Tq, D), lse (B, H, Tq, 1)).

    lse keeps its trailing unit dim end-to-end (kernel block layout is
    (block_q, 1)); it is a custom-vjp residual only.
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    Tq_p, Tk_p = qp.shape[2], kp.shape[2]
    n_q, n_k = Tq_p // block_q, Tk_p // block_k

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=Tk, num_k_blocks=n_k)

    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :Tq], lse[:, :, :Tq]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, scale: float, causal: bool,
                         block_q: int, block_k: int, kv_len: int,
                         num_k_blocks: int):
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                # (bq, d)
    k = k_ref[0, 0].astype(jnp.float32)                # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)              # (bq, d)
    lse = lse_ref[0, 0]                                # (bq, 1)
    delta = delta_ref[0, 0]                            # (bq, 1)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = col < kv_len
    if causal:
        row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        mask = jnp.logical_and(mask, col <= row)
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)         # (bq, bk)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    dq_acc[...] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ik == num_k_blocks - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                          causal: bool, block_q: int, block_k: int,
                          kv_len: int, num_q_blocks: int):
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    ik = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                # (bq, d)
    k = k_ref[0, 0].astype(jnp.float32)                # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    col = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = col < kv_len
    if causal:
        row = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        mask = jnp.logical_and(mask, col <= row)
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)         # (bq, bk)
    # dv += p^T do
    dv_acc[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    # dk += ds^T q
    dk_acc[...] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(iq == num_q_blocks - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, scale: float, causal: bool,
                    block_q: int, block_k: int, interpret: bool):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)            # (B, H, Tq, 1)
    qp = _pad_to(q, 2, block_q)
    dop = _pad_to(g, 2, block_q)
    lsep = _pad_to(lse, 2, block_q)
    deltap = _pad_to(delta, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    Tq_p, Tk_p = qp.shape[2], kp.shape[2]
    n_q, n_k = Tq_p // block_q, Tk_p // block_k

    q_spec = pl.BlockSpec((1, 1, block_q, D),
                          lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, i, j: (b, h, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          kv_len=Tk, num_k_blocks=n_k),
        grid=(B, H, n_q, n_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tq_p, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    # dk/dv: swap the roles — kv blocks on the parallel axis, q blocks
    # sequential
    qs_spec = pl.BlockSpec((1, 1, block_q, D),
                           lambda b, h, j, i: (b, h, i, 0))
    ks_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, j, i: (b, h, j, 0))
    rows_spec = pl.BlockSpec((1, 1, block_q, 1),
                             lambda b, h, j, i: (b, h, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, block_q=block_q, block_k=block_k,
                          kv_len=Tk, num_q_blocks=n_q),
        grid=(B, H, n_k, n_q),
        in_specs=[qs_spec, ks_spec, ks_spec, qs_spec, rows_spec,
                  rows_spec],
        out_specs=[ks_spec, ks_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, Tk_p, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Tk_p, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)
    return dq[:, :, :Tq], dk[:, :, :Tk], dv[:, :, :Tk]


def _dense_reference(q, k, v, scale: float, causal: bool):
    """O(T^2) reference in plain XLA (used for the backward pass)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        # top-left alignment (col <= row), matching the kernel's mask
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    interpret = jax.default_backend() == "cpu"
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                            interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    interpret = jax.default_backend() == "cpu"
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    interpret = jax.default_backend() == "cpu"
    return _flash_backward(q, k, v, o, lse, g, scale, causal, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128):
    """Flash attention over (B, T, H, D) inputs (jax layout convention)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # kernel blocks over (B, H, T, D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    block_q = min(block_q, max(qt.shape[2], 8))
    block_k = min(block_k, max(kt.shape[2], 8))
    out = _flash(qt, kt, vt, float(scale), bool(causal),
                 int(block_q), int(block_k))
    return jnp.swapaxes(out, 1, 2)
