"""Prologue-fused 1x1 convolution — the BN apply + ReLU folded into the
GEMM's operand read.

Reference parity (leezu/mxnet): the reference materializes every
``Convolution -> BatchNorm -> Activation`` junction through HBM
(``src/operator/nn/convolution.cc`` dispatches cuDNN per op;
``MXNET_SUBGRAPH_BACKEND`` fusion only covers pointwise chains).  On TPU
the ResNet-50 step is HBM-bound (BASELINE.md bandwidth roofline;
``benchmark/resnet_layer_probe.py``): every pass over an activation
tensor costs ~1/850 GB/s, and XLA cannot fuse producers into a
convolution's operand.  A 1x1 stride-1 convolution IS a GEMM, so Pallas
can: these kernels compute ``y = w @ f(x)`` where ``f`` (per-channel
affine = the BN apply, then ReLU) runs on the VMEM tile as it streams in
— the activated tensor never exists in HBM, forward or backward.

Savings per fused junction (vs the unfused chain): forward skips the
apply/ReLU write and the conv's read of it (2 HBM passes over the
activation); backward recomputes the ReLU mask and the wgrad operand
from ``x`` instead of saving ``f(x)`` (halves residual memory and skips
the separate relu-backward pass).

Kernel forms follow docs/performance.md rules: the forward contraction
is 'nn' (w's lane dim x h's sublane dim), dgrad is 'tn' (both sublane)
— MXU-native, no in-kernel transposes; wgrad contracts over the lane
(spatial) dim, the one unavoidable 'nt'.  Accumulation always runs over
the LAST grid axis (axes marked arbitrary), partials in f32 VMEM
scratch, with a no-scratch specialization when one block covers the
contraction.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# CompilerParams was named TPUCompilerParams before jax 0.5
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


def _prec(dtype):
    """bf16 MXU passes for low-precision inputs, exact fp32 for f32 —
    independent of the global jax_default_matmul_precision (see
    attention.py _prec)."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _block(dim: int, want: int, lane: bool, interpret: bool) -> int:
    """Legal block size for a BLOCKED (ci/co) axis: must divide the dim
    exactly (these axes are contracted or accumulator-indexed — a ragged
    block would silently drop channels), and lane dims need a multiple
    of 128, sublane dims a multiple of 8.  Falls back to the whole axis."""
    if dim <= want:
        return dim
    if dim % want:
        return dim
    if interpret:
        return want
    if lane:
        return want if want % 128 == 0 else dim
    return want if want % 8 == 0 else dim


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _choose_blocks(Ci, Co, M, interpret, block_co, block_m, block_ci):
    """Whole-M spatial blocks whenever VMEM allows: with m untiled the
    weight block is fetched once per co-block for the WHOLE batch (the
    grid runs batch inside co — weight-stationary), instead of once per
    (n, m) step.  Channel blocks shrink for big M to keep tiles ~1.6MB."""
    if M <= 1024:
        return (_block(Co, block_co, False, interpret), M,
                _block(Ci, block_ci, True, interpret))
    if M <= 4096:
        return (_block(Co, 128, False, interpret), M,
                _block(Ci, 128, True, interpret))
    return (_block(Co, block_co, False, interpret),
            block_m,
            _block(Ci, block_ci, True, interpret))


def fusion_profitable(N: int, Ci: int, Co: int, M: int) -> bool:
    """Traffic economics of the fused junction: the prologue saves ~2
    HBM passes over the (Ci, M) activation per sample, while the GEMM
    kernels re-read the (Co, Ci) weight once per sample (vs once total
    for XLA's batched conv).  Benefit 4*N*Ci*M bytes vs cost ~2*N*Co*Ci
    → fuse iff 2*M >= Co.  (ResNet-50 b128: stages 1-2 and stage-3 j1
    qualify — exactly where the per-stage attribution puts the time;
    stage 4 is weight-dominated and stays on XLA.)"""
    return 2 * M >= Co


def _prologue(x_ref, scale_ref, shift_ref, relu: bool):
    """f(x) on the streamed-in tile: per-channel affine (the BN apply),
    then ReLU.  x tile is (1, ci, m); scale/shift are (ci, 1) columns
    that broadcast over the spatial lanes."""
    a = x_ref[0].astype(jnp.float32)
    if scale_ref is not None:
        a = a * scale_ref[...] + shift_ref[...]
    if relu:
        a = jnp.maximum(a, 0.0)
    return a


# ---------------------------------------------------------------------------
# forward: y[n] = w @ f(x[n])   (grid co, n, m, ci — accumulate over ci;
# n INSIDE co keeps the w block resident across the whole batch)
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, n_ci: int, relu: bool, affine: bool, bias: bool,
                prec):
    refs = list(refs)
    scale_ref = refs.pop(0) if affine else None
    shift_ref = refs.pop(0) if affine else None
    x_ref, w_ref = refs.pop(0), refs.pop(0)
    bias_ref = refs.pop(0) if bias else None
    y_ref = refs.pop(0)
    h = _prologue(x_ref, scale_ref, shift_ref, relu).astype(w_ref.dtype)
    part = lax.dot_general(w_ref[...], h, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=prec)

    def _emit(val):
        if bias_ref is not None:
            val = val + bias_ref[...]      # (co, 1) broadcast over lanes
        y_ref[0] = val.astype(y_ref.dtype)

    if n_ci == 1:
        _emit(part)
        return
    acc_ref, = refs
    i_ci = pl.program_id(3)

    @pl.when(i_ci == 0)
    def _init():
        acc_ref[...] = part

    @pl.when(i_ci > 0)
    def _acc():
        acc_ref[...] += part

    @pl.when(i_ci == n_ci - 1)
    def _out():
        _emit(acc_ref[...])


def _fwd(x3, scale2, shift2, w, relu, interpret, bias2=None,
         block_co=256, block_m=512, block_ci=256):
    N, Ci, M = x3.shape
    Co = w.shape[0]
    affine = scale2 is not None
    # the spatial axis is never padded (a jnp.pad would cost a full HBM
    # copy of x, wiping out the fusion's savings): m is not contracted
    # here, so the ragged last block's garbage lanes land in dropped
    # output lanes
    block_co, block_m, block_ci = _choose_blocks(
        Ci, Co, M, interpret, block_co, block_m, block_ci)
    n_m, n_ci, n_co = _ceil_div(M, block_m), Ci // block_ci, Co // block_co

    kernel = functools.partial(_fwd_kernel, n_ci=n_ci, relu=relu,
                               affine=affine, bias=bias2 is not None,
                               prec=_prec(x3.dtype))
    in_specs = []
    args = []
    if affine:
        in_specs += [
            pl.BlockSpec((block_ci, 1), lambda co, n, m, ci: (ci, 0)),
            pl.BlockSpec((block_ci, 1), lambda co, n, m, ci: (ci, 0)),
        ]
        args += [scale2, shift2]
    in_specs += [
        pl.BlockSpec((1, block_ci, block_m),
                     lambda co, n, m, ci: (n, ci, m)),
        pl.BlockSpec((block_co, block_ci),
                     lambda co, n, m, ci: (co, ci)),
    ]
    args += [x3, w]
    if bias2 is not None:
        in_specs.append(
            pl.BlockSpec((block_co, 1), lambda co, n, m, ci: (co, 0)))
        args.append(bias2)
    y = pl.pallas_call(
        kernel,
        grid=(n_co, N, n_m, n_ci),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_co, block_m),
                               lambda co, n, m, ci: (n, co, m)),
        out_shape=jax.ShapeDtypeStruct((N, Co, M), x3.dtype),
        scratch_shapes=([] if n_ci == 1 else
                        [pltpu.VMEM((block_co, block_m), jnp.float32)]),
        interpret=interpret,
        compiler_params=_CompilerParams(dimension_semantics=(
            "parallel", "parallel", "parallel", "arbitrary")),
    )(*args)
    return y


# ---------------------------------------------------------------------------
# dgrad: da[n] = (w^T @ dy[n]) * relu'(a)   (grid ci, n, m, co — acc over
# co; n inside ci keeps the w block batch-resident).  The mask is
# recomputed from x in the LAST co step's epilogue, so the activated
# tensor is never read from (or written to) HBM
# ---------------------------------------------------------------------------

def _dgrad_kernel(*refs, n_co: int, relu: bool, affine: bool, prec):
    if affine:
        scale_ref, shift_ref, x_ref, dy_ref, w_ref, da_ref = refs[:6]
        rest = refs[6:]
    else:
        x_ref, dy_ref, w_ref, da_ref = refs[:4]
        scale_ref = shift_ref = None
        rest = refs[4:]
    part = lax.dot_general(w_ref[...], dy_ref[0], (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=prec)

    def _emit(val):
        if relu:
            a = _prologue(x_ref, scale_ref, shift_ref, relu=False)
            val = jnp.where(a > 0, val, 0.0)
        da_ref[0] = val.astype(da_ref.dtype)

    if n_co == 1:
        _emit(part)
        return
    acc_ref, = rest
    i_co = pl.program_id(3)

    @pl.when(i_co == 0)
    def _init():
        acc_ref[...] = part

    @pl.when(i_co > 0)
    def _acc():
        acc_ref[...] += part

    @pl.when(i_co == n_co - 1)
    def _out():
        _emit(acc_ref[...])


def _dgrad(x3, scale2, shift2, w, dy3, relu, interpret,
           block_co=256, block_m=512, block_ci=256):
    N, Ci, M = x3.shape
    Co = w.shape[0]
    affine = scale2 is not None
    # m is not contracted: ragged-last-block garbage stays in dropped
    # lanes (same no-pad rationale as _fwd)
    block_co, block_m, block_ci = _choose_blocks(
        Ci, Co, M, interpret, block_co, block_m, block_ci)
    n_m, n_ci, n_co = _ceil_div(M, block_m), Ci // block_ci, Co // block_co

    kernel = functools.partial(_dgrad_kernel, n_co=n_co, relu=relu,
                               affine=affine, prec=_prec(x3.dtype))
    in_specs = []
    args = []
    if affine:
        in_specs += [
            pl.BlockSpec((block_ci, 1), lambda ci, n, m, co: (ci, 0)),
            pl.BlockSpec((block_ci, 1), lambda ci, n, m, co: (ci, 0)),
        ]
        args += [scale2, shift2]
    in_specs += [
        pl.BlockSpec((1, block_ci, block_m),
                     lambda ci, n, m, co: (n, ci, m)),
        pl.BlockSpec((1, block_co, block_m),
                     lambda ci, n, m, co: (n, co, m)),
        pl.BlockSpec((block_co, block_ci),
                     lambda ci, n, m, co: (co, ci)),
    ]
    args += [x3, dy3, w]
    da = pl.pallas_call(
        kernel,
        grid=(n_ci, N, n_m, n_co),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_ci, block_m),
                               lambda ci, n, m, co: (n, ci, m)),
        out_shape=jax.ShapeDtypeStruct((N, Ci, M), jnp.float32),
        scratch_shapes=([] if n_co == 1 else
                        [pltpu.VMEM((block_ci, block_m), jnp.float32)]),
        interpret=interpret,
        compiler_params=_CompilerParams(dimension_semantics=(
            "parallel", "parallel", "parallel", "arbitrary")),
    )(*args)
    return da


# ---------------------------------------------------------------------------
# wgrad: dw = sum_n dy[n] @ f(x[n])^T   (grid co, ci, n, m — acc over n AND m)
# f recomputed in the prologue; the ragged last m-block is lane-masked
# on both operands (m is contracted — garbage must not enter the sum)
# ---------------------------------------------------------------------------

def _wgrad_kernel(*refs, n_n: int, n_m: int, relu: bool, affine: bool,
                  m_total: int, block_m: int, prec):
    if affine:
        scale_ref, shift_ref, x_ref, dy_ref, dw_ref, acc_ref = refs
    else:
        x_ref, dy_ref, dw_ref, acc_ref = refs
        scale_ref = shift_ref = None
    i_n, i_m = pl.program_id(2), pl.program_id(3)
    h = _prologue(x_ref, scale_ref, shift_ref, relu)
    dy = dy_ref[0].astype(jnp.float32)
    if m_total % block_m:
        # m IS contracted here: the ragged last block's garbage lanes
        # (potentially NaN) must be zeroed on BOTH operands
        valid = m_total - i_m * block_m
        h = jnp.where(lax.broadcasted_iota(jnp.int32, h.shape, 1)
                      < valid, h, 0.0)
        dy = jnp.where(lax.broadcasted_iota(jnp.int32, dy.shape, 1)
                       < valid, dy, 0.0)
    cd = jnp.bfloat16 if dy_ref.dtype == jnp.bfloat16 else jnp.float32
    part = lax.dot_general(dy.astype(cd), h.astype(cd),
                           (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=prec)
    first = jnp.logical_and(i_n == 0, i_m == 0)

    @pl.when(first)
    def _init():
        acc_ref[...] = part

    @pl.when(jnp.logical_not(first))
    def _acc():
        acc_ref[...] += part

    @pl.when(jnp.logical_and(i_n == n_n - 1, i_m == n_m - 1))
    def _out():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


def _wgrad(x3, scale2, shift2, dy3, relu, interpret, out_dtype,
           block_co=256, block_m=512, block_ci=256):
    N, Ci, M = x3.shape
    Co = dy3.shape[1]
    affine = scale2 is not None
    block_co, block_m, block_ci = _choose_blocks(
        Ci, Co, M, interpret, block_co, block_m, block_ci)
    # dw blocks index the OUTPUT: both are sublane-legal already (the
    # chooser only returns 8-multiples or whole axes)
    n_m, n_ci, n_co = _ceil_div(M, block_m), Ci // block_ci, Co // block_co

    kernel = functools.partial(_wgrad_kernel, n_n=N, n_m=n_m, relu=relu,
                               affine=affine, m_total=M, block_m=block_m,
                               prec=_prec(x3.dtype))
    in_specs = []
    args = []
    if affine:
        in_specs += [
            pl.BlockSpec((block_ci, 1), lambda co, ci, n, m: (ci, 0)),
            pl.BlockSpec((block_ci, 1), lambda co, ci, n, m: (ci, 0)),
        ]
        args += [scale2, shift2]
    in_specs += [
        pl.BlockSpec((1, block_ci, block_m),
                     lambda co, ci, n, m: (n, ci, m)),
        pl.BlockSpec((1, block_co, block_m),
                     lambda co, ci, n, m: (n, co, m)),
    ]
    args += [x3, dy3]
    dw = pl.pallas_call(
        kernel,
        grid=(n_co, n_ci, N, n_m),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_co, block_ci),
                               lambda co, ci, n, m: (co, ci)),
        out_shape=jax.ShapeDtypeStruct((Co, Ci), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_co, block_ci), jnp.float32)],
        interpret=interpret,
        compiler_params=_CompilerParams(dimension_semantics=(
            "parallel", "parallel", "arbitrary", "arbitrary")),
    )(*args)
    return dw


# ---------------------------------------------------------------------------
# custom-vjp ops (flat (N, Ci, M) form; the public wrapper reshapes NCHW)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused_core(x3, scale2, shift2, bias2, w, relu, affine, bias):
    return _fwd(x3, scale2 if affine else None,
                shift2 if affine else None, w, relu, _interpret(),
                bias2 if bias else None)


def _fused_core_fwd(x3, scale2, shift2, bias2, w, relu, affine, bias):
    y = _fused_core(x3, scale2, shift2, bias2, w, relu, affine, bias)
    return y, (x3, scale2, shift2, bias2, w)


def _fused_core_bwd(relu, affine, bias, res, dy):
    x3, scale2, shift2, bias2, w = res
    interp = _interpret()
    sc = scale2 if affine else None
    sh = shift2 if affine else None
    da = _dgrad(x3, sc, sh, w, dy, relu, interp)
    dw = _wgrad(x3, sc, sh, dy, relu, interp, w.dtype)
    if affine:
        # one fused XLA sweep over (da, x): dx + both per-channel sums
        dx = (da * scale2.reshape(1, -1, 1)).astype(x3.dtype)
        dscale = jnp.sum(da * x3.astype(jnp.float32), axis=(0, 2)) \
            .reshape(scale2.shape).astype(scale2.dtype)
        dshift = jnp.sum(da, axis=(0, 2)) \
            .reshape(shift2.shape).astype(shift2.dtype)
    else:
        dx = da.astype(x3.dtype)
        dscale = jnp.zeros_like(scale2)
        dshift = jnp.zeros_like(shift2)
    dbias = (jnp.sum(dy.astype(jnp.float32), axis=(0, 2))
             .reshape(bias2.shape).astype(bias2.dtype)
             if bias else jnp.zeros_like(bias2))
    return dx, dscale, dshift, dbias, dw


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


def fused_prologue_conv1x1(x, w, scale: Optional[jax.Array] = None,
                           shift: Optional[jax.Array] = None,
                           relu: bool = True,
                           bias: Optional[jax.Array] = None):
    """``y = w @ relu(x * scale + shift) + bias`` as ONE kernel, NCHW.

    x: (N, Ci, H, W); w: (Co, Ci) or (Co, Ci, 1, 1); scale/shift: (Ci,)
    per-channel affine (the BN apply) or None for a plain-ReLU prologue;
    bias: (Co,) conv bias or None.  Returns (N, Co, H, W) in x.dtype.
    Differentiable in x, w, scale, shift, bias (custom VJP; see module
    docstring for the backward shape).
    """
    N, Ci, H, W_ = x.shape
    if w.ndim == 4:
        w = w.reshape(w.shape[0], w.shape[1])
    Co = w.shape[0]
    x3 = x.reshape(N, Ci, H * W_)
    affine = scale is not None
    has_bias = bias is not None
    scale2 = (scale.astype(jnp.float32).reshape(Ci, 1) if affine
              else jnp.zeros((1, 1), jnp.float32))
    shift2 = (shift.astype(jnp.float32).reshape(Ci, 1) if affine
              else jnp.zeros((1, 1), jnp.float32))
    bias2 = (bias.astype(jnp.float32).reshape(Co, 1) if has_bias
             else jnp.zeros((1, 1), jnp.float32))
    y3 = _fused_core(x3, scale2, shift2, bias2, w, relu, affine, has_bias)
    return y3.reshape(N, Co, H, W_)
