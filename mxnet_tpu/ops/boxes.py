"""Bounding-box contrib ops: IoU, NMS, matching, multibox anchors.

Reference parity (leezu/mxnet): ``src/operator/contrib/bounding_box.cc``
(`_contrib_box_iou`, `_contrib_box_nms`, `_contrib_bipartite_matching`)
and ``src/operator/contrib/multibox_prior.cc`` — the SSD/YOLO-era
detection tool set behind gluon-cv.

Design (tpu-first): everything is static-shape. NMS keeps the (B, N, K)
layout and marks suppressed rows with -1 (reference semantics) instead
of compacting; suppression is the O(N^2)-mask sequential sweep expressed
as a ``lax.fori_loop`` over the score-sorted IoU matrix, which XLA maps
onto vector ops per step — no data-dependent shapes anywhere, so the
whole thing jits and vmaps over the batch.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..ndarray.ndarray import NDArray  # noqa: F401  (public type in sigs)
from ..ndarray.ops import _as_nd
from ..ndarray.register import invoke, register_op

__all__ = ["box_iou", "box_nms", "bipartite_matching", "multibox_prior"]


def _to_corner(b, fmt):
    """(..., 4) boxes to corner (x1, y1, x2, y2)."""
    if fmt == "corner":
        return b
    cx, cy, w, h = jnp.split(b, 4, axis=-1)
    return jnp.concatenate(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


def _pairwise_iou(a, b):
    """a: (..., M, 4), b: (..., N, 4) corner boxes -> (..., M, N)."""
    ax1, ay1, ax2, ay2 = jnp.split(a[..., :, None, :], 4, axis=-1)
    bx1, by1, bx2, by2 = jnp.split(b[..., None, :, :], 4, axis=-1)
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = (iw * ih)[..., 0]
    area_a = ((ax2 - ax1) * (ay2 - ay1))[..., 0]
    area_b = ((bx2 - bx1) * (by2 - by1))[..., 0]
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_iou(lhs, rhs, format: str = "corner"):  # noqa: A002
    """Pairwise IoU between (..., M, 4) and (..., N, 4) boxes
    (reference ``_contrib_box_iou``)."""
    fmt = format

    def impl(a, b):
        return _pairwise_iou(
            _to_corner(a.astype(jnp.float32), fmt),
            _to_corner(b.astype(jnp.float32), fmt))

    return invoke("box_iou", impl, (_as_nd(lhs), _as_nd(rhs)))


def box_nms(data, overlap_thresh: float = 0.5, valid_thresh: float = 0.0,
            topk: int = -1, coord_start: int = 2, score_index: int = 1,
            id_index: int = -1, background_id: int = -1,
            force_suppress: bool = False, in_format: str = "corner",
            out_format: str = "corner"):
    """Non-maximum suppression (reference ``_contrib_box_nms``).

    ``data``: (B, N, K) or (N, K) with per-box [..., score, ..., 4 coords,
    ...] at ``score_index``/``coord_start`` (and optional class at
    ``id_index``). Returns the same shape, score-sorted, with suppressed
    or invalid boxes as all -1 rows — the reference's static-shape
    contract, which is also exactly what a TPU wants.
    """
    nd = _as_nd(data)
    squeeze = nd.ndim == 2

    def impl(x):
        d = x[None] if squeeze else x
        d = d.astype(jnp.float32)
        B, N, K = d.shape
        scores = d[:, :, score_index]
        boxes = _to_corner(
            d[:, :, coord_start:coord_start + 4], in_format)
        cls = d[:, :, id_index] if id_index >= 0 else \
            jnp.zeros((B, N), jnp.float32)
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= cls != background_id

        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf), axis=1)
        if topk > 0:
            rank = jnp.arange(N)
            valid_sorted = jnp.take_along_axis(valid, order, 1) & \
                (rank[None, :] < topk)
        else:
            valid_sorted = jnp.take_along_axis(valid, order, 1)
        boxes_s = jnp.take_along_axis(boxes, order[..., None], 1)
        cls_s = jnp.take_along_axis(cls, order, 1)
        iou = _pairwise_iou(boxes_s, boxes_s)                    # B,N,N
        same_cls = (cls_s[:, :, None] == cls_s[:, None, :]) | \
            force_suppress
        later = jnp.arange(N)[None, :] > jnp.arange(N)[:, None]  # i<j
        sup_mask = (iou > overlap_thresh) & same_cls & later[None]

        def body(i, suppressed):
            row = sup_mask[:, i, :]                              # B,N
            alive = (~suppressed[:, i]) & valid_sorted[:, i]
            return suppressed | (row & alive[:, None])

        suppressed = lax.fori_loop(
            0, N, body, jnp.zeros((B, N), bool))
        keep = valid_sorted & ~suppressed
        out = jnp.take_along_axis(d, order[..., None], 1)
        out = jnp.where(keep[..., None], out, -1.0)
        if out_format != in_format:
            coords = out[:, :, coord_start:coord_start + 4]
            if out_format == "center":
                x1, y1, x2, y2 = jnp.split(coords, 4, axis=-1)
                conv = jnp.concatenate(
                    [(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], -1)
            else:
                conv = _to_corner(coords, in_format)
            conv = jnp.where(keep[..., None], conv, -1.0)
            out = jnp.concatenate(
                [out[:, :, :coord_start], conv,
                 out[:, :, coord_start + 4:]], axis=-1)
        return out[0] if squeeze else out

    return invoke("box_nms", impl, (nd,))


def bipartite_matching(data, threshold: float = 0.5, topk: int = -1,
                       is_ascend: bool = False):
    """Greedy bipartite matching over a (B, M, N) score matrix
    (reference ``_contrib_bipartite_matching``): repeatedly take the
    globally best remaining pair. Returns (row_match (B, M),
    col_match (B, N)) with -1 for unmatched."""
    nd = _as_nd(data)
    squeeze = nd.ndim == 2

    def impl(x):
        d = x[None] if squeeze else x
        d = d.astype(jnp.float32)
        B, M, N = d.shape
        sign = 1.0 if is_ascend else -1.0
        steps = min(M, N) if topk <= 0 else min(topk, min(M, N))

        def body(_, carry):
            rows, cols, mat = carry
            flat = (sign * mat).reshape(B, M * N)
            idx = jnp.argmin(flat, axis=1)
            ri, ci = idx // N, idx % N
            val = jnp.take_along_axis(
                mat.reshape(B, M * N), idx[:, None], 1)[:, 0]
            ok = (val >= threshold) if not is_ascend else \
                (val <= threshold)
            rows = rows.at[jnp.arange(B), ri].set(
                jnp.where(ok, ci, rows[jnp.arange(B), ri]))
            cols = cols.at[jnp.arange(B), ci].set(
                jnp.where(ok, ri, cols[jnp.arange(B), ci]))
            # retire the chosen row+col so they can't match again
            worst = -jnp.inf if not is_ascend else jnp.inf
            chosen = ok[:, None, None] & \
                ((jnp.arange(M)[None, :, None] == ri[:, None, None]) |
                 (jnp.arange(N)[None, None, :] == ci[:, None, None]))
            mat = jnp.where(chosen, worst, mat)
            return rows, cols, mat

        rows0 = jnp.full((B, M), -1.0)
        cols0 = jnp.full((B, N), -1.0)
        rows, cols, _ = lax.fori_loop(0, steps, body, (rows0, cols0, d))
        if squeeze:
            return rows[0], cols[0]
        return rows, cols

    return invoke("bipartite_matching", impl, (nd,))


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip: bool = False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """SSD anchor generation (reference ``_contrib_MultiBoxPrior``):
    for an (B, C, H, W) feature map, emit (1, H*W*A, 4) corner anchors,
    A = len(sizes) + len(ratios) - 1."""
    nd = _as_nd(data)
    szs = tuple(float(s) for s in sizes)
    rts = tuple(float(r) for r in ratios)

    def impl(x):
        h, w = x.shape[2], x.shape[3]
        sy = 1.0 / h if steps[0] <= 0 else steps[0]
        sx = 1.0 / w if steps[1] <= 0 else steps[1]
        cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * sy
        cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * sx
        gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
        # anchor set: (size_i, ratio_0) for all sizes, then
        # (size_0, ratio_j) for ratios[1:]
        whs = [(szs[i] * jnp.sqrt(rts[0]), szs[i] / jnp.sqrt(rts[0]))
               for i in range(len(szs))]
        whs += [(szs[0] * jnp.sqrt(r), szs[0] / jnp.sqrt(r))
                for r in rts[1:]]
        anchors = []
        for aw, ah in whs:
            anchors.append(jnp.stack(
                [gx - aw / 2, gy - ah / 2, gx + aw / 2, gy + ah / 2],
                axis=-1))
        out = jnp.stack(anchors, axis=2).reshape(1, -1, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out

    return invoke("multibox_prior", impl, (nd,))


for _name in __all__:
    register_op(_name, globals()[_name])


def multibox_target(anchor, label, cls_pred,
                    overlap_threshold: float = 0.5,
                    ignore_label: float = -1.0,
                    negative_mining_ratio: float = -1.0,
                    negative_mining_thresh: float = 0.5,
                    minimum_negative_samples: int = 0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training-target assignment (reference
    ``_contrib_MultiBoxTarget``, src/operator/contrib/multibox_target.cc).

    anchor (1, N, 4) corner; label (B, M, 5) rows [cls, x1, y1, x2, y2]
    with cls = -1 padding; cls_pred (B, C, N) (used for hard-negative
    mining). Returns (loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N)) — cls_target is gt_class+1 for matched anchors,
    0 for kept negatives, ``ignore_label`` for mined-away negatives.
    """
    v0, v1, v2, v3 = [float(v) for v in variances]

    def impl(anc, lab, pred):
        a = anc[0].astype(jnp.float32)                    # N,4 corner
        N = a.shape[0]
        B, M, _ = lab.shape
        acx = (a[:, 0] + a[:, 2]) / 2
        acy = (a[:, 1] + a[:, 3]) / 2
        aw = jnp.maximum(a[:, 2] - a[:, 0], 1e-12)
        ah = jnp.maximum(a[:, 3] - a[:, 1], 1e-12)

        gt_valid = lab[:, :, 0] >= 0                      # B,M
        iou = _pairwise_iou(a[None],
                            lab[:, :, 1:5].astype(jnp.float32))  # B,N,M
        iou = jnp.where(gt_valid[:, None, :], iou, -1.0)

        # each anchor's best gt + force-match the best anchor per gt.
        # scatter-max (not set): padding gts (argmax over an all -1 IoU
        # column lands on anchor 0) must not clobber a valid gt's forced
        # match, and two valid gts sharing a best anchor keep one
        # deterministic winner (highest gt index) instead of dropping one
        best_gt = jnp.argmax(iou, axis=2)                 # B,N
        best_iou = jnp.max(iou, axis=2)
        best_anchor = jnp.argmax(iou, axis=1)             # B,M
        rows = jnp.arange(B)[:, None]
        forced = jnp.zeros((B, N), bool).at[
            rows, best_anchor].max(gt_valid)
        cand = jnp.where(gt_valid,
                         jnp.arange(M, dtype=jnp.int32)[None, :], -1)
        forced_gt = jnp.full((B, N), -1, jnp.int32).at[
            rows, best_anchor].max(cand)
        matched = forced | (best_iou >= overlap_threshold)
        gt_idx = jnp.where(forced, forced_gt, best_gt)    # B,N

        g = jnp.take_along_axis(lab, gt_idx[..., None], 1)  # B,N,5
        gcx = (g[..., 1] + g[..., 3]) / 2
        gcy = (g[..., 2] + g[..., 4]) / 2
        gw = jnp.maximum(g[..., 3] - g[..., 1], 1e-12)
        gh = jnp.maximum(g[..., 4] - g[..., 2], 1e-12)
        dx = (gcx - acx) / aw / v0
        dy = (gcy - acy) / ah / v1
        dw = jnp.log(gw / aw) / v2
        dh = jnp.log(gh / ah) / v3
        loc_t = jnp.stack([dx, dy, dw, dh], -1)           # B,N,4
        loc_t = jnp.where(matched[..., None], loc_t, 0.0)
        loc_m = jnp.where(matched[..., None],
                          jnp.ones_like(loc_t), 0.0)

        cls_t = jnp.where(matched, g[..., 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negatives: unmatched anchors whose best-class
            # confidence is highest; keep ratio*num_pos, rest -> ignore
            max_conf = jnp.max(pred, axis=1)              # B,N over C
            neg = ~matched
            num_pos = jnp.sum(matched, axis=1)
            quota = jnp.maximum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                minimum_negative_samples)
            conf = jnp.where(neg & (best_iou < negative_mining_thresh),
                             max_conf, -jnp.inf)
            rank = jnp.argsort(jnp.argsort(-conf, axis=1), axis=1)
            keep_neg = neg & (rank < quota[:, None])
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(keep_neg, 0.0, ignore_label))
        return (loc_t.reshape(B, N * 4), loc_m.reshape(B, N * 4), cls_t)

    return invoke("multibox_target", impl,
                  (_as_nd(anchor), _as_nd(label), _as_nd(cls_pred)))


def multibox_detection(cls_prob, loc_pred, anchor, clip: bool = True,
                       threshold: float = 0.01, background_id: int = 0,
                       nms_threshold: float = 0.5,
                       force_suppress: bool = False,
                       variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk: int = -1):
    """SSD inference decode + NMS (reference
    ``_contrib_MultiBoxDetection``): cls_prob (B, C, N) softmax scores,
    loc_pred (B, N*4) encoded offsets, anchor (1, N, 4). Returns
    (B, N, 6) rows [class_id, score, x1, y1, x2, y2], suppressed/
    background rows marked -1 (class ids exclude background, 0-based:
    id = original class - 1, the reference convention).

    ``background_id`` must be 0 (the reference kernel hardcodes class 0 as
    background) or negative (no background class; ids are original class
    indices). Other values would silently shift ids for classes above the
    background and are rejected."""
    if background_id > 0:
        raise ValueError(
            "multibox_detection: background_id must be 0 (reference "
            "convention — class 0 is background) or negative (no "
            f"background class); got {background_id}. Nonzero background "
            "classes would shift the reported ids of higher classes.")
    v0, v1, v2, v3 = [float(v) for v in variances]

    def impl(prob, loc, anc):
        B, C, N = prob.shape
        a = anc[0].astype(jnp.float32)
        acx = (a[:, 0] + a[:, 2]) / 2
        acy = (a[:, 1] + a[:, 3]) / 2
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        p = loc.reshape(B, N, 4).astype(jnp.float32)
        cx = p[..., 0] * v0 * aw + acx
        cy = p[..., 1] * v1 * ah + acy
        w = jnp.exp(p[..., 2] * v2) * aw
        h = jnp.exp(p[..., 3] * v3) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best foreground class per anchor (background_id validated above:
        # 0 = drop class 0, negative = no background class)
        fg = prob[:, 1:] if background_id == 0 else prob
        cid = jnp.argmax(fg, axis=1).astype(jnp.float32)  # B,N
        score = jnp.max(fg, axis=1)
        valid = score > threshold
        rows = jnp.concatenate(
            [jnp.where(valid, cid, -1.0)[..., None],
             jnp.where(valid, score, -1.0)[..., None], boxes], -1)
        return rows

    decoded = invoke("multibox_detection", impl,
                     (_as_nd(cls_prob), _as_nd(loc_pred), _as_nd(anchor)))
    return box_nms(decoded, overlap_thresh=nms_threshold, valid_thresh=0.0,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)


__all__ += ["multibox_target", "multibox_detection"]
for _name in ("multibox_target", "multibox_detection"):
    register_op(_name, globals()[_name])
