"""INT8 quantization operators.

Reference parity (leezu/mxnet): ``src/operator/quantization/`` —
``quantize.cc``, ``quantize_v2.cc``, ``dequantize.cc``, ``requantize.cc``,
``quantized_fully_connected.cc``, ``quantized_conv.cc``,
``quantized_pooling.cc``, ``quantized_activation`` — the MKLDNN/cuDNN INT8
inference path driven by ``python/mxnet/contrib/quantization.py``.

Design (tpu-first): quantized tensors are plain int8 jax arrays plus
(min, max) float range scalars, exactly the reference's three-output
convention.  The compute ops feed ``lax.dot_general`` /
``lax.conv_general_dilated`` with int8 operands and
``preferred_element_type=int32`` so XLA lowers them onto the MXU's native
int8 path (double the bf16 MACs per cycle on TPU); there is no per-backend
kernel zoo to select from.  Symmetric int8 (zero-point 0) is used for
weights; activations may be uint8-style affine via shifted int8.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray.ops import _as_nd
from ..ndarray.register import invoke, register_op

__all__ = [
    "quantize", "quantize_v2", "dequantize", "requantize",
    "quantized_fully_connected", "quantized_conv", "quantized_pooling",
    "quantized_act", "quantized_flatten",
]

_INT8_MAX = 127.0
_UINT8_MAX = 255.0


def _range_for(out_type: str) -> float:
    if out_type == "int8":
        return _INT8_MAX
    if out_type == "uint8":
        return _UINT8_MAX
    raise MXNetError(f"unsupported quantized dtype {out_type!r} "
                     "(expected 'int8' or 'uint8')")


def quantize(data, min_range, max_range, out_type: str = "uint8"):
    """Quantize float data into ``out_type`` given a float range.

    Returns ``(q, min_range, max_range)`` like the reference's 3-output
    ``_contrib_quantize``. int8 is symmetric (zero-point 0, scale from
    max(|min|, |max|)); uint8 is affine on [min, max].
    """
    q_max = _range_for(out_type)
    inputs = (_as_nd(data), _as_nd(min_range), _as_nd(max_range))

    def impl(x, mn, mx):
        mn = mn.reshape(()).astype(jnp.float32)
        mx = mx.reshape(()).astype(jnp.float32)
        if out_type == "int8":
            amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
            scale = q_max / jnp.maximum(amax, 1e-30)
            q = jnp.clip(jnp.round(x * scale), -q_max, q_max)
            return q.astype(jnp.int8), -amax, amax
        scale = q_max / jnp.maximum(mx - mn, 1e-30)
        q = jnp.clip(jnp.round((x - mn) * scale), 0.0, q_max)
        return q.astype(jnp.uint8), mn, mx

    return invoke("quantize", impl, inputs)


def quantize_v2(data, min_calib_range: Optional[float] = None,
                max_calib_range: Optional[float] = None,
                out_type: str = "int8"):
    """Quantize with an optional pre-calibrated range (reference
    ``_contrib_quantize_v2``); without one the runtime min/max is used."""
    nd = _as_nd(data)
    q_max = _range_for(out_type)
    calibrated = min_calib_range is not None and max_calib_range is not None

    def impl(x):
        if calibrated:
            mn = jnp.float32(min_calib_range)
            mx = jnp.float32(max_calib_range)
        else:
            mn = x.min().astype(jnp.float32)
            mx = x.max().astype(jnp.float32)
        if out_type == "int8":
            amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
            scale = q_max / jnp.maximum(amax, 1e-30)
            q = jnp.clip(jnp.round(x * scale), -q_max, q_max)
            return q.astype(jnp.int8), -amax, amax
        scale = q_max / jnp.maximum(mx - mn, 1e-30)
        q = jnp.clip(jnp.round((x - mn) * scale), 0.0, q_max)
        return q.astype(jnp.uint8), mn, mx

    return invoke("quantize_v2", impl, (nd,))


def dequantize(data, min_range, max_range, out_type: str = "float32"):
    """int8/uint8 + range -> float (reference ``_contrib_dequantize``)."""
    inputs = (_as_nd(data), _as_nd(min_range), _as_nd(max_range))

    def impl(q, mn, mx):
        mn = mn.reshape(()).astype(jnp.float32)
        mx = mx.reshape(()).astype(jnp.float32)
        if q.dtype == jnp.uint8:
            return (q.astype(jnp.float32) * ((mx - mn) / _UINT8_MAX) + mn) \
                .astype(out_type)
        # signed (int8 weight/activation or int32 accumulator): symmetric
        qmax = float(jnp.iinfo(q.dtype).max)
        amax = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        return (q.astype(jnp.float32) * (amax / qmax)).astype(out_type)

    return invoke("dequantize", impl, inputs)


def requantize(data, min_range, max_range,
               min_calib_range: Optional[float] = None,
               max_calib_range: Optional[float] = None):
    """int32 accumulator + its float range -> int8 (reference
    ``_contrib_requantize``). With a calibrated range the rescale is a
    compile-time constant; otherwise the runtime abs-max is used."""
    inputs = (_as_nd(data), _as_nd(min_range), _as_nd(max_range))
    calibrated = min_calib_range is not None and max_calib_range is not None

    def impl(q32, mn, mx):
        mn = mn.reshape(()).astype(jnp.float32)
        mx = mx.reshape(()).astype(jnp.float32)
        # float value of one int32 step
        step = jnp.maximum(jnp.abs(mn), jnp.abs(mx)) / 2147483647.0
        real = q32.astype(jnp.float32) * step
        if calibrated:
            amax = jnp.float32(max(abs(min_calib_range),
                                   abs(max_calib_range)))
        else:
            amax = jnp.maximum(jnp.abs(real.min()), jnp.abs(real.max()))
        scale = _INT8_MAX / jnp.maximum(amax, 1e-30)
        q8 = jnp.clip(jnp.round(real * scale), -_INT8_MAX, _INT8_MAX)
        return q8.astype(jnp.int8), -amax, amax

    return invoke("requantize", impl, inputs)


def _int8_range_prod(min_a, max_a, min_b, max_b, k: float):
    """Float range of an int32 accumulator of a_q·b_q over k terms."""
    amax_a = jnp.maximum(jnp.abs(min_a), jnp.abs(max_a))
    amax_b = jnp.maximum(jnp.abs(min_b), jnp.abs(max_b))
    # worst case |acc| <= k * 127 * 127; its float value is
    # acc * (amax_a/127) * (amax_b/127)
    amax_out = amax_a * amax_b / (_INT8_MAX * _INT8_MAX) * 2147483647.0
    return -amax_out, amax_out


def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden: int = 0,
                              no_bias: bool = False, flatten: bool = True):
    """int8 x · Wᵀ (+ b) -> int32 + range (reference
    ``_contrib_quantized_fully_connected``).  The int8 dot rides the MXU
    via ``preferred_element_type=int32``; bias (int8) is rescaled into the
    accumulator's scale inside the op.
    """
    inputs = [_as_nd(data), _as_nd(weight)]
    has_bias = bias is not None and not no_bias
    if has_bias:
        inputs += [_as_nd(bias)]
    inputs += [_as_nd(min_data), _as_nd(max_data),
               _as_nd(min_weight), _as_nd(max_weight)]
    if has_bias:
        inputs += [_as_nd(min_bias), _as_nd(max_bias)]

    def impl(x, w, *rest):
        if has_bias:
            b, mn_x, mx_x, mn_w, mx_w, mn_b, mx_b = rest
        else:
            mn_x, mx_x, mn_w, mx_w = rest
        if flatten and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        y = lax.dot_general(x, w, (((x.ndim - 1,), (1,)), ((), ())),
                            preferred_element_type=jnp.int32)
        mn_o, mx_o = _int8_range_prod(
            mn_x.reshape(()).astype(jnp.float32),
            mx_x.reshape(()).astype(jnp.float32),
            mn_w.reshape(()).astype(jnp.float32),
            mx_w.reshape(()).astype(jnp.float32), float(x.shape[-1]))
        if has_bias:
            # rescale int8 bias into the int32 accumulator scale
            amax_b = jnp.maximum(jnp.abs(mn_b.reshape(())),
                                 jnp.abs(mx_b.reshape(()))) \
                .astype(jnp.float32)
            acc_step = mx_o / 2147483647.0
            b32 = jnp.round(b.astype(jnp.float32) * (amax_b / _INT8_MAX)
                            / jnp.maximum(acc_step, 1e-30)).astype(jnp.int32)
            y = y + b32
        return y, mn_o, mx_o

    return invoke("quantized_fully_connected", impl, tuple(inputs))


def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None,
                   kernel=None, stride=None, pad=None, dilate=None,
                   num_filter: int = 0, num_group: int = 1,
                   no_bias: bool = False, layout: str = "NCHW"):
    """int8 convolution -> int32 + range (reference
    ``_contrib_quantized_conv``)."""
    from .nn import _CONV_DIMNUMS, _pair
    nd_data = _as_nd(data)
    ndim = nd_data.ndim - 2
    stride = _pair(stride or 1, ndim)
    dilate = _pair(dilate or 1, ndim)
    pad = _pair(pad if pad is not None else 0, ndim)
    dn = _CONV_DIMNUMS[(layout,)]

    inputs = [nd_data, _as_nd(weight)]
    has_bias = bias is not None and not no_bias
    if has_bias:
        inputs += [_as_nd(bias)]
    inputs += [_as_nd(min_data), _as_nd(max_data),
               _as_nd(min_weight), _as_nd(max_weight)]
    if has_bias:
        inputs += [_as_nd(min_bias), _as_nd(max_bias)]

    def impl(x, w, *rest):
        if has_bias:
            b, mn_x, mx_x, mn_w, mx_w, mn_b, mx_b = rest
        else:
            mn_x, mx_x, mn_w, mx_w = rest
        y = lax.conv_general_dilated(
            x, w, window_strides=stride, padding=[(p, p) for p in pad],
            rhs_dilation=dilate, dimension_numbers=dn,
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        k = float(w.size // w.shape[0])
        mn_o, mx_o = _int8_range_prod(
            mn_x.reshape(()).astype(jnp.float32),
            mx_x.reshape(()).astype(jnp.float32),
            mn_w.reshape(()).astype(jnp.float32),
            mx_w.reshape(()).astype(jnp.float32), k)
        if has_bias:
            amax_b = jnp.maximum(jnp.abs(mn_b.reshape(())),
                                 jnp.abs(mx_b.reshape(()))) \
                .astype(jnp.float32)
            acc_step = mx_o / 2147483647.0
            b32 = jnp.round(b.astype(jnp.float32) * (amax_b / _INT8_MAX)
                            / jnp.maximum(acc_step, 1e-30)).astype(jnp.int32)
            shape = [1] * y.ndim
            shape[dn[2].index("C")] = b32.shape[0]
            y = y + b32.reshape(shape)
        return y, mn_o, mx_o

    return invoke("quantized_conv", impl, tuple(inputs))


def quantized_pooling(data, min_data, max_data, kernel=None, stride=None,
                      pad=None, pool_type: str = "max",
                      global_pool: bool = False, layout: str = "NCHW"):
    """Pooling directly on int8 (max) or via int32 mean (avg); range is
    unchanged (reference ``_contrib_quantized_pooling``)."""
    inputs = (_as_nd(data), _as_nd(min_data), _as_nd(max_data))

    def impl(q, mn, mx):
        from .nn import _pair
        ndim = q.ndim - 2
        if layout.endswith("C"):
            sp = tuple(range(1, 1 + ndim))
        else:
            sp = tuple(range(2, 2 + ndim))
        if global_pool:
            win = tuple(q.shape[i] for i in sp)
            st = win
            pd = (0,) * ndim
        else:
            win = _pair(kernel, ndim)
            st = _pair(stride or 1, ndim)
            pd = _pair(pad if pad is not None else 0, ndim)
        dims = [1] * q.ndim
        strides = [1] * q.ndim
        padding = [(0, 0)] * q.ndim
        for i, ax in enumerate(sp):
            dims[ax] = win[i]
            strides[ax] = st[i]
            padding[ax] = (pd[i], pd[i])
        if pool_type == "max":
            init = jnp.array(jnp.iinfo(q.dtype).min, dtype=q.dtype)
            out = lax.reduce_window(q, init, lax.max, dims, strides, padding)
        elif pool_type == "avg":
            s = lax.reduce_window(q.astype(jnp.int32), 0, lax.add, dims,
                                  strides, padding)
            n = 1
            for w_ in win:
                n *= w_
            out = jnp.round(s.astype(jnp.float32) / n).astype(q.dtype)
        else:
            raise MXNetError(f"unsupported quantized pool_type {pool_type!r}")
        return out, mn.reshape(()), mx.reshape(())

    return invoke("quantized_pooling", impl, inputs)


def quantized_act(data, min_data, max_data, act_type: str = "relu"):
    """relu on int8 keeps the affine mapping exact: clamp at the
    zero-point (0 for symmetric int8)."""
    if act_type != "relu":
        raise MXNetError("only act_type='relu' has an int8 fast path")
    inputs = (_as_nd(data), _as_nd(min_data), _as_nd(max_data))

    def impl(q, mn, mx):
        return jnp.maximum(q, 0).astype(q.dtype), \
            jnp.maximum(mn.reshape(()), 0.0), mx.reshape(())

    return invoke("quantized_act", impl, inputs)


def quantized_flatten(data, min_data, max_data):
    inputs = (_as_nd(data), _as_nd(min_data), _as_nd(max_data))

    def impl(q, mn, mx):
        return q.reshape(q.shape[0], -1), mn.reshape(()), mx.reshape(())

    return invoke("quantized_flatten", impl, inputs)


for _name in __all__:
    register_op(_name, globals()[_name])
