"""Neural-network operators: conv, pooling, norms, activations, embedding.

Reference parity (leezu/mxnet): ``src/operator/nn/`` — Convolution
(cudnn_convolution-inl.h), FullyConnected, BatchNorm, LayerNorm, GroupNorm,
Pooling, Activation, Softmax, Dropout, Embedding — and assorted
``src/operator/tensor`` NN helpers (``pick``, ``SequenceMask``).

Design (tpu-first): everything lowers to ``jax.lax`` convolution/reduce-window
/dot primitives that XLA tiles onto the MXU; there are no per-backend kernel
variants (cuDNN/MKLDNN dispatch collapses into XLA). Layouts accept the
reference's NCHW default but NHWC is supported and preferred on TPU; XLA's
layout assignment handles the rest. Dropout draws from the splittable
threefry stream (``ndarray/random.py``), active only in autograd train mode,
matching reference mode semantics (``mxnet.autograd.is_training``).
"""
from __future__ import annotations

import functools

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .._tape import is_training
from ..base import MXNetError, getenv, register_env
from ..ndarray.ndarray import NDArray
from ..ndarray.ops import _as_nd
from ..ndarray.register import invoke, register_op
from ..ndarray import random as _random

register_env("MXNET_BN_STATS", "shifted",
             "Training BatchNorm statistics: 'shifted' (default — one "
             "fused sweep, variance about a batch-slice mean; stable "
             "for any input statistics) or 'centered' (classic "
             "two-pass; one extra full sweep over the activation).")
register_env("MXNET_CONV_S2D", "1",
             "Rewrite stride-2 small-channel NCHW stem convolutions via "
             "space-to-depth (exact; better MXU lane utilization). "
             "Set 0 to dispatch the plain convolution.")

__all__ = [
    "activation", "relu", "leaky_relu", "prelu", "elu", "selu", "gelu",
    "silu", "swish", "mish", "softrelu", "softsign", "hard_sigmoid",
    "hard_swish", "log_sigmoid",
    "softmax", "log_softmax", "masked_softmax", "masked_log_softmax",
    "fully_connected", "convolution", "deconvolution", "pooling",
    "adaptive_avg_pool2d", "batch_norm", "batch_norm_relu_conv1x1",
    "relu_conv1x1", "conv_fusion_enabled", "layer_norm", "group_norm",
    "instance_norm", "rms_norm", "l2_normalization", "lrn",
    "dropout", "embedding", "pick", "take_positions", "sequence_mask",
    "sequence_last", "sequence_reverse", "topk_mask", "smooth_l1",
    "up_sampling", "roi_pooling", "ctc_loss",
]


def _pair(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


# ---------------------------------------------------------------------------
# Activations (reference: src/operator/nn/activation.cc, leaky_relu.cc,
# contrib gelu; python gluon.nn.activations)
# ---------------------------------------------------------------------------

_ACT_FNS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "log_sigmoid": jax.nn.log_sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "mish": jax.nn.mish,
    "identity": lambda x: x,
}


def activation(data, act_type: str = "relu"):
    """Apply a named activation (reference: ``Activation`` op)."""
    fn = _ACT_FNS[act_type]
    return invoke(f"activation_{act_type}", fn, (_as_nd(data),))


def relu(data):
    return invoke("relu", jax.nn.relu, (_as_nd(data),))


def leaky_relu(data, slope: float = 0.25, act_type: str = "leaky"):
    s = slope
    if act_type in ("leaky", "rrelu"):
        return invoke("leaky_relu", lambda x: jax.nn.leaky_relu(x, s),
                      (_as_nd(data),))
    if act_type == "elu":
        return elu(data, s)
    if act_type == "gelu":
        return invoke("gelu", jax.nn.gelu, (_as_nd(data),))
    if act_type == "selu":
        return selu(data)
    raise ValueError(f"unknown leaky_relu act_type {act_type}")


def prelu(data, gamma):
    def impl(x, g):
        return jnp.where(x >= 0, x, g * x)
    return invoke("prelu", impl, (_as_nd(data), _as_nd(gamma)))


def elu(data, alpha: float = 1.0):
    a = alpha
    return invoke("elu", lambda x: jax.nn.elu(x, a), (_as_nd(data),))


def selu(data):
    return invoke("selu", jax.nn.selu, (_as_nd(data),))


def gelu(data, approximate: bool = False):
    ap = approximate
    return invoke("gelu", lambda x: jax.nn.gelu(x, approximate=ap),
                  (_as_nd(data),))


def silu(data):
    return invoke("silu", jax.nn.silu, (_as_nd(data),))


swish = silu


def mish(data):
    return invoke("mish", jax.nn.mish, (_as_nd(data),))


def softrelu(data):
    return invoke("softrelu", jax.nn.softplus, (_as_nd(data),))


def softsign(data):
    return invoke("softsign", jax.nn.soft_sign, (_as_nd(data),))


def log_sigmoid(data):
    return invoke("log_sigmoid", jax.nn.log_sigmoid, (_as_nd(data),))


def hard_sigmoid(data, alpha: float = 0.2, beta: float = 0.5):
    a, b = alpha, beta
    return invoke("hard_sigmoid", lambda x: jnp.clip(a * x + b, 0.0, 1.0),
                  (_as_nd(data),))


def hard_swish(data):
    return invoke("hard_swish", lambda x: x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0),
                  (_as_nd(data),))


# ---------------------------------------------------------------------------
# Softmax family (reference: src/operator/nn/softmax.cc)
# ---------------------------------------------------------------------------

def softmax(data, axis: int = -1, temperature: Optional[float] = None,
            length=None):
    ax, t = axis, temperature
    if length is not None:
        return masked_softmax(data, _length_mask(data, length, axis), axis)
    def impl(x):
        if t is not None and t != 1.0:
            x = x / t
        return jax.nn.softmax(x, axis=ax)
    return invoke("softmax", impl, (_as_nd(data),))


def log_softmax(data, axis: int = -1, temperature: Optional[float] = None):
    ax, t = axis, temperature
    def impl(x):
        if t is not None and t != 1.0:
            x = x / t
        return jax.nn.log_softmax(x, axis=ax)
    return invoke("log_softmax", impl, (_as_nd(data),))


def _length_mask(data, length, axis):
    nd = _as_nd(data)
    L = nd.shape[axis]
    ln = _as_nd(length)
    def impl(l):
        ar = jnp.arange(L)
        shape = [1] * len(nd.shape)
        shape[axis] = L
        ar = ar.reshape(shape)
        ll = l.reshape(l.shape + (1,) * (len(nd.shape) - l.ndim))
        return ar < ll
    return invoke("length_mask", impl, (ln,))


def masked_softmax(data, mask, axis: int = -1):
    ax = axis
    def impl(x, m):
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
            else -1e9
        x = jnp.where(m, x, neg)
        out = jax.nn.softmax(x, axis=ax)
        return jnp.where(m, out, 0.0)
    return invoke("masked_softmax", impl, (_as_nd(data), _as_nd(mask)))


def masked_log_softmax(data, mask, axis: int = -1):
    ax = axis
    def impl(x, m):
        neg = jnp.finfo(x.dtype).min
        x = jnp.where(m, x, neg)
        return jax.nn.log_softmax(x, axis=ax)
    return invoke("masked_log_softmax", impl, (_as_nd(data), _as_nd(mask)))


# ---------------------------------------------------------------------------
# FullyConnected (reference: src/operator/nn/fully_connected.cc — cuBLAS gemm;
# here an MXU matmul)
# ---------------------------------------------------------------------------

def fully_connected(data, weight, bias=None, num_hidden: Optional[int] = None,
                    no_bias: bool = False, flatten: bool = True):
    """y = x · Wᵀ + b with reference weight layout (num_hidden, in_units)."""
    fl = flatten
    inputs = [_as_nd(data), _as_nd(weight)]
    has_bias = bias is not None and not no_bias
    if has_bias:
        inputs.append(_as_nd(bias))

    def impl(x, w, *b):
        if fl and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        y = jnp.matmul(x, w.T)
        if b:
            y = y + b[0]
        return y

    return invoke("fully_connected", impl, tuple(inputs))


# ---------------------------------------------------------------------------
# Convolution (reference: src/operator/nn/convolution.cc + cudnn autotune;
# XLA picks conv algorithms natively, so the CuDNNAlgoReg cache disappears)
# ---------------------------------------------------------------------------

_CONV_DIMNUMS = {
    ("NCW",): ("NCW", "OIW", "NCW"),
    ("NWC",): ("NWC", "WIO", "NWC"),
    ("NCHW",): ("NCHW", "OIHW", "NCHW"),
    ("NHWC",): ("NHWC", "HWIO", "NHWC"),
    ("NCDHW",): ("NCDHW", "OIDHW", "NCDHW"),
    ("NDHWC",): ("NDHWC", "DHWIO", "NDHWC"),
}


def _s2d_stem_conv(x, w, pad):
    """Space-to-depth rewrite of a stride-2 small-channel stem conv
    (NCHW, groups=1, dilation 1, odd kernel, pad=(k-1)//2): packs 2x2
    spatial parity phases into channels so the MXU sees C*4 input lanes
    instead of C (C=3 stems waste >95% of the lanes). Mathematically
    exact — the MLPerf-era ResNet trick expressed as an XLA graph rewrite
    (the reference's analog is cudnn algorithm selection). Returns None
    when the geometry doesn't apply."""
    B, C, H, W = x.shape
    O, _, KH, KW = w.shape
    q = (KH - 1) // 2
    if KH != KW or KH % 2 == 0 or any(t != (q, q) for t in pad):
        return None
    p = q
    kp = (KH + 1) // 2

    def packed_len(L):
        out = (L + 2 * p - KH) // 2 + 1
        need = 2 * (out - 1) + KH
        need += need % 2
        right = need - L - p
        return out, need, right

    outs, needs, rights = zip(*(packed_len(L) for L in (H, W)))
    if any(r < 0 for r in rights):
        return None
    xp = jnp.pad(x, ((0, 0), (0, 0), (p, rights[0]), (p, rights[1])))
    Hp, Wp = needs[0] // 2, needs[1] // 2
    x2 = xp.reshape(B, C, Hp, 2, Wp, 2).transpose(0, 1, 3, 5, 2, 4) \
        .reshape(B, C * 4, Hp, Wp)
    wp = jnp.pad(w, ((0, 0), (0, 0), (0, 2 * kp - KH), (0, 2 * kp - KW)))
    w2 = wp.reshape(O, C, kp, 2, kp, 2).transpose(0, 1, 3, 5, 2, 4) \
        .reshape(O, C * 4, kp, kp)
    y = lax.conv_general_dilated(
        x2, w2, (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return y[:, :, :outs[0], :outs[1]]


def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter: int = 0, num_group: int = 1,
                no_bias: bool = False, layout: str = "NCHW"):
    """N-D convolution. Weight layout follows ``layout`` (OIHW for NCHW).

    Stride-2 small-channel NCHW stems (ResNet 7x7 s2 C3 and friends) are
    rewritten via space-to-depth (see ``_s2d_stem_conv``); disable with
    ``MXNET_CONV_S2D=0``.
    """
    nd_data = _as_nd(data)
    ndim = nd_data.ndim - 2
    stride = _pair(stride or 1, ndim)
    dilate = _pair(dilate or 1, ndim)
    pad = _pair(pad if pad is not None else 0, ndim)
    dn = _CONV_DIMNUMS[(layout,)]
    groups = num_group
    padding = [(p, p) for p in pad]

    inputs = [nd_data, _as_nd(weight)]
    has_bias = bias is not None and not no_bias
    if has_bias:
        inputs.append(_as_nd(bias))
    chan_axis = layout.index("C")

    s2d_ok = (ndim == 2 and layout == "NCHW" and groups == 1 and
              tuple(stride) == (2, 2) and tuple(dilate) == (1, 1) and
              getenv("MXNET_CONV_S2D", "1") != "0")

    def impl(x, w, *b):
        # no preferred_element_type upcast for bf16: the TPU MXU already
        # accumulates bf16 convs in f32 internally, and an explicit f32
        # output breaks the conv transpose rule under reverse-mode AD
        y = None
        if s2d_ok and x.shape[1] <= 8:
            y = _s2d_stem_conv(x, w, padding)
        if y is None:
            y = lax.conv_general_dilated(
                x, w, window_strides=stride, padding=padding,
                rhs_dilation=dilate, dimension_numbers=dn,
                feature_group_count=groups)
        if b:
            shape = [1] * y.ndim
            shape[chan_axis] = b[0].shape[0]
            y = y + b[0].reshape(shape)
        return y

    return invoke("convolution", impl, tuple(inputs))


def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter: int = 0,
                  num_group: int = 1, no_bias: bool = True,
                  layout: str = "NCHW"):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc)."""
    nd_data = _as_nd(data)
    ndim = nd_data.ndim - 2
    stride = _pair(stride or 1, ndim)
    dilate = _pair(dilate or 1, ndim)
    pad = _pair(pad if pad is not None else 0, ndim)
    dn = _CONV_DIMNUMS[(layout,)]
    groups = num_group
    adj = _pair(adj if adj is not None else 0, ndim)
    inputs = [nd_data, _as_nd(weight)]
    has_bias = bias is not None and not no_bias
    if has_bias:
        inputs.append(_as_nd(bias))
    chan_axis = layout.index("C")
    # output_padding (adj) extends the high side: out = (in-1)*s - 2p +
    # d*(k-1) + 1 + adj, matching the reference's Deconvolution adj param
    padding = [(d * (k - 1) - p, d * (k - 1) - p + a)
               for k, p, d, a in zip(_pair(kernel, ndim), pad, dilate, adj)] \
        if kernel is not None else [(0, 0)] * ndim

    if groups != 1:
        raise MXNetError(
            "deconvolution with num_group > 1 is not implemented; "
            "use num_group=1 or a grouped conv + resize")

    def impl(x, w, *b):
        # gradient-of-conv formulation: lhs_dilation implements the
        # stride; the kernel is spatially flipped with in/out channel
        # axes swapped (reference deconv weight layout is (in, out, k...))
        if dn[1].startswith("OI"):        # w: (in, out, spatial...)
            wk = jnp.swapaxes(w, 0, 1)    # -> (out, in, spatial...)
            spatial = tuple(range(2, wk.ndim))
        else:                             # w: (spatial..., out, in)
            wk = jnp.swapaxes(w, -1, -2)  # -> (spatial..., in, out)
            spatial = tuple(range(0, wk.ndim - 2))
        wk = jnp.flip(wk, axis=spatial)
        y = lax.conv_general_dilated(
            x, wk, window_strides=(1,) * ndim,
            padding=padding, lhs_dilation=stride, rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=1)
        if b:
            shape = [1] * y.ndim
            shape[chan_axis] = b[0].shape[0]
            y = y + b[0].reshape(shape)
        return y

    return invoke("deconvolution", impl, tuple(inputs))


# ---------------------------------------------------------------------------
# Pooling (reference: src/operator/nn/pooling.cc → lax.reduce_window)
# ---------------------------------------------------------------------------

def pooling(data, kernel=None, pool_type: str = "max", stride=None, pad=None,
            global_pool: bool = False, count_include_pad: bool = True,
            layout: str = "NCHW"):
    nd_data = _as_nd(data)
    ndim = nd_data.ndim - 2
    spatial_axes = [i for i, c in enumerate(layout) if c not in "NC"]

    if global_pool:
        axes = tuple(spatial_axes)
        if pool_type == "max":
            return invoke("global_max_pool",
                          lambda x: jnp.max(x, axis=axes, keepdims=True),
                          (nd_data,))
        return invoke("global_avg_pool",
                      lambda x: jnp.mean(x, axis=axes, keepdims=True),
                      (nd_data,))

    kernel = _pair(kernel, ndim)
    stride = _pair(stride or kernel, ndim)
    pad = _pair(pad if pad is not None else 0, ndim)

    window = [1] * nd_data.ndim
    strides = [1] * nd_data.ndim
    padding = [(0, 0)] * nd_data.ndim
    for ax, k, s, p in zip(spatial_axes, kernel, stride, pad):
        window[ax], strides[ax], padding[ax] = k, s, (p, p)
    window, strides = tuple(window), tuple(strides)
    pt, cip = pool_type, count_include_pad

    def impl(x):
        if pt == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                else jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, window, strides, padding)
        if pt in ("avg", "sum"):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            if pt == "sum":
                return s
            if cip:
                denom = 1
                for k in kernel:
                    denom *= k
                return s / denom
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
            return s / cnt
        if pt == "lp":
            s = lax.reduce_window(jnp.abs(x) ** 2, 0.0, lax.add, window,
                                  strides, padding)
            return jnp.sqrt(s)
        raise ValueError(f"unknown pool_type {pt}")

    return invoke(f"pooling_{pt}", impl, (nd_data,))


def adaptive_avg_pool2d(data, output_size: Union[int, Tuple[int, int]] = 1,
                        layout: str = "NCHW"):
    """contrib.AdaptiveAvgPooling2D analog (common for squeeze-excite)."""
    out = _pair(output_size, 2)
    nd_data = _as_nd(data)
    h_ax, w_ax = layout.index("H"), layout.index("W")
    H, W = nd_data.shape[h_ax], nd_data.shape[w_ax]
    if H % out[0] or W % out[1]:
        raise ValueError("adaptive pool requires divisible spatial dims")
    kh, kw = H // out[0], W // out[1]

    def impl(x):
        window = [1] * x.ndim
        window[h_ax], window[w_ax] = kh, kw
        s = lax.reduce_window(x, 0.0, lax.add, tuple(window), tuple(window),
                              [(0, 0)] * x.ndim)
        return s / (kh * kw)

    return invoke("adaptive_avg_pool2d", impl, (nd_data,))


# ---------------------------------------------------------------------------
# Normalization (reference: batch_norm.cc, layer_norm.cc w/ fast CUDA path,
# group_norm.cc, instance_norm.cc, l2_normalization.cc)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _bn_train_core(red_axes, eps, centered_stats, x, g, b, shift):
    out, mean, var, _, _ = _bn_train_math(red_axes, eps, centered_stats,
                                          x, g, b, shift)
    return out, mean, var


def _bn_train_math(red_axes, eps, centered_stats, x, g, b, shift):
    """Batch-stat forward.

    Default (``centered_stats=False``): ONE fused f32 sweep computes
    E[x-s] and E[(x-s)^2] about ``shift`` (the layer's running mean —
    already an op input, so the reduction starts immediately; ANY
    x-derived shift was measured to serialize a pre-pass and cost
    15-20% of a ResNet-50 step). The naive unshifted one-pass
    E[x^2]-E[x]^2 catastrophically cancels for large-mean inputs; the
    shift bounds the cancellation by |E[x]-shift|/std, which the gluon
    layer keeps ~0 by passing its stat-shift buffer (the PREVIOUS
    batch's mean) and using centered stats for the one virgin-shift
    forward (the fix for the round-2 advisor cold-start finding).
    Exact in infinite precision regardless of shift.

    ``centered_stats=True`` (``MXNET_BN_STATS=centered``): classic
    mean-then-E[(x-m)^2] — unconditionally stable, but the variance
    reduction serializes after the mean, costing one extra full sweep
    over x (~7% of a ResNet-50 step on v5e).
    """
    xf = x.astype(jnp.float32)
    shape = [1] * x.ndim
    for i in range(x.ndim):
        if i not in red_axes:
            shape[i] = x.shape[i]
    if centered_stats:
        mean = jnp.mean(xf, axis=red_axes)
        centered = xf - mean.reshape(shape)
        var = jnp.mean(centered * centered, axis=red_axes)
    else:
        s = lax.stop_gradient(shift.astype(jnp.float32))
        centered = xf - s.reshape(shape)
        mean_c = jnp.mean(centered, axis=red_axes)
        m2 = jnp.mean(centered * centered, axis=red_axes)
        var = jnp.maximum(m2 - mean_c * mean_c, 0.0)
        mean = mean_c + s
    inv = lax.rsqrt(var + eps)
    xhat = (xf - mean.reshape(shape)) * inv.reshape(shape)
    out = (xhat * g.astype(jnp.float32).reshape(shape)
           + b.astype(jnp.float32).reshape(shape)).astype(x.dtype)
    return out, mean, var, shape, inv


def _bn_train_fwd(red_axes, eps, centered_stats, x, g, b, shift):
    out, mean, var, shape, inv = _bn_train_math(
        red_axes, eps, centered_stats, x, g, b, shift)
    # residuals: x (original dtype) + per-channel stats; xhat is
    # recomputed in bwd (one fused elementwise op) to halve live memory
    return (out, mean, var), (x, g, mean, inv, tuple(shape), shift)


def _bn_train_bwd(red_axes, eps, centered_stats, res, cots):
    """Fused BN backward (the cudnn BatchNormalizationBackward recipe):
    dx = g*inv*(dy - db/N - xhat*dg/N), one stat sweep + one apply sweep.
    Direct cotangents on the mean/var outputs (normally zero — the layer
    consumes them outside the tape) are folded into the same pass."""
    x, g, mean, inv, shape, shift = res
    dy, dmean, dvar = cots
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    n = 1
    for i in red_axes:
        n *= x.shape[i]
    xhat = (xf - mean.reshape(shape)) * inv.reshape(shape)
    dg = jnp.sum(dyf * xhat, axis=red_axes)
    db = jnp.sum(dyf, axis=red_axes)
    gf = g.astype(jnp.float32)
    dx = (gf * inv).reshape(shape) * (
        dyf - (db / n).reshape(shape) - xhat * (dg / n).reshape(shape))
    if getattr(dmean, "dtype", None) != jax.dtypes.float0:
        dx = dx + (dmean.astype(jnp.float32) / n).reshape(shape)
    if getattr(dvar, "dtype", None) != jax.dtypes.float0:
        dx = dx + (dvar.astype(jnp.float32) * (2.0 / n)).reshape(shape) \
            * (xhat / inv.reshape(shape))
    return (dx.astype(x.dtype), dg.astype(g.dtype), db.astype(g.dtype),
            jnp.zeros_like(shift))  # shift (stop_gradient) gets no grad


_bn_train_core.defvjp(_bn_train_fwd, _bn_train_bwd)


def batch_norm(data, gamma, beta, running_mean, running_var,
               eps: float = 1e-5, momentum: float = 0.9,
               fix_gamma: bool = False, use_global_stats: bool = False,
               axis: int = 1, training: Optional[bool] = None,
               stats: Optional[str] = None, shift=None):
    """BatchNorm forward. Returns (out, batch_mean, batch_var).

    The moving-stat update is done by the caller (gluon BatchNorm layer)
    outside the tape — the reference mutates aux states inside the op; a
    functional XLA op cannot, so the layer owns that side effect.

    Training-mode stats use a single-pass E[x]/E[x^2] reduction with f32
    accumulation and a hand-fused backward (reference: the cuDNN
    BatchNormalization kernels the reference dispatches to from
    ``src/operator/nn/batch_norm.cc``).
    """
    nd = _as_nd(data)
    ax = axis % nd.ndim  # normalize negative axis (e.g. -1 for NHWC)
    ep, fg = eps, fix_gamma
    train = is_training() if training is None else training
    use_batch_stats = train and not use_global_stats

    red_axes = tuple(i for i in range(nd.ndim) if i != ax)

    # stats: per-call override for the training statistics scheme — the
    # gluon layer forces 'centered' on its first (virgin-shift) training
    # forward so the shifted one-pass never sees a cold shift.
    # shift: explicit variance-shift vector for the one-pass stats (the
    # gluon layer passes its stat-shift buffer = the previous batch's
    # mean, always ~E[x]); defaults to the running mean for direct op
    # callers.
    if stats is None:
        stats = getenv("MXNET_BN_STATS", "shifted")
    centered_stats = stats == "centered"
    has_shift = shift is not None

    def impl(x, g, b, rm, rv, *rest):
        gg = jnp.ones_like(g) if fg else g
        if use_batch_stats:
            sh = rest[0] if has_shift else rm
            out, m, v = _bn_train_core(red_axes, ep, centered_stats,
                                       x, gg, b, sh)
            # stats return in the running-stat dtype so the layer's
            # moving-average update cannot silently promote rm/rv
            # (and thus eval-mode outputs) to f32 on a bf16-cast model
            return out, m.astype(rm.dtype), v.astype(rv.dtype)
        shape = [1] * x.ndim
        shape[ax] = x.shape[ax]
        inv = lax.rsqrt(rv + ep)
        out = (x - rm.reshape(shape)) * (inv * gg).reshape(shape) \
            + b.reshape(shape)
        return out, rm, rv

    inputs = (nd, _as_nd(gamma), _as_nd(beta),
              _as_nd(running_mean), _as_nd(running_var))
    if has_shift:
        inputs = inputs + (_as_nd(shift),)
    return invoke("batch_norm", impl, inputs)


# ---------------------------------------------------------------------------
# Prologue-fused 1x1 convolution (TPU bandwidth optimization): the BN
# apply + ReLU run on the VMEM tile as the consuming conv reads it, so
# the activated tensor never exists in HBM.  The reference materializes
# every Convolution->BatchNorm->Activation junction (convolution.cc /
# batch_norm.cc dispatch per-op); on TPU the ResNet step is HBM-bound
# (BASELINE.md bandwidth roofline) and XLA cannot fuse producers into a
# conv operand, so this is a Pallas kernel (ops/pallas/conv_fused.py).
# ---------------------------------------------------------------------------

register_env("MXNET_FUSE_BN_CONV", "0",
             "Fuse BatchNorm-apply+ReLU (or a plain ReLU) into a consuming "
             "1x1 stride-1 convolution as one Pallas GEMM. 0 (default) "
             "disables; 'auto' enables on a single-device TPU backend; 1 "
             "forces on (CPU runs the kernels in interpret mode). "
             "Numerically invisible (tests/test_fused_conv.py); default-off "
             "until the kernels beat XLA's convs at the gated shapes "
             "(benchmark/fused_conv_probe.py).")

_FUSE_BN_CONV_LAST: list = [None]


def conv_fusion_enabled() -> bool:
    """Resolve MXNET_FUSE_BN_CONV OUTSIDE traced closures (graph-knob
    contract: a toggle bumps the gluon graph epoch rather than silently
    replaying a stale executable).  'auto' restricts to single-device TPU
    backends: the Pallas call is not SPMD-partitionable under a
    multi-device pjit, and CPU interpret mode is for tests only."""
    val = str(getenv("MXNET_FUSE_BN_CONV", "0")).lower()
    if val == "auto":
        cur = (jax.default_backend() == "tpu" and jax.device_count() == 1)
    else:
        cur = val not in ("0", "false", "off")
    if _FUSE_BN_CONV_LAST[0] is None:
        _FUSE_BN_CONV_LAST[0] = cur
    elif _FUSE_BN_CONV_LAST[0] != cur:
        _FUSE_BN_CONV_LAST[0] = cur
        from ..gluon.block import invalidate_cached_graphs
        invalidate_cached_graphs()
    return cur


from ..base import register_graph_knob as _register_graph_knob  # noqa: E402
_register_graph_knob(conv_fusion_enabled)


def _bn_batch_stats(xf, red_axes, centered_stats, shift):
    """Differentiable batch mean/var — the same shifted one-pass scheme
    as _bn_train_math, but in plain jnp so autodiff carries gradients
    through the stats (the fused-conv op composes them with the Pallas
    kernel's custom VJP; XLA fuses the resulting sweeps)."""
    if centered_stats:
        mean = jnp.mean(xf, axis=red_axes)
        centered = xf - mean.reshape([1, -1] + [1] * (xf.ndim - 2))
        var = jnp.mean(centered * centered, axis=red_axes)
        return mean, var
    s = lax.stop_gradient(shift.astype(jnp.float32))
    sh = s.reshape([1, -1] + [1] * (xf.ndim - 2))
    centered = xf - sh
    mean_c = jnp.mean(centered, axis=red_axes)
    m2 = jnp.mean(centered * centered, axis=red_axes)
    var = jnp.maximum(m2 - mean_c * mean_c, 0.0)
    return mean_c + s, var


def batch_norm_relu_conv1x1(data, gamma, beta, running_mean, running_var,
                            weight, conv_bias=None, eps: float = 1e-5,
                            fix_gamma: bool = False,
                            use_global_stats: bool = False,
                            training: Optional[bool] = None,
                            stats: Optional[str] = None, shift=None,
                            relu: bool = True):
    """``conv1x1(relu(batch_norm(data)))`` as ONE fused kernel, NCHW.

    Same statistics contract as ``batch_norm`` (axis=1 only): shifted
    one-pass batch stats (or 'centered' for the virgin step), moving-stat
    update left to the caller.  Returns ``(out, batch_mean, batch_var)``
    with out of shape (N, Co, H, W) from weight (Co, Ci, 1, 1).
    """
    from .pallas.conv_fused import fused_prologue_conv1x1
    nd = _as_nd(data)
    if nd.ndim != 4:
        raise MXNetError("batch_norm_relu_conv1x1 expects NCHW data")
    ep, fg = eps, fix_gamma
    train = is_training() if training is None else training
    use_batch_stats = train and not use_global_stats
    if stats is None:
        stats = getenv("MXNET_BN_STATS", "shifted")
    centered_stats = stats == "centered"
    has_shift = shift is not None
    has_bias = conv_bias is not None
    red_axes = (0, 2, 3)

    def impl(x, g, b, rm, rv, w, *rest):
        # optional operands ride at fixed slots: [conv_bias][shift]
        cb = rest[0] if has_bias else None
        sh_arr = rest[1 if has_bias else 0] if has_shift else rm
        gg = jnp.ones_like(g) if fg else g
        if use_batch_stats:
            mean, var = _bn_batch_stats(x.astype(jnp.float32), red_axes,
                                        centered_stats, sh_arr)
        else:
            mean = rm.astype(jnp.float32)
            var = rv.astype(jnp.float32)
        inv = lax.rsqrt(var + ep)
        scale = gg.astype(jnp.float32) * inv
        shiftv = b.astype(jnp.float32) - mean * scale
        y = fused_prologue_conv1x1(x, w, scale, shiftv, relu=relu, bias=cb)
        return y, mean.astype(rm.dtype), var.astype(rv.dtype)

    inputs = (nd, _as_nd(gamma), _as_nd(beta),
              _as_nd(running_mean), _as_nd(running_var), _as_nd(weight))
    inputs = inputs + ((_as_nd(conv_bias),) if has_bias else ())
    if has_shift:
        inputs = inputs + (_as_nd(shift),)
    return invoke("batch_norm_relu_conv1x1", impl, inputs)


def relu_conv1x1(data, weight, conv_bias=None):
    """``conv1x1(relu(data))`` as one fused Pallas GEMM (NCHW) — the
    bottleneck-epilogue junction (see ops/pallas/conv_fused.py)."""
    from .pallas.conv_fused import fused_prologue_conv1x1
    nd = _as_nd(data)
    if nd.ndim != 4:
        raise MXNetError("relu_conv1x1 expects NCHW data")
    has_bias = conv_bias is not None

    def impl(x, w, *rest):
        return fused_prologue_conv1x1(x, w, None, None, relu=True,
                                      bias=rest[0] if has_bias else None)

    inputs = (nd, _as_nd(weight)) + \
        ((_as_nd(conv_bias),) if has_bias else ())
    return invoke("relu_conv1x1", impl, inputs)


def layer_norm(data, gamma, beta, axis: int = -1, eps: float = 1e-5):
    ax, ep = axis, eps
    def impl(x, g, b):
        mean = jnp.mean(x, axis=ax, keepdims=True)
        var = jnp.var(x, axis=ax, keepdims=True)
        out = (x - mean) * lax.rsqrt(var + ep)
        shape = [1] * x.ndim
        shape[ax] = x.shape[ax]
        return out * g.reshape(shape) + b.reshape(shape)
    return invoke("layer_norm", impl,
                  (_as_nd(data), _as_nd(gamma), _as_nd(beta)))


def rms_norm(data, gamma, axis: int = -1, eps: float = 1e-6):
    """RMSNorm (beyond-reference; standard in modern LLM blocks)."""
    ax, ep = axis, eps
    def impl(x, g):
        ms = jnp.mean(jnp.square(x), axis=ax, keepdims=True)
        shape = [1] * x.ndim
        shape[ax] = x.shape[ax]
        return x * lax.rsqrt(ms + ep) * g.reshape(shape)
    return invoke("rms_norm", impl, (_as_nd(data), _as_nd(gamma)))


def group_norm(data, gamma, beta, num_groups: int = 1, eps: float = 1e-5):
    """GroupNorm over NC... layout (reference: src/operator/nn/group_norm.cc)."""
    ng, ep = num_groups, eps
    def impl(x, g, b):
        N, C = x.shape[0], x.shape[1]
        rest = x.shape[2:]
        xg = x.reshape((N, ng, C // ng) + rest)
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        xg = (xg - mean) * lax.rsqrt(var + ep)
        x = xg.reshape(x.shape)
        shape = [1, C] + [1] * len(rest)
        return x * g.reshape(shape) + b.reshape(shape)
    return invoke("group_norm", impl,
                  (_as_nd(data), _as_nd(gamma), _as_nd(beta)))


def instance_norm(data, gamma, beta, eps: float = 1e-5):
    ep = eps
    def impl(x, g, b):
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        out = (x - mean) * lax.rsqrt(var + ep)
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        return out * g.reshape(shape) + b.reshape(shape)
    return invoke("instance_norm", impl,
                  (_as_nd(data), _as_nd(gamma), _as_nd(beta)))


def l2_normalization(data, eps: float = 1e-10, mode: str = "instance"):
    ep, md = eps, mode
    def impl(x):
        if md == "instance":
            axes = tuple(range(1, x.ndim))
        elif md == "channel":
            axes = (1,)
        else:  # spatial
            axes = tuple(range(2, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + ep)
        return x / n
    return invoke("l2_normalization", impl, (_as_nd(data),))


def lrn(data, alpha: float = 1e-4, beta: float = 0.75, knorm: float = 2.0,
        nsize: int = 5):
    """Local response norm (reference: src/operator/nn/lrn.cc)."""
    a, b, k, n = alpha, beta, knorm, nsize
    def impl(x):
        sq = jnp.square(x)
        # sum over channel window: pad channel axis then reduce_window
        window = [1, n] + [1] * (x.ndim - 2)
        pads = [(0, 0), (n // 2, n // 2)] + [(0, 0)] * (x.ndim - 2)
        s = lax.reduce_window(sq, 0.0, lax.add, tuple(window),
                              (1,) * x.ndim, pads)
        return x / jnp.power(k + a / n * s, b)
    return invoke("lrn", impl, (_as_nd(data),))


# ---------------------------------------------------------------------------
# Dropout (reference: src/operator/nn/dropout.cc)
# ---------------------------------------------------------------------------

def dropout(data, p: float = 0.5, mode: str = "training", axes=None,
            training: Optional[bool] = None):
    train = is_training() if training is None else training
    if (not train and mode != "always") or p <= 0.0:
        return _as_nd(data)
    rate, axs = p, axes
    key = _random.split_key()
    def impl(x):
        shape = list(x.shape)
        if axs:
            # variational dropout: mask is SHARED along the listed axes
            # (mask dim = 1 there), matching the reference's Dropout(axes=)
            for ax in axs:
                shape[ax] = 1
        keep = jax.random.bernoulli(key, 1.0 - rate, tuple(shape))
        return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)
    return invoke("dropout", impl, (_as_nd(data),))


# ---------------------------------------------------------------------------
# Embedding / indexing helpers (reference: indexing_op.cc Embedding, pick)
# ---------------------------------------------------------------------------

def embedding(data, weight, input_dim: Optional[int] = None,
              output_dim: Optional[int] = None, dtype=None,
              sparse_grad: bool = False):
    """Table lookup: out[i...] = weight[data[i...]].

    ``sparse_grad=True`` produces a row-sparse weight gradient
    (reference: Embedding's kRowSparseStorage grad — only touched rows
    are stored, feeding the lazy sparse optimizer updates)."""
    nd_idx, nd_w = _as_nd(data), _as_nd(weight)

    def impl(idx, w):
        return jnp.take(w, idx.astype(jnp.int32), axis=0)

    if not sparse_grad:
        return invoke("embedding", impl, (nd_idx, nd_w))

    from .._tape import RowSparseCot
    from ..ndarray.register import invoke_with_custom_vjp

    idx_raw = nd_idx._data
    w_shape = tuple(nd_w.shape)

    def vjp_fn(g):
        flat_idx = idx_raw.reshape(-1).astype(jnp.int32)
        vals = g.reshape((-1,) + w_shape[1:])
        return (None, RowSparseCot(flat_idx, vals, w_shape))

    return invoke_with_custom_vjp("embedding", impl, (nd_idx, nd_w),
                                  vjp_fn)


def take_positions(data, positions):
    """Gather per-batch sequence positions: (B,T,C),(B,P) -> (B,P,C)
    (gluon-nlp ``select_vectors_by_position`` — the MLM-head gather)."""
    def impl(x, pos):
        pos = pos.astype(jnp.int32)
        return jnp.take_along_axis(x, pos[:, :, None], axis=1)
    return invoke("take_positions", impl, (_as_nd(data), _as_nd(positions)))


def pick(data, index, axis: int = -1, keepdims: bool = False,
         mode: str = "clip"):
    ax, kd = axis, keepdims
    def impl(x, i):
        i = jnp.expand_dims(i.astype(jnp.int32), ax)
        out = jnp.take_along_axis(x, i, axis=ax)
        return out if kd else jnp.squeeze(out, axis=ax)
    return invoke("pick", impl, (_as_nd(data), _as_nd(index)))


# ---------------------------------------------------------------------------
# Sequence ops (reference: sequence_mask.cc / last.cc / reverse.cc — the
# building blocks of the era's long-sequence handling, SURVEY.md 5.7)
# ---------------------------------------------------------------------------

def sequence_mask(data, sequence_length=None, use_sequence_length: bool = False,
                  value: float = 0.0, axis: int = 0):
    if not use_sequence_length or sequence_length is None:
        return _as_nd(data)
    v, ax = value, axis
    nd = _as_nd(data)
    T = nd.shape[ax]
    def impl(x, sl):
        ar = jnp.arange(T)
        if ax == 0:  # (T, N, ...)
            mask = ar[:, None] < sl[None, :]
        else:        # (N, T, ...)
            mask = ar[None, :] < sl[:, None]
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        return jnp.where(mask, x, v)
    return invoke("sequence_mask", impl, (nd, _as_nd(sequence_length)))


def sequence_last(data, sequence_length=None, use_sequence_length: bool = False,
                  axis: int = 0):
    nd = _as_nd(data)
    ax = axis
    if not use_sequence_length or sequence_length is None:
        idx = nd.shape[ax] - 1
        def impl(x):
            return lax.index_in_dim(x, idx, axis=ax, keepdims=False)
        return invoke("sequence_last", impl, (nd,))
    def impl2(x, sl):
        last = (sl.astype(jnp.int32) - 1)
        if ax == 0:
            xt = jnp.moveaxis(x, 0, 1)  # (N, T, ...)
        else:
            xt = x
        idx = last.reshape((-1,) + (1,) * (xt.ndim - 1))
        out = jnp.take_along_axis(xt, idx, axis=1)
        return jnp.squeeze(out, axis=1)
    return invoke("sequence_last", impl2, (nd, _as_nd(sequence_length)))


def sequence_reverse(data, sequence_length=None,
                     use_sequence_length: bool = False, axis: int = 0):
    nd = _as_nd(data)
    ax = axis
    if not use_sequence_length or sequence_length is None:
        def impl(x):
            return jnp.flip(x, axis=ax)
        return invoke("sequence_reverse", impl, (nd,))
    T = nd.shape[ax]
    def impl2(x, sl):
        ar = jnp.arange(T)
        sl = sl.astype(jnp.int32)
        # per-batch index: reverse within [0, len), identity beyond
        if ax == 0:
            idx = jnp.where(ar[:, None] < sl[None, :],
                            sl[None, :] - 1 - ar[:, None], ar[:, None])
            return jnp.take_along_axis(
                x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=0)
        idx = jnp.where(ar[None, :] < sl[:, None],
                        sl[:, None] - 1 - ar[None, :], ar[None, :])
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return invoke("sequence_reverse", impl2, (nd, _as_nd(sequence_length)))


def topk_mask(data, k: int, axis: int = -1):
    kk, ax = k, axis
    def impl(x):
        xm = jnp.moveaxis(x, ax, -1)
        thresh = jax.lax.top_k(xm, kk)[0][..., -1:]
        mask = xm >= thresh
        return jnp.moveaxis(mask, -1, ax)
    return invoke("topk_mask", impl, (_as_nd(data),))


def smooth_l1(data, scalar: float = 1.0):
    """Smooth-L1 (reference: src/operator/tensor/elemwise_unary_op)."""
    s = scalar
    def impl(x):
        s2 = s * s
        return jnp.where(jnp.abs(x) < 1.0 / s2,
                         0.5 * s2 * jnp.square(x),
                         jnp.abs(x) - 0.5 / s2)
    return invoke("smooth_l1", impl, (_as_nd(data),))


# ---------------------------------------------------------------------------
# Loss-head output ops (reference: src/operator/softmax_output.cc and
# src/operator/regression_output-inl.h). These are the symbolic-API loss
# heads: forward is the prediction; backward IGNORES the incoming output
# cotangent and injects the loss gradient directly — the reference's
# "implicit loss" contract that Module/Executor training relies on.
# ---------------------------------------------------------------------------

def _zero_cot(lab):
    """A cotangent for the label input (float0 for ints, zeros for floats)."""
    import numpy as onp
    if jnp.issubdtype(lab.dtype, jnp.integer) or lab.dtype == jnp.bool_:
        return onp.zeros(lab.shape, dtype=jax.dtypes.float0)
    return jnp.zeros_like(lab)


def softmax_output(data, label, grad_scale: float = 1.0,
                   ignore_label: float = -1.0, use_ignore: bool = False,
                   normalization: str = "null", multi_output: bool = False,
                   preserve_shape: bool = False, smooth_alpha: float = 0.0,
                   out_grad: bool = False):
    """Softmax forward with cross-entropy gradient injected on backward.

    ``multi_output``: softmax over axis 1 with label shaped like the
    remaining axes (the reference's per-position classification mode).
    """
    gs, il, ui, nrm = grad_scale, ignore_label, use_ignore, normalization
    ax = 1 if multi_output else -1
    sa = smooth_alpha

    @jax.custom_vjp
    def _core(x, lab):
        return jax.nn.softmax(x, axis=ax)

    def _fwd(x, lab):
        return _core(x, lab), (x, lab)

    def _bwd(res, g):
        x, lab = res
        prob = jax.nn.softmax(x, axis=ax)
        ncls = x.shape[ax]
        oh = jax.nn.one_hot(lab.astype(jnp.int32), ncls, dtype=x.dtype,
                            axis=ax)
        if sa:
            oh = oh * (1.0 - sa) + sa / (ncls - 1) * (1.0 - oh)
        grad = prob - oh
        valid = None
        if ui:
            valid = (lab != il).astype(x.dtype)
            grad = grad * jnp.expand_dims(valid, ax)
        if nrm == "batch":
            grad = grad / x.shape[0]
        elif nrm == "valid":
            cnt = jnp.sum(valid) if valid is not None else \
                float(lab.size)
            grad = grad / jnp.maximum(cnt, 1.0)
        return grad * gs, _zero_cot(lab)

    _core.defvjp(_fwd, _bwd)
    return invoke("softmax_output", _core, (_as_nd(data), _as_nd(label)))


def _regression_output(name, fwd_fn, grad_fn, data, label, grad_scale):
    gs = grad_scale

    @jax.custom_vjp
    def _core(x, lab):
        return fwd_fn(x)

    def _fwd(x, lab):
        return _core(x, lab), (x, lab)

    def _bwd(res, g):
        x, lab = res
        out = fwd_fn(x)
        # the reference normalizes regression grads by the label size per
        # batch row (DivNum over num_output)
        nout = max(1, int(_np_prod(x.shape[1:]) if x.ndim > 1 else 1))
        grad = grad_fn(out, lab.astype(x.dtype)) * (gs / nout)
        return grad, _zero_cot(lab)

    _core.defvjp(_fwd, _bwd)
    return invoke(name, _core, (_as_nd(data), _as_nd(label)))


def _np_prod(shape):
    n = 1
    for s in shape:
        n *= s
    return n


def linear_regression_output(data, label, grad_scale: float = 1.0):
    """out = data; grad = (out - label) (L2 loss head)."""
    return _regression_output("linear_regression_output", lambda x: x,
                              lambda o, l: o - l, data, label, grad_scale)


def mae_regression_output(data, label, grad_scale: float = 1.0):
    """out = data; grad = sign(out - label) (L1 loss head)."""
    return _regression_output("mae_regression_output", lambda x: x,
                              lambda o, l: jnp.sign(o - l),
                              data, label, grad_scale)


def logistic_regression_output(data, label, grad_scale: float = 1.0):
    """out = sigmoid(data); grad = (out - label) (logistic loss head)."""
    return _regression_output("logistic_regression_output", jax.nn.sigmoid,
                              lambda o, l: o - l, data, label, grad_scale)


def make_loss(data, grad_scale: float = 1.0, normalization: str = "null",
              valid_thresh: float = 0.0):
    """Mark ``data`` as a loss: backward injects ``grad_scale`` ones
    (reference: ``MakeLoss``), ignoring any incoming cotangent."""
    gs, nrm = grad_scale, normalization

    @jax.custom_vjp
    def _core(x):
        return x

    def _fwd(x):
        return x, (x.shape, x.dtype)

    def _bwd(res, g):
        shape, dt = res
        scale = gs / shape[0] if nrm == "batch" else gs
        return (jnp.full(shape, scale, dtype=dt),)

    _core.defvjp(_fwd, _bwd)
    return invoke("make_loss", _core, (_as_nd(data),))


# ---------------------------------------------------------------------------
# UpSampling / ROIPooling / CTC (reference: src/operator/nn/upsampling.cc,
# src/operator/roi_pooling.cc, src/operator/contrib/ctc_loss.cc)
# ---------------------------------------------------------------------------

def up_sampling(data, scale: int = 2, sample_type: str = "nearest",
                num_filter: int = 0):
    """Spatial upsample of NCHW data by an integer ``scale``.
    sample_type: 'nearest' (repeat) or 'bilinear' (jax.image.resize —
    the reference realizes bilinear as a fixed deconv kernel)."""
    nd = _as_nd(data)
    s = int(scale)

    def impl(x):
        N, C, H, W = x.shape
        if sample_type == "nearest":
            return jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
        if sample_type == "bilinear":
            return jax.image.resize(x, (N, C, H * s, W * s), "bilinear")
        raise MXNetError(f"unknown sample_type {sample_type!r}")

    return invoke("up_sampling", impl, (nd,))


def roi_pooling(data, rois, pooled_size, spatial_scale: float = 1.0):
    """Max pooling over regions of interest (reference ``ROIPooling``).

    data: (N, C, H, W); rois: (R, 5) of [batch_idx, x1, y1, x2, y2] in
    image coordinates (scaled by ``spatial_scale`` onto the feature map).
    Returns (R, C, ph, pw).  TPU-first formulation: every output bin is a
    masked max over the full (H, W) plane — static shapes, no gathers.
    """
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else tuple(pooled_size)
    ss = float(spatial_scale)

    def impl(x, r):
        N, C, H, W = x.shape
        batch_idx = r[:, 0].astype(jnp.int32)            # (R,)
        # quantized roi bounds on the feature map (reference rounding)
        x1 = jnp.round(r[:, 1] * ss).astype(jnp.int32)
        y1 = jnp.round(r[:, 2] * ss).astype(jnp.int32)
        x2 = jnp.round(r[:, 3] * ss).astype(jnp.int32)
        y2 = jnp.round(r[:, 4] * ss).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        bin_h = rh / ph                                  # (R,)
        bin_w = rw / pw

        iy = jnp.arange(ph)
        ix = jnp.arange(pw)
        # bin edges per roi: (R, ph[+1])
        hstart = jnp.floor(iy[None, :] * bin_h[:, None]).astype(
            jnp.int32) + y1[:, None]
        hend = jnp.ceil((iy[None, :] + 1) * bin_h[:, None]).astype(
            jnp.int32) + y1[:, None]
        wstart = jnp.floor(ix[None, :] * bin_w[:, None]).astype(
            jnp.int32) + x1[:, None]
        wend = jnp.ceil((ix[None, :] + 1) * bin_w[:, None]).astype(
            jnp.int32) + x1[:, None]

        hh = jnp.arange(H)
        ww = jnp.arange(W)
        # membership masks: (R, ph, H) and (R, pw, W)
        hmask = (hh[None, None, :] >= hstart[:, :, None]) \
            & (hh[None, None, :] < jnp.minimum(hend, H)[:, :, None])
        wmask = (ww[None, None, :] >= wstart[:, :, None]) \
            & (ww[None, None, :] < jnp.minimum(wend, W)[:, :, None])
        feats = x[batch_idx]                             # (R, C, H, W)
        neg = jnp.finfo(x.dtype).min
        # rectangle max separates into two staged masked maxes — peak
        # intermediate stays O(R*C*H*W), not O(R*C*ph*pw*H*W)
        rows = []
        for i in range(ph):
            m = jnp.where(hmask[:, i][:, None, :, None], feats, neg) \
                .max(axis=2)                             # (R, C, W)
            cells = []
            for j in range(pw):
                cells.append(jnp.where(wmask[:, j][:, None, :], m, neg)
                             .max(axis=-1))              # (R, C)
            rows.append(jnp.stack(cells, axis=-1))       # (R, C, pw)
        out = jnp.stack(rows, axis=-2)                   # (R, C, ph, pw)
        # empty bins (degenerate rois) produce 0, like the reference
        empty = ~(hmask.any(-1)[:, :, None]
                  & wmask.any(-1)[:, None, :])           # (R, ph, pw)
        return jnp.where(empty[:, None], 0.0, out).astype(x.dtype)

    return invoke("roi_pooling", impl, (_as_nd(data), _as_nd(rois)))


def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             layout: str = "NTC"):
    """Functional CTC loss (reference ``nd.ctc_loss`` /
    ``_contrib_CTCLoss``); the log-domain DP lives in gluon.loss.CTCLoss."""
    from ..gluon.loss import CTCLoss as _CTC
    return _CTC(layout=layout)(data, label, data_lengths, label_lengths)


__all__ += ["softmax_output", "linear_regression_output",
            "mae_regression_output", "logistic_regression_output",
            "make_loss"]

for _name in __all__:
    register_op(_name, globals()[_name])


def arange_like(data, start: float = 0.0, step: float = 1.0, axis=None):
    """Range shaped like ``data`` (axis=None: the full shape, ravel
    order; otherwise a 1-D range matching that axis's length) —
    reference ``npx.arange_like``."""
    nd = _as_nd(data)
    if axis is None:
        shape = nd.shape
        n = nd.size
        return invoke("arange_like",
                      lambda x: (jnp.arange(n, dtype=jnp.float32) * step
                                 + start).reshape(shape), (nd,))
    n = nd.shape[axis]
    return invoke("arange_like",
                  lambda x: jnp.arange(n, dtype=jnp.float32) * step + start,
                  (nd,))


def rnn(data, parameters, state, state_cell=None, mode: str = "lstm",
        state_size: Optional[int] = None, num_layers: int = 1,
        bidirectional: bool = False, p: float = 0.0,
        state_outputs: bool = False, use_sequence_length: bool = False,
        sequence_length=None, training: Optional[bool] = None):
    """Functional fused RNN over a packed parameter vector — the
    reference's stateful ``RNN`` op (``src/operator/rnn-inl.h`` /
    ``npx.rnn``): cuDNN packed layout (all i2h/h2h weights layer-major,
    direction-minor; then all biases), TNC data, (L*D, N, H) states.

    TPU-first: unpacks the vector and runs the same hoisted-matmul
    ``lax.scan`` as ``gluon.rnn`` layers — one compiled program under
    jit, weight layout identical to the reference for checkpoint interop.
    """
    from ..gluon.rnn.rnn_layer import (_gates, _run_single_direction,
                                       _run_single_direction_varlen)

    varlen = use_sequence_length and sequence_length is not None
    if use_sequence_length and sequence_length is None:
        raise ValueError(
            "npx.rnn: use_sequence_length=True needs sequence_length")
    train = is_training() if training is None else training
    x_nd = _as_nd(data)
    params_nd = _as_nd(parameters)
    h0_nd = _as_nd(state)
    inputs = [x_nd, params_nd, h0_nd]
    if mode == "lstm":
        if state_cell is None:
            raise ValueError("lstm mode needs state_cell")
        inputs.append(_as_nd(state_cell))
    if varlen:
        inputs.append(_as_nd(sequence_length))
    H = state_size
    D = 2 if bidirectional else 1
    G = _gates(mode)
    I = x_nd.shape[2]  # noqa: E741

    # validate the packed vector length up front: a mis-sized vector
    # must error, not silently read duplicated/truncated tail data
    expected = 0
    for layer in range(num_layers):
        in_sz = I if layer == 0 else H * D
        expected += D * (G * H * in_sz + G * H * H)  # i2h + h2h weights
    expected += num_layers * D * 2 * G * H           # i2h + h2h biases
    if params_nd.size != expected:
        raise ValueError(
            f"rnn: packed parameter vector has {params_nd.size} elements, "
            f"expected {expected} for mode={mode!r} state_size={H} "
            f"num_layers={num_layers} bidirectional={bidirectional} "
            f"input size {I}")

    def impl(x, params, h0, *rest):
        rest = list(rest)
        lens = rest.pop().astype(jnp.int32) if varlen else None
        c0 = rest[0] if rest else None
        # -- unpack the cuDNN-ordered flat parameter vector
        off = 0

        def take(shape):
            nonlocal off
            n = 1
            for s in shape:
                n *= s
            seg = params[off:off + n]
            off += n
            return seg.reshape(shape)

        wi, wh, bi, bh = [], [], [], []
        for layer in range(num_layers):
            in_size = I if layer == 0 else H * D
            for d in range(D):
                wi.append(take((G * H, in_size)))
                wh.append(take((G * H, H)))
        for layer in range(num_layers):
            for d in range(D):
                bi.append(take((G * H,)))
                bh.append(take((G * H,)))

        outs = x
        h_finals, c_finals = [], []
        for layer in range(num_layers):
            dir_outs = []
            for d in range(D):
                k = layer * D + d
                h_init = h0[k]
                c_init = c0[k] if c0 is not None else None
                if varlen:
                    hs, carry = _run_single_direction_varlen(
                        mode, outs, lens, h_init, c_init, wi[k], wh[k],
                        bi[k], bh[k], reverse=(d == 1))
                else:
                    hs, carry = _run_single_direction(
                        mode, outs, h_init, c_init, wi[k], wh[k],
                        bi[k], bh[k], reverse=(d == 1))
                dir_outs.append(hs)
                h_finals.append(carry[0])
                if mode == "lstm":
                    c_finals.append(carry[1])
            outs = dir_outs[0] if D == 1 else \
                jnp.concatenate(dir_outs, axis=-1)
            if p > 0.0 and train and layer < num_layers - 1:
                from ..ndarray import random as _random
                keep = 1.0 - p
                mask = jax.random.bernoulli(
                    _random.split_key(), keep, outs.shape)
                outs = jnp.where(mask, outs / keep, 0.0).astype(outs.dtype)
        res = [outs, jnp.stack(h_finals)]
        if mode == "lstm":
            res.append(jnp.stack(c_finals))
        return tuple(res)

    out = invoke("rnn", impl, inputs)
    if not state_outputs:
        return out[0]
    return out


__all__ += ["arange_like", "rnn"]
for _name in ("arange_like", "rnn"):
    register_op(_name, globals()[_name])
