"""Transformer attention ops.

Reference parity (leezu/mxnet): ``src/operator/contrib/transformer.{cc,cu}``
— the gluon-nlp BERT-era interleaved self-attention matmuls
(``_contrib_interleaved_matmul_selfatt_qk`` / ``_valatt``) — SURVEY.md
section 2.2. Those exist because cuBLAS wanted one interleaved QKV buffer;
on TPU the fused form is a single ``dot_product_attention`` that XLA maps
onto the MXU (and a Pallas flash kernel for long sequences — see
``mxnet_tpu/ops/pallas/attention.py``). The interleaved API is provided
for source parity and lowers to the same fused path.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .._tape import is_training
from ..base import getenv, register_env
from ..ndarray.ndarray import NDArray
from ..ndarray.ops import _as_nd
from ..ndarray.register import invoke, register_op

__all__ = ["dot_product_attention", "multi_head_attention",
           "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
           "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt"]

register_env("MXNET_ATTENTION_USE_PALLAS", 0,
             "Force the Pallas flash-attention kernel on every sequence "
             "length (it auto-engages from MXNET_FLASH_MIN_SEQ up).")
register_env("MXNET_FLASH_MIN_SEQ", 512,
             "Sequence length at/above which attention auto-routes to "
             "the Pallas flash kernel (the measured v5e crossover vs "
             "XLA materialized-scores attention).")
register_env("MXNET_FLASH_BLOCK_Q", 0,
             "Flash-attention query-block rows. 0 (default) = "
             "shape-aware auto: the FULL sequence as one block at "
             "T<=512 (one grid row per head — measured +5.5% BERT-base "
             "step throughput vs 256-row blocks at T=512), 256-row "
             "blocks (the attn_probe sweep's pick) from T=1024 up.")
register_env("MXNET_FLASH_BLOCK_K", 1024,
             "Flash-attention key-block rows (v5e-tuned default; "
             "clamped to the sequence length per call).")


def _mask_to_bias(mask, dtype, batch: int, tq: int, tk: int):
    """Normalize a mask to an additive bias of rank 4 (B/1, H/1, Tq/1, Tk).

    Accepted shapes: (B, Tk) key-padding mask (the canonical BERT
    valid-length mask), (Tq, Tk) score mask, (B, Tq, Tk), or rank-4
    (B/1, H/1, Tq/1, Tk). Boolean True = attend.
    """
    if mask.dtype == jnp.bool_:
        bias = jnp.where(mask, jnp.asarray(0.0, dtype),
                         jnp.finfo(dtype).min)
    else:
        bias = mask
    if bias.ndim == 2:
        if bias.shape == (batch, tk) and (batch != tq or tq == tk):
            bias = bias[:, None, None, :]      # key-padding: (B,1,1,Tk)
        else:
            bias = bias[None, None, :, :]      # score mask: (1,1,Tq,Tk)
    elif bias.ndim == 3:
        bias = bias[:, None, :, :]             # (B,1,Tq,Tk)
    return bias


def dot_product_attention(query, key, value, mask=None,
                          scale: Optional[float] = None,
                          dropout: float = 0.0, causal: bool = False):
    """Fused scaled dot-product attention.

    Shapes: (B, T, H, D) for q/k/v (jax convention — batch, time, heads,
    head_dim). Returns (B, T, H, D). Uses XLA's fused attention; the
    Pallas flash kernel (ops/pallas/attention.py) engages on TPU for long
    sequences or when MXNET_ATTENTION_USE_PALLAS=1.
    """
    inputs = [_as_nd(query), _as_nd(key), _as_nd(value)]
    has_mask = mask is not None
    if has_mask:
        inputs.append(_as_nd(mask))
    # training flag and RNG draw resolve OUTSIDE impl: the per-op exec
    # cache would otherwise bake both into the compiled program (stale
    # dropout mode; one frozen mask reused every step) — the seed rides
    # as an op INPUT so every call gets fresh randomness
    train_rate = float(dropout) if is_training() else 0.0
    if train_rate > 0.0:
        inputs.append(_as_nd(_attn_seed()))
    sc, cz = scale, causal
    # env-dependent routing resolves OUTSIDE impl so it lands in the
    # closure cells the per-op exec cache keys on — toggling
    # MXNET_ATTENTION_USE_PALLAS / MXNET_FLASH_BLOCK_* at runtime must
    # re-dispatch, not silently hit a stale executable
    use_flash = _use_pallas_len(inputs[0].shape[1])
    blk_q = _flash_block("Q", seq=inputs[0].shape[1])
    blk_k = _flash_block("K")

    def impl(q, k, v, *rest):
        rest = list(rest)
        seed = rest.pop() if train_rate > 0.0 else None
        bias = None
        mask_learned = False
        if rest:
            bias = _mask_to_bias(rest[0], q.dtype, q.shape[0], q.shape[1],
                                 k.shape[1])
            mask_learned = rest[0].dtype != jnp.bool_
        ring = _use_ring(q, k)
        if ring is not None and _ring_bias_ok(bias, q, k):
            # padding masks and dropout stay ON the ring path (r3): the
            # bias row-stripe shards with q, dropout masks regenerate
            # per (shard, block)
            from ..parallel.ring import ring_attention
            mesh, axis = ring
            return ring_attention(q, k, v, mesh, axis=axis,
                                  scale=sc, causal=cz, bias=bias,
                                  dropout=train_rate, dropout_seed=seed)
        if use_flash and _flash_bias_ok(bias, q, k):
            from .pallas.attention import flash_attention
            return flash_attention(
                q, k, v, scale=sc, causal=cz, bias=bias,
                block_q=blk_q, block_k=blk_k,
                dropout=train_rate, dropout_seed=seed,
                bias_grad=mask_learned)
        if train_rate > 0.0:
            from .pallas.attention import dense_dropout_attention_bhtd
            import math as _math
            s = sc if sc is not None else 1.0 / _math.sqrt(q.shape[-1])
            qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
            out = dense_dropout_attention_bhtd(
                qt, kt, vt, bias, seed, train_rate, float(s), bool(cz))
            return jnp.swapaxes(out, 1, 2)
        return jax.nn.dot_product_attention(
            q, k, v, bias=bias, scale=sc, is_causal=cz)

    return invoke("dot_product_attention", impl, inputs)


def _flash_block(which: str, seq: int = 0) -> int:
    from .pallas.attention import DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    env = int(getenv(f"MXNET_FLASH_BLOCK_{which}", 0))
    if env:
        return env
    if which == "Q":
        # shape-aware default (r4 measured, BERT-base b64xT=512:
        # 138.6k tok/s with a full-T block vs 131.4k with 256): at
        # T<=512 one query block per (B,H) head removes per-block grid
        # overhead; at 1024+ the 256-row blocks from the attn_probe
        # sweep win.
        if 0 < seq <= 512:
            return seq
        return DEFAULT_BLOCK_Q
    return DEFAULT_BLOCK_K


def _flash_bias_ok(bias, q, k) -> bool:
    """The Pallas kernel broadcasts bias over dims 0/1 and (r3) over a
    unit query dim — (B,1,1,Tk) key-padding masks, the canonical BERT
    case, stream as per-tile rows. Only the trailing key dim must be
    full-size."""
    if bias is None:
        return True
    return (bias.ndim == 4 and bias.shape[2] in (1, q.shape[1]) and
            bias.shape[3] == k.shape[1])


def _attn_seed():
    """(2,) int32 seed from the framework RNG stream; under a hybridize
    trace this rides the threaded threefry key, so compiled programs get
    fresh dropout per step."""
    from ..ndarray import random as _random
    key = _random.split_key()
    return jax.random.key_data(key).reshape(-1)[:2].astype(jnp.int32)


# Ring attention shards bias rows with q and slices columns per ring
# step — the SAME (B|1, H|1, 1|Tq, Tk) contract as the flash kernel.
_ring_bias_ok = _flash_bias_ok


def _use_ring(q, k):
    """Sequence-parallel policy: a sequence_parallel context is active and
    the sequence divides over the axis → (mesh, axis), else None."""
    from ..parallel.ring import current_sequence_parallel
    sp = current_sequence_parallel()
    if sp is None:
        return None
    mesh, axis = sp
    if axis not in mesh.axis_names:
        return None
    n = mesh.shape[axis]
    if n <= 1 or q.shape[1] % n or k.shape[1] % n:
        return None
    return mesh, axis


def _use_pallas(q) -> bool:
    """Pallas flash kernel policy: explicit opt-in, or long sequences on
    TPU where the O(T^2) materialized-scores path thrashes HBM."""
    return _use_pallas_len(q.shape[1])


def _flash_threshold() -> int:
    """Sequence length at/above which the Pallas flash kernel beats XLA's
    materialized-scores attention. Measured crossover on v5e (r3 kernel:
    input-dtype MXU matmuls, causal tile skip, grid semantics): GPT-2
    tok/s pallas-vs-xla is 104k/115k at T=256, 101k/97k at 512,
    94k/71k at 1024, 81k/50k at 2048 — flash wins from 512 up.

    r5: the backward IS now a fused single pass whenever Tk fits one
    k-block (every T <= MXNET_FLASH_BLOCK_K=1024 — all headline
    shapes), halving kernel launches/q-k-v reads/probability
    recomputes.  Measured effect (attn_probe, b32 h12 d64, 60-iter
    scan, fwdbwd ms/step, flash uses 256x1024 blocks clamped to T):

        T      xla    flash(fused)   flash(two-pass, bk=T/2)
        128    1.79      2.26              —
        256    2.11      2.78             3.59
        512    5.60      4.68             6.30
        1024  17.51      8.64            12.96

    Fused is 26-33 percent faster than the two-pass recipe at equal shapes,
    flipping T=512 from marginal to +16 percent over XLA and widening T=1024
    to 2x; it also lifted BERT b48x512 train by +3.9 percent.  T <= 256
    STAYS on XLA: both paths are latency-floored there (2-6 TFLOP/s on
    a 193 TFLOP/s chip — the op can't fill the MXU at any kernel
    structure), and XLA's single fused program has the smaller fixed
    cost.  The crossover therefore remains 512 — measured, not
    assumed; the auto-threshold keeps every config on its faster
    path."""
    return int(getenv("MXNET_FLASH_MIN_SEQ", 512))


def _use_pallas_len(seq_len: int) -> bool:
    import jax as _jax
    if getenv("MXNET_ATTENTION_USE_PALLAS", 0):
        return True
    try:
        on_tpu = _jax.default_backend() not in ("cpu",)
    except Exception:
        return False
    return on_tpu and seq_len >= _flash_threshold()


def multi_head_attention(query, key, value, num_heads: int, mask=None,
                         causal: bool = False, scale: Optional[float] = None,
                         dropout: float = 0.0):
    """(B, T, C) inputs already projected; splits heads, attends, merges.
    ``dropout`` is attention-probability dropout (training mode only)."""
    nh, cz, sc = num_heads, causal, scale
    inputs = [_as_nd(query), _as_nd(key), _as_nd(value)]
    has_mask = mask is not None
    if has_mask:
        inputs.append(_as_nd(mask))
    # resolved outside impl — see dot_product_attention
    train_rate = float(dropout) if is_training() else 0.0
    if train_rate > 0.0:
        inputs.append(_as_nd(_attn_seed()))
    # resolved outside impl (exec-cache closure token) — see
    # dot_product_attention
    use_flash = _use_pallas_len(inputs[0].shape[1])
    blk_q = _flash_block("Q", seq=inputs[0].shape[1])
    blk_k = _flash_block("K")

    def impl(q, k, v, *rest):
        rest = list(rest)
        seed = rest.pop() if train_rate > 0.0 else None
        B, Tq, C = q.shape
        Tk = k.shape[1]
        d = C // nh
        qh = q.reshape(B, Tq, nh, d)
        kh = k.reshape(B, Tk, nh, d)
        vh = v.reshape(B, Tk, nh, d)
        bias = None
        mask_learned = False
        if rest:
            bias = _mask_to_bias(rest[0], q.dtype, B, Tq, Tk)
            mask_learned = rest[0].dtype != jnp.bool_
        ring = _use_ring(qh, kh)
        if ring is not None and _ring_bias_ok(bias, qh, kh):
            from ..parallel.ring import ring_attention
            mesh, axis = ring
            out = ring_attention(qh, kh, vh, mesh, axis=axis,
                                 scale=sc, causal=cz, bias=bias,
                                 dropout=train_rate, dropout_seed=seed)
        elif use_flash and _flash_bias_ok(bias, qh, kh):
            from .pallas.attention import flash_attention
            out = flash_attention(
                qh, kh, vh, scale=sc, causal=cz, bias=bias,
                block_q=blk_q, block_k=blk_k,
                dropout=train_rate, dropout_seed=seed,
                bias_grad=mask_learned)
        elif train_rate > 0.0:
            from .pallas.attention import dense_dropout_attention_bhtd
            import math as _math
            s = sc if sc is not None else 1.0 / _math.sqrt(d)
            out = jnp.swapaxes(dense_dropout_attention_bhtd(
                jnp.swapaxes(qh, 1, 2), jnp.swapaxes(kh, 1, 2),
                jnp.swapaxes(vh, 1, 2), bias, seed, train_rate,
                float(s), bool(cz)), 1, 2)
        else:
            out = jax.nn.dot_product_attention(qh, kh, vh, bias=bias,
                                               scale=sc, is_causal=cz)
        return out.reshape(B, Tq, C)

    return invoke("multi_head_attention", impl, inputs)


# ---------------------------------------------------------------------------
# Interleaved-QKV API parity (reference transformer.cc). Layout matches the
# reference: qkv is (T, N, 3*H*D) with per-head interleaving [q|k|v].
# ---------------------------------------------------------------------------

def interleaved_matmul_selfatt_qk(queries_keys_values, heads: int):
    """scores = scaled Q·Kᵀ from interleaved QKV, out (N*heads, T, T)."""
    nh = heads

    def impl(qkv):
        T, N, C3 = qkv.shape
        d = C3 // (3 * nh)
        x = qkv.reshape(T, N, nh, 3, d)
        q = x[:, :, :, 0]  # (T, N, H, D)
        k = x[:, :, :, 1]
        q = jnp.transpose(q, (1, 2, 0, 3)).reshape(N * nh, T, d)
        k = jnp.transpose(k, (1, 2, 0, 3)).reshape(N * nh, T, d)
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
        return jnp.einsum("btd,bsd->bts", q * scale, k)

    return invoke("interleaved_matmul_selfatt_qk", impl,
                  (_as_nd(queries_keys_values),))


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads: int):
    """out = att·V back to (T, N, H*D) from interleaved QKV."""
    nh = heads

    def impl(qkv, att):
        T, N, C3 = qkv.shape
        d = C3 // (3 * nh)
        x = qkv.reshape(T, N, nh, 3, d)
        v = x[:, :, :, 2]
        v = jnp.transpose(v, (1, 2, 0, 3)).reshape(N * nh, T, d)
        out = jnp.einsum("bts,bsd->btd", att, v)  # (N*H, T, D)
        out = out.reshape(N, nh, T, d)
        return jnp.transpose(out, (2, 0, 1, 3)).reshape(T, N, nh * d)

    return invoke("interleaved_matmul_selfatt_valatt", impl,
                  (_as_nd(queries_keys_values), _as_nd(attention)))


def interleaved_matmul_encdec_qk(queries, keys_values, heads: int):
    nh = heads

    def impl(q, kv):
        Tq, N, C = q.shape
        Tk = kv.shape[0]
        d = C // nh
        qh = jnp.transpose(q.reshape(Tq, N, nh, d), (1, 2, 0, 3)) \
            .reshape(N * nh, Tq, d)
        k = kv.reshape(Tk, N, nh, 2, d)[:, :, :, 0]
        kh = jnp.transpose(k, (1, 2, 0, 3)).reshape(N * nh, Tk, d)
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
        return jnp.einsum("btd,bsd->bts", qh * scale, kh)

    return invoke("interleaved_matmul_encdec_qk", impl,
                  (_as_nd(queries), _as_nd(keys_values)))


def interleaved_matmul_encdec_valatt(keys_values, attention, heads: int):
    nh = heads

    def impl(kv, att):
        Tk, N, C2 = kv.shape
        d = C2 // (2 * nh)
        v = kv.reshape(Tk, N, nh, 2, d)[:, :, :, 1]
        vh = jnp.transpose(v, (1, 2, 0, 3)).reshape(N * nh, Tk, d)
        out = jnp.einsum("bts,bsd->btd", att, vh)
        Tq = att.shape[1]
        out = out.reshape(N, nh, Tq, d)
        return jnp.transpose(out, (2, 0, 1, 3)).reshape(Tq, N, nh * d)

    return invoke("interleaved_matmul_encdec_valatt", impl,
                  (_as_nd(keys_values), _as_nd(attention)))


for _name in __all__:
    register_op(_name, globals()[_name])
