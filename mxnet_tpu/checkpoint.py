"""Checkpoint management — restart-safe training state.

Reference parity (leezu/mxnet): ``mod.save_checkpoint`` / epoch-numbered
``prefix-000N.params`` files + ``Trainer.save_states`` (SURVEY.md 5.4),
and the 5.3 blueprint note that the TPU build's failure story is
checkpoint-restart: this manager adds atomicity (tmp + rename), a
``latest`` pointer, keep-last-k retention, and one-call resume.

Works with anything exposing ``save_checkpoint(prefix)`` /
``load_checkpoint(prefix)`` (SPMDTrainer), or a (block, trainer) pair
(gluon save_parameters + Trainer.save_states).
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, List, Optional, Tuple

from .base import MXNetError

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Numbered, atomic, self-pruning checkpoints under ``directory``."""

    def __init__(self, directory: str, max_to_keep: int = 5) -> None:
        if max_to_keep < 1:
            raise MXNetError("max_to_keep must be >= 1")
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    # -- bookkeeping -------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.directory, "checkpoint.json")

    def _read_meta(self) -> dict:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"checkpoints": []}

    def _write_meta(self, meta: dict) -> None:
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._meta_path())

    @property
    def checkpoints(self) -> List[int]:
        return list(self._read_meta()["checkpoints"])

    @property
    def latest_step(self) -> Optional[int]:
        cks = self._read_meta()["checkpoints"]
        return cks[-1] if cks else None

    def _prefix(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step:07d}")

    # -- save / restore ----------------------------------------------------
    def save(self, target: Any, step: int,
             block: Optional[Any] = None) -> str:
        """Write checkpoint ``step`` atomically and prune old ones.

        target: an object with ``save_checkpoint(prefix)`` (SPMDTrainer),
        or a gluon Trainer when ``block`` is given (block params +
        trainer states).
        """
        # stage into a temp dir in the same filesystem, then rename files
        staging = tempfile.mkdtemp(dir=self.directory)
        try:
            stage_prefix = os.path.join(staging, "ckpt")
            if hasattr(target, "save_checkpoint"):
                target.save_checkpoint(stage_prefix)
            elif block is not None:
                block.save_parameters(stage_prefix + ".params")
                target.save_states(stage_prefix + ".states")
            else:
                raise MXNetError(
                    "target needs save_checkpoint(), or pass block=")
            final = self._prefix(step)
            for fname in os.listdir(staging):
                suffix = fname[len("ckpt"):]
                os.replace(os.path.join(staging, fname), final + suffix)
        finally:
            shutil.rmtree(staging, ignore_errors=True)

        meta = self._read_meta()
        meta["checkpoints"] = [s for s in meta["checkpoints"]
                               if s != step] + [step]
        while len(meta["checkpoints"]) > self.max_to_keep:
            old = meta["checkpoints"].pop(0)
            for f in os.listdir(self.directory):
                # match 'ckpt-NNNNNNN.<suffix>' exactly — a bare prefix
                # would also delete longer step numbers it prefixes
                if f.startswith(f"ckpt-{old:07d}."):
                    os.remove(os.path.join(self.directory, f))
        self._write_meta(meta)
        return self._prefix(step)

    def restore(self, target: Any, step: Optional[int] = None,
                block: Optional[Any] = None) -> Optional[int]:
        """Load checkpoint ``step`` (default: latest). Returns the step
        restored, or None if the directory has no checkpoints (fresh
        start)."""
        if step is None:
            step = self.latest_step
            if step is None:
                return None
        elif step not in self.checkpoints:
            raise MXNetError(f"no checkpoint for step {step}; have "
                             f"{self.checkpoints}")
        prefix = self._prefix(step)
        if hasattr(target, "load_checkpoint"):
            target.load_checkpoint(prefix)
        elif block is not None:
            block.load_parameters(prefix + ".params")
            target.load_states(prefix + ".states")
        else:
            raise MXNetError(
                "target needs load_checkpoint(), or pass block=")
        return step
