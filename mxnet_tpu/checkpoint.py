"""Checkpoint management — restart-safe, corruption-detecting training
state.

Reference parity (leezu/mxnet): ``mod.save_checkpoint`` / epoch-numbered
``prefix-000N.params`` files + ``Trainer.save_states`` (SURVEY.md 5.4),
and the 5.3 blueprint note that the TPU build's failure story is
checkpoint-restart: this manager adds atomicity (tmp + fsync + rename),
a ``latest`` pointer, keep-last-k retention, one-call resume — and,
because preemption/crash mid-save is a ROUTINE event on preemptible TPU
capacity, durability hardening:

* every staged file (and the directory) is **fsynced** before the
  rename, so a power cut after ``save()`` returns cannot surface a
  half-written checkpoint;
* ``checkpoint.json`` records a **SHA-256 per file**; ``restore()``
  verifies the digests and falls back to the newest checkpoint that
  verifies (``mxnet_checkpoint_restore_fallbacks_total`` counts this) —
  a truncated latest checkpoint is a recoverable event, not a dead run;
* retention never prunes the **last verified-good** checkpoint;
* orphaned staging tempdirs left by a crash between ``mkdtemp`` and the
  renames are swept on ``__init__``.

Works with anything exposing ``save_checkpoint(prefix)`` /
``load_checkpoint(prefix)`` (SPMDTrainer), or a (block, trainer) pair
(gluon save_parameters + Trainer.save_states).

:class:`CoordinatedCheckpointManager` extends the manager to a
**cluster**: a two-phase mark-then-commit rendezvous (backed by the
dist_async parameter service's ``C`` command, or any object with
``ckpt_mark(step) -> agreed`` / ``ckpt_commit(step)``) makes every
rank agree on ONE checkpoint step before any rank treats it as
resumable — a restarted cluster always resumes from one consistent
step, never a mix.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional

from .base import MXNetError
from . import metrics as _metrics
from . import faults as _faults
from ._durable import (fsync_dir as _fsync_dir,
                       sha256_file as _sha256_file, sweep_orphans)

__all__ = ["CheckpointManager", "CoordinatedCheckpointManager"]

# Staging dirs carry a recognizable prefix so the orphan sweep can never
# touch user data; plain 'tmpXXXXXXXX' dirs (pre-hardening staging) are
# swept too.  The sweep discipline (age guard, prefix scoping) lives in
# mxnet_tpu._durable, shared with the persistent compile cache.
_STAGING_PREFIX = "ckpt-staging-"
_LEGACY_STAGING = re.compile(r"^tmp[a-z0-9_]{8}$")

CHECKPOINT_SAVES = _metrics.counter(
    "mxnet_checkpoint_saves_total",
    "Checkpoints written by CheckpointManager.save.")
CHECKPOINT_SAVE_SECONDS = _metrics.histogram(
    "mxnet_checkpoint_save_seconds",
    "Wall time of CheckpointManager.save (stage + fsync + rename + "
    "prune).")
CHECKPOINT_CORRUPT = _metrics.counter(
    "mxnet_checkpoint_corrupt_total",
    "Checkpoints that failed SHA-256 verification on restore (missing "
    "or truncated/garbled files).")
CHECKPOINT_FALLBACKS = _metrics.counter(
    "mxnet_checkpoint_restore_fallbacks_total",
    "restore() calls that skipped a corrupt newer checkpoint and loaded "
    "an older verified one.")
CHECKPOINT_ORPHANS = _metrics.counter(
    "mxnet_checkpoint_orphan_sweeps_total",
    "Orphaned staging tempdirs (crash mid-save) removed by the "
    "CheckpointManager __init__ sweep.")
CKPT_COORD_SECONDS = _metrics.histogram(
    "mxnet_ckpt_coordination_seconds",
    "Wall time this rank spent blocked in the coordinated-checkpoint "
    "rendezvous (CoordinatedCheckpointManager), by phase: mark = "
    "agreeing on the step, commit = waiting for every rank's save, "
    "restore = agreeing on the resume step.", labels=("phase",))


class CheckpointManager:
    """Numbered, atomic, self-pruning, self-verifying checkpoints under
    ``directory``."""

    def __init__(self, directory: str, max_to_keep: int = 5) -> None:
        if max_to_keep < 1:
            raise MXNetError("max_to_keep must be >= 1")
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)
        self._sweep_orphan_staging()

    def _sweep_orphan_staging(self) -> None:
        """Remove staging dirs a crashed save() left behind (nothing in
        them was ever referenced by checkpoint.json); young dirs are
        left alone — they may belong to a preempted process still
        finishing its final save (see _durable.sweep_orphans)."""
        removed = sweep_orphans(
            self.directory, (_STAGING_PREFIX,),
            match=lambda e: bool(_LEGACY_STAGING.match(e)))
        if removed:
            CHECKPOINT_ORPHANS.inc(removed)

    # -- bookkeeping -------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.directory, "checkpoint.json")

    def _read_meta(self) -> dict:
        try:
            with open(self._meta_path()) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {}
        meta.setdefault("checkpoints", [])
        meta.setdefault("digests", {})
        return meta

    def _write_meta(self, meta: dict) -> None:
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())
        _fsync_dir(self.directory)

    @property
    def checkpoints(self) -> List[int]:
        return list(self._read_meta()["checkpoints"])

    @property
    def latest_step(self) -> Optional[int]:
        cks = self._read_meta()["checkpoints"]
        return cks[-1] if cks else None

    def _prefix(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step:07d}")

    # -- verification ------------------------------------------------------
    def verify(self, step: int, meta: Optional[dict] = None) -> bool:
        """True when checkpoint ``step``'s files are present and match
        their recorded SHA-256 digests.  Pre-hardening checkpoints
        (no digest record) verify by file existence alone."""
        if meta is None:
            meta = self._read_meta()
        digests: Dict[str, str] = meta["digests"].get(str(step), {})
        prefix = self._prefix(step)
        if not digests:
            # legacy checkpoint: any file with this prefix counts
            stem = os.path.basename(prefix)
            return any(f.startswith(stem + ".")
                       for f in os.listdir(self.directory))
        for suffix, want in digests.items():
            path = prefix + suffix
            try:
                if _sha256_file(path) != want:
                    return False
            except OSError:
                return False
        return True

    def _last_verified(self, meta: dict) -> Optional[int]:
        for step in reversed(meta["checkpoints"]):
            if self.verify(step, meta):
                return step
        return None

    def _protected_steps(self, meta: dict, just_saved: int) -> set:
        """Steps retention must never prune (see save())."""
        return {just_saved}

    # -- save / restore ----------------------------------------------------
    def save(self, target: Any, step: int,
             block: Optional[Any] = None) -> str:
        """Write checkpoint ``step`` atomically and prune old ones.

        target: an object with ``save_checkpoint(prefix)`` (SPMDTrainer),
        or a gluon Trainer when ``block`` is given (block params +
        trainer states).
        """
        t0 = time.perf_counter()
        # stage into a temp dir in the same filesystem, then rename files
        staging = tempfile.mkdtemp(prefix=_STAGING_PREFIX,
                                   dir=self.directory)
        digests: Dict[str, str] = {}
        try:
            _faults.maybe_fault("checkpoint.write", step=step)
            stage_prefix = os.path.join(staging, "ckpt")
            if hasattr(target, "save_checkpoint"):
                target.save_checkpoint(stage_prefix)
            elif block is not None:
                block.save_parameters(stage_prefix + ".params")
                target.save_states(stage_prefix + ".states")
            else:
                raise MXNetError(
                    "target needs save_checkpoint(), or pass block=")
            final = self._prefix(step)
            for fname in sorted(os.listdir(staging)):
                path = os.path.join(staging, fname)
                # digest + fsync BEFORE the rename: after save()
                # returns, the bytes the digest covers are the bytes on
                # disk, crash or no crash
                digests[fname[len("ckpt"):]] = _sha256_file(path)
                with open(path, "rb") as f:
                    os.fsync(f.fileno())
            for fname in sorted(os.listdir(staging)):
                suffix = fname[len("ckpt"):]
                os.replace(os.path.join(staging, fname), final + suffix)
            _fsync_dir(self.directory)
        finally:
            shutil.rmtree(staging, ignore_errors=True)

        meta = self._read_meta()
        meta["checkpoints"] = [s for s in meta["checkpoints"]
                               if s != step] + [step]
        meta["digests"][str(step)] = digests
        # retention: the just-saved step is verified-good by construction
        # (its digests were computed from the staged, fsynced bytes), so
        # pruning oldest-first while keeping it can never remove the last
        # verified checkpoint.  Subclasses can protect more steps (the
        # coordinated manager keeps the newest cluster-committed step).
        protected = self._protected_steps(meta, step)
        while len(meta["checkpoints"]) > self.max_to_keep:
            old = next((s for s in meta["checkpoints"]
                        if s not in protected), None)
            if old is None:
                break
            meta["checkpoints"].remove(old)
            meta["digests"].pop(str(old), None)
            for f in os.listdir(self.directory):
                # match 'ckpt-NNNNNNN.<suffix>' exactly — a bare prefix
                # would also delete longer step numbers it prefixes
                if f.startswith(f"ckpt-{old:07d}."):
                    try:
                        os.remove(os.path.join(self.directory, f))
                    except FileNotFoundError:
                        # pruned concurrently / already gone: retention
                        # is best-effort, never fatal to a save
                        pass
        self._write_meta(meta)
        CHECKPOINT_SAVES.inc()
        CHECKPOINT_SAVE_SECONDS.observe(time.perf_counter() - t0)
        return self._prefix(step)

    def restore(self, target: Any, step: Optional[int] = None,
                block: Optional[Any] = None) -> Optional[int]:
        """Load checkpoint ``step`` (default: newest VERIFIED).  Returns
        the step restored, or None if the directory has no checkpoints
        (fresh start).  A corrupt newer checkpoint (crash mid-write,
        truncation) is skipped with a fallback counter bump; if every
        checkpoint fails verification, raises."""
        meta = self._read_meta()
        if step is None:
            cks = meta["checkpoints"]
            if not cks:
                return None
            step = self._last_verified(meta)
            if step is None:
                CHECKPOINT_CORRUPT.inc(len(cks))
                raise MXNetError(
                    f"all {len(cks)} checkpoints in {self.directory} "
                    "failed SHA-256 verification — no safe state to "
                    "resume from")
            if step != cks[-1]:
                skipped = [s for s in cks if s > step]
                CHECKPOINT_CORRUPT.inc(len(skipped))
                CHECKPOINT_FALLBACKS.inc()
                import logging
                logging.getLogger("mxnet_tpu.checkpoint").warning(
                    "checkpoint(s) %s failed verification (truncated or "
                    "garbled); falling back to verified step %d",
                    skipped, step)
        else:
            if step not in meta["checkpoints"]:
                raise MXNetError(f"no checkpoint for step {step}; have "
                                 f"{meta['checkpoints']}")
            if not self.verify(step, meta):
                CHECKPOINT_CORRUPT.inc()
                raise MXNetError(
                    f"checkpoint {step} failed SHA-256 verification "
                    "(truncated or garbled on disk)")
        prefix = self._prefix(step)
        if hasattr(target, "load_checkpoint"):
            target.load_checkpoint(prefix)
        elif block is not None:
            block.load_parameters(prefix + ".params")
            target.load_states(prefix + ".states")
        else:
            raise MXNetError(
                "target needs load_checkpoint(), or pass block=")
        return step


class CoordinatedCheckpointManager(CheckpointManager):
    """Cluster-consistent checkpoints: two-phase mark-then-commit over a
    coordinator (the dist_async kvstore client, or anything exposing
    ``ckpt_mark(step) -> agreed_step`` and ``ckpt_commit(step)``).

    * ``save(target, step)``: **mark** — block until every rank
      proposed its step, all ranks receive the agreed step (the min
      proposed); save locally under the agreed label; **commit** —
      block until every rank's save is durably on disk, then record
      the step as *committed* in this rank's ``checkpoint.json``.
      Until a step commits, no rank treats it as resumable, so a
      crash between any two ranks' saves can never strand the cluster
      on a half-written cluster checkpoint.
    * ``restore(target)``: each rank proposes its newest committed
      (falling back to newest verified) local step through the same
      mark rendezvous; everyone restores the agreed **min** — one
      consistent step cluster-wide, or a cluster-wide fresh start
      when any rank has nothing (a half-resumed cluster is worse
      than a restart).
    * retention additionally protects the newest committed step, so a
      rank can never prune the only state the *cluster* can agree on.

    All ranks must call save/restore in the same order (the SPMD
    discipline both ``fit`` loops already follow); a dead rank is
    named in a structured error instead of hanging the rendezvous
    (heartbeat lease, ``MXNET_PS_HEARTBEAT_DEADLINE_S``).
    """

    def __init__(self, directory: str, coordinator: Any,
                 max_to_keep: int = 5) -> None:
        super().__init__(directory, max_to_keep=max_to_keep)
        for attr in ("ckpt_mark", "ckpt_commit"):
            if not callable(getattr(coordinator, attr, None)):
                raise MXNetError(
                    "coordinator needs ckpt_mark(step)/ckpt_commit"
                    "(step) — pass the dist_async kvstore client")
        self.coordinator = coordinator

    # -- committed bookkeeping ---------------------------------------------
    def _committed(self, meta: dict) -> List[int]:
        return [s for s in meta.get("committed", [])
                if s in meta["checkpoints"]]

    @property
    def committed_steps(self) -> List[int]:
        return self._committed(self._read_meta())

    def _protected_steps(self, meta: dict, just_saved: int) -> set:
        protected = {just_saved}
        committed = self._committed(meta)
        if committed:
            protected.add(max(committed))
        return protected

    # -- save / restore ----------------------------------------------------
    def save(self, target: Any, step: int,
             block: Optional[Any] = None) -> str:
        t0 = time.perf_counter()
        agreed = int(self.coordinator.ckpt_mark(int(step)))
        CKPT_COORD_SECONDS.labels(phase="mark").observe(
            time.perf_counter() - t0)
        prefix = super().save(target, agreed, block=block)
        t1 = time.perf_counter()
        self.coordinator.ckpt_commit(agreed)
        CKPT_COORD_SECONDS.labels(phase="commit").observe(
            time.perf_counter() - t1)
        # only now — every rank's save is on disk — the step becomes
        # resumable on this rank
        meta = self._read_meta()
        committed = self._committed(meta)
        if agreed not in committed:
            committed.append(agreed)
        meta["committed"] = sorted(committed)
        self._write_meta(meta)
        return prefix

    def restore(self, target: Any, step: Optional[int] = None,
                block: Optional[Any] = None) -> Optional[int]:
        if step is not None:             # explicit step: no rendezvous
            return super().restore(target, step, block=block)
        meta = self._read_meta()
        candidate: Optional[int] = None
        for s in reversed(self._committed(meta)):
            if self.verify(s, meta):
                candidate = s
                break
        if candidate is None:
            # no committed step on this rank (first run, or a crash
            # before any commit): offer the newest verified local step
            # — the min rule still yields a cluster-consistent answer
            candidate = self._last_verified(meta)
        t0 = time.perf_counter()
        agreed = int(self.coordinator.ckpt_mark(
            -1 if candidate is None else candidate))
        CKPT_COORD_SECONDS.labels(phase="restore").observe(
            time.perf_counter() - t0)
        if agreed < 0:
            return None                  # cluster-wide fresh start
        if agreed not in meta["checkpoints"] \
                or not self.verify(agreed, meta):
            raise MXNetError(
                f"coordinated restore: the cluster agreed on step "
                f"{agreed} but this rank's directory {self.directory} "
                f"has no verified checkpoint for it (have "
                f"{meta['checkpoints']}) — restore the rank's state "
                "or clear every rank's checkpoint directory for a "
                "clean cluster restart")
        return super().restore(target, agreed, block=block)
