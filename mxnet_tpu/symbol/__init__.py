"""mx.sym — the symbolic API (reference: ``python/mxnet/symbol/``).

Every op registered in the shared registry is available as a symbol
builder (``mx.sym.relu``, ``mx.sym.FullyConnected`` CamelCase aliases
included), generated on first access — the analog of the reference's
import-time codegen from ``MXListAllOpNames``.
"""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     _apply_op, _ALIASES)
from .executor import Executor
from ..ndarray.register import list_ops as _list_ops

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "Executor"]


class _SymContrib:
    """``mx.sym.contrib`` — contrib ops as symbol builders (accepts
    plain or ``_contrib_``-prefixed names, like ``mx.nd.contrib``)."""

    def __getattr__(self, name: str):
        plain = name[len("_contrib_"):] if name.startswith("_contrib_") \
            else name
        if plain not in _list_ops():
            raise AttributeError(f"no contrib op {name!r}")

        def op_fn(*args, **kwargs):
            return _apply_op(plain, *args, **kwargs)

        op_fn.__name__ = name
        setattr(self, name, op_fn)
        return op_fn


contrib = _SymContrib()


def __getattr__(name: str):
    canonical = _ALIASES.get(name, name)
    if canonical not in _list_ops():
        raise AttributeError(f"module 'mxnet_tpu.symbol' has no op {name!r}")

    def op_fn(*args, **kwargs):
        return _apply_op(canonical, *args, **kwargs)

    op_fn.__name__ = name
    op_fn.__qualname__ = name
    op_fn.__doc__ = f"Symbolic form of op {canonical!r} (see mx.nd.{canonical})."
    globals()[name] = op_fn
    return op_fn


def __dir__():
    return sorted(set(__all__) | set(_list_ops()) | set(_ALIASES))
