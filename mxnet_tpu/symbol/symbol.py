"""Symbol — declarative graph composition (the symbolic half of the API).

Reference parity (leezu/mxnet): ``python/mxnet/symbol/symbol.py``
(Symbol composition, ``infer_shape``, ``bind``/``simple_bind``, JSON
save/load) over the NNVM graph IR (``3rdparty/tvm/nnvm`` ``nnvm::Graph``).

Design (tpu-first): a Symbol is a lightweight Python DAG over the SAME op
registry the imperative layer uses (one op set, two runtimes — SURVEY.md
section 0). There is no separate symbolic kernel path: evaluation calls the
registered op functions on NDArrays, so an Executor is a thin shell over the
imperative runtime + autograd tape, exactly as the reference's GraphExecutor
is a shell over the dependency engine. Shape/type inference is abstract
interpretation with ``jax.eval_shape`` per node — XLA's shape calculus
replaces NNVM's per-op FInferShape functions.
"""
from __future__ import annotations

import ast
import inspect
import itertools
import json
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, from_jax
from ..ndarray.register import get_op, list_ops

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "FORMAT_VERSION"]

FORMAT_VERSION = 1

_UID = itertools.count()


def _auto_name(op: str) -> str:
    """Auto-name via the single mx.name namespace — the active
    NameManager/Prefix scope, else the process-wide default counter (ONE
    namespace, so scoped and unscoped names never collide)."""
    from ..name import NameManager
    return NameManager.current().get(None, op.lower().replace("_", ""))


class _SymNode:
    """One graph node: an op application or a variable (op == 'null')."""

    __slots__ = ("op", "name", "attrs", "inputs", "layout", "is_aux",
                 "uid", "_user_attrs")

    def __init__(self, op: str, name: str, attrs: Dict[str, Any],
                 inputs: List[Tuple["_SymNode", int]],
                 layout: List[Tuple[str, ...]], is_aux: bool = False) -> None:
        self.op = op            # registered op name, or "null"
        self.name = name
        self.attrs = attrs      # python values (repr'd on save)
        self.inputs = inputs    # [(node, out_idx)]
        # layout: how to rebuild the python call; entries
        #   ("sym", param)           one Symbol input bound to `param`
        #   ("symlist", param, n)    n inputs bound as a list to `param`
        #   ("varsym", n)            n inputs bound as *args
        self.layout = layout
        self.is_aux = is_aux
        self.uid = next(_UID)
        self._user_attrs: Dict[str, str] = {}

    def n_outputs(self) -> int:
        return len(_multi_out_slots(self.op)) if self.op in _MULTI_OUT else 1


# ops whose python fn returns a tuple; maps op -> output name suffixes.
# batch_norm's (mean, var) outputs are consumed by the executor for the
# moving-stat update and not exposed as graph outputs (reference parity:
# BatchNorm's aux update happens inside the op).
_MULTI_OUT: Dict[str, Tuple[str, ...]] = {}


def _multi_out_slots(op: str) -> Tuple[str, ...]:
    return _MULTI_OUT.get(op, ("output",))


def _topo_order(heads: Sequence[Tuple[_SymNode, int]]) -> List[_SymNode]:
    seen: Dict[int, _SymNode] = {}
    order: List[_SymNode] = []

    def visit(node: _SymNode) -> None:
        if node.uid in seen:
            return
        seen[node.uid] = node
        for n, _ in node.inputs:
            visit(n)
        order.append(node)

    for n, _ in heads:
        visit(n)
    return order


class Symbol:
    """A symbolic multi-output expression (reference: ``mx.sym.Symbol``)."""

    __slots__ = ("_heads",)

    def __init__(self, heads: Sequence[Tuple[_SymNode, int]]) -> None:
        self._heads = list(heads)

    # -- introspection -----------------------------------------------------
    @property
    def name(self) -> str:
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return f"group[{','.join(n.name for n, _ in self._heads)}]"

    def __repr__(self) -> str:
        args = ", ".join(self.list_arguments())
        return f"<Symbol {self.name}({args})>"

    def list_arguments(self) -> List[str]:
        return [n.name for n in _topo_order(self._heads)
                if n.op == "null" and not n.is_aux]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in _topo_order(self._heads)
                if n.op == "null" and n.is_aux]

    def list_outputs(self) -> List[str]:
        outs = []
        for node, idx in self._heads:
            slots = _multi_out_slots(node.op)
            suffix = slots[idx] if idx < len(slots) else f"output{idx}"
            outs.append(f"{node.name}_{suffix}" if node.op != "null"
                        else node.name)
        return outs

    def list_inputs(self) -> List[str]:
        return [n.name for n in _topo_order(self._heads) if n.op == "null"]

    @property
    def num_outputs(self) -> int:
        return len(self._heads)

    def __len__(self) -> int:
        return len(self._heads)

    def __iter__(self):
        for i in range(len(self._heads)):
            yield self[i]

    def __getitem__(self, key) -> "Symbol":
        if isinstance(key, str):
            names = self.list_outputs()
            if key not in names:
                raise MXNetError(f"no output named {key!r}; have {names}")
            return Symbol([self._heads[names.index(key)]])
        if isinstance(key, slice):
            return Symbol(self._heads[key])
        return Symbol([self._heads[key]])

    def get_internals(self) -> "Symbol":
        """Every node's primary output as a group (reference:
        ``Symbol.get_internals``)."""
        return Symbol([(n, 0) for n in _topo_order(self._heads)])

    def get_children(self) -> Optional["Symbol"]:
        node = self._head_node()
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def _head_node(self) -> _SymNode:
        if len(self._heads) != 1:
            raise MXNetError("operation requires a single-output symbol")
        return self._heads[0][0]

    # -- attributes --------------------------------------------------------
    def attr(self, key: str) -> Optional[str]:
        return self._head_node()._user_attrs.get(key)

    def list_attr(self) -> Dict[str, str]:
        return dict(self._head_node()._user_attrs)

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out: Dict[str, Dict[str, str]] = {}
        for n in _topo_order(self._heads):
            if n._user_attrs:
                out[n.name] = dict(n._user_attrs)
        return out

    def _set_attr(self, **kwargs: str) -> None:
        self._head_node()._user_attrs.update(
            {k: str(v) for k, v in kwargs.items()})

    # -- arithmetic sugar --------------------------------------------------
    def _binop(self, op: str, other: Any, swap: bool = False) -> "Symbol":
        a, b = (other, self) if swap else (self, other)
        return _apply_op(op, a, b)

    def __add__(self, o): return self._binop("add", o)
    def __radd__(self, o): return self._binop("add", o, True)
    def __sub__(self, o): return self._binop("subtract", o)
    def __rsub__(self, o): return self._binop("subtract", o, True)
    def __mul__(self, o): return self._binop("multiply", o)
    def __rmul__(self, o): return self._binop("multiply", o, True)
    def __truediv__(self, o): return self._binop("divide", o)
    def __rtruediv__(self, o): return self._binop("divide", o, True)
    def __pow__(self, o): return self._binop("power", o)
    def __rpow__(self, o): return self._binop("power", o, True)
    def __mod__(self, o): return self._binop("mod", o)
    def __neg__(self): return self._binop("multiply", -1.0)
    def __matmul__(self, o): return self._binop("matmul", o)
    def __eq__(self, o): return self._binop("equal", o)
    def __ne__(self, o): return self._binop("not_equal", o)
    def __lt__(self, o): return self._binop("less", o)
    def __le__(self, o): return self._binop("less_equal", o)
    def __gt__(self, o): return self._binop("greater", o)
    def __ge__(self, o): return self._binop("greater_equal", o)
    __hash__ = None  # type: ignore[assignment]

    def abs(self): return _apply_op("abs", self)
    def exp(self): return _apply_op("exp", self)
    def log(self): return _apply_op("log", self)
    def sqrt(self): return _apply_op("sqrt", self)
    def square(self): return _apply_op("square", self)
    def reshape(self, shape): return _apply_op("reshape", self, shape)
    def transpose(self, axes=None): return _apply_op("transpose", self, axes)
    def sum(self, **kw): return _apply_op("sum", self, **kw)
    def mean(self, **kw): return _apply_op("mean", self, **kw)
    def astype(self, dtype): return _apply_op("cast", self, dtype=dtype)

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Returns ``(arg_shapes, out_shapes, aux_shapes)`` aligned with
        ``list_arguments()`` / ``list_outputs()`` / ``list_auxiliary_states``.
        """
        res = self._infer(kwargs, partial=False)
        return res

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer(kwargs, partial=True)

    def _infer(self, known: Dict[str, tuple], partial: bool):
        structs = _infer_structs(self, known, partial=partial)
        if structs is None:
            return None, None, None
        var_structs, out_structs = structs
        args = [var_structs.get(n) for n in self.list_arguments()]
        auxs = [var_structs.get(n) for n in self.list_auxiliary_states()]
        to_shape = lambda s: tuple(s.shape) if s is not None else None
        arg_shapes = [to_shape(s) for s in args]
        aux_shapes = [to_shape(s) for s in auxs]
        out_shapes = [to_shape(s) for s in out_structs]
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            missing = [n for n, s in zip(self.list_arguments(), arg_shapes)
                       if s is None]
            raise MXNetError(
                f"infer_shape: unresolved shapes for {missing}; provide "
                f"them as keyword shapes (e.g. data=(batch, ...))")
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Dtype propagation (promotion-based — XLA's result_type calculus
        replaces NNVM per-op FInferType). Returns
        ``(arg_types, out_types, aux_types)``."""
        var_t: Dict[str, Any] = {}
        memo: Dict[int, Any] = {}
        for node in _topo_order(self._heads):
            if node.op == "null":
                dt = kwargs.get(node.name, node.attrs.get("__dtype__"))
                var_t[node.name] = _np.dtype(dt) if dt is not None else None
                memo[node.uid] = var_t[node.name]
                continue
            in_t = [memo.get(m.uid) for m, _ in node.inputs]
            out_t = _propagate_dtype(node, in_t)
            # back-fill implicit-param dtypes from the node result (NNVM's
            # back-inference); only the spec'd param slots, never data
            spec = _PARAM_SPECS.get(node.op)
            if out_t is not None and spec is not None:
                for kind, pname, pairs in _iter_layout(node.inputs, node.layout):
                    if kind != "sym" or pname not in spec:
                        continue
                    m, _ = pairs[0]
                    if m.op == "null" and var_t.get(m.name) is None:
                        var_t[m.name] = memo[m.uid] = out_t
            memo[node.uid] = out_t
        args_out = [var_t.get(n) for n in self.list_arguments()]
        outs = [memo.get(n.uid) for n, _ in self._heads]
        auxs = [var_t.get(n) for n in self.list_auxiliary_states()]
        return args_out, outs, auxs

    # -- evaluation / binding ---------------------------------------------
    def eval(self, ctx: Optional[Context] = None, **kwargs: Any):
        """Evaluate imperatively with named NDArray inputs."""
        feed = {k: v if isinstance(v, NDArray) else NDArray(v)
                for k, v in kwargs.items()}
        return _eval_graph(self, feed)

    def bind(self, ctx: Optional[Context] = None, args: Any = None,
             args_grad: Any = None, grad_req: Any = "write",
             aux_states: Any = None, **kwargs: Any):
        from .executor import Executor
        return Executor(self, ctx or current_context(), args, args_grad,
                        grad_req, aux_states)

    def simple_bind(self, ctx: Optional[Context] = None,
                    grad_req: Any = "write", **shapes: Any):
        from .executor import Executor
        return Executor.simple_bind(self, ctx or current_context(),
                                    grad_req, shapes)

    # -- serialization -----------------------------------------------------
    def tojson(self) -> str:
        order = _topo_order(self._heads)
        nid = {n.uid: i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            attrs = {k: repr(v) for k, v in n.attrs.items()}
            if n.layout:
                attrs["__layout__"] = repr(n.layout)
            if n.is_aux:
                attrs["__aux__"] = "1"
            if n._user_attrs:
                attrs["__user__"] = repr(n._user_attrs)
            nodes.append({
                "op": n.op, "name": n.name, "attrs": attrs,
                "inputs": [[nid[m.uid], idx, 0] for m, idx in n.inputs],
            })
        payload = {
            "nodes": nodes,
            "arg_nodes": [i for i, n in enumerate(order) if n.op == "null"],
            "heads": [[nid[n.uid], idx, 0] for n, idx in self._heads],
            "attrs": {"mxnet_version": ("str", "mxnet_tpu"),
                      "format_version": ("int", FORMAT_VERSION)},
        }
        return json.dumps(payload, indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())


# ---------------------------------------------------------------------------
# Construction API
# ---------------------------------------------------------------------------

def Variable(name: str, shape: Optional[tuple] = None, dtype: Any = None,
             attr: Optional[Dict[str, str]] = None, init: Any = None,
             lr_mult: Optional[float] = None, wd_mult: Optional[float] = None,
             stype: Optional[str] = None, **kwargs: Any) -> Symbol:
    """A named graph input (reference: ``mx.sym.Variable``)."""
    attrs: Dict[str, Any] = {}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = _np.dtype(dtype).name
    if init is not None:
        attrs["__init__"] = str(init)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    node = _SymNode("null", name, attrs, [], [])
    from ..attribute import AttrScope
    scope = AttrScope.current()
    merged = scope.get(attr) if scope is not None else (attr or {})
    if merged:
        node._user_attrs.update({k: str(v) for k, v in merged.items()})
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    """Combine symbols into one multi-output symbol."""
    heads: List[Tuple[_SymNode, int]] = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def _parse_attr_value(v: Any) -> Any:
    """Attr values round-trip through ``repr``; reference-format files
    store plain strings (``act_type: "relu"``) — fall back to the raw
    string when it is not a python literal."""
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def _default_layout(op: str, attrs: Dict[str, Any],
                    n_inputs: int) -> List[Tuple[str, ...]]:
    """Synthesize an input layout for graphs saved without ``__layout__``
    (reference-format json): bind inputs positionally to the op's leading
    non-attr parameters."""
    fn = get_op(op)
    sig = inspect.signature(fn)
    layout: List[Tuple[str, ...]] = []
    taken = 0
    for pname, p in sig.parameters.items():
        if taken >= n_inputs:
            break
        if pname in attrs:
            continue
        if p.kind is inspect.Parameter.VAR_POSITIONAL:
            layout.append(("varsym", n_inputs - taken))
            taken = n_inputs
            break
        layout.append(("sym", pname))
        taken += 1
    if taken < n_inputs:
        raise MXNetError(
            f"load: cannot map {n_inputs} inputs onto op {op!r} signature")
    return layout


def load_json(json_str: str) -> Symbol:
    payload = json.loads(json_str)
    nodes_js = payload["nodes"]
    built: List[_SymNode] = []
    for nd_js in nodes_js:
        raw_attrs = dict(nd_js.get("attrs") or nd_js.get("param") or {})
        layout_s = raw_attrs.pop("__layout__", None)
        is_aux = raw_attrs.pop("__aux__", None) == "1"
        user = ast.literal_eval(raw_attrs.pop("__user__", "{}"))
        attrs = {k: _parse_attr_value(v) for k, v in raw_attrs.items()}
        op = nd_js["op"]
        if op != "null" and op not in list_ops():
            alias = _ALIAS_TO_CANONICAL.get(op)
            if alias is None:
                raise MXNetError(f"load: unknown op {op!r} in graph json")
            op = alias
        inputs = [(built[i], idx) for i, idx, *_ in nd_js["inputs"]]
        if layout_s is not None:
            layout = [tuple(e) for e in ast.literal_eval(layout_s)]
        elif op != "null" and inputs:
            layout = _default_layout(op, attrs, len(inputs))
        else:
            layout = []
        node = _SymNode(op, nd_js["name"], attrs, inputs, layout,
                        is_aux=is_aux)
        node._user_attrs = {str(k): str(v) for k, v in user.items()}
        built.append(node)
    heads = [(built[i], idx) for i, idx, *_ in payload["heads"]]
    return Symbol(heads)


# ---------------------------------------------------------------------------
# Generic op application: bind python args, split Symbol inputs from attrs
# ---------------------------------------------------------------------------

# ops that auto-create parameter variables when omitted (the reference's
# implicit-weight UX: sym.FullyConnected(data, num_hidden=10) creates
# fc_weight/fc_bias). Each entry: param kwarg -> (suffix, is_aux).
_PARAM_SPECS: Dict[str, Dict[str, Tuple[str, bool]]] = {
    "fully_connected": {"weight": ("weight", False), "bias": ("bias", False)},
    "convolution": {"weight": ("weight", False), "bias": ("bias", False)},
    "deconvolution": {"weight": ("weight", False), "bias": ("bias", False)},
    "batch_norm": {"gamma": ("gamma", False), "beta": ("beta", False),
                   "running_mean": ("moving_mean", True),
                   "running_var": ("moving_var", True)},
    "layer_norm": {"gamma": ("gamma", False), "beta": ("beta", False)},
    "group_norm": {"gamma": ("gamma", False), "beta": ("beta", False)},
    "instance_norm": {"gamma": ("gamma", False), "beta": ("beta", False)},
    "rms_norm": {"gamma": ("gamma", False)},
    "embedding": {"weight": ("weight", False)},
    "prelu": {"gamma": ("gamma", False)},
    # loss heads auto-create their label variable (reference:
    # sym.SoftmaxOutput(net) binds a `<name>_label` input)
    "softmax_output": {"label": ("label", False)},
    "linear_regression_output": {"label": ("label", False)},
    "logistic_regression_output": {"label": ("label", False)},
    "mae_regression_output": {"label": ("label", False)},
}

# per-op hooks resolving auto-created param shapes from the data shape
# (the NNVM FInferShape back-inference the symbolic API depends on)
def _fc_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    nh = attrs.get("num_hidden")
    if nh is None:
        return {}
    flat = attrs.get("flatten", True)
    in_units = int(_np.prod(d.shape[1:])) if (flat and len(d.shape) > 2) \
        else d.shape[-1]
    return {"weight": (nh, in_units), "bias": (nh,)}


def _conv_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    layout = attrs.get("layout", "NCHW")
    kernel = attrs.get("kernel")
    nf = attrs.get("num_filter")
    if kernel is None or not nf:
        return {}
    if isinstance(kernel, int):
        kernel = (kernel,) * (len(d.shape) - 2)
    c = d.shape[layout.index("C")]
    ng = attrs.get("num_group", 1)
    return {"weight": (nf, c // ng) + tuple(kernel), "bias": (nf,)}


def _deconv_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    layout = attrs.get("layout", "NCHW")
    kernel = attrs.get("kernel")
    nf = attrs.get("num_filter")
    if kernel is None or not nf:
        return {}
    if isinstance(kernel, int):
        kernel = (kernel,) * (len(d.shape) - 2)
    c = d.shape[layout.index("C")]
    ng = attrs.get("num_group", 1)
    return {"weight": (c, nf // ng) + tuple(kernel), "bias": (nf,)}


def _bn_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    ax = attrs.get("axis", 1) % len(d.shape)
    c = (d.shape[ax],)
    return {"gamma": c, "beta": c, "running_mean": c, "running_var": c}


def _ln_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    ax = attrs.get("axis", -1) % len(d.shape)
    return {"gamma": (d.shape[ax],), "beta": (d.shape[ax],)}


def _gn_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    return {"gamma": (d.shape[1],), "beta": (d.shape[1],)}


def _emb_shapes(structs, attrs):
    i, o = attrs.get("input_dim"), attrs.get("output_dim")
    if i and o:
        return {"weight": (i, o)}
    return {}


def _prelu_shapes(structs, attrs):
    d = structs.get("data")
    if d is None:
        return {}
    return {"gamma": (d.shape[1] if len(d.shape) > 1 else 1,)}


# ops whose implicit params are float regardless of the data input dtype
_FLOAT_PARAM_OPS = frozenset(["embedding"])

_SHAPE_HOOKS: Dict[str, Callable] = {
    "fully_connected": _fc_shapes,
    "convolution": _conv_shapes,
    "deconvolution": _deconv_shapes,
    "batch_norm": _bn_shapes,
    "layer_norm": _ln_shapes,
    "group_norm": _gn_shapes,
    "instance_norm": _gn_shapes,
    "rms_norm": lambda s, a: ({"gamma": (s["data"].shape[a.get("axis", -1)],)}
                              if s.get("data") is not None else {}),
    "embedding": _emb_shapes,
    "prelu": _prelu_shapes,
}

# CamelCase aliases (the reference exposes both spellings)
_ALIASES: Dict[str, str] = {
    "FullyConnected": "fully_connected",
    "Convolution": "convolution",
    "Deconvolution": "deconvolution",
    "Activation": "activation",
    "BatchNorm": "batch_norm",
    "LayerNorm": "layer_norm",
    "GroupNorm": "group_norm",
    "InstanceNorm": "instance_norm",
    "Pooling": "pooling",
    "Dropout": "dropout",
    "Embedding": "embedding",
    "LeakyReLU": "leaky_relu",
    "SoftmaxOutput": "softmax_output",
    "LinearRegressionOutput": "linear_regression_output",
    "LogisticRegressionOutput": "logistic_regression_output",
    "MAERegressionOutput": "mae_regression_output",
    "MakeLoss": "make_loss",
    "BlockGrad": "stop_gradient",
    "SoftmaxActivation": "softmax",
    "Concat": "concat",
    "Reshape": "reshape",
    "Flatten": "flatten",
    "Cast": "cast",
    "SwapAxis": "swapaxes",
    "SequenceMask": "sequence_mask",
    "SequenceLast": "sequence_last",
    "SequenceReverse": "sequence_reverse",
    "L2Normalization": "l2_normalization",
    "LRN": "lrn",
    "Pad": "pad",
    "SliceChannel": "slice_channel",
    "UpSampling": "up_sampling",
    "softmax_cross_entropy": "softmax_cross_entropy",
}
_ALIAS_TO_CANONICAL = dict(_ALIASES)


def _apply_op(op: str, *args: Any, **kwargs: Any) -> Symbol:
    """Create a graph node for op applied to Symbol/attr arguments."""
    op = _ALIASES.get(op, op)
    fn = get_op(op)
    name = kwargs.pop("name", None) or _auto_name(op)
    user_attr = kwargs.pop("attr", None)

    sig = inspect.signature(fn)
    try:
        bound = sig.bind_partial(*args, **kwargs)
    except TypeError as e:
        raise MXNetError(f"symbol op {op!r}: {e}") from None

    inputs: List[Tuple[_SymNode, int]] = []
    layout: List[Tuple[str, ...]] = []
    attrs: Dict[str, Any] = {}

    for pname, value in bound.arguments.items():
        kind = sig.parameters[pname].kind
        if kind is inspect.Parameter.VAR_POSITIONAL:
            if all(isinstance(v, Symbol) for v in value) and value:
                for v in value:
                    inputs.extend(v._heads[:1])
                layout.append(("varsym", len(value)))
            else:
                attrs[pname] = value
        elif isinstance(value, Symbol):
            if len(value._heads) != 1:
                raise MXNetError(
                    f"symbol op {op!r}: input {pname!r} must be a "
                    f"single-output symbol (got {len(value._heads)} outputs)")
            inputs.extend(value._heads)
            layout.append(("sym", pname))
        elif isinstance(value, (list, tuple)) and value and \
                all(isinstance(v, Symbol) for v in value):
            for v in value:
                inputs.extend(v._heads[:1])
            layout.append(("symlist", pname, len(value)))
        elif kind is inspect.Parameter.VAR_KEYWORD:
            attrs.update(value)
        else:
            attrs[pname] = value

    # implicit parameter variables (fc_weight etc.)
    spec = _PARAM_SPECS.get(op)
    if spec is not None:
        bound_names = {e[1] for e in layout if e[0] == "sym"}
        for pname, (suffix, is_aux) in spec.items():
            if pname in bound_names or pname in attrs:
                continue
            if pname == "bias" and attrs.get("no_bias"):
                continue
            vnode = _SymNode("null", f"{name}_{suffix}", {}, [], [],
                             is_aux=is_aux)
            inputs.append((vnode, 0))
            layout.append(("sym", pname))
        # aux slots the user wired explicitly still count as aux states
        for kind, pname, pairs in _iter_layout(inputs, layout):
            if kind == "sym" and pname in spec and spec[pname][1] and \
                    pairs[0][0].op == "null":
                pairs[0][0].is_aux = True

    node = _SymNode(op, name, attrs, inputs, layout)
    from ..attribute import AttrScope
    scope = AttrScope.current()
    merged_attr = scope.get(user_attr) if scope is not None \
        else (user_attr or {})
    if merged_attr:
        node._user_attrs.update({k: str(v)
                                 for k, v in merged_attr.items()})

    # statically-known multi-output ops (reference: SliceChannel etc.)
    n_out = 1
    if op == "slice_channel":
        n_out = attrs.get("num_outputs", 1)
    elif op in ("split", "array_split"):
        sections = attrs.get("indices_or_sections")
        if isinstance(sections, int):
            n_out = sections
        elif isinstance(sections, (list, tuple)):
            n_out = len(sections) + 1
    return Symbol([(node, i) for i in range(n_out)])


def _iter_layout(inputs, layout):
    """Walk an input layout, yielding ``(kind, param_name, pairs)``
    where ``pairs`` is the list of ``(input_node, out_idx)`` consumed by
    that entry (param_name is None for varargs)."""
    it = iter(inputs)
    for entry in layout:
        if entry[0] == "sym":
            yield "sym", entry[1], [next(it)]
        elif entry[0] == "symlist":
            yield "symlist", entry[1], [next(it) for _ in range(entry[2])]
        elif entry[0] == "varsym":
            yield "varsym", None, [next(it) for _ in range(entry[1])]
        else:
            raise MXNetError(f"bad layout entry {entry!r}")


def _call_node(node: _SymNode, in_vals: Sequence[Any],
               training: bool = False) -> Tuple[Any, ...]:
    """Rebuild the python call for a node and run it on NDArrays."""
    fn = get_op(node.op)
    kwargs = dict(node.attrs)
    varargs: List[Any] = []
    it = iter(in_vals)
    for entry in node.layout:
        if entry[0] == "sym":
            kwargs[entry[1]] = next(it)
        elif entry[0] == "symlist":
            kwargs[entry[1]] = [next(it) for _ in range(entry[2])]
        elif entry[0] == "varsym":
            varargs = [next(it) for _ in range(entry[1])]
        else:
            raise MXNetError(f"bad layout entry {entry!r}")
    if node.op in ("batch_norm", "dropout"):
        kwargs.setdefault("training", training)
    out = fn(*varargs, **kwargs)
    return tuple(out) if isinstance(out, (tuple, list)) else (out,)


def _eval_graph(sym: Symbol, feed: Dict[str, NDArray],
                training: bool = False,
                aux_hook: Optional[Callable] = None) -> List[NDArray]:
    """Imperatively evaluate a symbol. ``aux_hook(name, value)`` receives
    moving-stat updates from batch_norm nodes in training mode."""
    memo: Dict[int, Tuple[Any, ...]] = {}
    for node in _topo_order(sym._heads):
        if node.op == "null":
            if node.name not in feed:
                raise MXNetError(f"eval: missing input {node.name!r}")
            memo[node.uid] = (feed[node.name],)
            continue
        ins = [memo[m.uid][idx] for m, idx in node.inputs]
        outs = _call_node(node, ins, training=training)
        if node.op == "batch_norm":
            out, mean, vvar = outs
            if training and not node.attrs.get("use_global_stats", False) \
                    and aux_hook is not None:
                mom = node.attrs.get("momentum", 0.9)
                names = _bn_aux_names(node)
                if names is not None:
                    rm_name, rv_name = names
                    rm, rv = feed[rm_name], feed[rv_name]
                    aux_hook(rm_name, rm * mom + mean.detach() * (1 - mom))
                    aux_hook(rv_name, rv * mom + vvar.detach() * (1 - mom))
            outs = (out,)
        memo[node.uid] = outs
    return [memo[n.uid][idx] for n, idx in sym._heads]


def _bn_aux_names(node: _SymNode) -> Optional[Tuple[str, str]]:
    names = {}
    for kind, pname, pairs in _iter_layout(node.inputs, node.layout):
        if kind == "sym" and pname in ("running_mean", "running_var"):
            names[pname] = pairs[0][0].name
    if "running_mean" in names and "running_var" in names:
        return names["running_mean"], names["running_var"]
    return None


_BOOL_OUT_OPS = frozenset(["equal", "not_equal", "less", "less_equal",
                           "greater", "greater_equal", "logical_and",
                           "logical_or", "logical_xor", "logical_not",
                           "isnan", "isinf", "isfinite"])
_INT_OUT_OPS = frozenset(["argmax", "argmin", "argsort", "nonzero"])


def _propagate_dtype(node: _SymNode, in_dtypes: List[Any]):
    """Promotion-based per-node dtype rule for ``infer_type``."""
    if node.op == "cast" or node.op == "astype":
        dt = node.attrs.get("dtype")
        return _np.dtype(dt) if dt is not None else None
    if node.op in _BOOL_OUT_OPS:
        return _np.dtype(_np.bool_)
    if node.op in _INT_OUT_OPS:
        return _np.dtype(_np.int64)
    if node.op in _FLOAT_PARAM_OPS:
        # Embedding: result follows the (float) table, not the int indices
        for kind, pname, pairs in _iter_layout(node.inputs, node.layout):
            if kind == "sym" and pname == "weight":
                wt = in_dtypes[node.inputs.index(pairs[0])]
                return wt if wt is not None else _np.dtype(_np.float32)
        return _np.dtype(_np.float32)
    known = [d for d in in_dtypes if d is not None]
    if not known:
        # creation ops (zeros/ones/...) carry a dtype attr
        dt = node.attrs.get("dtype")
        return _np.dtype(dt) if dt is not None else (
            _np.dtype(_np.float32) if not node.inputs else None)
    try:
        return _np.dtype(_np.result_type(*known))
    except TypeError:
        return known[0]


# ---------------------------------------------------------------------------
# Abstract interpretation (shape/type inference)
# ---------------------------------------------------------------------------

def _infer_structs(sym: Symbol, known: Dict[str, tuple], partial: bool,
                   var_dtypes: Optional[Dict[str, Any]] = None):
    """Walk the graph propagating ShapeDtypeStructs.

    Returns (var_structs: name->struct, out_structs aligned with heads),
    with None entries where inference was impossible (partial mode).
    """
    var_dtypes = var_dtypes or {}
    var_structs: Dict[str, Optional[jax.ShapeDtypeStruct]] = {}
    memo: Dict[int, Optional[Tuple[Any, ...]]] = {}

    order = _topo_order(sym._heads)
    node_by_name = {n.name: n for n in order}

    def struct_for_var(node: _SymNode) -> Optional[jax.ShapeDtypeStruct]:
        if node.name in var_structs:
            return var_structs[node.name]
        shape = known.get(node.name, node.attrs.get("__shape__"))
        dtype = var_dtypes.get(node.name,
                               node.attrs.get("__dtype__", "float32"))
        s = jax.ShapeDtypeStruct(tuple(shape), _np.dtype(dtype)) \
            if shape is not None else None
        var_structs[node.name] = s
        return s

    for node in order:
        if node.op == "null":
            memo[node.uid] = (struct_for_var(node),)
            continue

        # resolve implicit-param shapes from data shape (back-inference)
        hook = _SHAPE_HOOKS.get(node.op)
        if hook is not None:
            in_named: Dict[str, Any] = {}
            for kind, pname, pairs in _iter_layout(node.inputs, node.layout):
                if kind == "sym":
                    m, idx = pairs[0]
                    st = memo.get(m.uid)
                    in_named[pname] = st[idx] if st else None
            inferred = hook(in_named, node.attrs)
            for kind, pname, pairs in _iter_layout(node.inputs, node.layout):
                if kind != "sym":
                    continue
                m, idx = pairs[0]
                if m.op == "null" and var_structs.get(m.name) is None \
                        and pname in inferred:
                    dt = var_dtypes.get(
                        m.name, m.attrs.get("__dtype__", None))
                    if dt is None:
                        d = in_named.get("data")
                        # params of index-consuming ops (Embedding) are
                        # float even when the data input is integer
                        if node.op in _FLOAT_PARAM_OPS or d is None:
                            dt = "float32"
                        else:
                            dt = d.dtype
                    var_structs[m.name] = jax.ShapeDtypeStruct(
                        tuple(inferred[pname]), _np.dtype(dt))
                    memo[m.uid] = (var_structs[m.name],)

        in_structs = []
        ok = True
        for m, idx in node.inputs:
            st = memo.get(m.uid)
            if st is None or st[idx] is None:
                ok = False
                break
            in_structs.append(st[idx])
        if not ok:
            if not partial:
                raise MXNetError(
                    f"infer_shape: inputs of node {node.name!r} "
                    f"({node.op}) are unresolved")
            memo[node.uid] = None
            continue

        def f(*raw):
            ins = [from_jax(r) for r in raw]
            outs = _call_node(node, ins, training=False)
            return [o._data for o in outs]

        try:
            out = jax.eval_shape(f, *in_structs)
        except Exception as e:
            if partial:
                memo[node.uid] = None
                continue
            raise MXNetError(
                f"infer_shape failed at node {node.name!r} ({node.op}): "
                f"{e}") from None
        if node.op == "batch_norm":
            out = out[:1]
        memo[node.uid] = tuple(out)

    out_structs = []
    for n, idx in sym._heads:
        st = memo.get(n.uid)
        out_structs.append(st[idx] if st else None)
    return var_structs, out_structs
