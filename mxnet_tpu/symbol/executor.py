"""Executor — bound symbolic graph, runnable forward/backward.

Reference parity (leezu/mxnet): ``include/mxnet/executor.h`` /
``src/executor/graph_executor.cc`` (``GraphExecutor::Init``, ``RunOps``,
``Executor::SimpleBind``) and the python wrapper
``python/mxnet/executor.py``.

Design (tpu-first): the reference's executor plans memory and pushes
per-node closures into the dependency engine; here the "engine" is jax's
async dispatch, so the Executor is a thin shell that walks the graph
imperatively through the shared op registry, recording on the autograd tape
when ``is_train`` — the backward graph is the tape's vjp chain instead of a
separate NNVM Gradient pass. Memory planning (buffer sharing, inplace) is
XLA's job under hybridize; the executor path favors correctness and API
parity.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as _np

from .. import autograd
from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray
from ..ndarray import ops as _nd_ops
from .symbol import Symbol, _eval_graph, _infer_structs

__all__ = ["Executor"]


def _as_dict(values: Any, names: Sequence[str], what: str
             ) -> Dict[str, NDArray]:
    if values is None:
        return {}
    if isinstance(values, dict):
        return dict(values)
    values = list(values)
    if len(values) != len(names):
        raise MXNetError(
            f"{what}: expected {len(names)} arrays ({list(names)}), "
            f"got {len(values)}")
    return dict(zip(names, values))


class Executor:
    """A symbol bound to argument/gradient/aux buffers on a context."""

    def __init__(self, sym: Symbol, ctx: Context, args: Any = None,
                 args_grad: Any = None, grad_req: Any = "write",
                 aux_states: Any = None) -> None:
        self._sym = sym
        self._ctx = ctx
        self._arg_names = sym.list_arguments()
        self._aux_names = sym.list_auxiliary_states()

        self.arg_dict: Dict[str, NDArray] = {
            k: v if isinstance(v, NDArray) else NDArray(v, ctx=ctx)
            for k, v in _as_dict(args, self._arg_names, "args").items()}
        missing = [n for n in self._arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(f"bind: missing argument arrays for {missing}")
        self.aux_dict: Dict[str, NDArray] = {
            k: v if isinstance(v, NDArray) else NDArray(v, ctx=ctx)
            for k, v in _as_dict(aux_states, self._aux_names,
                                 "aux_states").items()}
        for n in self._aux_names:
            if n not in self.aux_dict:
                raise MXNetError(f"bind: missing auxiliary state {n!r}")

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, dict):
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self._arg_names}
        else:
            self._grad_req = dict(zip(self._arg_names, grad_req))

        self.grad_dict: Dict[str, NDArray] = _as_dict(
            args_grad, self._arg_names, "args_grad")
        for n, req in self._grad_req.items():
            if req != "null" and n not in self.grad_dict:
                arr = self.arg_dict[n]
                self.grad_dict[n] = NDArray(
                    _np.zeros(arr.shape, dtype=arr.dtype), ctx=ctx)

        self.outputs: List[NDArray] = []

    # -- construction ------------------------------------------------------
    @staticmethod
    def simple_bind(sym: Symbol, ctx: Context, grad_req: Any = "write",
                    shapes: Optional[Dict[str, tuple]] = None) -> "Executor":
        """Infer all shapes from the given input shapes and allocate
        argument/grad/aux buffers (reference: ``Symbol.simple_bind``)."""
        shapes = shapes or {}
        structs = _infer_structs(sym, shapes, partial=False)
        var_structs, _ = structs
        args: Dict[str, NDArray] = {}
        for n in sym.list_arguments():
            st = var_structs.get(n)
            if st is None:
                raise MXNetError(
                    f"simple_bind: could not infer shape of {n!r}; pass it "
                    f"explicitly (e.g. {n}=(...))")
            args[n] = NDArray(_np.zeros(st.shape, dtype=st.dtype), ctx=ctx)
        aux: Dict[str, NDArray] = {}
        for n in sym.list_auxiliary_states():
            st = var_structs.get(n)
            if st is None:
                raise MXNetError(
                    f"simple_bind: could not infer shape of aux {n!r}")
            init = _np.zeros(st.shape, dtype=st.dtype)
            if n.endswith("_moving_var"):
                init = _np.ones(st.shape, dtype=st.dtype)
            aux[n] = NDArray(init, ctx=ctx)
        return Executor(sym, ctx, args, None, grad_req, aux)

    # -- properties mirroring the reference --------------------------------
    @property
    def arg_arrays(self) -> List[NDArray]:
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self) -> List[Optional[NDArray]]:
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self) -> List[NDArray]:
        return [self.aux_dict[n] for n in self._aux_names]

    # -- execution ---------------------------------------------------------
    def forward(self, is_train: bool = False, **kwargs: Any
                ) -> List[NDArray]:
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k!r}")
            src = v if isinstance(v, NDArray) else NDArray(v, ctx=self._ctx)
            # rebind in place so tape identity and grad wiring persist
            self.arg_dict[k]._data = src.as_in_context(self._ctx)._data

        feed = dict(self.arg_dict)
        feed.update(self.aux_dict)

        def aux_hook(name: str, value: NDArray) -> None:
            self.aux_dict[name]._data = value._data

        if is_train:
            for n, arr in self.arg_dict.items():
                req = self._grad_req[n]
                arr._grad_req = req
                arr._grad = self.grad_dict.get(n) if req != "null" else None
            with autograd.record():
                outs = _eval_graph(self._sym, feed, training=True,
                                   aux_hook=aux_hook)
        else:
            outs = _eval_graph(self._sym, feed, training=False)
        self.outputs = outs
        return outs

    def backward(self, out_grads: Any = None) -> None:
        """Propagate gradients into ``grad_dict``/``grad_arrays``."""
        if not self.outputs:
            raise MXNetError("backward: call forward(is_train=True) first")
        from .._tape import backward_arrays

        def wrap(g):
            # head grads must land on the EXECUTOR's context, not the
            # process default (under the accelerator ctx-flip a raw
            # numpy out_grad would otherwise mix devices with
            # cpu-bound executors)
            if g is None:
                return None
            if isinstance(g, NDArray):
                return g.as_in_context(self._ctx)
            return NDArray(g, ctx=self._ctx)

        if out_grads is None:
            grads = [None] * len(self.outputs)
        elif isinstance(out_grads, (list, tuple)):
            grads = [wrap(g) for g in out_grads]
        else:
            grads = [wrap(out_grads)]
        backward_arrays(self.outputs, grads)
        # sparse-grad leaves rebind arr._grad to a fresh RowSparseNDArray;
        # keep grad_dict pointing at the live gradient object
        for n, arr in self.arg_dict.items():
            if arr._grad is not None and \
                    self.grad_dict.get(n) is not arr._grad:
                self.grad_dict[n] = arr._grad

    # -- params ------------------------------------------------------------
    def copy_params_from(self, arg_params: Dict[str, Any],
                         aux_params: Optional[Dict[str, Any]] = None,
                         allow_extra_params: bool = False) -> None:
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = NDArray(v, ctx=self._ctx)._data
            elif not allow_extra_params:
                raise MXNetError(f"copy_params_from: unknown arg {k!r}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._data = NDArray(v, ctx=self._ctx)._data
            elif not allow_extra_params:
                raise MXNetError(f"copy_params_from: unknown aux {k!r}")

    def reshape(self, **shapes: Any) -> "Executor":
        """Return a new executor bound with the given input shapes (shapes
        of parameters are re-inferred; parameter values are shared)."""
        ex = Executor.simple_bind(self._sym, self._ctx,
                                  grad_req=self._grad_req, shapes=shapes)
        for n, arr in self.arg_dict.items():
            if n in ex.arg_dict and ex.arg_dict[n].shape == arr.shape:
                ex.arg_dict[n] = arr
        for n, arr in self.aux_dict.items():
            if n in ex.aux_dict and ex.aux_dict[n].shape == arr.shape:
                ex.aux_dict[n] = arr
        return ex
