"""KV-cache incremental decoding for the encoder-decoder Transformer.

Same design as ``generation.py`` (GPT): static-shape self-attention
caches written with ``dynamic_update_slice``, one compiled
encode+prefill+``lax.scan`` program per shape signature, on-device
sampling, beam search with batched cache reorder. The seq2seq twists:

* the encoder runs once; each decoder layer's CROSS-attention keys and
  values are projected from the memory once at prefill and stay fixed
  through the scan (no cache writes);
* decoding starts from ``bos_token`` with an empty self-cache rather
  than from a prompt prefill;
* the source padding mask rides along as an additive bias on the
  cross-attention scores.

The pure-jax math mirrors ``TransformerDecoderLayer.forward`` exactly;
``tests/test_transformer.py`` pins greedy decode to a naive
full-recompute reference.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...base import MXNetError
from .generation import _LRU, _ln, _select

__all__ = ["translate", "beam_translate"]

_PROG_CACHE: Dict[Any, Any] = _LRU()


def _j(p) -> jnp.ndarray:
    return jnp.asarray(p.data()._data)


def _enc_layer_params(lyr) -> Dict[str, jnp.ndarray]:
    return {
        "ln1_g": _j(lyr.ln1.gamma), "ln1_b": _j(lyr.ln1.beta),
        "qkv_w": _j(lyr.attn_qkv.weight), "qkv_b": _j(lyr.attn_qkv.bias),
        "out_w": _j(lyr.attn_out.weight), "out_b": _j(lyr.attn_out.bias),
        "ln2_g": _j(lyr.ln2.gamma), "ln2_b": _j(lyr.ln2.beta),
        "f1_w": _j(lyr.ffn1.weight), "f1_b": _j(lyr.ffn1.bias),
        "f2_w": _j(lyr.ffn2.weight), "f2_b": _j(lyr.ffn2.bias),
    }


def _collect(model) -> Dict[str, Any]:
    enc = [_enc_layer_params(l)
           for l in model.enc_layers._children.values()]
    dec = []
    for l in model.dec_layers._children.values():
        p = _enc_layer_params(l)
        p.update({
            "lnc_g": _j(l.ln_cross.gamma), "lnc_b": _j(l.ln_cross.beta),
            "cq_w": _j(l.cross_q.weight), "cq_b": _j(l.cross_q.bias),
            "ckv_w": _j(l.cross_kv.weight), "ckv_b": _j(l.cross_kv.bias),
            "co_w": _j(l.cross_out.weight), "co_b": _j(l.cross_out.bias),
        })
        dec.append(p)
    return {
        "src_embed": _j(model.src_embed.weight),
        "tgt_embed": _j(model.tgt_embed.weight),
        "src_pos": _j(model.src_pos), "tgt_pos": _j(model.tgt_pos),
        "encln_g": _j(model.enc_ln.gamma), "encln_b": _j(model.enc_ln.beta),
        "decln_g": _j(model.dec_ln.gamma), "decln_b": _j(model.dec_ln.beta),
        "enc": enc, "dec": dec,
    }


def _attn(qh, kh, vh, bias=None):
    """(B, Tq, nh, d) x (B, Tk, nh, d) -> (B, Tq, nh, d); bias is an
    additive (B or 1, 1, Tq or 1, Tk) term."""
    d = qh.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / math.sqrt(d)
    if bias is not None:
        scores = scores + bias
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vh)


def _encode(params, src, src_vl, nh, eps):
    B, Ts = src.shape
    x = params["src_embed"][src] + params["src_pos"][None, :Ts]
    src_bias = None
    if src_vl is not None:
        keep = jnp.arange(Ts)[None, :] < src_vl[:, None].astype(jnp.int32)
        # finfo.min, not -inf: a fully-padded row (valid_length 0) must
        # degrade to uniform attention like the training path
        # (_mask_to_bias), not softmax(-inf...) = NaN
        src_bias = jnp.where(keep, 0.0,
                             jnp.finfo(jnp.float32).min)[:, None, None, :]
    for p in params["enc"]:
        h = _ln(x, p["ln1_g"], p["ln1_b"], eps)
        qkv = h @ p["qkv_w"].T + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        C = q.shape[-1]
        d = C // nh
        out = _attn(q.reshape(B, Ts, nh, d), k.reshape(B, Ts, nh, d),
                    v.reshape(B, Ts, nh, d), src_bias)
        x = x + (out.reshape(B, Ts, C) @ p["out_w"].T + p["out_b"])
        h = _ln(x, p["ln2_g"], p["ln2_b"], eps)
        ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"], approximate=False)
        x = x + (ffn @ p["f2_w"].T + p["f2_b"])
    memory = _ln(x, params["encln_g"], params["encln_b"], eps)
    # project every decoder layer's cross k/v ONCE
    cross = []
    for p in params["dec"]:
        kv = memory @ p["ckv_w"].T + p["ckv_b"]
        k, v = jnp.split(kv, 2, axis=-1)
        C = k.shape[-1]
        d = C // nh
        cross.append((k.reshape(B, Ts, nh, d), v.reshape(B, Ts, nh, d)))
    return cross, src_bias


def _dec_step(params, tok, self_caches, cross, src_bias, pos, nh, eps,
              L):
    """One decode step: tok (B,), self caches (B, L, nh, d) per layer."""
    B = tok.shape[0]
    x = params["tgt_embed"][tok][:, None, :] + \
        lax.dynamic_slice_in_dim(params["tgt_pos"], pos, 1,
                                 axis=0)[None, :, :]
    new_caches = []
    for p, (ck, cv), (mk, mv) in zip(params["dec"], self_caches, cross):
        C = x.shape[-1]
        d = C // nh
        h = _ln(x, p["ln1_g"], p["ln1_b"], eps)
        qkv = h @ p["qkv_w"].T + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        ck = lax.dynamic_update_slice_in_dim(
            ck, k.reshape(B, 1, nh, d), pos, axis=1)
        cv = lax.dynamic_update_slice_in_dim(
            cv, v.reshape(B, 1, nh, d), pos, axis=1)
        visible = (jnp.arange(L) <= pos)
        self_bias = jnp.where(
            visible, 0.0, jnp.finfo(jnp.float32).min)[None, None, None, :]
        out = _attn(q.reshape(B, 1, nh, d), ck, cv, self_bias)
        x = x + (out.reshape(B, 1, C) @ p["out_w"].T + p["out_b"])
        h = _ln(x, p["lnc_g"], p["lnc_b"], eps)
        cq = (h @ p["cq_w"].T + p["cq_b"]).reshape(B, 1, nh, d)
        cout = _attn(cq, mk, mv, src_bias)
        x = x + (cout.reshape(B, 1, C) @ p["co_w"].T + p["co_b"])
        h = _ln(x, p["ln2_g"], p["ln2_b"], eps)
        ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"], approximate=False)
        x = x + (ffn @ p["f2_w"].T + p["f2_b"])
        new_caches.append((ck, cv))
    x = _ln(x, params["decln_g"], params["decln_b"], eps)
    return x[:, 0, :] @ params["tgt_embed"].T, new_caches


def _prepare(model, src, max_new_tokens, src_valid_length):
    import numpy as onp
    s = onp.asarray(src.asnumpy() if hasattr(src, "asnumpy") else src,
                    dtype="int32")
    if s.ndim == 1:
        s = s[None, :]
    if max_new_tokens < 1:
        raise MXNetError("max_new_tokens must be >= 1")
    if max_new_tokens > model._max_length:
        raise MXNetError(
            f"max_new_tokens ({max_new_tokens}) exceeds max_length "
            f"{model._max_length}")
    if s.shape[1] > model._max_length:
        raise MXNetError(
            f"source length {s.shape[1]} exceeds max_length "
            f"{model._max_length}")
    vl = None
    if src_valid_length is not None:
        vl = onp.asarray(
            src_valid_length.asnumpy()
            if hasattr(src_valid_length, "asnumpy") else src_valid_length,
            dtype="int32")
    nh = next(iter(model.dec_layers._children.values()))._num_heads
    eps = float(next(iter(
        model.dec_layers._children.values())).ln1._epsilon)
    params = _collect(model)
    return s, vl, params, nh, eps


def _model_sig(params, nh, eps):
    V, C = params["tgt_embed"].shape
    return (nh, V, C, params["tgt_pos"].shape[0], len(params["enc"]),
            len(params["dec"]), eps)


def _empty_caches(params, B, L, nh):
    C = params["tgt_embed"].shape[1]
    d = C // nh
    dt = params["tgt_embed"].dtype        # cast models cache in kind
    return [(jnp.zeros((B, L, nh, d), dt),
             jnp.zeros((B, L, nh, d), dt))
            for _ in params["dec"]]


def translate(model, src, max_new_tokens: int, bos_token: int,
              eos_token: Optional[int] = None, src_valid_length=None,
              method: str = "greedy", temperature: float = 1.0,
              top_k: int = 40, seed: int = 0, top_p: float = 0.9):
    """Decode target tokens for ``src`` starting from ``bos_token``.
    ``method``: greedy / sample / top_k / top_p (nucleus)."""
    import numpy as onp
    s, vl, params, nh, eps = _prepare(model, src, max_new_tokens,
                                      src_valid_length)
    B, Ts = s.shape
    eos = -1 if eos_token is None else int(eos_token)
    bos = int(bos_token)
    if method == "top_k":
        if top_k < 1:
            raise MXNetError(f"top_k must be >= 1, got {top_k}")
        top_k = min(int(top_k), params["tgt_embed"].shape[0])
    if method == "top_p" and not 0.0 < top_p <= 1.0:
        raise MXNetError(f"top_p must be in (0, 1], got {top_p}")
    has_vl = vl is not None
    L = max_new_tokens

    sig = ("tr", _model_sig(params, nh, eps), B, Ts, max_new_tokens,
           method, float(temperature), int(top_k), float(top_p), eos,
           bos, has_vl)
    prog = _PROG_CACHE.get(sig)
    if prog is None:
        def run(params, s, vl, key):
            cross, src_bias = _encode(params, s, vl, nh, eps)
            caches = _empty_caches(params, B, L, nh)

            def step(carry, i):
                caches, tok, done, key = carry
                logits, caches = _dec_step(params, tok, caches, cross,
                                           src_bias, i, nh, eps, L)
                key, sub = jax.random.split(key)
                nxt = _select(logits, method, temperature, top_k, top_p,
                              sub)
                if eos >= 0:
                    nxt = jnp.where(done, eos, nxt)
                    done = done | (nxt == eos)
                return (caches, nxt, done, key), nxt

            bos_t = jnp.full((B,), bos, jnp.int32)
            done0 = jnp.zeros((B,), bool)
            (_, _, _, _), toks = lax.scan(
                step, (caches, bos_t, done0, key),
                jnp.arange(max_new_tokens))
            return toks.T                          # (B, max_new)

        prog = jax.jit(run, static_argnums=())
        _PROG_CACHE[sig] = prog
    out = prog(params, jnp.asarray(s),
               None if vl is None else jnp.asarray(vl),
               jax.random.PRNGKey(seed))
    from ...ndarray.ops import array
    return array(onp.asarray(out))


def beam_translate(model, src, max_new_tokens: int, bos_token: int,
                   beam_size: int = 4, eos_token: Optional[int] = None,
                   src_valid_length=None, alpha: float = 1.0):
    """Length-normalized beam search; returns (sequences (B, beam,
    max_new_tokens), scores (B, beam)) best-first."""
    import numpy as onp
    s, vl, params, nh, eps = _prepare(model, src, max_new_tokens,
                                      src_valid_length)
    B, Ts = s.shape
    K = int(beam_size)
    if K < 1:
        raise MXNetError(f"beam_size must be >= 1, got {K}")
    eos = -1 if eos_token is None else int(eos_token)
    bos = int(bos_token)
    has_vl = vl is not None
    L = max_new_tokens
    NEG = jnp.float32(-1e30)

    sig = ("btr", _model_sig(params, nh, eps), B, Ts, max_new_tokens,
           K, eos, bos, float(alpha), has_vl)
    prog = _PROG_CACHE.get(sig)
    if prog is None:
        def run(params, s, vl):
            cross, src_bias = _encode(params, s, vl, nh, eps)
            # expand beam state: rows beam-major within batch. Cross k/v
            # and the source bias depend only on the batch element, so a
            # within-batch beam permutation never changes them — expand
            # once, never reorder.
            cross = jax.tree_util.tree_map(
                lambda c: jnp.repeat(c, K, axis=0), cross)
            if src_bias is not None:
                src_bias = jnp.repeat(src_bias, K, axis=0)
            caches = _empty_caches(params, B * K, L, nh)
            V = params["tgt_embed"].shape[0]

            # step 0: all beams feed bos; keep only beam 0 live so the
            # K continuations seed from the bos distribution
            bos_t = jnp.full((B * K,), bos, jnp.int32)
            logits, caches = _dec_step(params, bos_t, caches, cross,
                                       src_bias, 0, nh, eps, L)
            logp = jax.nn.log_softmax(
                logits.reshape(B, K, V)[:, 0, :], axis=-1)
            scores, first = lax.top_k(logp, K)       # (B, K)
            tok = first.reshape(B * K)
            done = (tok == eos) if eos >= 0 else \
                jnp.zeros((B * K,), bool)
            seqs0 = jnp.zeros((B, K, max_new_tokens), jnp.int32)
            seqs0 = seqs0.at[:, :, 0].set(first)

            def step(carry, i):
                caches, tok, scores, seqs, done = carry
                logits, caches = _dec_step(params, tok, caches, cross,
                                           src_bias, i, nh, eps, L)
                logp = jax.nn.log_softmax(logits, axis=-1).reshape(
                    B, K, V)
                if eos >= 0:
                    only_eos = jnp.full((V,), NEG).at[eos].set(0.0)
                    logp = jnp.where(done.reshape(B, K, 1), only_eos,
                                     logp)
                cand = (scores[:, :, None] + logp).reshape(B, K * V)
                scores, idx = lax.top_k(cand, K)
                beam_src = idx // V
                tok2 = (idx % V).astype(jnp.int32)
                gather = (jnp.arange(B)[:, None] * K
                          + beam_src).reshape(B * K)
                caches = jax.tree_util.tree_map(lambda c: c[gather],
                                                caches)
                seqs = jnp.take_along_axis(seqs, beam_src[:, :, None],
                                           axis=1)
                seqs = seqs.at[:, :, i].set(tok2)
                done = done[gather]
                tokf = tok2.reshape(B * K)
                if eos >= 0:
                    done = done | (tokf == eos)
                return (caches, tokf, scores, seqs, done), None

            if max_new_tokens > 1:
                (caches, tok, scores, seqs, done), _ = lax.scan(
                    step, (caches, tok, scores, seqs0, done),
                    jnp.arange(1, max_new_tokens))
            else:
                seqs = seqs0
            if eos >= 0:
                lengths = jnp.sum(
                    jnp.cumsum(seqs == eos, axis=-1) == 0, axis=-1) + 1
                lengths = jnp.minimum(lengths, max_new_tokens)
            else:
                lengths = jnp.full((B, K), max_new_tokens)
            norm = scores / (lengths.astype(jnp.float32) ** alpha)
            order = jnp.argsort(-norm, axis=-1)
            seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
            norm = jnp.take_along_axis(norm, order, axis=1)
            return seqs, norm

        prog = jax.jit(run)
        _PROG_CACHE[sig] = prog
    seqs, scores = prog(params, jnp.asarray(s),
                        None if vl is None else jnp.asarray(vl))
    from ...ndarray.ops import array
    return array(onp.asarray(seqs)), array(onp.asarray(scores))
