"""Autoregressive text generation for the GPT family — KV-cache
incremental decoding, TPU-native.

Reference-ecosystem parity: gluon-nlp's ``SequenceSampler`` /
``BeamSearchSampler`` were the inference story beside BERT (the
reference's own repo had no decoder-only LM). Here decoding is designed
for XLA from the start:

* **Static shapes everywhere** — the KV cache is a fixed
  ``(B, max_len, heads, d)`` buffer written with
  ``lax.dynamic_update_slice_in_dim``; attention over the cache masks
  positions ``> pos`` instead of slicing a dynamic length.
* **One compiled program per decode** — prefill + a ``lax.scan`` over
  decode steps compile once per (batch, prompt-length, new-tokens,
  method) signature and are cached.
* **Sampling on-device** — greedy / temperature / top-k draw from the
  threefry PRNG inside the scan; beam search reorders the cache with
  batched gathers.

The pure-jax block math mirrors ``GPTBlock.forward`` exactly (same LN /
GELU / scale conventions); the equivalence is pinned by
``tests/test_gpt.py`` (cached decode logits == full forward logits).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...base import MXNetError

__all__ = ["generate", "beam_search"]


# ---------------------------------------------------------------------------
# parameter extraction (block objects -> pure pytrees)
# ---------------------------------------------------------------------------

def _j(p) -> jnp.ndarray:
    return jnp.asarray(p.data()._data)


def _collect(model) -> Dict[str, Any]:
    blocks: List[Dict[str, jnp.ndarray]] = []
    for blk in model.blocks._children.values():
        if blk.moe is not None:
            raise MXNetError(
                "generate() does not support MoE blocks yet — decode "
                "routing is not implemented (train-time MoE is)")
        blocks.append({
            "ln1_g": _j(blk.ln1.gamma), "ln1_b": _j(blk.ln1.beta),
            "qkv_w": _j(blk.attn_qkv.weight),
            "qkv_b": _j(blk.attn_qkv.bias),
            "out_w": _j(blk.attn_out.weight),
            "out_b": _j(blk.attn_out.bias),
            "ln2_g": _j(blk.ln2.gamma), "ln2_b": _j(blk.ln2.beta),
            "f1_w": _j(blk.ffn1.weight), "f1_b": _j(blk.ffn1.bias),
            "f2_w": _j(blk.ffn2.weight), "f2_b": _j(blk.ffn2.bias),
        })
    approx = any(blk._gelu_approximate
                 for blk in model.blocks._children.values())
    eps = float(next(iter(
        model.blocks._children.values())).ln1._epsilon)
    return {
        "gelu_approx": approx,
        "ln_eps": eps,
        "embed": _j(model.word_embed.weight),
        "pos": _j(model.position_weight),
        "lnf_g": _j(model.ln_f.gamma), "lnf_b": _j(model.ln_f.beta),
        "blocks": blocks,
    }


# ---------------------------------------------------------------------------
# pure block math (must mirror GPTBlock.forward / ops.nn exactly)
# ---------------------------------------------------------------------------

def _ln(x, g, b, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps) * g + b


def _block_prefill(p, x, nh: int, L: int, ga=(False, 1e-5)):
    gelu_approx, eps = ga
    """Full causal pass over the prompt; returns (x_out, ck, cv) with
    the caches zero-padded to length L."""
    B, T, C = x.shape
    d = C // nh
    h = _ln(x, p["ln1_g"], p["ln1_b"], eps)
    qkv = h @ p["qkv_w"].T + p["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    qh = q.reshape(B, T, nh, d)
    kh = k.reshape(B, T, nh, d)
    vh = v.reshape(B, T, nh, d)
    out = jax.nn.dot_product_attention(qh, kh, vh, is_causal=True)
    x = x + (out.reshape(B, T, C) @ p["out_w"].T + p["out_b"])
    h = _ln(x, p["ln2_g"], p["ln2_b"], eps)
    ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"],
                      approximate=gelu_approx)
    x = x + (ffn @ p["f2_w"].T + p["f2_b"])
    pad = [(0, 0), (0, L - T), (0, 0), (0, 0)]
    return x, jnp.pad(kh, pad), jnp.pad(vh, pad)


def _block_step(p, x, ck, cv, pos, nh: int, ga=(False, 1e-5)):
    gelu_approx, eps = ga
    """One-token decode: x (B, 1, C), caches (B, L, nh, d), pos scalar.
    Writes position ``pos`` then attends over cache[0..pos]."""
    B, _, C = x.shape
    d = C // nh
    L = ck.shape[1]
    h = _ln(x, p["ln1_g"], p["ln1_b"], eps)
    qkv = h @ p["qkv_w"].T + p["qkv_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    qh = q.reshape(B, 1, nh, d)
    ck = lax.dynamic_update_slice_in_dim(ck, k.reshape(B, 1, nh, d),
                                         pos, axis=1)
    cv = lax.dynamic_update_slice_in_dim(cv, v.reshape(B, 1, nh, d),
                                         pos, axis=1)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, ck) / math.sqrt(d)
    visible = jnp.arange(L) <= pos                  # static-shape mask
    scores = jnp.where(visible[None, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, cv).reshape(B, 1, C)
    x = x + (out @ p["out_w"].T + p["out_b"])
    h = _ln(x, p["ln2_g"], p["ln2_b"], eps)
    ffn = jax.nn.gelu(h @ p["f1_w"].T + p["f1_b"],
                      approximate=gelu_approx)
    x = x + (ffn @ p["f2_w"].T + p["f2_b"])
    return x, ck, cv


def _embed_one(params, tok, pos):
    """(B,) token ids at scalar position pos -> (B, 1, C)."""
    x = params["embed"][tok][:, None, :]
    return x + lax.dynamic_slice_in_dim(params["pos"], pos, 1,
                                        axis=0)[None, :, :]


def _forward_step(params, tok, caches, pos, nh, ga=(False, 1e-5)):
    """Embed one token, run all blocks against the caches, return
    (logits (B, V), new caches)."""
    x = _embed_one(params, tok, pos)
    new_caches = []
    for p, (ck, cv) in zip(params["blocks"], caches):
        x, ck, cv = _block_step(p, x, ck, cv, pos, nh, ga)
        new_caches.append((ck, cv))
    x = _ln(x, params["lnf_g"], params["lnf_b"], ga[1])
    return x[:, 0, :] @ params["embed"].T, new_caches


def _prefill(params, tokens, nh, L, ga=(False, 1e-5)):
    x = params["embed"][tokens] + params["pos"][None, :tokens.shape[1]]
    caches = []
    for p in params["blocks"]:
        x, ck, cv = _block_prefill(p, x, nh, L, ga)
        caches.append((ck, cv))
    x = _ln(x, params["lnf_g"], params["lnf_b"], ga[1])
    return x[:, -1, :] @ params["embed"].T, caches


def _select(logits, method, temperature, top_k, top_p, key):
    if method == "greedy":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if method == "top_k":
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    elif method == "top_p":
        # nucleus sampling: keep the smallest prefix of the
        # probability-sorted vocab whose cumulative mass reaches top_p
        # (the most probable token is always kept)
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p
        kth = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                      axis=-1, keepdims=True)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    elif method != "sample":
        raise MXNetError(f"unknown generation method {method!r}")
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class _LRU(dict):
    """Bounded program cache: compiled decode executables are big, and a
    serving loop over varying prompt lengths must not pin one per shape
    forever."""

    MAX = 32

    def get(self, key, default=None):
        v = super().get(key, default)
        if key in self:                     # refresh recency
            super().__delitem__(key)
            super().__setitem__(key, v)
        return v

    def __setitem__(self, key, value):
        if key in self:
            super().__delitem__(key)
        elif len(self) >= self.MAX:
            super().__delitem__(next(iter(self)))
        super().__setitem__(key, value)


_PROG_CACHE: Dict[Any, Any] = _LRU()


def _prepare(model, tokens, max_new_tokens: int):
    """Shared generate/beam prolog: coerce tokens, validate lengths,
    collect params. Returns (toks (B,T0) int32 numpy, params, nh, L)."""
    import numpy as onp
    toks = onp.asarray(tokens.asnumpy() if hasattr(tokens, "asnumpy")
                       else tokens, dtype="int32")
    if toks.ndim == 1:
        toks = toks[None, :]
    if max_new_tokens < 1:
        raise MXNetError("max_new_tokens must be >= 1")
    L = toks.shape[1] + max_new_tokens
    if L > model._max_length:
        raise MXNetError(
            f"prompt ({toks.shape[1]}) + new tokens ({max_new_tokens}) "
            f"exceeds max_length {model._max_length}")
    nh = next(iter(model.blocks._children.values()))._num_heads
    params = _collect(model)
    # static compile-time config — must NOT ride the jitted pytree
    ga = (params.pop("gelu_approx"), params.pop("ln_eps"))
    return toks, params, nh, L, ga


def _model_sig(params, nh, ga):
    """Structural cache key — NOT id(model): a reused address must not
    serve a stale program, and identical-architecture models can share
    one compiled decode."""
    V, C = params["embed"].shape
    return (nh, V, C, params["pos"].shape[0], len(params["blocks"]), ga)


def generate(model, tokens, max_new_tokens: int, method: str = "greedy",
             temperature: float = 1.0, top_k: int = 40,
             eos_token: Optional[int] = None, seed: int = 0,
             top_p: float = 0.9):
    """Decode ``max_new_tokens`` continuations of ``tokens`` (B, T0).

    ``method``: 'greedy', 'sample', 'top_k', or 'top_p' (nucleus —
    sample from the smallest probability-sorted vocab prefix whose
    cumulative mass reaches ``top_p``). Returns an int32 array
    (B, max_new_tokens). After ``eos_token`` (if given) a sequence keeps
    emitting ``eos_token``. One XLA program per (shape, method)
    signature — repeated calls reuse the compiled prefill+scan.
    """
    import numpy as onp
    toks, params, nh, L, ga = _prepare(model, tokens, max_new_tokens)
    B, T0 = toks.shape
    eos = -1 if eos_token is None else int(eos_token)
    if method == "top_k":
        V = params["embed"].shape[0]
        if not 1 <= top_k:
            raise MXNetError(f"top_k must be >= 1, got {top_k}")
        top_k = min(int(top_k), V)
    if method == "top_p" and not 0.0 < top_p <= 1.0:
        raise MXNetError(f"top_p must be in (0, 1], got {top_p}")

    sig = ("gen", _model_sig(params, nh, ga), B, T0, max_new_tokens,
           method, float(temperature), int(top_k), float(top_p), eos)
    prog = _PROG_CACHE.get(sig)
    if prog is None:
        def run(params, toks, key):
            logits, caches = _prefill(params, toks, nh, L, ga)
            key, sub = jax.random.split(key)
            first = _select(logits, method, temperature, top_k, top_p,
                            sub)
            if eos >= 0:
                done0 = first == eos
            else:
                done0 = jnp.zeros((B,), bool)

            def step(carry, i):
                caches, tok, done, key = carry
                pos = T0 + i
                logits, caches = _forward_step(params, tok, caches,
                                               pos, nh, ga)
                key, sub = jax.random.split(key)
                nxt = _select(logits, method, temperature, top_k, top_p,
                              sub)
                if eos >= 0:
                    nxt = jnp.where(done, eos, nxt)
                    done = done | (nxt == eos)
                return (caches, nxt, done, key), nxt

            if max_new_tokens == 1:
                return first[:, None]
            (_, _, _, _), rest = lax.scan(
                step, (caches, first, done0, key),
                jnp.arange(max_new_tokens - 1))
            return jnp.concatenate([first[:, None], rest.T], axis=1)

        prog = jax.jit(run)
        _PROG_CACHE[sig] = prog
    out = prog(params, jnp.asarray(toks),
               jax.random.PRNGKey(seed))
    from ...ndarray.ops import array
    return array(onp.asarray(out))


def beam_search(model, tokens, max_new_tokens: int, beam_size: int = 4,
                eos_token: Optional[int] = None, alpha: float = 1.0):
    """Length-normalized beam search (gluon-nlp ``BeamSearchSampler``
    analog: scores = logprob_sum / length^alpha).

    ``tokens`` (B, T0) -> (sequences (B, beam, max_new_tokens), scores
    (B, beam)), beams sorted best-first. The KV caches expand to
    B*beam rows once and are reordered per step with batched gathers —
    no re-prefill, static shapes throughout.
    """
    import numpy as onp
    toks, params, nh, L, ga = _prepare(model, tokens, max_new_tokens)
    B, T0 = toks.shape
    K = int(beam_size)
    if K < 1:
        raise MXNetError(f"beam_size must be >= 1, got {K}")
    eos = -1 if eos_token is None else int(eos_token)
    NEG = jnp.float32(-1e30)

    sig = ("beam", _model_sig(params, nh, ga), B, T0, max_new_tokens,
           K, eos, float(alpha))
    prog = _PROG_CACHE.get(sig)
    if prog is None:
        def run(params, toks):
            logits, caches = _prefill(params, toks, nh, L, ga)  # (B, V)
            V = logits.shape[-1]
            logp = jax.nn.log_softmax(logits, axis=-1)
            # seed the beams from the prompt's top-K continuations
            scores, first = lax.top_k(logp, K)               # (B, K)
            # expand caches to B*K rows (beam-major within batch)
            caches = jax.tree_util.tree_map(
                lambda c: jnp.repeat(c, K, axis=0), caches)
            tok = first.reshape(B * K)
            done = (tok == eos) if eos >= 0 else jnp.zeros((B * K,), bool)
            seqs0 = jnp.zeros((B, K, max_new_tokens), jnp.int32)
            seqs0 = seqs0.at[:, :, 0].set(first)

            def step(carry, i):
                caches, tok, scores, seqs, done = carry
                pos = T0 + i
                logits, caches = _forward_step(params, tok, caches,
                                               pos, nh, ga)   # (B*K, V)
                logp = jax.nn.log_softmax(logits, axis=-1)
                logp = logp.reshape(B, K, V)
                if eos >= 0:
                    # a finished beam only extends with eos at no cost
                    only_eos = jnp.full((V,), NEG).at[eos].set(0.0)
                    logp = jnp.where(done.reshape(B, K, 1), only_eos,
                                     logp)
                cand = scores[:, :, None] + logp              # (B, K, V)
                flat = cand.reshape(B, K * V)
                scores, idx = lax.top_k(flat, K)              # (B, K)
                beam_src = idx // V                           # (B, K)
                tok = (idx % V).astype(jnp.int32)
                # reorder beam state: rows are beam-major per batch
                gather = (jnp.arange(B)[:, None] * K
                          + beam_src).reshape(B * K)
                caches = jax.tree_util.tree_map(
                    lambda c: c[gather], caches)
                seqs = jnp.take_along_axis(
                    seqs, beam_src[:, :, None], axis=1)
                seqs = seqs.at[:, :, i + 1].set(tok)
                done = done[gather]
                tokf = tok.reshape(B * K)
                if eos >= 0:
                    done = done | (tokf == eos)
                return (caches, tokf, scores, seqs, done), None

            if max_new_tokens > 1:
                (caches, tok, scores, seqs, done), _ = lax.scan(
                    step, (caches, tok, scores, seqs0, done),
                    jnp.arange(max_new_tokens - 1))
            else:
                seqs = seqs0
            # length-normalized final ranking (finished beams measure
            # their true length up to eos)
            if eos >= 0:
                lengths = jnp.sum(
                    jnp.cumsum(seqs == eos, axis=-1) == 0, axis=-1) + 1
                lengths = jnp.minimum(lengths, max_new_tokens)
            else:
                lengths = jnp.full((B, K), max_new_tokens)
            norm = scores / (lengths.astype(jnp.float32) ** alpha)
            order = jnp.argsort(-norm, axis=-1)
            seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
            norm = jnp.take_along_axis(norm, order, axis=1)
            return seqs, norm

        prog = jax.jit(run)
        _PROG_CACHE[sig] = prog
    seqs, scores = prog(params, jnp.asarray(toks))
    from ...ndarray.ops import array
    return array(onp.asarray(seqs)), array(onp.asarray(scores))
