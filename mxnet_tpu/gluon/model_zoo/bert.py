"""BERT — transformer encoder + pretraining heads.

Reference parity: gluon-nlp's BERTModel (the model behind the reference's
``src/operator/contrib/transformer.cc`` interleaved-attention ops; BASELINE
config 3). Architecture: embeddings (word+position+token-type, layernorm,
dropout), N transformer layers (pre/post-LN, GELU FFN), pooler, MLM and
NSP heads with tied decoder weights.

TPU-first: attention goes through ``npx.multi_head_attention`` (XLA fused;
Pallas flash kernel for long sequences), bf16-friendly throughout.
"""
from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

from ... import npx
from ... import numpy as mxnp
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, HybridSequential, LayerNorm
from ..parameter import Parameter

__all__ = ["BERTEncoderLayer", "BERTEncoder", "BERTModel", "get_bert",
           "bert_base", "bert_large"]


class BERTEncoderLayer(HybridBlock):
    """One transformer layer (post-LN like BERT)."""

    def __init__(self, units: int = 768, hidden_size: int = 3072,
                 num_heads: int = 12, dropout: float = 0.1,
                 layer_norm_eps: float = 1e-12, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_heads = num_heads
        self._units = units
        self.attn_qkv = Dense(3 * units, in_units=units, flatten=False)
        self.attn_out = Dense(units, in_units=units, flatten=False)
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn1 = Dense(hidden_size, in_units=units, flatten=False)
        self.ffn2 = Dense(units, in_units=hidden_size, flatten=False)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self._dropout = dropout

    def forward(self, x: NDArray, mask: Optional[NDArray] = None) -> NDArray:
        qkv = self.attn_qkv(x)  # (B, T, 3C)
        q, k, v = mxnp.split(qkv, 3, axis=-1)
        att = npx.multi_head_attention(q, k, v, self._num_heads, mask=mask,
                                       dropout=self._dropout)
        att = self.attn_out(att)
        if self._dropout:
            att = npx.dropout(att, self._dropout)
        x = self.ln1(x + att)
        ffn = self.ffn2(npx.gelu(self.ffn1(x)))
        if self._dropout:
            ffn = npx.dropout(ffn, self._dropout)
        return self.ln2(x + ffn)


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers: int = 12, units: int = 768,
                 hidden_size: int = 3072, num_heads: int = 12,
                 max_length: int = 512, dropout: float = 0.1,
                 layer_norm_eps: float = 1e-12,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._max_length = max_length
        self._units = units
        self.position_weight = Parameter("position_weight",
                                         shape=(max_length, units),
                                         init="normal")
        self.ln = LayerNorm(in_channels=units, epsilon=layer_norm_eps)
        self._dropout = dropout
        self.layers = HybridSequential()
        for _ in range(num_layers):
            self.layers.add(BERTEncoderLayer(units, hidden_size, num_heads,
                                             dropout,
                                             layer_norm_eps=layer_norm_eps))

    def forward(self, x: NDArray, mask: Optional[NDArray] = None) -> NDArray:
        if not self.position_weight.is_initialized:
            self.position_weight._finish_deferred_init(
                (self._max_length, self._units))
        T = x.shape[1]
        from ...ndarray import ops
        pos = ops.slice_axis(self.position_weight.data(), axis=0,
                             begin=0, end=T)
        x = x + pos.expand_dims(0)
        x = self.ln(x)
        if self._dropout:
            x = npx.dropout(x, self._dropout)
        # activation checkpointing per layer under MXNET_REMAT
        from ..block import remat_stack
        return remat_stack(list(self.layers), x, mask,
                           dropout=self._dropout)


class BERTModel(HybridBlock):
    """Full BERT with MLM + NSP heads (gluon-nlp BERTModel parity).

    ``forward(inputs, token_types, valid_length, masked_positions)``:
      - no ``masked_positions``: returns (sequence_output, pooled_output)
      - with ``masked_positions``: additionally returns MLM logits.
    """

    def __init__(self, vocab_size: int = 30522, num_layers: int = 12,
                 units: int = 768, hidden_size: int = 3072,
                 num_heads: int = 12, max_length: int = 512,
                 token_type_vocab_size: int = 2, dropout: float = 0.1,
                 use_pooler: bool = True, use_decoder: bool = True,
                 use_classifier: bool = True,
                 layer_norm_eps: float = 1e-12,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._units = units
        self.word_embed = Embedding(vocab_size, units)
        self.token_type_embed = Embedding(token_type_vocab_size, units)
        self.encoder = BERTEncoder(num_layers, units, hidden_size, num_heads,
                                   max_length, dropout,
                                   layer_norm_eps=layer_norm_eps)
        self.pooler = Dense(units, in_units=units, flatten=False,
                            activation="tanh") if use_pooler else None
        if use_decoder:
            # MLM head: transform + layernorm + decode (weights tied to
            # word embedding, reference-style)
            self.mlm_transform = Dense(units, in_units=units, flatten=False)
            self.mlm_ln = LayerNorm(in_channels=units,
                                    epsilon=layer_norm_eps)
            self.mlm_bias = Parameter("mlm_bias", shape=(vocab_size,),
                                      init="zeros")
        else:
            self.mlm_transform = None
        self.classifier = Dense(2, in_units=units) if use_classifier else None

    def _attention_mask(self, inputs: NDArray,
                        valid_length: Optional[NDArray]):
        if valid_length is None:
            return None
        B, T = inputs.shape[:2]
        from ...ndarray.ops import _as_nd
        from ...ndarray.register import invoke

        def impl(vl):
            import jax.numpy as jnp
            ar = jnp.arange(T)
            keep = ar[None, :] < vl[:, None].astype(jnp.int32)  # (B, Tk)
            return keep[:, None, None, :]  # (B, 1, 1, Tk)
        return invoke("bert_mask", impl, (_as_nd(valid_length),))

    def forward(self, inputs: NDArray,
                token_types: Optional[NDArray] = None,
                valid_length: Optional[NDArray] = None,
                masked_positions: Optional[NDArray] = None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        mask = self._attention_mask(inputs, valid_length)
        seq = self.encoder(x, mask)

        outputs: List[Any] = [seq]
        if self.pooler is not None:
            from ...ndarray import ops
            cls = ops.slice_axis(seq, axis=1, begin=0, end=1).squeeze(1)
            outputs.append(self.pooler(cls))
        if self.mlm_transform is not None and masked_positions is not None:
            if not self.mlm_bias.is_initialized:
                self.mlm_bias._finish_deferred_init(self.mlm_bias.shape)
            gathered = npx.take_positions(seq, masked_positions)
            h = npx.gelu(self.mlm_transform(gathered))
            h = self.mlm_ln(h)
            logits = mxnp.dot(h.reshape(-1, self._units),
                              self.word_embed.weight.data().T)
            logits = logits + self.mlm_bias.data()
            logits = logits.reshape(gathered.shape[0], gathered.shape[1], -1)
            outputs.append(logits)
        return tuple(outputs) if len(outputs) > 1 else outputs[0]


_BERT_SPEC = {
    "bert_12_768_12": dict(num_layers=12, units=768, hidden_size=3072,
                           num_heads=12),
    "bert_24_1024_16": dict(num_layers=24, units=1024, hidden_size=4096,
                            num_heads=16),
}


def get_bert(model_name: str = "bert_12_768_12", vocab_size: int = 30522,
             **kwargs: Any) -> BERTModel:
    from ...base import MXNetError
    if model_name not in _BERT_SPEC:
        raise MXNetError(f"unknown bert spec {model_name!r}; "
                         f"options: {sorted(_BERT_SPEC)}")
    cfg = dict(_BERT_SPEC[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, **cfg)


def bert_base(**kw) -> BERTModel:
    return get_bert("bert_12_768_12", **kw)


def bert_large(**kw) -> BERTModel:
    return get_bert("bert_24_1024_16", **kw)
