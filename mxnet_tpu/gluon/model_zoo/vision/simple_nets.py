"""AlexNet, VGG, SqueezeNet, DenseNet, MobileNet v1/v2 (reference:
``python/mxnet/gluon/model_zoo/vision/{alexnet,vgg,squeezenet,densenet,
mobilenet}.py`` — same architectures, same factory names)."""
from __future__ import annotations

from typing import Any, List

from ....base import MXNetError
from ...block import HybridBlock
from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Dropout,
                   Flatten, GlobalAvgPool2D, HybridSequential, MaxPool2D)

__all__ = ["AlexNet", "alexnet", "VGG", "get_vgg", "vgg11", "vgg13", "vgg16",
           "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
           "SqueezeNet", "squeezenet1_0", "squeezenet1_1",
           "DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "MobileNet", "MobileNetV2", "mobilenet1_0",
           "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
           "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
           "mobilenet_v2_0_25", "MobileNetV3", "mobilenet_v3_large",
           "mobilenet_v3_small"]


class AlexNet(HybridBlock):
    def __init__(self, classes: int = 1000, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.features = HybridSequential()
        self.features.add(Conv2D(64, 11, 4, 2, activation="relu"))
        self.features.add(MaxPool2D(3, 2))
        self.features.add(Conv2D(192, 5, padding=2, activation="relu"))
        self.features.add(MaxPool2D(3, 2))
        self.features.add(Conv2D(384, 3, padding=1, activation="relu"))
        self.features.add(Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(Conv2D(256, 3, padding=1, activation="relu"))
        self.features.add(MaxPool2D(3, 2))
        self.features.add(Flatten())
        self.features.add(Dense(4096, activation="relu"))
        self.features.add(Dropout(0.5))
        self.features.add(Dense(4096, activation="relu"))
        self.features.add(Dropout(0.5))
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(classes: int = 1000, ctx: Any = None, **kw) -> AlexNet:
    net = AlexNet(classes=classes, **kw)
    if ctx is not None:
        net.initialize(ctx=ctx)
    return net


_VGG_SPEC = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers: List[int], filters: List[int],
                 classes: int = 1000, batch_norm: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.features = HybridSequential()
        for i, num in enumerate(layers):
            for _ in range(num):
                self.features.add(Conv2D(filters[i], 3, padding=1))
                if batch_norm:
                    self.features.add(BatchNorm())
                self.features.add(Activation("relu"))
            self.features.add(MaxPool2D(2, 2))
        self.features.add(Flatten())
        self.features.add(Dense(4096, activation="relu"))
        self.features.add(Dropout(0.5))
        self.features.add(Dense(4096, activation="relu"))
        self.features.add(Dropout(0.5))
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def get_vgg(num_layers: int, batch_norm: bool = False, classes: int = 1000,
            ctx: Any = None, **kw) -> VGG:
    if num_layers not in _VGG_SPEC:
        raise MXNetError(f"invalid vgg depth {num_layers}")
    layers, filters = _VGG_SPEC[num_layers]
    net = VGG(layers, filters, classes=classes, batch_norm=batch_norm, **kw)
    if ctx is not None:
        net.initialize(ctx=ctx)
    return net


def vgg11(**kw): return get_vgg(11, **kw)
def vgg13(**kw): return get_vgg(13, **kw)
def vgg16(**kw): return get_vgg(16, **kw)
def vgg19(**kw): return get_vgg(19, **kw)
def vgg11_bn(**kw): return get_vgg(11, batch_norm=True, **kw)
def vgg13_bn(**kw): return get_vgg(13, batch_norm=True, **kw)
def vgg16_bn(**kw): return get_vgg(16, batch_norm=True, **kw)
def vgg19_bn(**kw): return get_vgg(19, batch_norm=True, **kw)


class _Fire(HybridBlock):
    def __init__(self, squeeze: int, expand1x1: int, expand3x3: int,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.squeeze = Conv2D(squeeze, 1, activation="relu")
        self.expand1 = Conv2D(expand1x1, 1, activation="relu")
        self.expand3 = Conv2D(expand3x3, 3, padding=1, activation="relu")

    def forward(self, x):
        from .... import numpy as mxnp
        s = self.squeeze(x)
        return mxnp.concatenate([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version: str = "1.0", classes: int = 1000,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.features = HybridSequential()
        if version == "1.0":
            self.features.add(Conv2D(96, 7, 2, activation="relu"))
            self.features.add(MaxPool2D(3, 2, ceil_mode=True))
            for spec in [(16, 64, 64), (16, 64, 64), (32, 128, 128)]:
                self.features.add(_Fire(*spec))
            self.features.add(MaxPool2D(3, 2, ceil_mode=True))
            for spec in [(32, 128, 128), (48, 192, 192), (48, 192, 192),
                         (64, 256, 256)]:
                self.features.add(_Fire(*spec))
            self.features.add(MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_Fire(64, 256, 256))
        else:
            self.features.add(Conv2D(64, 3, 2, activation="relu"))
            self.features.add(MaxPool2D(3, 2, ceil_mode=True))
            for spec in [(16, 64, 64), (16, 64, 64)]:
                self.features.add(_Fire(*spec))
            self.features.add(MaxPool2D(3, 2, ceil_mode=True))
            for spec in [(32, 128, 128), (32, 128, 128)]:
                self.features.add(_Fire(*spec))
            self.features.add(MaxPool2D(3, 2, ceil_mode=True))
            for spec in [(48, 192, 192), (48, 192, 192), (64, 256, 256),
                         (64, 256, 256)]:
                self.features.add(_Fire(*spec))
        self.features.add(Dropout(0.5))
        self.output = HybridSequential()
        self.output.add(Conv2D(classes, 1, activation="relu"))
        self.output.add(GlobalAvgPool2D())
        self.output.add(Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(**kw): return SqueezeNet("1.0", **kw)
def squeezenet1_1(**kw): return SqueezeNet("1.1", **kw)


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate: int, bn_size: int, dropout: float,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.body = HybridSequential()
        self.body.add(BatchNorm(), Activation("relu"),
                      Conv2D(bn_size * growth_rate, 1, use_bias=False),
                      BatchNorm(), Activation("relu"),
                      Conv2D(growth_rate, 3, padding=1, use_bias=False))
        self._dropout = dropout

    def forward(self, x):
        from .... import numpy as mxnp, npx
        out = self.body(x)
        if self._dropout:
            out = npx.dropout(out, self._dropout)
        return mxnp.concatenate([x, out], axis=1)


_DENSENET_SPEC = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


class DenseNet(HybridBlock):
    def __init__(self, num_init_features: int, growth_rate: int,
                 block_config: List[int], bn_size: int = 4,
                 dropout: float = 0.0, classes: int = 1000,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.features = HybridSequential()
        self.features.add(Conv2D(num_init_features, 7, 2, 3, use_bias=False))
        self.features.add(BatchNorm(), Activation("relu"), MaxPool2D(3, 2, 1))
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            block = HybridSequential()
            for _ in range(num_layers):
                block.add(_DenseLayer(growth_rate, bn_size, dropout))
            self.features.add(block)
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                trans = HybridSequential()
                trans.add(BatchNorm(), Activation("relu"),
                          Conv2D(num_features // 2, 1, use_bias=False),
                          AvgPool2D(2, 2))
                self.features.add(trans)
                num_features //= 2
        self.features.add(BatchNorm(), Activation("relu"), GlobalAvgPool2D(),
                          Flatten())
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _densenet(n, **kw):
    init, growth, config = _DENSENET_SPEC[n]
    return DenseNet(init, growth, config, **kw)


def densenet121(**kw): return _densenet(121, **kw)
def densenet161(**kw): return _densenet(161, **kw)
def densenet169(**kw): return _densenet(169, **kw)
def densenet201(**kw): return _densenet(201, **kw)


class MobileNet(HybridBlock):
    """MobileNet v1 with width multiplier."""

    def __init__(self, multiplier: float = 1.0, classes: int = 1000,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        m = multiplier
        def c(ch): return max(8, int(ch * m))
        self.features = HybridSequential()
        self.features.add(Conv2D(c(32), 3, 2, 1, use_bias=False),
                          BatchNorm(), Activation("relu"))
        spec = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
                *[(512, 1)] * 5, (1024, 2), (1024, 1)]
        in_c = c(32)
        for ch, stride in spec:
            # depthwise
            self.features.add(Conv2D(in_c, 3, stride, 1, groups=in_c,
                                     use_bias=False, in_channels=in_c),
                              BatchNorm(), Activation("relu"))
            # pointwise
            self.features.add(Conv2D(c(ch), 1, use_bias=False),
                              BatchNorm(), Activation("relu"))
            in_c = c(ch)
        self.features.add(GlobalAvgPool2D(), Flatten())
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _InvertedResidual(HybridBlock):
    def __init__(self, in_channels: int, channels: int, stride: int,
                 expansion: int, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        hidden = in_channels * expansion
        self.body = HybridSequential()
        if expansion != 1:
            self.body.add(Conv2D(hidden, 1, use_bias=False), BatchNorm(),
                          Activation("relu"))
        self.body.add(Conv2D(hidden, 3, stride, 1, groups=hidden,
                             use_bias=False, in_channels=hidden),
                      BatchNorm(), Activation("relu"))
        self.body.add(Conv2D(channels, 1, use_bias=False), BatchNorm())

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_shortcut else out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier: float = 1.0, classes: int = 1000,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        m = multiplier
        def c(ch): return max(8, int(ch * m))
        self.features = HybridSequential()
        self.features.add(Conv2D(c(32), 3, 2, 1, use_bias=False),
                          BatchNorm(), Activation("relu"))
        spec = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = c(32)
        for t, ch, n, s in spec:
            for i in range(n):
                self.features.add(_InvertedResidual(
                    in_c, c(ch), s if i == 0 else 1, t))
                in_c = c(ch)
        last = max(1280, int(1280 * m))
        self.features.add(Conv2D(last, 1, use_bias=False), BatchNorm(),
                          Activation("relu"), GlobalAvgPool2D(), Flatten())
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def mobilenet1_0(**kw): return MobileNet(1.0, **kw)
def mobilenet0_75(**kw): return MobileNet(0.75, **kw)
def mobilenet0_5(**kw): return MobileNet(0.5, **kw)
def mobilenet0_25(**kw): return MobileNet(0.25, **kw)
def mobilenet_v2_1_0(**kw): return MobileNetV2(1.0, **kw)
def mobilenet_v2_0_75(**kw): return MobileNetV2(0.75, **kw)
def mobilenet_v2_0_5(**kw): return MobileNetV2(0.5, **kw)
def mobilenet_v2_0_25(**kw): return MobileNetV2(0.25, **kw)


class _HardSwish(HybridBlock):
    def forward(self, x):
        return x * (x + 3.0).clip(0.0, 6.0) / 6.0


class _SE(HybridBlock):
    """Squeeze-and-excitation with hard-sigmoid gate (MobileNetV3)."""

    def __init__(self, channels: int, reduction: int = 4, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.pool = GlobalAvgPool2D()
        self.fc1 = Conv2D(max(8, channels // reduction), 1)
        self.fc2 = Conv2D(channels, 1)

    def forward(self, x):
        w = self.pool(x)
        w = self.fc1(w).relu()
        w = (self.fc2(w) + 3.0).clip(0.0, 6.0) / 6.0
        return x * w


class _V3Bottleneck(HybridBlock):
    def __init__(self, in_c: int, exp: int, out_c: int, kernel: int,
                 stride: int, se: bool, act: str, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_c == out_c
        act_blk = _HardSwish if act == "hswish" else \
            (lambda: Activation("relu"))
        self.body = HybridSequential()
        if exp != in_c:
            self.body.add(Conv2D(exp, 1, use_bias=False), BatchNorm(),
                          act_blk())
        self.body.add(Conv2D(exp, kernel, stride, kernel // 2, groups=exp,
                             use_bias=False, in_channels=exp),
                      BatchNorm(), act_blk())
        if se:
            self.body.add(_SE(exp))
        self.body.add(Conv2D(out_c, 1, use_bias=False), BatchNorm())

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_shortcut else out


class MobileNetV3(HybridBlock):
    """MobileNet v3 large/small (reference era: gluoncv mobilenetv3;
    SURVEY.md 2.5 zoo inventory). Hard-swish + SE bottlenecks."""

    # k, exp, out, se, act, stride
    _LARGE = [(3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
              (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
              (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
              (3, 240, 80, False, "hswish", 2), (3, 200, 80, False, "hswish", 1),
              (3, 184, 80, False, "hswish", 1), (3, 184, 80, False, "hswish", 1),
              (3, 480, 112, True, "hswish", 1), (3, 672, 112, True, "hswish", 1),
              (5, 672, 160, True, "hswish", 2), (5, 960, 160, True, "hswish", 1),
              (5, 960, 160, True, "hswish", 1)]
    _SMALL = [(3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
              (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hswish", 2),
              (5, 240, 40, True, "hswish", 1), (5, 240, 40, True, "hswish", 1),
              (5, 120, 48, True, "hswish", 1), (5, 144, 48, True, "hswish", 1),
              (5, 288, 96, True, "hswish", 2), (5, 576, 96, True, "hswish", 1),
              (5, 576, 96, True, "hswish", 1)]

    def __init__(self, mode: str = "large", classes: int = 1000,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ("large", "small"):
            raise MXNetError("MobileNetV3 mode must be 'large' or 'small'")
        spec = self._LARGE if mode == "large" else self._SMALL
        last_exp = 960 if mode == "large" else 576
        head = 1280 if mode == "large" else 1024
        self.features = HybridSequential()
        self.features.add(Conv2D(16, 3, 2, 1, use_bias=False), BatchNorm(),
                          _HardSwish())
        in_c = 16
        for k, exp, out_c, se, act, s in spec:
            self.features.add(_V3Bottleneck(in_c, exp, out_c, k, s, se, act))
            in_c = out_c
        self.features.add(Conv2D(last_exp, 1, use_bias=False), BatchNorm(),
                          _HardSwish(), GlobalAvgPool2D(),
                          Conv2D(head, 1), _HardSwish(), Flatten())
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def mobilenet_v3_large(**kw): return MobileNetV3("large", **kw)
def mobilenet_v3_small(**kw): return MobileNetV3("small", **kw)
