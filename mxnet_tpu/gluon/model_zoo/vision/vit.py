"""Vision Transformer (ViT) classification family.

Beyond-reference model family (the reference's zoo predates ViT;
``python/mxnet/gluon/model_zoo/vision`` stops at CNNs): ViT is the
natural TPU citizen — the whole network is large batched matmuls, so it
rides the same MXU-native attention path as BERT/GPT (flash kernels via
``npx.multi_head_attention``, per-layer activation checkpointing under
``MXNET_REMAT``).

Architecture follows the original recipe (patchify-conv embedding, a
learned class token + learned position embeddings, PRE-LayerNorm
encoder blocks with GELU MLPs, classification off the class token).
Factories: vit_tiny/small/base_patch16 (224 default, any multiple of
the patch size works at construction time).
"""
from __future__ import annotations

from typing import Any, Optional

from .... import npx
from .... import numpy as mxnp
from ....ndarray import ops as ndops
from ....ndarray.ndarray import NDArray
from ...block import HybridBlock, remat_stack
from ...nn import Conv2D, Dense, HybridSequential, LayerNorm
from ...parameter import Parameter

__all__ = ["VisionTransformer", "ViTEncoderLayer",
           "vit_tiny_patch16", "vit_small_patch16", "vit_base_patch16"]


class ViTEncoderLayer(HybridBlock):
    """One pre-LN transformer block: x + attn(ln1(x)), x + mlp(ln2(x))."""

    def __init__(self, units: int, hidden_size: int, num_heads: int,
                 dropout: float = 0.0, layer_norm_eps: float = 1e-6,
                 gelu_approximate: bool = True,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_heads = num_heads
        self._dropout = dropout
        # tanh-approx GELU by default (the flax-ViT convention): the
        # exact-erf backward measures ~2 ms/block at B=128 T=197 on v5e
        # (~15% of the whole train step); there is no pretrained-weight
        # parity at stake in this zoo, so fast is the right default
        self._gelu_approximate = gelu_approximate
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn_qkv = Dense(3 * units, in_units=units, flatten=False)
        self.attn_out = Dense(units, in_units=units, flatten=False)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn1 = Dense(hidden_size, in_units=units, flatten=False)
        self.ffn2 = Dense(units, in_units=hidden_size, flatten=False)

    def forward(self, x: NDArray,
                mask: Optional[NDArray] = None) -> NDArray:
        h = self.ln1(x)
        qkv = self.attn_qkv(h)
        q, k, v = mxnp.split(qkv, 3, axis=-1)
        att = npx.multi_head_attention(q, k, v, self._num_heads,
                                       mask=mask, dropout=self._dropout)
        att = self.attn_out(att)
        if self._dropout:
            att = npx.dropout(att, self._dropout)
        x = x + att
        h = self.ffn2(npx.gelu(self.ffn1(self.ln2(x)),
                               approximate=self._gelu_approximate))
        if self._dropout:
            h = npx.dropout(h, self._dropout)
        return x + h


class VisionTransformer(HybridBlock):
    """ViT classifier: patchify -> [cls | patches] + pos -> pre-LN
    encoder stack -> final LN -> head(cls)."""

    def __init__(self, img_size: int = 224, patch_size: int = 16,
                 units: int = 768, num_layers: int = 12,
                 num_heads: int = 12, hidden_size: int = 3072,
                 classes: int = 1000, in_channels: int = 3,
                 dropout: float = 0.0, layer_norm_eps: float = 1e-6,
                 gelu_approximate: bool = True,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if img_size % patch_size:
            from ....base import MXNetError
            raise MXNetError(f"img_size {img_size} not divisible by "
                             f"patch_size {patch_size}")
        self._units = units
        self._dropout = dropout
        self._num_patches = (img_size // patch_size) ** 2
        self.patch_embed = Conv2D(units, kernel_size=patch_size,
                                  strides=patch_size,
                                  in_channels=in_channels)
        self.cls_token = Parameter("cls_token", shape=(1, 1, units),
                                   init="zeros")
        self.pos_embed = Parameter(
            "pos_embed", shape=(1, self._num_patches + 1, units),
            init="normal")
        self.blocks = HybridSequential()
        for _ in range(num_layers):
            self.blocks.add(ViTEncoderLayer(units, hidden_size, num_heads,
                                            dropout, layer_norm_eps,
                                            gelu_approximate))
        self.ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.head = Dense(classes, in_units=units)

    def forward(self, x: NDArray) -> NDArray:
        for p in (self.cls_token, self.pos_embed):
            if not p.is_initialized:
                p._finish_deferred_init(p.shape)
        B = x.shape[0]
        h = self.patch_embed(x)                      # (B, C, H/p, W/p)
        h = h.reshape(B, self._units, -1)            # (B, C, N)
        h = mxnp.swapaxes(h, 1, 2)                   # (B, N, C)
        cls = mxnp.broadcast_to(self.cls_token.data(),
                                (B, 1, self._units))
        h = mxnp.concatenate([cls, h], axis=1)
        h = h + self.pos_embed.data()
        if self._dropout:
            h = npx.dropout(h, self._dropout)
        # per-layer activation checkpointing under MXNET_REMAT, same as
        # the BERT/GPT encoders
        h = remat_stack(list(self.blocks), h, None,
                        dropout=self._dropout)
        h = self.ln(h)
        return self.head(ndops.slice_axis(h, axis=1, begin=0, end=1)
                         .reshape(B, self._units))


def _vit(units, num_layers, num_heads, hidden_size, **kw):
    kw.setdefault("units", units)
    kw.setdefault("num_layers", num_layers)
    kw.setdefault("num_heads", num_heads)
    kw.setdefault("hidden_size", hidden_size)
    return VisionTransformer(**kw)


def vit_tiny_patch16(**kw):
    return _vit(192, 12, 3, 768, **kw)


def vit_small_patch16(**kw):
    return _vit(384, 12, 6, 1536, **kw)


def vit_base_patch16(**kw):
    return _vit(768, 12, 12, 3072, **kw)
