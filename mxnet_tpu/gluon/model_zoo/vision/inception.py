"""Inception v3 (reference: ``python/mxnet/gluon/model_zoo/vision/
inception.py`` — same architecture and factory name).

Built from the same HybridBlock layers as the rest of the zoo; all convs are
channels-first NCHW so XLA lays them onto the MXU directly.
"""
from __future__ import annotations

from typing import Any

from ....ndarray import ops as ndops
from ...block import HybridBlock
from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Dropout,
                   Flatten, GlobalAvgPool2D, HybridSequential, MaxPool2D)

__all__ = ["Inception3", "inception_v3"]


def _conv_bn(channels: int, kernel, stride=1, padding=0) -> HybridSequential:
    out = HybridSequential()
    out.add(Conv2D(channels, kernel, stride, padding, use_bias=False),
            BatchNorm(epsilon=0.001), Activation("relu"))
    return out


class _Concurrent(HybridSequential):
    """Run children on the same input and concat outputs on channel axis
    (reference: gluon.contrib.nn.HybridConcurrent used by inception)."""

    def forward(self, x):
        outs = [blk(x) for blk in self._children_list()]
        return ndops.concat(*outs, dim=1)

    def _children_list(self):
        return list(self._children.values())

    def deploy_emit(self, em, prefix, vid):
        """Native C-deployment emission (gluon.deploy SSA hook): fan the
        input to every child, concat outputs on channels."""
        if type(self).forward is not _Concurrent.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        outs = [em.emit(child, f"{prefix}{name}.", vid)
                for name, child in self._children.items()]
        if len(outs) < 2:
            em.fail("concat of < 2 branches")
        return em.push({"op": "concat", "axis": 1}, outs)


def _make_A(pool_features: int) -> _Concurrent:
    out = _Concurrent()
    out.add(_conv_bn(64, 1))
    b2 = HybridSequential(); b2.add(_conv_bn(48, 1), _conv_bn(64, 5, 1, 2))
    b3 = HybridSequential()
    b3.add(_conv_bn(64, 1), _conv_bn(96, 3, 1, 1), _conv_bn(96, 3, 1, 1))
    b4 = HybridSequential()
    b4.add(AvgPool2D(3, 1, 1), _conv_bn(pool_features, 1))
    out.add(b2, b3, b4)
    return out


def _make_B() -> _Concurrent:
    out = _Concurrent()
    out.add(_conv_bn(384, 3, 2))
    b2 = HybridSequential()
    b2.add(_conv_bn(64, 1), _conv_bn(96, 3, 1, 1), _conv_bn(96, 3, 2))
    b3 = HybridSequential(); b3.add(MaxPool2D(3, 2))
    out.add(b2, b3)
    return out


def _make_C(channels_7x7: int) -> _Concurrent:
    out = _Concurrent()
    out.add(_conv_bn(192, 1))
    c = channels_7x7
    b2 = HybridSequential()
    b2.add(_conv_bn(c, 1), _conv_bn(c, (1, 7), 1, (0, 3)),
           _conv_bn(192, (7, 1), 1, (3, 0)))
    b3 = HybridSequential()
    b3.add(_conv_bn(c, 1), _conv_bn(c, (7, 1), 1, (3, 0)),
           _conv_bn(c, (1, 7), 1, (0, 3)), _conv_bn(c, (7, 1), 1, (3, 0)),
           _conv_bn(192, (1, 7), 1, (0, 3)))
    b4 = HybridSequential()
    b4.add(AvgPool2D(3, 1, 1), _conv_bn(192, 1))
    out.add(b2, b3, b4)
    return out


def _make_D() -> _Concurrent:
    out = _Concurrent()
    b1 = HybridSequential(); b1.add(_conv_bn(192, 1), _conv_bn(320, 3, 2))
    b2 = HybridSequential()
    b2.add(_conv_bn(192, 1), _conv_bn(192, (1, 7), 1, (0, 3)),
           _conv_bn(192, (7, 1), 1, (3, 0)), _conv_bn(192, 3, 2))
    b3 = HybridSequential(); b3.add(MaxPool2D(3, 2))
    out.add(b1, b2, b3)
    return out


class _SplitConcat(HybridBlock):
    """1x1 reduce then parallel (1,3)/(3,1) convs concatenated (the E-block
    arm that fans one tensor into two convs)."""

    def __init__(self, reduce: HybridSequential, arms, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reduce = reduce
        for i, arm in enumerate(arms):
            setattr(self, f"arm{i}", arm)
        self._n_arms = len(arms)

    def forward(self, x):
        if self.reduce is not None:
            x = self.reduce(x)
        outs = [getattr(self, f"arm{i}")(x) for i in range(self._n_arms)]
        return ndops.concat(*outs, dim=1)

    def deploy_emit(self, em, prefix, vid):
        if type(self).forward is not _SplitConcat.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        if self._n_arms < 2:
            em.fail("concat of < 2 arms")
        h = (em.emit(self.reduce, prefix + "reduce.", vid)
             if self.reduce is not None else vid)
        outs = [em.emit(getattr(self, f"arm{i}"), f"{prefix}arm{i}.", h)
                for i in range(self._n_arms)]
        return em.push({"op": "concat", "axis": 1}, outs)


def _make_E() -> _Concurrent:
    out = _Concurrent()
    out.add(_conv_bn(320, 1))
    out.add(_SplitConcat(_conv_bn(384, 1),
                         [_conv_bn(384, (1, 3), 1, (0, 1)),
                          _conv_bn(384, (3, 1), 1, (1, 0))]))
    pre = HybridSequential(); pre.add(_conv_bn(448, 1), _conv_bn(384, 3, 1, 1))
    out.add(_SplitConcat(pre,
                         [_conv_bn(384, (1, 3), 1, (0, 1)),
                          _conv_bn(384, (3, 1), 1, (1, 0))]))
    b4 = HybridSequential()
    b4.add(AvgPool2D(3, 1, 1), _conv_bn(192, 1))
    out.add(b4)
    return out


class Inception3(HybridBlock):
    """Inception v3 (299x299 input; reference ``Inception3``)."""

    def __init__(self, classes: int = 1000, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.features = HybridSequential()
        self.features.add(_conv_bn(32, 3, 2),
                          _conv_bn(32, 3),
                          _conv_bn(64, 3, 1, 1),
                          MaxPool2D(3, 2),
                          _conv_bn(80, 1),
                          _conv_bn(192, 3),
                          MaxPool2D(3, 2),
                          _make_A(32), _make_A(64), _make_A(64),
                          _make_B(),
                          _make_C(128), _make_C(160), _make_C(160),
                          _make_C(192),
                          _make_D(),
                          _make_E(), _make_E(),
                          AvgPool2D(8),
                          Dropout(0.5),
                          Flatten())
        self.output = Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))

    def deploy_emit(self, em, prefix, vid):
        if type(self).forward is not Inception3.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        h = em.emit(self.features, prefix + "features.", vid)
        return em.emit(self.output, prefix + "output.", h)


def inception_v3(classes: int = 1000, **kwargs: Any) -> Inception3:
    return Inception3(classes=classes, **kwargs)
