"""ResNet v1/v2 (reference: ``python/mxnet/gluon/model_zoo/vision/resnet.py``).

Same architecture family and factory API: resnet18_v1 ... resnet152_v2,
``get_resnet(version, num_layers)``. BASELINE config 2's model.
"""
from __future__ import annotations

from typing import Any, List, Optional, Type

from ....base import MXNetError
from ...block import HybridBlock
from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Flatten,
                   GlobalAvgPool2D, HybridSequential, MaxPool2D)

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _conv3x3(channels: int, stride: int, in_channels: int) -> Conv2D:
    return Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                  use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels: int, stride: int, downsample: bool = False,
                 in_channels: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.body = HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(BatchNorm())
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(BatchNorm())
        if downsample:
            self.downsample = HybridSequential()
            self.downsample.add(Conv2D(channels, kernel_size=1,
                                       strides=stride, use_bias=False,
                                       in_channels=in_channels))
            self.downsample.add(BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(x)
        from .... import npx
        return npx.relu(out + residual)

    def deploy_emit(self, em, prefix, vid):
        return _emit_v1_block(self, BasicBlockV1, em, prefix, vid)


class BottleneckV1(HybridBlock):
    """The deep-ResNet block.  Under MXNET_FUSE_BN_CONV both of its 1x1
    junctions run as Pallas prologue-fused GEMMs (ops/pallas/
    conv_fused.py): the (bn2, relu, conv3) triple fuses inside ``body``
    (HybridSequential pattern), and the block's epilogue ReLU is
    DEFERRED (gluon.block.PreActivation) so the next block's conv1
    takes it as a kernel prologue — the activated tensors never
    round-trip HBM.  Semantics are unchanged; the fusion is numerically
    invisible (tests/test_fused_conv.py)."""

    _consumes_preactivation = True

    def __init__(self, channels: int, stride: int, downsample: bool = False,
                 in_channels: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.body = HybridSequential()
        self.body.add(Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(BatchNorm())
        self.body.add(Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(BatchNorm())
        self.body.add(Activation("relu"))
        self.body.add(Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(BatchNorm())
        if downsample:
            self.downsample = HybridSequential()
            self.downsample.add(Conv2D(channels, kernel_size=1,
                                       strides=stride, use_bias=False,
                                       in_channels=in_channels))
            self.downsample.add(BatchNorm())
        else:
            self.downsample = None

    @staticmethod
    def _head_fusable(conv) -> bool:
        from ...nn.basic_layers import _conv1x1_fusable
        return _conv1x1_fusable(conv)

    def _block_out(self, x):
        """out + residual BEFORE the epilogue ReLU (accepts a deferred
        PreActivation input from the previous sibling)."""
        from .... import npx
        from ...block import PreActivation
        from ...nn.basic_layers import _sequential_forward

        from ...nn.basic_layers import _has_hooks
        body = list(self.body._children.values())
        if isinstance(x, PreActivation):
            z = x.z
            from ....ops.pallas.conv_fused import fusion_profitable
            if (npx.conv_fusion_enabled() and self._head_fusable(body[0])
                    and not _has_hooks(self.body)
                    and fusion_profitable(z.shape[0], z.shape[1],
                                          body[0]._channels,
                                          z.shape[2] * z.shape[3])):
                conv1 = body[0]
                conv1._infer(z)
                h = npx.relu_conv1x1(
                    z, conv1.weight.data(),
                    None if conv1.bias is None else conv1.bias.data())
                out = _sequential_forward(body[1:], h)
                xin = None      # activated input materialized lazily
            else:
                xin = x.materialize()
                out = self.body(xin)
        else:
            z = None
            xin = x
            out = self.body(xin)
        if self.downsample is not None:
            residual = self.downsample(
                xin if xin is not None else npx.relu(z))
        else:
            # XLA fuses the recomputed ReLU into the add's operand read
            residual = xin if xin is not None else npx.relu(z)
        return out + residual

    def forward(self, x):
        from .... import npx
        return npx.relu(self._block_out(x))

    def _forward_deferred(self, x):
        """Like forward(), but hands the consumer the PRE-activation so
        its 1x1 conv1 can take the ReLU as a kernel prologue.  Only
        _ResidualStage calls this (the box never reaches user code)."""
        from ...block import PreActivation
        from ....ndarray.ndarray import NDArray
        zsum = self._block_out(x)
        if isinstance(zsum, NDArray):
            return PreActivation(zsum)
        from .... import npx
        return npx.relu(zsum)

    def deploy_emit(self, em, prefix, vid):
        return _emit_v1_block(self, BottleneckV1, em, prefix, vid)


def _emit_v1_block(self, cls, em, prefix, vid):
    """Native C-deployment emission (gluon.deploy SSA hook):
    ``relu(body(x) + downsample(x))`` — exactly ``forward`` above."""
    if type(self).forward is not cls.forward:
        em.fail(f"{type(self).__name__} overrides forward")
    body = em.emit(self.body, prefix + "body.", vid)
    res = (em.emit(self.downsample, prefix + "downsample.", vid)
           if self.downsample is not None else vid)
    s = em.push({"op": "add"}, [body, res])
    return em.push({"op": "activation", "act": "relu"}, [s])


class BasicBlockV2(HybridBlock):
    def __init__(self, channels: int, stride: int, downsample: bool = False,
                 in_channels: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.bn1 = BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = Conv2D(channels, 1, strides=stride,
                                     use_bias=False, in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        from .... import npx
        residual = x
        out = npx.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(out)
        out = self.conv1(out)
        out = npx.relu(self.bn2(out))
        out = self.conv2(out)
        return out + residual

    def deploy_emit(self, em, prefix, vid):
        """Pre-activation residual (matches ``forward``: residual taken
        at relu(bn1(x)) when downsampling, at x otherwise)."""
        if type(self).forward is not BasicBlockV2.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        h = em.push(em.bn(self.bn1, prefix + "bn1."), [vid])
        h = em.push({"op": "activation", "act": "relu"}, [h])
        res = (em.emit(self.downsample, prefix + "downsample.", h)
               if self.downsample is not None else vid)
        o = em.emit(self.conv1, prefix + "conv1.", h)
        o = em.push(em.bn(self.bn2, prefix + "bn2."), [o])
        o = em.push({"op": "activation", "act": "relu"}, [o])
        o = em.emit(self.conv2, prefix + "conv2.", o)
        return em.push({"op": "add"}, [o, res])


class BottleneckV2(HybridBlock):
    def __init__(self, channels: int, stride: int, downsample: bool = False,
                 in_channels: int = 0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.bn1 = BatchNorm()
        self.conv1 = Conv2D(channels // 4, 1, strides=1, use_bias=False)
        self.bn2 = BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = BatchNorm()
        self.conv3 = Conv2D(channels, 1, strides=1, use_bias=False)
        if downsample:
            self.downsample = Conv2D(channels, 1, strides=stride,
                                     use_bias=False, in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        from .... import npx
        residual = x
        out = npx.relu(self.bn1(x))
        if self.downsample is not None:
            residual = self.downsample(out)
        out = self.conv1(out)
        out = npx.relu(self.bn2(out))
        out = self.conv2(out)
        out = npx.relu(self.bn3(out))
        out = self.conv3(out)
        return out + residual

    def deploy_emit(self, em, prefix, vid):
        if type(self).forward is not BottleneckV2.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        h = em.push(em.bn(self.bn1, prefix + "bn1."), [vid])
        h = em.push({"op": "activation", "act": "relu"}, [h])
        res = (em.emit(self.downsample, prefix + "downsample.", h)
               if self.downsample is not None else vid)
        o = em.emit(self.conv1, prefix + "conv1.", h)
        o = em.push(em.bn(self.bn2, prefix + "bn2."), [o])
        o = em.push({"op": "activation", "act": "relu"}, [o])
        o = em.emit(self.conv2, prefix + "conv2.", o)
        o = em.push(em.bn(self.bn3, prefix + "bn3."), [o])
        o = em.push({"op": "activation", "act": "relu"}, [o])
        o = em.emit(self.conv3, prefix + "conv3.", o)
        return em.push({"op": "add"}, [o, res])


class _ResidualStage(HybridSequential):
    """A stage of residual blocks that drives the epilogue-ReLU deferral
    between siblings (BottleneckV1._forward_deferred): each non-final
    block hands its successor the pre-activation sum so the successor's
    1x1 conv1 fuses the ReLU as a Pallas prologue.  The stage always
    RETURNS a materialized NDArray — the deferral box is an internal
    protocol, invisible to user code.  With fusion disabled (or for
    blocks without the protocol) this is exactly HybridSequential."""

    def forward(self, x, *args):
        from .... import npx
        from ...block import PreActivation
        children = list(self._children.values())
        fuse = npx.conv_fusion_enabled() and not args
        from ...nn.basic_layers import _has_hooks
        for i, child in enumerate(children):
            defer = (fuse and i + 1 < len(children)
                     and hasattr(type(child), "_forward_deferred")
                     and getattr(type(children[i + 1]),
                                 "_consumes_preactivation", False)
                     and not _has_hooks(child, children[i + 1]))
            if defer:
                x = child._forward_deferred(x)
            else:
                x = child(x, *args)
            args = ()
        if isinstance(x, PreActivation):   # safety: never leak the box
            x = x.materialize()
        return x

    def deploy_emit(self, em, prefix, vid):
        # the fusion is numerically invisible: emit as a plain chain
        for name, child in self._children.items():
            vid = em.emit(child, f"{prefix}{name}.", vid)
        return vid


_BLOCK_V1 = {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1}
_BLOCK_V2 = {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}

# num_layers -> (block_type, layers-per-stage, channels-per-stage)
_RESNET_SPEC = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


class ResNetV1(HybridBlock):
    def __init__(self, block: type, layers: List[int], channels: List[int],
                 classes: int = 1000, thumbnail: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = HybridSequential()
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(Conv2D(channels[0], 7, strides=2, padding=3,
                                     use_bias=False))
            self.features.add(BatchNorm())
            self.features.add(Activation("relu"))
            self.features.add(MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(GlobalAvgPool2D())
        self.output = Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0):
        layer = _ResidualStage()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(Flatten()(x))

    def deploy_emit(self, em, prefix, vid):
        if type(self).forward is not ResNetV1.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        h = em.emit(self.features, prefix + "features.", vid)
        h = em.push({"op": "flatten"}, [h])
        return em.emit(self.output, prefix + "output.", h)


class ResNetV2(HybridBlock):
    def __init__(self, block: type, layers: List[int], channels: List[int],
                 classes: int = 1000, thumbnail: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.features = HybridSequential()
        self.features.add(BatchNorm(scale=False, center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(Conv2D(channels[0], 7, strides=2, padding=3,
                                     use_bias=False))
            self.features.add(BatchNorm())
            self.features.add(Activation("relu"))
            self.features.add(MaxPool2D(3, 2, 1))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(BatchNorm())
        self.features.add(Activation("relu"))
        self.features.add(GlobalAvgPool2D())
        self.output = Dense(classes, in_units=channels[-1])

    _make_layer = ResNetV1._make_layer

    def forward(self, x):
        x = self.features(x)
        return self.output(Flatten()(x))

    def deploy_emit(self, em, prefix, vid):
        if type(self).forward is not ResNetV2.forward:
            em.fail(f"{type(self).__name__} overrides forward")
        h = em.emit(self.features, prefix + "features.", vid)
        h = em.push({"op": "flatten"}, [h])
        return em.emit(self.output, prefix + "output.", h)


def get_resnet(version: int, num_layers: int, pretrained: bool = False,
               ctx: Any = None, classes: int = 1000,
               **kwargs: Any) -> HybridBlock:
    """Factory (reference: ``get_resnet``); pretrained weights require
    local files (no egress) via ``load_parameters``."""
    if num_layers not in _RESNET_SPEC:
        raise MXNetError(f"invalid resnet depth {num_layers}; "
                         f"options: {sorted(_RESNET_SPEC)}")
    block_type, layers, channels = _RESNET_SPEC[num_layers]
    if version == 1:
        net = ResNetV1(_BLOCK_V1[block_type], layers, channels,
                       classes=classes, **kwargs)
    elif version == 2:
        net = ResNetV2(_BLOCK_V2[block_type], layers, channels,
                       classes=classes, **kwargs)
    else:
        raise MXNetError(f"invalid resnet version {version}")
    if pretrained:
        raise MXNetError("pretrained weights unavailable without network "
                         "egress; call net.load_parameters(path) instead")
    if ctx is not None:
        net.initialize(ctx=ctx)
    return net


def resnet18_v1(**kw): return get_resnet(1, 18, **kw)
def resnet34_v1(**kw): return get_resnet(1, 34, **kw)
def resnet50_v1(**kw): return get_resnet(1, 50, **kw)
def resnet101_v1(**kw): return get_resnet(1, 101, **kw)
def resnet152_v1(**kw): return get_resnet(1, 152, **kw)
def resnet18_v2(**kw): return get_resnet(2, 18, **kw)
def resnet34_v2(**kw): return get_resnet(2, 34, **kw)
def resnet50_v2(**kw): return get_resnet(2, 50, **kw)
def resnet101_v2(**kw): return get_resnet(2, 101, **kw)
def resnet152_v2(**kw): return get_resnet(2, 152, **kw)
