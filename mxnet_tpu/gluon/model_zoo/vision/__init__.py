"""``gluon.model_zoo.vision`` — classification model zoo (reference:
``python/mxnet/gluon/model_zoo/vision/__init__.py`` with get_model)."""
from .resnet import *  # noqa: F401,F403
from .resnet import __all__ as _resnet_all
from .simple_nets import *  # noqa: F401,F403
from .simple_nets import __all__ as _simple_all
from .inception import *  # noqa: F401,F403
from .inception import __all__ as _inception_all
from .vit import *  # noqa: F401,F403
from .vit import __all__ as _vit_all

from ....base import MXNetError

_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1, "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn, "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "mobilenetv3_large": mobilenet_v3_large,
    "mobilenetv3_small": mobilenet_v3_small,
    "inceptionv3": inception_v3,
    "vit_tiny_patch16": vit_tiny_patch16,
    "vit_small_patch16": vit_small_patch16,
    "vit_base_patch16": vit_base_patch16,
}


def get_model(name: str, **kwargs):
    """Create a model by name (reference: ``vision.get_model``)."""
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name!r} not in zoo; available: {sorted(_models)}")
    return _models[name](**kwargs)


__all__ = (list(_resnet_all) + list(_simple_all) + list(_inception_all)
           + list(_vit_all) + ["get_model"])
