"""GPT — decoder-only causal transformer language model.

Beyond-reference model family (the reference's NLP story was gluon-nlp
BERT, SURVEY.md section 2.5; the fork era predates decoder-only LMs as a
zoo staple) built from the same primitives: pre-LN blocks,
``npx.multi_head_attention(causal=True)`` (XLA attention, Pallas flash
kernel for long sequences, ring attention when the mesh has an 'sp'
axis), GELU FFN, weight-tied LM head. Works imperatively, hybridized,
and under SPMDTrainer (DEFAULT_TRANSFORMER_RULES name the qkv/out/ffn
parameters this model uses).
"""
from __future__ import annotations

from typing import Any, Optional

from ... import npx
from ... import numpy as mxnp
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn import Dense, Embedding, HybridSequential, LayerNorm
from ..parameter import Parameter

__all__ = ["GPTBlock", "GPTModel", "get_gpt", "gpt2_124m"]


class GPTBlock(HybridBlock):
    """One pre-LN causal transformer block.

    ``moe_experts > 0`` replaces the dense FFN with a routed
    mixture-of-experts FFN (top-2 GShard gating by default): the
    pre-LN residual carries tokens an over-capacity expert drops —
    the Switch-Transformer integration pattern. Expert weights shard
    over the mesh's ``ep`` axis via MOE_TRANSFORMER_RULES.
    """

    def __init__(self, units: int = 768, hidden_size: int = 3072,
                 num_heads: int = 12, dropout: float = 0.1,
                 layer_norm_eps: float = 1e-5, moe_experts: int = 0,
                 moe_top_k: int = 2, moe_capacity_factor: float = 1.25,
                 moe_router_z_loss: float = 1e-3,
                 gelu_approximate: bool = False,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_heads = num_heads
        # GPT-2 proper uses the tanh approximation ("gelu_new"); exact
        # erf GELU is the default here (and what BERT uses)
        self._gelu_approximate = gelu_approximate
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn_qkv = Dense(3 * units, in_units=units, flatten=False)
        self.attn_out = Dense(units, in_units=units, flatten=False)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        if moe_experts > 0:
            from ...parallel.moe import MoEDense
            self.moe = MoEDense(moe_experts, hidden_size, units=units,
                                top_k=moe_top_k,
                                capacity_factor=moe_capacity_factor,
                                router_z_loss=moe_router_z_loss)
            self.ffn1 = self.ffn2 = None
        else:
            self.moe = None
            self.ffn1 = Dense(hidden_size, in_units=units, flatten=False)
            self.ffn2 = Dense(units, in_units=hidden_size, flatten=False)
        self._dropout = dropout

    def forward(self, x: NDArray) -> NDArray:
        h = self.ln1(x)
        qkv = self.attn_qkv(h)
        q, k, v = mxnp.split(qkv, 3, axis=-1)
        att = npx.multi_head_attention(q, k, v, self._num_heads,
                                       causal=True,
                                       dropout=self._dropout)
        att = self.attn_out(att)
        if self._dropout:
            att = npx.dropout(att, self._dropout)
        x = x + att
        h = self.ln2(x)
        if self.moe is not None:
            ffn = self.moe(h)
        else:
            ffn = self.ffn2(npx.gelu(self.ffn1(h),
                                     approximate=self._gelu_approximate))
        if self._dropout:
            ffn = npx.dropout(ffn, self._dropout)
        return x + ffn


class GPTModel(HybridBlock):
    """Decoder-only LM: tokens (B, T) int -> logits (B, T, vocab).

    The LM head is weight-tied to ``word_embed`` (standard GPT-2
    practice; also what DEFAULT_TRANSFORMER_RULES expects for
    vocab-parallel sharding of the embedding).
    """

    def __init__(self, vocab_size: int = 50257, num_layers: int = 12,
                 units: int = 768, hidden_size: int = 3072,
                 num_heads: int = 12, max_length: int = 1024,
                 dropout: float = 0.1, moe_every_n: int = 0,
                 moe_experts: int = 8, moe_top_k: int = 2,
                 moe_capacity_factor: float = 1.25,
                 moe_router_z_loss: float = 1e-3,
                 gelu_approximate: bool = False,
                 layer_norm_eps: float = 1e-5,
                 **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.word_embed = Embedding(vocab_size, units)
        self.position_weight = Parameter(
            "position_weight", shape=(max_length, units), init="normal")
        self.blocks = HybridSequential()
        for i in range(num_layers):
            # moe_every_n > 0: every n-th block swaps its dense FFN for a
            # routed expert FFN (GShard/ST-MoE interleaving)
            is_moe = moe_every_n > 0 and (i + 1) % moe_every_n == 0
            self.blocks.add(GPTBlock(units, hidden_size, num_heads,
                                     dropout,
                                     layer_norm_eps=layer_norm_eps,
                                     moe_experts=moe_experts if is_moe
                                     else 0,
                                     moe_top_k=moe_top_k,
                                     moe_capacity_factor=moe_capacity_factor,
                                     moe_router_z_loss=moe_router_z_loss,
                                     gelu_approximate=gelu_approximate))
        self.ln_f = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self._dropout = dropout

    def forward(self, tokens: NDArray) -> NDArray:
        T = tokens.shape[1]
        if T > self._max_length:
            from ...base import MXNetError
            raise MXNetError(
                f"sequence length {T} exceeds max_length "
                f"{self._max_length}")
        if not self.position_weight.is_initialized:
            self.position_weight._finish_deferred_init(
                (self._max_length, self._units))
        x = self.word_embed(tokens)
        from ...ndarray import ops
        pos = ops.slice_axis(self.position_weight.data(), axis=0,
                             begin=0, end=T)
        x = x + pos.expand_dims(0)
        if self._dropout:
            x = npx.dropout(x, self._dropout)
        # activation checkpointing per block under MXNET_REMAT
        from ..block import remat_stack
        x = remat_stack(list(self.blocks), x, dropout=self._dropout)
        x = self.ln_f(x)
        # weight-tied LM head: logits = x @ E^T
        w = self.word_embed.weight.data()
        return mxnp.matmul(x, w.T)

    def generate(self, tokens, max_new_tokens: int,
                 method: str = "greedy", temperature: float = 1.0,
                 top_k: int = 40, eos_token: Optional[int] = None,
                 seed: int = 0, top_p: float = 0.9) -> NDArray:
        """KV-cache incremental decoding (greedy / 'sample' / 'top_k' /
        'top_p' nucleus): one compiled prefill + lax.scan program per
        shape signature. See ``model_zoo.generation``."""
        from .generation import generate as _gen
        return _gen(self, tokens, max_new_tokens, method=method,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    eos_token=eos_token, seed=seed)

    def beam_search(self, tokens, max_new_tokens: int,
                    beam_size: int = 4,
                    eos_token: Optional[int] = None,
                    alpha: float = 1.0):
        """Length-normalized beam search over the KV-cache decoder
        (gluon-nlp BeamSearchSampler analog)."""
        from .generation import beam_search as _beam
        return _beam(self, tokens, max_new_tokens, beam_size=beam_size,
                     eos_token=eos_token, alpha=alpha)


_SPECS = {
    # name: (num_layers, units, hidden, heads, max_length)
    "gpt2_124m": (12, 768, 3072, 12, 1024),
    "gpt2_350m": (24, 1024, 4096, 16, 1024),
    "gpt2_774m": (36, 1280, 5120, 20, 1024),
}


def get_gpt(model_name: str = "gpt2_124m", vocab_size: int = 50257,
            dropout: float = 0.1, max_length: Optional[int] = None,
            **kwargs: Any) -> GPTModel:
    if model_name not in _SPECS:
        raise ValueError(
            f"unknown GPT spec {model_name!r}; choose from "
            f"{sorted(_SPECS)}")
    L, u, h, nh, ml = _SPECS[model_name]
    return GPTModel(vocab_size=vocab_size, num_layers=L, units=u,
                    hidden_size=h, num_heads=nh,
                    max_length=max_length or ml, dropout=dropout,
                    **kwargs)


def gpt2_124m(**kw: Any) -> GPTModel:
    return get_gpt("gpt2_124m", **kw)
