"""``gluon.model_zoo`` (reference: python/mxnet/gluon/model_zoo) plus the
NLP models (BERT per gluon-nlp; GPT beyond-reference)."""
from . import vision
from . import bert
from . import gpt
from .bert import get_bert
from .gpt import get_gpt

__all__ = ["vision", "bert", "gpt", "get_bert", "get_gpt"]
