"""``gluon.model_zoo`` (reference: python/mxnet/gluon/model_zoo) plus the
NLP models (BERT per gluon-nlp; GPT and the encoder-decoder Transformer
beyond-reference, with KV-cache generation)."""
from . import vision
from . import bert
from . import gpt
from . import transformer
from .bert import get_bert
from .gpt import get_gpt
from .transformer import get_transformer

__all__ = ["vision", "bert", "gpt", "transformer", "get_bert",
           "get_gpt", "get_transformer"]
