"""Transformer encoder-decoder — sequence-to-sequence model family.

Reference-ecosystem parity: gluon-nlp's NMT Transformer
(``gluon-nlp/scripts/machine_translation``, the "Attention is All You
Need" lineage) was the flagship seq2seq model beside BERT. This is the
same family built from this framework's primitives: pre-LN blocks (the
stable-training variant), ``npx.multi_head_attention`` for self- and
cross-attention (XLA attention, flash kernel for long sequences), GELU
FFN, tied target embedding / output head, and source padding masks that
flow through both encoder self-attention and decoder cross-attention.

Inference uses KV-cache incremental decoding (``translate`` /
``beam_translate`` — see ``transformer_generation.py``): decoder
self-attention caches grow stepwise like GPT's, while cross-attention
keys/values are projected ONCE from the encoder memory at prefill.
"""
from __future__ import annotations

from typing import Any, Optional

from ... import npx
from ... import numpy as mxnp
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn import Dense, Embedding, HybridSequential, LayerNorm
from ..parameter import Parameter

__all__ = ["TransformerEncoderLayer", "TransformerDecoderLayer",
           "TransformerModel", "get_transformer"]


class TransformerEncoderLayer(HybridBlock):
    """Pre-LN encoder block: self-attention + GELU FFN."""

    def __init__(self, units: int = 512, hidden_size: int = 2048,
                 num_heads: int = 8, dropout: float = 0.1,
                 layer_norm_eps: float = 1e-5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_heads = num_heads
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn_qkv = Dense(3 * units, in_units=units, flatten=False)
        self.attn_out = Dense(units, in_units=units, flatten=False)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn1 = Dense(hidden_size, in_units=units, flatten=False)
        self.ffn2 = Dense(units, in_units=hidden_size, flatten=False)
        self._dropout = dropout

    def forward(self, x: NDArray, mask: Optional[NDArray] = None) -> NDArray:
        h = self.ln1(x)
        q, k, v = mxnp.split(self.attn_qkv(h), 3, axis=-1)
        att = npx.multi_head_attention(q, k, v, self._num_heads,
                                       mask=mask, dropout=self._dropout)
        att = self.attn_out(att)
        if self._dropout:
            att = npx.dropout(att, self._dropout)
        x = x + att
        h = self.ln2(x)
        ffn = self.ffn2(npx.gelu(self.ffn1(h)))
        if self._dropout:
            ffn = npx.dropout(ffn, self._dropout)
        return x + ffn


class TransformerDecoderLayer(HybridBlock):
    """Pre-LN decoder block: causal self-attention, cross-attention over
    the encoder memory, GELU FFN."""

    def __init__(self, units: int = 512, hidden_size: int = 2048,
                 num_heads: int = 8, dropout: float = 0.1,
                 layer_norm_eps: float = 1e-5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._num_heads = num_heads
        self.ln1 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.attn_qkv = Dense(3 * units, in_units=units, flatten=False)
        self.attn_out = Dense(units, in_units=units, flatten=False)
        self.ln_cross = LayerNorm(epsilon=layer_norm_eps,
                                  in_channels=units)
        self.cross_q = Dense(units, in_units=units, flatten=False)
        self.cross_kv = Dense(2 * units, in_units=units, flatten=False)
        self.cross_out = Dense(units, in_units=units, flatten=False)
        self.ln2 = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.ffn1 = Dense(hidden_size, in_units=units, flatten=False)
        self.ffn2 = Dense(units, in_units=hidden_size, flatten=False)
        self._dropout = dropout

    def forward(self, x: NDArray, memory: NDArray,
                memory_mask: Optional[NDArray] = None) -> NDArray:
        h = self.ln1(x)
        q, k, v = mxnp.split(self.attn_qkv(h), 3, axis=-1)
        att = npx.multi_head_attention(q, k, v, self._num_heads,
                                       causal=True,
                                       dropout=self._dropout)
        att = self.attn_out(att)
        if self._dropout:
            att = npx.dropout(att, self._dropout)
        x = x + att
        h = self.ln_cross(x)
        cq = self.cross_q(h)
        ck, cv = mxnp.split(self.cross_kv(memory), 2, axis=-1)
        catt = npx.multi_head_attention(cq, ck, cv, self._num_heads,
                                        mask=memory_mask,
                                        dropout=self._dropout)
        catt = self.cross_out(catt)
        if self._dropout:
            catt = npx.dropout(catt, self._dropout)
        x = x + catt
        h = self.ln2(x)
        ffn = self.ffn2(npx.gelu(self.ffn1(h)))
        if self._dropout:
            ffn = npx.dropout(ffn, self._dropout)
        return x + ffn


class TransformerModel(HybridBlock):
    """Encoder-decoder Transformer: (src (B, Ts), tgt (B, Tt)) ->
    logits (B, Tt, tgt_vocab).

    ``share_embed=True`` (default when the vocabularies match) ties
    source embedding, target embedding, and the output head to one
    matrix — the NMT weight-tying standard.
    """

    def __init__(self, src_vocab_size: int = 32000,
                 tgt_vocab_size: Optional[int] = None,
                 num_encoder_layers: int = 6, num_decoder_layers: int = 6,
                 units: int = 512, hidden_size: int = 2048,
                 num_heads: int = 8, max_length: int = 512,
                 dropout: float = 0.1, share_embed: Optional[bool] = None,
                 layer_norm_eps: float = 1e-5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        tgt_vocab_size = tgt_vocab_size or src_vocab_size
        if share_embed is None:
            share_embed = tgt_vocab_size == src_vocab_size
        if share_embed and tgt_vocab_size != src_vocab_size:
            raise MXNetError("share_embed requires equal vocabularies")
        self._units = units
        self._max_length = max_length
        self._share = share_embed
        self.src_embed = Embedding(src_vocab_size, units)
        self.tgt_embed = self.src_embed if share_embed else \
            Embedding(tgt_vocab_size, units)
        self.src_pos = Parameter("src_pos", shape=(max_length, units),
                                 init="normal")
        self.tgt_pos = Parameter("tgt_pos", shape=(max_length, units),
                                 init="normal")
        self.enc_layers = HybridSequential()
        for _ in range(num_encoder_layers):
            self.enc_layers.add(TransformerEncoderLayer(
                units, hidden_size, num_heads, dropout,
                layer_norm_eps=layer_norm_eps))
        self.enc_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self.dec_layers = HybridSequential()
        for _ in range(num_decoder_layers):
            self.dec_layers.add(TransformerDecoderLayer(
                units, hidden_size, num_heads, dropout,
                layer_norm_eps=layer_norm_eps))
        self.dec_ln = LayerNorm(epsilon=layer_norm_eps, in_channels=units)
        self._dropout = dropout

    # -- pieces -----------------------------------------------------------
    def _src_mask(self, src: NDArray,
                  src_valid_length: Optional[NDArray]):
        if src_valid_length is None:
            return None
        T = src.shape[1]
        from ...ndarray.ops import _as_nd
        from ...ndarray.register import invoke

        def impl(vl):
            import jax.numpy as jnp
            keep = jnp.arange(T)[None, :] < vl[:, None].astype(jnp.int32)
            return keep[:, None, None, :]            # (B, 1, 1, Ts)
        return invoke("transformer_src_mask", impl,
                      (_as_nd(src_valid_length),))

    def _pos(self, weight: Parameter, T: int):
        if not weight.is_initialized:
            weight._finish_deferred_init((self._max_length, self._units))
        from ...ndarray import ops
        return ops.slice_axis(weight.data(), axis=0, begin=0,
                              end=T).expand_dims(0)

    def encode(self, src: NDArray,
               src_valid_length: Optional[NDArray] = None) -> NDArray:
        """Source tokens -> encoder memory (B, Ts, units)."""
        if src.shape[1] > self._max_length:
            raise MXNetError(
                f"source length {src.shape[1]} exceeds max_length "
                f"{self._max_length}")
        mask = self._src_mask(src, src_valid_length)
        x = self.src_embed(src) + self._pos(self.src_pos, src.shape[1])
        if self._dropout:
            x = npx.dropout(x, self._dropout)
        for layer in self.enc_layers:
            x = layer(x, mask)
        return self.enc_ln(x)

    def decode(self, tgt: NDArray, memory: NDArray,
               src_valid_length: Optional[NDArray] = None,
               src: Optional[NDArray] = None) -> NDArray:
        """Teacher-forcing decode: target tokens + memory -> logits."""
        if tgt.shape[1] > self._max_length:
            raise MXNetError(
                f"target length {tgt.shape[1]} exceeds max_length "
                f"{self._max_length}")
        mmask = None
        if src_valid_length is not None:
            # the cross-attention key axis is the SOURCE length
            ref = src if src is not None else memory
            mmask = self._src_mask(ref, src_valid_length)
        x = self.tgt_embed(tgt) + self._pos(self.tgt_pos, tgt.shape[1])
        if self._dropout:
            x = npx.dropout(x, self._dropout)
        for layer in self.dec_layers:
            x = layer(x, memory, mmask)
        x = self.dec_ln(x)
        w = self.tgt_embed.weight.data()
        return mxnp.matmul(x, w.T)                   # tied head

    def forward(self, src: NDArray, tgt: NDArray,
                src_valid_length: Optional[NDArray] = None) -> NDArray:
        memory = self.encode(src, src_valid_length)
        return self.decode(tgt, memory, src_valid_length, src=src)

    # -- inference --------------------------------------------------------
    def translate(self, src, max_new_tokens: int, bos_token: int,
                  eos_token: Optional[int] = None,
                  src_valid_length=None, method: str = "greedy",
                  temperature: float = 1.0, top_k: int = 40,
                  seed: int = 0, top_p: float = 0.9):
        """KV-cache incremental decoding from ``bos_token`` (greedy /
        sample / top_k / top_p nucleus). Returns (B, max_new_tokens)
        int32 target tokens."""
        from .transformer_generation import translate as _tr
        return _tr(self, src, max_new_tokens, bos_token,
                   eos_token=eos_token, src_valid_length=src_valid_length,
                   method=method, temperature=temperature, top_k=top_k,
                   seed=seed, top_p=top_p)

    def beam_translate(self, src, max_new_tokens: int, bos_token: int,
                       beam_size: int = 4,
                       eos_token: Optional[int] = None,
                       src_valid_length=None, alpha: float = 1.0):
        """Length-normalized beam search over the KV-cache decoder."""
        from .transformer_generation import beam_translate as _bt
        return _bt(self, src, max_new_tokens, bos_token,
                   beam_size=beam_size, eos_token=eos_token,
                   src_valid_length=src_valid_length, alpha=alpha)


_SPECS = {
    # name: (enc_layers, dec_layers, units, hidden, heads)
    "transformer_base": (6, 6, 512, 2048, 8),
    "transformer_big": (6, 6, 1024, 4096, 16),
}


def get_transformer(model_name: str = "transformer_base",
                    src_vocab_size: int = 32000,
                    tgt_vocab_size: Optional[int] = None,
                    **kwargs: Any) -> TransformerModel:
    if model_name not in _SPECS:
        raise MXNetError(
            f"unknown transformer spec {model_name!r}; choose from "
            f"{sorted(_SPECS)}")
    e, d, u, h, nh = _SPECS[model_name]
    return TransformerModel(src_vocab_size=src_vocab_size,
                            tgt_vocab_size=tgt_vocab_size,
                            num_encoder_layers=e, num_decoder_layers=d,
                            units=u, hidden_size=h, num_heads=nh,
                            **kwargs)
